"""mxtpu.embedding — sharded large-table embeddings + row-sparse updates.

The TPU-native rebuild of the reference framework's recsys machinery
(row_sparse NDArray gradients + lazy_update optimizers), re-architected
for GSPMD (docs/embedding.md):

* :mod:`.lookup` — the pure kernels: one id policy
  (``normalize_ids``: int32 + documented clip/error out-of-range
  handling, shared with `gluon.nn.Embedding`), the dedup lookup
  (unique → gather → inverse-take inside the jit, so the sharded
  table's collective scales with unique ids), and the segment-summed
  row-gradient backward.
* :mod:`.blocks` — :class:`ShardedEmbedding` / :class:`EmbeddingBag`,
  whose (vocab, dim) table is annotated on the logical ``vocab`` axis
  and shards across ``mp``/``tp`` under the standard axis rules.
* :mod:`.optimizers` — :class:`RowSparseAdaGrad` / :class:`LazyAdam`:
  scatter-update only touched rows and their per-row state, verified
  equivalent to the dense reference rule on overlapping ids
  (tests/test_embedding.py).
* :mod:`.stats` — the table census behind ``extra.embedding`` in BENCH
  json (per-device vs replicated table bytes, dedup rate, rows
  touched/step), schema-gated by tools/trace_check.py.

``BENCH_MODEL=recsys`` (bench.py + models/dlrm.py) is the workload that
exercises all of it end to end.
"""
from .lookup import (OOR_POLICIES, normalize_ids, dedup_lookup,
                     dedup_capacity, segment_rowgrads, embed)
from .blocks import ShardedEmbedding, EmbeddingBag
from .optimizers import RowSparseAdaGrad, LazyAdam, adagrad_rows, adam_rows
from .stats import (register_table, observe_batch, table_stats, bench_extra,
                    reset)

__all__ = [
    "OOR_POLICIES", "normalize_ids", "dedup_lookup", "dedup_capacity",
    "segment_rowgrads", "embed",
    "ShardedEmbedding", "EmbeddingBag",
    "RowSparseAdaGrad", "LazyAdam", "adagrad_rows", "adam_rows",
    "register_table", "observe_batch", "table_stats", "bench_extra", "reset",
]
