"""Table census + per-step lookup accounting for ``extra.embedding``.

Every :class:`~.blocks.ShardedEmbedding` registers itself here at
construction; :func:`bench_extra` walks the live tables and reports the
numbers the BENCH json schema (tools/trace_check.py
``check_embedding_extra``) gates:

* ``table_bytes_logical`` — what a replicated copy of every table costs
  per device (the number memscope would show with no sharding);
* ``table_bytes_per_device`` — what device 0 actually holds, read off
  the jax arrays' addressable shards (ground truth, not an estimate).
  Sharded correctly, this is strictly below logical — the acceptance
  criterion the embedding smoke asserts;
* ``dedup_rate`` / ``rows_touched_per_step`` / ``ids_per_step`` — from
  :func:`observe_batch`, which the bench's eager loop feeds with the
  raw id stream (host-side numpy: the jit'd program cannot count for
  us, and the bench already owns the concrete batch).

dedup_rate = 1 - unique/total: 0.0 means dedup buys nothing, 0.75 means
the gather moves a quarter of the naive traffic. perf_regress.py gates
a drop in this number — a dedup regression is a silent comms blowup.
"""
from __future__ import annotations

import threading
import weakref

import numpy as np

__all__ = ["register_table", "observe_batch", "table_stats", "bench_extra",
           "reset"]

_lock = threading.Lock()
_TABLES: "list[dict]" = []        # {"ref": weakref to block, "name": str}
_STEP = {"ids": 0, "rows": 0, "batches": 0, "dedup_num": 0.0}


def register_table(block) -> None:
    with _lock:
        _TABLES.append({"ref": weakref.ref(block)})
    from ..profiler.counters import set_gauge
    set_gauge("embedding.tables", len(_live_blocks()), "embedding")


def _live_blocks():
    with _lock:
        out = []
        for t in _TABLES:
            b = t["ref"]()
            if b is not None:
                out.append(b)
        return out


def observe_batch(ids, input_dim: int) -> dict:
    """Account one concrete id batch (any shape, any integer/float
    carrier): total ids, unique rows touched, dedup rate. Called from
    the bench's eager loop; cheap host-side numpy."""
    ids = np.asarray(ids)
    total = int(ids.size)
    uniq = int(np.unique(np.rint(ids.reshape(-1)).astype(np.int64)).size)
    rate = 1.0 - (uniq / total) if total else 0.0
    with _lock:
        _STEP["ids"] += total
        _STEP["rows"] += uniq
        _STEP["batches"] += 1
        _STEP["dedup_num"] += rate
    from ..profiler.counters import set_gauge
    set_gauge("embedding.ids_per_step", total, "embedding")
    set_gauge("embedding.rows_touched_per_step", uniq, "embedding")
    set_gauge("embedding.dedup_rate", round(rate, 6), "embedding")
    return {"ids": total, "rows_touched": uniq, "dedup_rate": rate}


def _param_device_bytes(p) -> "tuple[int, int]":
    """(logical_bytes, device0_bytes) for one Parameter; device0 bytes
    read from the raw array's addressable shards when initialized."""
    import jax

    shape = tuple(p._shape or ())
    logical = int(np.prod(shape)) * np.dtype(p.dtype or "float32").itemsize
    dev_bytes = logical      # an uninitialized/unsharded table is replicated
    try:
        raw = p.data()._data
        dev0 = jax.devices()[0]
        shards = [s for s in raw.addressable_shards if s.device == dev0]
        if shards:
            dev_bytes = int(sum(int(np.prod(s.data.shape)) *
                                s.data.dtype.itemsize for s in shards))
    except Exception:  # noqa: BLE001 — census never breaks a bench
        pass
    return logical, dev_bytes


def table_stats() -> "list[dict]":
    out = []
    for b in _live_blocks():
        p = getattr(b, "weight", None)
        if p is None:
            continue
        logical, dev = _param_device_bytes(p)
        out.append({
            "name": getattr(p, "name", "weight"),
            "vocab": int(b._input_dim),
            "dim": int(b._output_dim),
            "bytes_logical": logical,
            "bytes_device0": dev,
            "dedup": bool(b._dedup),
            "oor_policy": b._oor_policy,
        })
    return out


def bench_extra() -> dict:
    """The ``extra.embedding`` block for BENCH json."""
    from ..profiler.counters import counters as _counters
    from ..profiler.counters import set_gauge as _set_gauge
    tables = table_stats()
    with _lock:
        batches = _STEP["batches"]
        ids = _STEP["ids"] / batches if batches else 0.0
        rows = _STEP["rows"] / batches if batches else 0.0
        rate = _STEP["dedup_num"] / batches if batches else 0.0
    ctrs = _counters()
    logical = sum(t["bytes_logical"] for t in tables)
    per_dev = sum(t["bytes_device0"] for t in tables)
    _set_gauge("embedding.table_bytes_logical", logical, "embedding")
    _set_gauge("embedding.table_bytes_per_device", per_dev, "embedding")
    return {
        "tables": len(tables),
        "table_bytes_logical": logical,
        "table_bytes_per_device": per_dev,
        "rows_total": sum(t["vocab"] for t in tables),
        "ids_per_step": round(ids, 3),
        "rows_touched_per_step": round(rows, 3),
        "dedup_rate": round(rate, 6),
        "oor_policy": (tables[0]["oor_policy"] if tables else "clip"),
        "oor_ids": int(ctrs.get("embedding/embedding.oor_ids", 0)),
        "lookups": int(ctrs.get("embedding/embedding.lookups", 0)),
        "sparse_rows_updated": int(
            ctrs.get("embedding/embedding.sparse_rows_updated", 0)),
        "table_detail": tables,
    }


def reset() -> None:
    with _lock:
        _TABLES.clear()
        _STEP.update({"ids": 0, "rows": 0, "batches": 0, "dedup_num": 0.0})
