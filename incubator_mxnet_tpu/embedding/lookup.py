"""Pure lookup kernels for the embedding subsystem (docs/embedding.md).

Everything here operates on raw jax arrays and is jit-safe — these are
the functions that run INSIDE the fused train step's single program, so
the sharded gather, the dedup machinery, and the segment-summed row
gradients all land in one XLA module where commscope can attribute the
resulting collective.

Three design points, fixed here so every consumer agrees:

* **One id policy.** ``normalize_ids`` is the single home for index
  normalization: any carrier dtype (float ids from a record stream, i64
  from numpy) becomes int32, and out-of-range ids are resolved by ONE
  documented policy — ``"clip"`` (clamp into ``[0, vocab)``; the
  reference backend's GPU take semantics) or ``"error"`` (raise on
  concrete arrays; under a tracer values are unknown, so the policy
  degrades to clip and the degradation is documented rather than
  silent). `gluon.nn.Embedding`, `nd.embedding` and `ShardedEmbedding`
  all route through it, which closes the historical hole where
  non-integer / out-of-range ids meant backend-dependent garbage.
* **Dedup lookup.** ``dedup_lookup`` compresses the id stream before
  touching the (vocab, dim) table: ``unique → gather → inverse-take``.
  With the table sharded on the model axis, the cross-device traffic of
  the gather scales with ``capacity`` (the static unique bound), not
  with the raw id count — on a recsys batch where hot ids repeat, that
  is the 2-3x comms saving perf_regress.py gates. ``capacity`` must be
  a static python int (jit requires it); correctness needs
  ``capacity >= true unique count``, so the default is
  ``min(n_ids, vocab)`` — never lossy, maximally compressed.
* **Row-sparse gradients.** ``segment_rowgrads`` is the backward
  half: (ids, out_grad) → (unique_ids, row_grads, valid) via
  segment-sum, the exact payload the row-sparse optimizer path
  (optimizers.py) scatter-applies to touched rows only.
"""
from __future__ import annotations

import numpy as np

__all__ = ["OOR_POLICIES", "normalize_ids", "dedup_lookup",
           "dedup_capacity", "segment_rowgrads", "embed"]

OOR_POLICIES = ("clip", "error")


def _jnp():
    import jax.numpy as jnp
    return jnp


def _is_concrete(x) -> bool:
    import jax.core
    return not isinstance(x, jax.core.Tracer)


def normalize_ids(ids, input_dim: int, policy: str = "clip"):
    """int32-normalize an id array and apply the out-of-range policy.

    Float carriers are rounded (``rint``), not truncated: ids that ride
    a float32 record stream arrive as e.g. ``41.999996`` and truncation
    would silently shift them — the original `nn.Embedding` bug this
    satellite fixes. Integer carriers are cast straight to int32.

    Policy ``"clip"`` clamps into ``[0, input_dim)``. Policy
    ``"error"`` raises ``ValueError`` when `ids` is concrete and any id
    is out of range; under a tracer (inside jit) values are
    unobservable, so it clamps like "clip" — the eager-mode error is
    the debugging affordance, the in-jit clamp is the safety net.
    Out-of-range occurrences on concrete arrays are counted on the
    ``embedding/embedding.oor_ids`` counter under either policy.
    """
    jnp = _jnp()
    if policy not in OOR_POLICIES:
        raise ValueError(
            f"oor_policy must be one of {OOR_POLICIES}, got {policy!r}")
    ids = jnp.asarray(ids)
    if jnp.issubdtype(ids.dtype, jnp.floating):
        ids = jnp.rint(ids).astype(jnp.int32)
    elif ids.dtype != jnp.int32:
        ids = ids.astype(jnp.int32)
    if _is_concrete(ids):
        n_oor = int(jnp.sum((ids < 0) | (ids >= input_dim)))
        if n_oor:
            from ..profiler.counters import counter
            counter("embedding.oor_ids", "embedding").increment(n_oor)
            if policy == "error":
                raise ValueError(
                    f"embedding lookup: {n_oor} id(s) outside "
                    f"[0, {input_dim}) under oor_policy='error'")
    return jnp.clip(ids, 0, input_dim - 1)


def dedup_capacity(n_ids: int, input_dim: int, capacity=None) -> int:
    """The static unique-id bound for one lookup: the requested
    `capacity` clamped to ``min(n_ids, input_dim)`` (a batch cannot
    contain more unique valid ids than either)."""
    cap = min(int(n_ids), int(input_dim))
    if capacity is not None:
        cap = min(cap, max(1, int(capacity)))
    return max(1, cap)


def dedup_lookup(weight, ids, capacity: int):
    """unique → gather → inverse-take, all jit-safe.

    `ids` must already be normalized (int32, in-range); `capacity` is a
    static int >= the number of unique ids (use :func:`dedup_capacity`).
    Returns ``ids.shape + (dim,)`` rows. The table gather touches only
    ``capacity`` rows — under a vocab-sharded table that gather is the
    one collective of the lookup (XLA:CPU spells it as a masked local
    gather + all-reduce of the (capacity, dim) block; a TPU target
    spells it all-to-all) — and the inverse-take is local fan-out, no
    comms. Unused capacity slots are filled with id 0; their gathered
    rows are never selected by the inverse map, so padding is inert.
    """
    jnp = _jnp()
    flat = ids.reshape(-1)
    uniq, inv = jnp.unique(flat, size=capacity, fill_value=0,
                           return_inverse=True)
    rows = jnp.take(weight, uniq, axis=0)
    return jnp.take(rows, inv.reshape(ids.shape), axis=0)


def segment_rowgrads(ids, out_grad, capacity: int):
    """(ids, dL/d_lookup) → (unique_ids, row_grads, valid).

    The row-sparse backward: duplicate ids' gradients are segment-summed
    into one row gradient per unique id. `out_grad` has shape
    ``ids.shape + (dim,)``. Returns ``(capacity,)`` unique ids,
    ``(capacity, dim)`` summed row grads, and a ``(capacity,)`` bool
    mask marking the slots that hold a real id (padding slots alias id
    0 with an all-zero gradient, but the mask lets the optimizer skip
    even their weight-decay term — lazy semantics touch ONLY rows the
    batch used). Pure under jit.
    """
    import jax
    jnp = _jnp()
    flat = ids.reshape(-1)
    uniq, inv, counts = jnp.unique(flat, size=capacity, fill_value=0,
                                   return_inverse=True, return_counts=True)
    g = jax.ops.segment_sum(out_grad.reshape(flat.shape[0], -1),
                            inv.reshape(-1), num_segments=capacity)
    return uniq, g, counts > 0


def embed(ids, weight, input_dim: int, policy: str = "clip",
          dedup: bool = True, capacity=None):
    """The full lookup: normalize → (dedup'd or plain) gather.

    The single entry point the blocks and `nd.embedding` share; `ids`
    may be any carrier dtype and any shape."""
    jnp = _jnp()
    ids = normalize_ids(ids, input_dim, policy=policy)
    if not dedup:
        return jnp.take(weight, ids, axis=0)
    n = int(np.prod(ids.shape)) if ids.shape else 1
    cap = dedup_capacity(n, input_dim, capacity)
    return dedup_lookup(weight, ids, cap)
