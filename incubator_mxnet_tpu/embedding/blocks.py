"""Gluon blocks for sharded large-table embeddings (docs/embedding.md).

:class:`ShardedEmbedding` is `gluon.nn.Embedding` re-architected for
tables that do not fit one device: the (vocab, dim) weight is annotated
``PartitionSpec('vocab', None)`` at construction, so under any mesh with
an ``mp``/``tp`` axis the existing logical axis rules
(parallel/sharding.DEFAULT_RULES) shard the rows across the model axis —
no per-callsite mesh knowledge, the same annotation path `Block.shard`
uses. The lookup goes through the dedup path (lookup.dedup_lookup) so
the one collective XLA emits for the sharded gather moves
``capacity × dim`` floats instead of ``n_ids × dim``.

:class:`EmbeddingBag` adds the recsys pooling mode: a (batch, bag) id
matrix pools (sum/mean) into one (batch, dim) vector per sample —
DLRM's per-feature multi-hot aggregation.

Knob defaults (all through autotune/knobs.py, mxlint-governed):
``MXTPU_EMBEDDING_DEDUP`` (default on) and
``MXTPU_EMBEDDING_OOR_POLICY`` (default ``clip``) set the
construction-time defaults; explicit constructor args win.
"""
from __future__ import annotations

from ..gluon.block import HybridBlock
from ..ndarray import _apply
from . import lookup as _lookup
from . import stats as _stats

__all__ = ["ShardedEmbedding", "EmbeddingBag"]


def _default_dedup() -> bool:
    from ..autotune.knobs import env_flag
    return env_flag("MXTPU_EMBEDDING_DEDUP", True)


def _default_policy() -> str:
    from ..autotune.knobs import env_str
    return env_str("MXTPU_EMBEDDING_OOR_POLICY", "clip")


class ShardedEmbedding(HybridBlock):
    """Embedding whose table rides the logical ``vocab`` axis.

    forward(x): ids of any shape/carrier dtype -> ``x.shape + (dim,)``.
    ``dedup=True`` routes through unique→gather→inverse-take;
    ``dedup_capacity`` caps the static unique bound (default
    ``min(n_ids, vocab)`` — lossless). ``oor_policy`` is the shared
    id policy (lookup.normalize_ids): 'clip' or 'error'."""

    def __init__(self, input_dim, output_dim, dtype="float32",
                 weight_initializer=None, dedup=None, dedup_capacity=None,
                 oor_policy=None, logical_axis="vocab", prefix=None,
                 params=None):
        super().__init__(prefix, params)
        self._input_dim = int(input_dim)
        self._output_dim = int(output_dim)
        self._dedup = _default_dedup() if dedup is None else bool(dedup)
        self._capacity = dedup_capacity
        policy = _default_policy() if oor_policy is None else oor_policy
        if policy not in _lookup.OOR_POLICIES:
            raise ValueError(f"oor_policy must be one of "
                             f"{_lookup.OOR_POLICIES}, got {policy!r}")
        self._oor_policy = policy
        self.weight = self.params.get("weight",
                                      shape=(input_dim, output_dim),
                                      dtype=dtype, init=weight_initializer)
        from jax.sharding import PartitionSpec
        self.weight._sharding = PartitionSpec(logical_axis, None)
        _stats.register_table(self)

    def _lookup_fn(self, pool=None):
        input_dim, policy = self._input_dim, self._oor_policy
        dedup, capacity = self._dedup, self._capacity

        def fn(i, w):
            out = _lookup.embed(i, w, input_dim, policy=policy,
                                dedup=dedup, capacity=capacity)
            if pool is not None:
                import jax.numpy as jnp
                out = (jnp.mean(out, axis=-2) if pool == "mean"
                       else jnp.sum(out, axis=-2))
            return out
        return fn

    def _count(self):
        from ..profiler.counters import counter
        counter("embedding.lookups", "embedding").increment()
        if self._dedup:
            counter("embedding.dedup_lookups", "embedding").increment()

    def forward(self, x):
        self._count()
        return _apply(self._lookup_fn(), [x, self.weight.data()],
                      name="sharded_embedding")


class EmbeddingBag(ShardedEmbedding):
    """Pooled embedding: (…, bag) ids -> (…,) pooled ``dim`` vectors.

    ``mode='sum'`` (default) or ``'mean'`` — pooling runs inside the
    same fused op as the lookup, after the dedup inverse-take."""

    def __init__(self, input_dim, output_dim, mode="sum", **kwargs):
        if mode not in ("sum", "mean"):
            raise ValueError(f"mode must be 'sum' or 'mean', got {mode!r}")
        super().__init__(input_dim, output_dim, **kwargs)
        self._mode = mode

    def forward(self, x):
        self._count()
        return _apply(self._lookup_fn(pool=self._mode),
                      [x, self.weight.data()], name="embedding_bag")
