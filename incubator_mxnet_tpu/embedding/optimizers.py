"""TPU-native row-sparse optimizers (docs/embedding.md).

The reference framework's recsys trick is ``lazy_update``: when the
backward produces a RowSparse gradient, the optimizer touches ONLY the
rows the batch used — weight rows AND their per-row optimizer state.
Here SGD already has that path (optimizer/__init__.py); this module adds
the two rules large-table training actually runs on:

* :class:`RowSparseAdaGrad` — AdaGrad whose per-row ``hist`` accumulator
  only advances for touched rows (parity: reference
  ``adagrad_update`` on row_sparse weight/grad).
* :class:`LazyAdam` — Adam whose ``m``/``v`` only advance for touched
  rows, with bias correction by the GLOBAL step count (parity:
  reference ``mx.optimizer.LazyAdam`` semantics: staleness of untouched
  rows' moments is accepted by design).

Both inherit the dense rule (``_update``) from their parent, so inside a
FusedTrainStep — where the gradient is a dense array whose untouched
rows are exact zeros produced by the XLA scatter — they run the dense
math unchanged, and with ``wd == 0`` a zero grad row moves nothing:
the fused one-jit program IS the row-sparse update, expressed densely.
The ``_update_sparse`` override below is the eager/KVStore route, where
materializing a (vocab, dim) dense gradient would defeat the point.

The row kernels (:func:`adagrad_rows`, :func:`adam_rows`) are pure and
jit-safe; the ``valid`` mask lets callers feed the padded output of
``lookup.segment_rowgrads`` directly — padding slots are dropped by
scattering them out of bounds (jax's documented drop semantics), so a
padding slot aliasing row 0 can never race a real row-0 update.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..optimizer import register, Optimizer, AdaGrad, Adam

__all__ = ["RowSparseAdaGrad", "LazyAdam", "adagrad_rows", "adam_rows"]


def _safe_rows(rows, valid, vocab):
    """Redirect padding slots out of bounds: jax scatters DROP
    out-of-bounds updates, so invalid slots vanish instead of writing
    stale values over a real row they alias."""
    if valid is None:
        return rows
    return jnp.where(valid, rows, jnp.int32(vocab))


def adagrad_rows(w, hist, rows, g, lr, wd, eps, valid=None):
    """AdaGrad on `rows` only; identical math to AdaGrad._update
    restricted to the touched rows (wd included — lazy semantics decay
    only rows the batch used). Returns (w, hist)."""
    w_rows = jnp.take(w, rows, axis=0).astype(jnp.float32)
    h_rows = jnp.take(hist, rows, axis=0)
    g = g + wd * w_rows
    h_new = h_rows + jnp.square(g)
    w_new = w_rows - lr * g / (jnp.sqrt(h_new) + eps)
    tgt = _safe_rows(rows, valid, w.shape[0])
    return (w.at[tgt].set(w_new.astype(w.dtype)),
            hist.at[tgt].set(h_new))


def adam_rows(w, m, v, rows, g, lr, wd, t, beta1, beta2, eps, valid=None):
    """Adam on `rows` only, bias-corrected by the global step `t`;
    identical math to Adam._update restricted to the touched rows.
    Returns (w, m, v)."""
    w_rows = jnp.take(w, rows, axis=0).astype(jnp.float32)
    m_rows = jnp.take(m, rows, axis=0)
    v_rows = jnp.take(v, rows, axis=0)
    g = g + wd * w_rows
    m_new = beta1 * m_rows + (1 - beta1) * g
    v_new = beta2 * v_rows + (1 - beta2) * jnp.square(g)
    tf = t.astype(jnp.float32)
    mhat = m_new / (1 - beta1 ** tf)
    vhat = v_new / (1 - beta2 ** tf)
    w_new = w_rows - lr * mhat / (jnp.sqrt(vhat) + eps)
    tgt = _safe_rows(rows, valid, w.shape[0])
    return (w.at[tgt].set(w_new.astype(w.dtype)),
            m.at[tgt].set(m_new), v.at[tgt].set(v_new))


class _RowSparseMixin:
    """The shared eager lazy path: gather touched rows + per-row state,
    run the row kernel, scatter back — one jitted computation, cached
    per (shape, nnz) like SGD's sparse_step."""

    lazy_update = True

    def _row_kernel(self, w, state, rows, g32, lr, wd, t):
        raise NotImplementedError

    def _update_sparse(self, index, weight, grad, state, skip=None):
        if (not self.lazy_update
                or (self.multi_precision
                    and weight._data.dtype in (jnp.float16, jnp.bfloat16))):
            return Optimizer._update_sparse(self, index, weight, grad, state,
                                            skip=skip)
        self._update_count(index)
        lr, wd = self._get_lr_wd(index)
        t = self._index_update_count[index]
        has_clip = self.clip_gradient is not None
        has_skip = skip is not None
        key = ("rsp", weight.shape, str(weight._data.dtype), int(grad.nnz),
               has_clip, has_skip)
        fn = self._jit_cache.get(key)
        if fn is None:
            def sparse_step(w, s, rows, g, lr_, wd_, t_, rs_, cl_, sk_):
                g32 = g.astype(jnp.float32) * rs_
                if cl_ is not None:
                    g32 = jnp.clip(g32, -cl_, cl_)
                new_w, new_s = self._row_kernel(w, s, rows, g32, lr_, wd_, t_)
                if sk_ is not None:
                    new_w = jnp.where(sk_, w, new_w)
                    new_s = jax.tree_util.tree_map(
                        lambda ns, os: jnp.where(sk_, os, ns), new_s, s)
                return new_w, new_s

            fn = jax.jit(sparse_step)
            self._jit_cache[key] = fn
        cl = jnp.float32(self.clip_gradient) if has_clip else None
        new_w, new_state = fn(weight._data, state,
                              grad.indices.astype(jnp.int32), grad._data,
                              jnp.float32(lr), jnp.float32(wd), jnp.int32(t),
                              jnp.float32(self.rescale_grad), cl, skip)
        weight._data = new_w
        from ..profiler.counters import counter
        counter("embedding.sparse_updates", "embedding").increment()
        counter("embedding.sparse_rows_updated",
                "embedding").increment(int(grad.nnz))
        return new_state


@register("rowsparseadagrad")
class RowSparseAdaGrad(_RowSparseMixin, AdaGrad):
    """AdaGrad with the lazy row-sparse update path (dense rule inherited
    verbatim, so FusedTrainStep fuses it like stock AdaGrad)."""

    def __init__(self, learning_rate=0.01, eps=1e-7, lazy_update=True,
                 **kwargs):
        super().__init__(learning_rate=learning_rate, eps=eps, **kwargs)
        self.lazy_update = lazy_update

    def _row_kernel(self, w, state, rows, g32, lr, wd, t):
        (hist,) = state
        new_w, new_hist = adagrad_rows(w, hist, rows, g32, lr, wd,
                                       self.float_stable_eps)
        return new_w, (new_hist,)


@register("lazyadam")
class LazyAdam(_RowSparseMixin, Adam):
    """Adam with the lazy row-sparse update path (global-step bias
    correction; untouched rows' moments stay stale by design)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, lazy_update=True, **kwargs):
        super().__init__(learning_rate=learning_rate, beta1=beta1,
                         beta2=beta2, epsilon=epsilon, **kwargs)
        self.lazy_update = lazy_update

    def _row_kernel(self, w, state, rows, g32, lr, wd, t):
        m, v = state
        new_w, new_m, new_v = adam_rows(w, m, v, rows, g32, lr, wd, t,
                                        self.beta1, self.beta2, self.epsilon)
        return new_w, (new_m, new_v)
