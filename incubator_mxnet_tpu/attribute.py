"""Scoped symbol attributes (reference parity: python/mxnet/attribute.py).

`AttrScope` itself lives with the Symbol implementation; this module is the
reference's import location (`mx.attribute.AttrScope`).
"""
from .symbol import AttrScope

__all__ = ["AttrScope"]

# reference attribute.py exposes the merged active attrs via the scope object
current = AttrScope.current_attrs
