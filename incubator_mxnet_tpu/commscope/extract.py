"""Collective inventory, link-time estimates, and the resharding detector.

Per captured program this module turns :mod:`.hlo`'s raw collective
records into the ``extra.commscope`` shape the bench embeds and
``tools/mxdiag.py comms`` renders:

* **aggregation** — records grouped by (op kind, mesh axis): count,
  payload bytes, analytic link-time estimate;
* **axis attribution** — replica groups matched against the partitions
  a mesh axis induces on the device grid (``{{0,2},{1,3}}`` on a 2×2
  ``(dp, mp)`` mesh is the dp axis; a single full group is the whole
  mesh);
* **estimates** — ring-algorithm lower bounds against per-topology ICI
  peak-bandwidth tables (v5e/v4/v5p + a CPU fallback, same table-row
  matching as perfscope's FLOP peaks; ``MXTPU_PEAK_ICI_BW`` overrides).
  These are *analytic estimates from static shapes*, clearly marked so
  downstream consumers (the step budget, BENCH json) never confuse them
  with a measurement;
* **resharding detection** — a collective is flagged as
  compiler-inserted resharding when (a) its kind is outside the mode's
  expected signature (a reduce-scatter in a pure-dp program moves
  layout, not gradients), or (b) in dp/auto modes, an
  all-gather/all-to-all whose operand provenance walks back to a
  program *parameter* — the compiler un-doing an annotated input
  sharding the computation can't use (the "accidental all-gather" a bad
  ``Block.shard()`` or missing axis rule causes). FSDP is exempt from
  (b): gathering parameters is that mode's contract.

Everything lands in the ``commscope.*`` counter family, flight-recorder
compile spans, and a process-wide program table mirrored into
``extra.commscope`` by ``bench.py``.
"""
from __future__ import annotations

import threading
import warnings

import numpy as np

from ..diagnostics import flight as _flight
from ..profiler.counters import counter as _counter, set_gauge as _set_gauge
from . import hlo as _hlo

__all__ = ["ici_peaks", "estimate_ms", "attribute_axis", "axis_for_groups",
           "expected_kinds", "detect_resharding", "record_inventory",
           "capture", "programs", "reset_programs", "step_estimate",
           "axis_by_kind", "EXPECTED_KINDS", "ICI_TABLE"]

# Per-chip aggregate ICI bandwidth (bytes/s, one direction). Published
# per-chip interconnect numbers: v4 ≈ 2.4 Tb/s, v5e ≈ 1.6 Tb/s,
# v5p ≈ 4.8 Tb/s. The CPU row is a deliberately round fallback — on the
# tier-1 fake-device mesh the *relative* estimates and the schema are
# the point, not the absolute milliseconds (docs/commscope.md).
ICI_TABLE = {
    "v5e": 200e9,
    "v4": 300e9,
    "v5p": 600e9,
    "cpu": 1e9,
}

# Expected collective-kind signature per sharding mode
# (parallel/sharding.MODES). Anything outside the set is flagged as a
# resharding collective. `None` (unknown mode: jit-cache / serving
# programs) expects everything except "other".
EXPECTED_KINDS = {
    # pure data parallel: gradient all-reduce; small batch-axis gathers
    # (loss index plumbing) are legitimate, so all-gather stays in the
    # set and the PARAM-provenance rule catches the accidental ones
    "dp": frozenset(("all-reduce", "all-gather")),
    # zero-style: param all-gather + grad reduce-scatter — which
    # XLA:CPU decomposes into all-to-all + local reduce, so both
    # spellings are the mode's signature
    "fsdp": frozenset(("all-reduce", "all-gather", "reduce-scatter",
                       "all-to-all")),
    # model-axis layouts: Megatron f/g pairs (activation all-reduce /
    # all-gather) + the dp gradient reduce; all-to-all stays in the set
    # because XLA:CPU spells reduce-scatter that way (same decomposition
    # the fsdp row documents), and collective-permute because XLA's
    # SPMD partitioner spells the reshard of an activation whose dim
    # does NOT divide the mesh axis as pad + halo permute (DLRM's
    # 28-wide interaction output on an mp4 mesh, e.g.) — the
    # param-provenance rule still catches an accidental
    # all-to-all/permute of an input
    "auto": frozenset(("all-reduce", "all-gather", "reduce-scatter",
                       "all-to-all", "collective-permute")),
    None: frozenset(("all-reduce", "all-gather", "reduce-scatter",
                     "all-to-all", "collective-permute")),
}

# ring-algorithm traffic factor per kind: the fraction of the payload
# each device moves over its links (n = participating devices)
_RING_FACTOR = {
    "all-reduce": lambda n: 2.0 * (n - 1) / n,
    "all-gather": lambda n: (n - 1) / n,
    "reduce-scatter": lambda n: (n - 1) / n,
    "all-to-all": lambda n: (n - 1) / n,
    "collective-permute": lambda n: 1.0,
    "other": lambda n: 1.0,
}


def _env_float(name):
    # never-raise contract: a typo'd override keeps the table
    from ..autotune.knobs import env_float
    return env_float(name, None, on_error="default")


def ici_peaks(device=None) -> dict:
    """Peak interconnect bandwidth for the device's topology row.

    Reuses perfscope's device-kind pattern matching (one place decides
    that "TPU v5 lite" is the v5e row); ``MXTPU_PEAK_ICI_BW`` overrides
    the table for new hardware without a code change."""
    from ..perfscope import cost as _pcost
    base = _pcost.device_peaks(device)
    row = base.get("table_row", "cpu")
    bw = ICI_TABLE.get(row, ICI_TABLE["cpu"])
    env = _env_float("MXTPU_PEAK_ICI_BW")
    if env:
        bw = env
    return {"device_kind": base.get("device_kind"), "table_row": row,
            "ici_bytes_per_s": bw}


def estimate_ms(kind, nbytes, group_size, bw) -> float:
    """Analytic ring lower bound for one collective: milliseconds of
    link time to move `nbytes` across a group of `group_size`."""
    try:
        n = max(1, int(group_size or 1))
        b = float(nbytes or 0)
        if n <= 1 or b <= 0 or not bw:
            return 0.0
        factor = _RING_FACTOR.get(kind, _RING_FACTOR["other"])(n)
        return factor * b / float(bw) * 1e3
    except Exception:  # noqa: BLE001
        return 0.0


# --------------------------------------------------------------------------
# mesh-axis attribution
# --------------------------------------------------------------------------

def _id_grid(mesh):
    """Device-id array shaped like the mesh (replica groups name global
    device ids, not mesh positions)."""
    devs = np.asarray(mesh.devices, dtype=object)
    ids = np.empty(devs.shape, dtype=np.int64)
    for idx in np.ndindex(devs.shape):
        ids[idx] = int(getattr(devs[idx], "id", -1))
    return ids


def attribute_axis(groups, id_grid, axis_names):
    """Which mesh axis a replica-group partition communicates over.

    `groups`: list of device-id lists; `id_grid`: ndarray of device ids
    in mesh layout; `axis_names`: mesh axis names in grid order.
    Returns an axis name, ``"all"`` (single group spanning the mesh),
    ``"mixed"`` (a partition no single axis induces — combined-axis
    groups land here), or ``None`` when groups are unparseable."""
    if not groups:
        return None
    try:
        gset = frozenset(frozenset(int(i) for i in g) for g in groups)
        all_ids = frozenset(int(i) for i in id_grid.ravel())
        if gset == frozenset((all_ids,)):
            return axis_names[0] if len(axis_names) == 1 else "all"
        for ax, name in enumerate(axis_names):
            moved = np.moveaxis(id_grid, ax, -1)
            expected = frozenset(
                frozenset(int(i) for i in moved[idx])
                for idx in np.ndindex(moved.shape[:-1]))
            if gset == expected:
                return name
        return "mixed"
    except Exception:  # noqa: BLE001
        return None


def axis_for_groups(groups, mesh):
    """Mesh wrapper around :func:`attribute_axis`."""
    if mesh is None:
        return None
    return attribute_axis(groups, _id_grid(mesh), list(mesh.axis_names))


# --------------------------------------------------------------------------
# resharding detection
# --------------------------------------------------------------------------

def expected_kinds(mode):
    return EXPECTED_KINDS.get(mode, EXPECTED_KINDS[None])


def detect_resharding(collectives, defs, mode) -> list:
    """The subset of `collectives` that look like compiler-inserted
    layout changes, each annotated with a `reason`:

    * ``"unexpected-kind"`` — op kind outside the mode's signature;
    * ``"param-gather"`` — (dp/auto only) an all-gather/all-to-all (or,
      in auto mode, a collective-permute — the kind XLA spells
      uneven-dim reshards with) whose operand is a program input: the
      compiler is un-sharding an annotated parameter the computation
      needed replicated.

    The ``"other"`` bucket (unknown spellings) is exempt from both
    rules: unrecognized is not mis-laid-out."""
    expect = expected_kinds(mode)
    flagged = []
    for c in collectives:
        if c["kind"] == "other":
            # an unknown HLO spelling (future op, renamed after an XLA
            # upgrade) is inventoried but never indicted — "we don't
            # recognize it" is not evidence of a layout bug, and the
            # parser's never-raise contract would be undone by a
            # detector that hard-fails CI on it
            continue
        if c["kind"] not in expect:
            flagged.append(dict(c, reason="unexpected-kind"))
            continue
        provenance_kinds = (("all-gather", "all-to-all",
                             "collective-permute") if mode == "auto"
                            else ("all-gather", "all-to-all"))
        if (mode in ("dp", "auto")
                and c["kind"] in provenance_kinds
                and defs
                and any(_hlo.chases_to_parameter(defs, op)
                        for op in c.get("operands", ()))):
            flagged.append(dict(c, reason="param-gather"))
    return flagged


# --------------------------------------------------------------------------
# program table + capture
# --------------------------------------------------------------------------

_PROGRAMS: "dict[str, dict]" = {}
_plock = threading.Lock()
_warned: set = set()


def programs() -> list:
    """Snapshot of every captured program's inventory, insertion-ordered."""
    with _plock:
        return [dict(v) for v in _PROGRAMS.values()]


def reset_programs() -> None:
    with _plock:
        _PROGRAMS.clear()
    _warned.clear()


def step_estimate():
    """The steady-phase train program's per-step collective estimate —
    what perfscope's StepBudget splits out of device_compute in sharded
    mode. Scan-body inventories (fused_step_k) are static, i.e. per
    micro-step, so the newest ``train_step``-kind record IS the per-step
    number. None when no train program was captured."""
    with _plock:
        recs = [v for v in _PROGRAMS.values() if v.get("kind") == "train_step"]
    if not recs:
        return None
    rec = recs[-1]
    t = rec.get("totals") or {}
    mesh = rec.get("mesh")
    devices = 1
    if isinstance(mesh, dict):
        for s in mesh.values():
            devices *= int(s)
    return {"program": rec.get("name"), "est_ms": t.get("est_ms"),
            "bytes": t.get("bytes"), "count": t.get("count"),
            # the CAPTURED program's mesh — the provenance decision must
            # not depend on the process-global registry (an explicit
            # mesh= FusedTrainStep never registers one)
            "mesh": mesh, "devices": devices,
            # False = the optimized HLO could not be read/parsed: the
            # zero inventory is IGNORANCE, not a finding — the step
            # budget must report 'unavailable', never an estimated zero
            "hlo_available": bool(rec.get("hlo_available", True)),
            "resharding_collectives": rec.get("resharding_collectives", 0)}


def axis_by_kind(program) -> dict:
    """``op kind -> mesh axis`` for one captured program — the join
    mxtpu.devicescope uses to attribute MEASURED collective-lane time
    to a mesh axis (the trace's op events carry kind but not replica
    groups; the static inventory carries both).

    ``program``: a program name (looked up in the capture table) or a
    record dict. A kind whose rows span more than one axis maps to
    None — ambiguous attribution is reported as unknown, never
    guessed. Returns {} for unknown programs. Never raises."""
    try:
        rec = program
        if not isinstance(rec, dict):
            with _plock:
                rec = _PROGRAMS.get(program)
        if not isinstance(rec, dict):
            return {}
        out = {}
        for row in rec.get("collectives") or []:
            k = row.get("kind")
            if k is None:
                continue
            if k in out and out[k] != row.get("axis"):
                out[k] = None
            else:
                out[k] = row.get("axis")
        return out
    except Exception:  # noqa: BLE001
        return {}


_KIND_COUNTER = {k: "commscope." + k.replace("-", "_")
                 for k in _hlo.COLLECTIVE_KINDS}


def record_inventory(name, collectives, defs=None, mesh=None, mode=None,
                     kind: str = "program", hlo_available: bool = True,
                     extra: dict | None = None) -> dict:
    """Aggregate one program's parsed collectives, run the resharding
    detector, publish counters/flight/table. This is `capture`'s tail
    and the entry point for tests that parsed their own text."""
    peaks = ici_peaks()
    bw = peaks["ici_bytes_per_s"]
    axes = list(getattr(mesh, "axis_names", ()) or ())
    grid = _id_grid(mesh) if mesh is not None else None
    groups_out: "dict[tuple, dict]" = {}
    total_bytes = total_count = 0
    total_est = 0.0
    default_n = int(getattr(mesh, "size", 1) or 1)
    for c in collectives:
        axis = (attribute_axis(c.get("replica_groups"), grid, axes)
                if grid is not None else None)
        n = c.get("group_size") or default_n
        est = estimate_ms(c["kind"], c.get("bytes", 0), n, bw)
        key = (c["kind"], axis)
        slot = groups_out.setdefault(
            key, {"kind": c["kind"], "axis": axis, "count": 0, "bytes": 0,
                  "est_ms": 0.0})
        slot["count"] += 1
        slot["bytes"] += int(c.get("bytes", 0))
        slot["est_ms"] += est
        total_count += 1
        total_bytes += int(c.get("bytes", 0))
        total_est += est
        _counter(_KIND_COUNTER[c["kind"]], "commscope").increment()
    flagged = detect_resharding(collectives, defs or {}, mode)
    rec = {
        "name": name,
        "kind": kind,
        "mode": mode,
        "mesh": dict(getattr(mesh, "shape", {}) or {}) if mesh is not None
                else None,
        "hlo_available": bool(hlo_available),
        "collectives": sorted(groups_out.values(),
                              key=lambda s: -s["bytes"]),
        "totals": {"count": total_count, "bytes": total_bytes,
                   "est_ms": round(total_est, 6)},
        "resharding_collectives": len(flagged),
        "resharding": [{"name": f.get("name"), "kind": f["kind"],
                        "reason": f["reason"],
                        "result_shape": f.get("result_shape"),
                        "operand_shapes": f.get("operand_shapes")}
                       for f in flagged[:16]],
        "estimated": True,     # link time here is analytic, never measured
    }
    if extra:
        rec.update(extra)
    with _plock:
        _PROGRAMS[name] = rec
    _counter("commscope.programs_analyzed", "commscope").increment()
    if total_count:
        _counter("commscope.collectives", "commscope").increment(total_count)
        _counter("commscope.payload_bytes", "commscope").increment(total_bytes)
    if flagged:
        _counter("commscope.resharding_collectives",
                 "commscope").increment(len(flagged))
        if name not in _warned:
            _warned.add(name)
            shapes = [f.get("result_shape") for f in flagged[:4]]
            warnings.warn(
                f"commscope: program {name!r} (mode={mode}) contains "
                f"{len(flagged)} compiler-inserted resharding "
                f"collective(s) ({flagged[0]['reason']}; result shapes "
                f"{shapes}) — an annotation/axis-rule likely does not "
                f"match the computation (docs/commscope.md)",
                stacklevel=3)
    if kind == "train_step":
        _set_gauge("commscope.step_collective_est_ms",
                   round(total_est, 6), "commscope")
        _set_gauge("commscope.step_collective_bytes", total_bytes,
                   "commscope")
    if _flight._REC is not None:
        _flight.record("compile", f"commscope.comms:{name}", {
            "collectives": total_count, "bytes": total_bytes,
            "est_ms": round(total_est, 6),
            "resharding": len(flagged), "mode": mode})
    return rec


def capture(name, lowered=None, compiled=None, mesh=None, mode=None,
            kind: str = "program", extra: dict | None = None):
    """Extract one compiled program's collective inventory.

    Called from perfscope's compile-site hooks when commscope is armed.
    With no mesh (or a 1-device mesh) the program cannot contain GSPMD
    collectives, so an empty inventory is recorded WITHOUT compiling —
    zero cost on every unsharded run. Under a real mesh the optimized
    HLO is read from `compiled` when the site already has it (serving
    buckets) or produced by compiling `lowered` (the one extra compile
    commscope pays; docs/commscope.md). Never raises."""
    try:
        if mesh is None:
            from ..parallel import sharding as _sharding
            mesh = _sharding.get_mesh()
        if mesh is None or int(getattr(mesh, "size", 1) or 1) <= 1:
            return record_inventory(name, [], mesh=mesh, mode=mode,
                                    kind=kind, extra=extra)
        text = None
        try:
            if compiled is None and lowered is not None:
                compiled = lowered.compile()
            if compiled is not None:
                text = compiled.as_text()
        except Exception:  # noqa: BLE001 — backend-dependent surface
            text = None
        if not text:
            return record_inventory(name, [], mesh=mesh, mode=mode,
                                    kind=kind, hlo_available=False,
                                    extra=extra)
        colls = _hlo.parse_collectives(text)
        defs = _hlo.parse_instructions(text) if colls else {}
        return record_inventory(name, colls, defs=defs, mesh=mesh,
                                mode=mode, kind=kind, extra=extra)
    except Exception:  # noqa: BLE001 — extraction must never break compiles
        return None
