"""mxtpu.commscope — collective & resharding observability for GSPMD.

The fifth observability layer (docs/observability.md). mxtpu.sharding
(PR 8) replaced explicit KVStore collectives with compiler-inserted
GSPMD collectives inside one jit program — which made perfscope's step
budget structurally blind to communication in exactly the sharded modes
that matter: on a dp4/fsdp4 mesh, all-reduce/all-gather/reduce-scatter
time silently lands in ``device_compute`` while the measured
``kvstore.collective_ms`` reads zero. Commscope makes those collectives
visible again:

* **static HLO extraction** (:mod:`.hlo`) — at every perfscope compile
  site (FusedTrainStep, TrainLoop chunks, the hybridize jit cache,
  serving buckets) the compiled program's optimized HLO is walked for
  its collective inventory: op kind, count, payload bytes (shapes ×
  dtype), replica-group → mesh-axis attribution;
* **analytic link-time estimates** (:mod:`.extract`) — ring-algorithm
  lower bounds against per-topology ICI peak tables (v5e/v4/v5p + CPU
  fallback, ``MXTPU_PEAK_ICI_BW`` override), clearly marked
  ``estimated`` — never confused with a measurement;
* **resharding detector** — compiler-inserted layout-change collectives
  that don't correspond to any annotated spec (the "accidental
  all-gather" a bad ``Block.shard()`` causes) are flagged per program
  with the offending operand shapes, warned about, and counted in
  ``commscope.resharding_collectives``;
* **step-budget integration** — perfscope's decomposition consumes
  :func:`step_estimate` so sharded-mode BENCH json splits ``collective``
  out of ``device_compute`` again, with the component's provenance
  pinned (``measured`` | ``estimated`` | ``unavailable``).

Everything lands in the ``commscope.*`` counter family, flight-recorder
compile spans, ``extra.commscope`` in BENCH json (``BENCH_MESH`` runs),
and ``tools/mxdiag.py comms``.

Cost model: with no mesh registered a capture records an empty
inventory without compiling anything — zero cost on unsharded runs.
Under a mesh, sites that only *lower* (FusedTrainStep, jit cache) pay
one extra XLA compile per captured program signature, which is why
commscope is **off by default**: ``enable()`` arms it (bench.py does,
unless ``BENCH_COMMSCOPE=0``), ``MXTPU_COMMSCOPE=1`` arms it at import.
Commscope rides perfscope's capture hooks, so enabling it arms
perfscope too.
"""
from __future__ import annotations

import os

from . import extract
from . import hlo
from .extract import (attribute_axis, axis_by_kind, axis_for_groups,
                      capture, detect_resharding, estimate_ms,
                      expected_kinds, ici_peaks, programs,
                      record_inventory, reset_programs, step_estimate,
                      EXPECTED_KINDS, ICI_TABLE)
from .hlo import (chases_to_parameter, parse_collectives,
                  parse_instructions, parse_replica_groups, parse_shape,
                  shape_bytes, COLLECTIVE_KINDS)

__all__ = ["enable", "disable", "enabled", "enable_from_env",
           "bench_extra", "capture", "programs", "reset_programs",
           "step_estimate", "ici_peaks", "estimate_ms", "attribute_axis",
           "axis_by_kind",
           "axis_for_groups", "detect_resharding", "expected_kinds",
           "record_inventory", "parse_collectives", "parse_instructions",
           "parse_replica_groups", "parse_shape", "shape_bytes",
           "chases_to_parameter", "COLLECTIVE_KINDS", "EXPECTED_KINDS",
           "ICI_TABLE", "hlo", "extract"]

# module global: None = commscope off (the fast-path predicate;
# perfscope's capture hooks guard with `if _cs._CS is not None:`)
_CS = None


class _CommScope:
    """Marker object holding enable-time options (the perfscope/healthmon
    module-global discipline)."""

    def __init__(self):
        pass


def enable():
    """Arm collective extraction at every perfscope compile site. The
    hooks live inside perfscope's analyze functions, so perfscope is
    armed too if it isn't already."""
    global _CS
    from .. import perfscope as _ps
    if _ps._PS is None:
        _ps.enable()
    _CS = _CommScope()
    return _CS


def disable():
    global _CS
    _CS = None


def enabled() -> bool:
    return _CS is not None


def enable_from_env():
    """MXTPU_COMMSCOPE=1 arms commscope at import (like MXTPU_PERFSCOPE)."""
    if os.environ.get("MXTPU_COMMSCOPE", "") == "1":
        enable()


def bench_extra() -> dict:
    """The ``extra.commscope`` payload for BENCH json: every captured
    program's collective inventory, the ICI peak row the estimates were
    scored against, and the steady train program's per-step summary."""
    return {"programs": programs(), "peaks": ici_peaks(),
            "step": step_estimate()}
