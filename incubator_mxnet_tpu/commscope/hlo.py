"""Optimized-HLO text parsing for collective extraction.

GSPMD collectives do not exist in the traced program — the SPMD
partitioner inserts them at compile time, so the only artifact that
names every all-reduce/all-gather the step will actually run is the
compiled module's HLO text (``Compiled.as_text()``). This module turns
that text into structured records without ever raising: the capture
path runs inside compile sites, and a parse surprise must cost a
collective's attribution, not the compile.

What the parser understands (validated against the XLA:CPU dumps the
tier-1 matrix compiles — see tests/test_commscope.py for captured
shapes):

* instruction lines ``%name = <shape> <opcode>(<operands>), attrs`` —
  including ``ROOT`` markers, tuple-typed results, and typed operands;
* the collective op family ``all-reduce`` / ``all-gather`` /
  ``reduce-scatter`` / ``all-to-all`` / ``collective-permute`` plus
  their async ``-start``/``-done`` split (counted once, on the start),
  with any other ``collective-*``/``all-*`` spelling mapped to
  ``"other"`` rather than dropped or raised on;
* both replica-group syntaxes: explicit ``{{0,1},{2,3}}`` and iota
  ``[2,2]<=[4]`` / ``[2,2]<=[2,2]T(1,0)`` (reshape-transpose form);
* shape strings ``f32[64,32]{1,0}`` (layout suffix ignored) and tuple
  shapes, with per-dtype byte widths for payload accounting.

The operand-provenance chase (:func:`chases_to_parameter`) is the
resharding detector's evidence: a collective whose input walks back
through layout-only ops (copy/bitcast/transpose/reshape/convert) to a
program ``parameter`` is moving an *input* the caller annotated, not a
computed value — the "accidental all-gather" signature.
"""
from __future__ import annotations

import re

__all__ = ["COLLECTIVE_KINDS", "DTYPE_BYTES", "parse_shape", "shape_bytes",
           "shape_max_leaf_bytes", "parse_replica_groups",
           "parse_instructions", "parse_collectives",
           "chases_to_parameter"]

# the closed op-kind taxonomy (tools/trace_check.py enforces it in
# extra.commscope): every record's `kind` is one of these. Unknown
# collective spellings land on "other" — never a raise, never a drop.
COLLECTIVE_KINDS = ("all-reduce", "all-gather", "reduce-scatter",
                    "all-to-all", "collective-permute", "other")

# HLO primitive-type byte widths (token/opaque/tuple have no payload)
DTYPE_BYTES = {
    "pred": 1, "s2": 1, "s4": 1, "s8": 1, "s16": 2, "s32": 4, "s64": 8,
    "u2": 1, "u4": 1, "u8": 1, "u16": 2, "u32": 4, "u64": 8,
    "f8e5m2": 1, "f8e4m3": 1, "f8e4m3fn": 1, "f8e4m3b11fnuz": 1,
    "f8e5m2fnuz": 1, "f8e4m3fnuz": 1,
    "f16": 2, "bf16": 2, "f32": 4, "f64": 8, "c64": 8, "c128": 16,
}

_SHAPE_RE = re.compile(r"([a-z][a-z0-9]*)\[([0-9,]*)\](?:\{[^}]*\})?")

# one collective instruction: "%name = <shape> <op>(" with the op drawn
# from the all-*/collective-* family (async -start/-done included)
_COLL_RE = re.compile(
    r"%([\w.\-]+)\s*=\s*"                     # instruction name
    r"((?:\([^=]*?\))|(?:\S+))\s+"            # result shape (maybe tuple)
    r"((?:all|collective|reduce-scatter)[a-z\-]*)"   # op name
    r"\(")
_DEF_RE = re.compile(
    r"^\s*(?:ROOT\s+)?%([\w.\-]+)\s*=\s*"
    r"(?:\([^=]*?\)|\S+)\s+([\w\-]+)\(")
_GROUPS_RE = re.compile(r"replica_groups=(\{\{[0-9,{} ]*\}\}|\[[^\]]*\]"
                        r"<=\[[0-9,]*\](?:T\([0-9,]*\))?)")
_CHANNEL_RE = re.compile(r"channel_id=(\d+)")
_DIMS_RE = re.compile(r"dimensions=\{([0-9,]*)\}")
# a typed operand inside the call parens: "f32[16,32]{1,0} %param.1"
_OPERAND_RE = re.compile(r"([a-z][a-z0-9]*\[[0-9,]*\](?:\{[^}]*\})?)?\s*"
                         r"%([\w.\-]+)")

# ops that only change layout/metadata — chasing THROUGH them preserves
# "this value is a program input" provenance
_PASSTHROUGH_OPS = frozenset(
    ("copy", "bitcast", "reshape", "transpose", "convert", "copy-start",
     "copy-done", "optimization-barrier"))


def parse_shape(s):
    """One HLO shape string → list of (dtype, dims) leaves.

    ``"f32[64,32]{1,0}"`` → ``[("f32", (64, 32))]``; a tuple shape
    yields one leaf per element; anything unrecognizable yields ``[]``
    (never raises)."""
    out = []
    try:
        for m in _SHAPE_RE.finditer(s or ""):
            dims = tuple(int(d) for d in m.group(2).split(",") if d != "")
            out.append((m.group(1), dims))
    except Exception:  # noqa: BLE001 — parser contract: never raise
        return []
    return out


def shape_bytes(s) -> int:
    """Total payload bytes of a shape string (tuples summed; unknown
    dtypes count 0 so garbage can't inflate the accounting)."""
    total = 0
    for dtype, dims in parse_shape(s):
        width = DTYPE_BYTES.get(dtype)
        if width is None:
            continue
        n = 1
        for d in dims:
            n *= d
        total += n * width
    return total


def shape_max_leaf_bytes(s) -> int:
    """Largest single leaf's bytes — the right result accounting for
    async ``-start`` ops, whose tuple result aliases the source operand
    and context buffers NEXT TO the destination (summing would count
    the payload ~twice)."""
    best = 0
    for dtype, dims in parse_shape(s):
        width = DTYPE_BYTES.get(dtype)
        if width is None:
            continue
        n = 1
        for d in dims:
            n *= d
        best = max(best, n * width)
    return best


def _iota_groups(dims, reshape, perm):
    n = 1
    for d in reshape:
        n *= d
    flat = list(range(n))
    if perm:
        # reshape to `reshape`, transpose by `perm`, flatten (row-major)
        import itertools
        strides = [0] * len(reshape)
        acc = 1
        for i in range(len(reshape) - 1, -1, -1):
            strides[i] = acc
            acc *= reshape[i]
        out = []
        for idx in itertools.product(*[range(reshape[p]) for p in perm]):
            out.append(sum(idx[k] * strides[perm[k]]
                           for k in range(len(perm))))
        flat = out
    if len(dims) < 1:
        return None
    group_size = dims[-1]
    if group_size <= 0 or len(flat) % group_size:
        return None
    return [flat[i:i + group_size] for i in range(0, len(flat), group_size)]


def parse_replica_groups(s):
    """Replica-group attribute → list of device-id lists, or None.

    Handles the explicit form ``{{0,1},{2,3}}`` and the iota form
    ``[groups,size]<=[reshape-dims]`` with an optional ``T(perm)``
    transpose suffix (the two spellings XLA's CPU/TPU pipelines emit)."""
    if not s:
        return None
    s = s.strip()
    try:
        if s.startswith("{"):
            groups = []
            for grp in re.findall(r"\{([0-9, ]*)\}", s):
                ids = [int(x) for x in grp.replace(" ", "").split(",")
                       if x != ""]
                if ids:
                    groups.append(ids)
            return groups or None
        m = re.match(r"\[([0-9,]*)\]<=\[([0-9,]*)\](?:T\(([0-9,]*)\))?$", s)
        if not m:
            return None
        dims = [int(x) for x in m.group(1).split(",") if x != ""]
        reshape = [int(x) for x in m.group(2).split(",") if x != ""]
        perm = tuple(int(x) for x in m.group(3).split(",") if x != "") \
            if m.group(3) else None
        return _iota_groups(dims, reshape, perm)
    except Exception:  # noqa: BLE001
        return None


def parse_instructions(text) -> dict:
    """All instruction definitions in an HLO module text:
    ``{name: (opcode, first_operand_name)}`` — the minimum the
    provenance chase needs. Malformed lines are skipped."""
    defs = {}
    for line in (text or "").splitlines():
        m = _DEF_RE.match(line)
        if not m:
            continue
        first = None
        paren = line[m.end():]
        om = re.search(r"%([\w.\-]+)", paren)
        if om:
            first = om.group(1)
        defs[m.group(1)] = (m.group(2), first)
    return defs


def chases_to_parameter(defs: dict, name, max_depth: int = 8) -> bool:
    """True when `name`'s value is a program input reached only through
    layout-preserving ops. ``defs`` comes from :func:`parse_instructions`."""
    seen = 0
    while name is not None and seen <= max_depth:
        entry = defs.get(name)
        if entry is None:
            return False
        opcode, first = entry
        if opcode == "parameter":
            return True
        if opcode not in _PASSTHROUGH_OPS:
            return False
        name = first
        seen += 1
    return False


def _normalize_kind(raw: str):
    """Raw HLO op name → (taxonomy kind, counted) — async ``-done``
    halves are the uncounted tail of their ``-start``."""
    if raw.endswith("-done"):
        return None, False
    base = raw[:-6] if raw.endswith("-start") else raw
    if base in COLLECTIVE_KINDS:
        return base, True
    # anything else in the all-*/collective-* family: closed-taxonomy
    # bucket, never a raise (collective-broadcast, future op kinds, ...)
    return "other", True


def parse_collectives(text) -> list:
    """Every collective instruction in an HLO module text, as records::

        {"name", "kind", "raw_kind", "result_bytes", "operand_bytes",
         "bytes", "dtype", "replica_groups", "group_size", "dims",
         "channel_id", "operands", "operand_shapes", "result_shape"}

    ``bytes`` is the larger of result/operand payload — the full
    (gathered / pre-scatter) array a ring algorithm actually moves.
    Never raises; returns ``[]`` for text with no collectives."""
    out = []
    if not text:
        return out
    try:
        lines = text.splitlines()
    except Exception:  # noqa: BLE001
        return out
    for line in lines:
        try:
            m = _COLL_RE.search(line)
            if not m:
                continue
            name, result_shape, raw = m.group(1), m.group(2), m.group(3)
            kind, counted = _normalize_kind(raw)
            if not counted:
                continue
            # operands: the parenthesized list right after the op name
            paren = line[m.end():]
            depth, end = 1, len(paren)
            for i, ch in enumerate(paren):
                if ch == "(":
                    depth += 1
                elif ch == ")":
                    depth -= 1
                    if depth == 0:
                        end = i
                        break
            operand_str = paren[:end]
            operands, operand_shapes = [], []
            for om in _OPERAND_RE.finditer(operand_str):
                operands.append(om.group(2))
                if om.group(1):
                    operand_shapes.append(om.group(1))
            attrs = paren[end:]
            gm = _GROUPS_RE.search(attrs)
            groups = parse_replica_groups(gm.group(1)) if gm else None
            cm = _CHANNEL_RE.search(attrs)
            dm = _DIMS_RE.search(attrs)
            # async -start results are tuples bundling the source
            # operand (and context scratch) WITH the destination; the
            # payload is the largest leaf, not the tuple sum — a sync
            # op's tuple result (variadic all-to-all) genuinely sums
            if raw.endswith("-start"):
                result_bytes = shape_max_leaf_bytes(result_shape)
            else:
                result_bytes = shape_bytes(result_shape)
            operand_bytes = sum(shape_bytes(s) for s in operand_shapes)
            leaves = parse_shape(result_shape)
            out.append({
                "name": name,
                "kind": kind,
                "raw_kind": raw,
                "result_shape": result_shape,
                "operand_shapes": operand_shapes,
                "operands": operands,
                "result_bytes": result_bytes,
                "operand_bytes": operand_bytes,
                "bytes": max(result_bytes, operand_bytes),
                "dtype": leaves[0][0] if leaves else None,
                "replica_groups": groups,
                "group_size": (len(groups[0]) if groups and groups[0]
                               else None),
                "dims": ([int(x) for x in dm.group(1).split(",") if x != ""]
                         if dm else None),
                "channel_id": int(cm.group(1)) if cm else None,
            })
        except Exception:  # noqa: BLE001 — skip the line, keep the rest
            continue
    return out
