"""Black-box flight recorder: a bounded ring of recent runtime events.

The Dapper/black-box pattern: always-on, cheap, bounded recording of what
the framework just did — op dispatches, bulk-segment flushes, collective
launches, jit compile spans, trainer steps — so that when a training job
dies, the dump answers "what happened in the seconds before the crash"
without anyone having had a trace session open. The reference analogue is
MXNet's engine audit logging + the process-state dumps its launcher
collects on failure.

Design:

* **ring** — `collections.deque(maxlen=capacity)`; append is O(1) and
  GIL-atomic, so hot-path recording takes no lock (the lock is only held
  to snapshot at dump time).
* **hooks** — subsystems check one module global (`_REC is not None`)
  before calling :func:`record`; the ndarray funnel gets a dedicated
  `_flight_hook` global installed only while the recorder runs, same
  zero-overhead-off discipline as the profiler.
* **dump** — JSON with a versioned schema (``mxtpu.flight/1``): env +
  config snapshot captured at enable time, a consistent counters-registry
  snapshot, the (ts-sorted) events, and the exception when dumped from
  the crash path. `tools/trace_check.py` validates it; `tools/mxdiag.py`
  pretty-prints it.
* **crash path** — `enable_flight_recorder(dump_on_crash=True)` chains a
  `sys.excepthook` wrapper (and a SIGTERM handler when installable) that
  writes ONE dump per process — repeated invocations are idempotent and
  return the same path, so a cascade of handlers can't shred the file.
"""
from __future__ import annotations

import collections
import json
import os
import signal
import sys
import threading
import time

from ..profiler.counters import counters as _counters_snapshot
from ..profiler.counters import counter_kinds as _counter_kinds


def _snapshot_registry(best_effort: bool):
    """Counters + kinds. `best_effort` is the signal-handler path: the
    interrupted main thread may HOLD the registry lock (Counter.increment
    takes it on hot paths), so a blocking acquire would deadlock the
    process inside its own SIGTERM handler. Read lock-free instead —
    worker threads may mutate the dict mid-iteration, so retry on the
    RuntimeError and settle for an empty snapshot rather than a hang."""
    if not best_effort:
        return _counters_snapshot(), _counter_kinds()
    from ..profiler.counters import _registry
    for _ in range(3):
        try:
            items = list(_registry.items())
            return ({k: c.value for k, c in items},
                    {k: c.kind for k, c in items})
        except RuntimeError:
            continue
    return {}, {}

__all__ = ["FlightRecorder", "enable_flight_recorder",
           "disable_flight_recorder", "flight_enabled", "record",
           "dump", "crash_dump", "last_dump_path", "SCHEMA"]

SCHEMA = "mxtpu.flight/1"

# module-global: None = recorder off (THE fast-path predicate)
_REC = None

_prev_excepthook = None
_prev_sigterm = None


def _env_snapshot() -> dict:
    """Config-relevant environment at enable time (crash dumps must carry
    enough to reproduce the run's knobs)."""
    keep = {k: v for k, v in os.environ.items()
            if k.startswith(("MXTPU_", "BENCH_", "JAX_", "XLA_"))}
    snap = {"argv": list(sys.argv), "pid": os.getpid(),
            "python": sys.version.split()[0], "env": keep}
    # rank tag: lets tools/mxdiag.py merge interleave several ranks'
    # dumps into one cluster timeline without filename conventions. The
    # launcher env wins: when the recorder is armed at import (MXTPU_DIAG
    # =1), the cluster is not formed yet and jax.process_index() would
    # report 0 on EVERY rank — mis-tagging all dumps as rank 0.
    try:
        snap["rank"] = int(os.environ["MXTPU_PROCESS_ID"])
    except (KeyError, ValueError):
        pass
    try:
        import jax
        snap["jax_backend"] = jax.default_backend()
        snap["jax_device_count"] = jax.device_count()
        snap.setdefault("rank", jax.process_index())
        snap["num_ranks"] = jax.process_count()
    except Exception:
        pass
    try:
        from .. import __version__
        snap["mxtpu_version"] = __version__
    except Exception:
        pass
    return snap


class FlightRecorder:
    def __init__(self, capacity: int = 4096, dump_dir: str | None = None):
        self.capacity = int(capacity)
        self.events = collections.deque(maxlen=self.capacity)
        self.dump_dir = dump_dir or os.environ.get("MXTPU_DIAG_DIR", "/tmp")
        self.config = {"capacity": self.capacity, "dump_dir": self.dump_dir}
        self.env = _env_snapshot()
        self.started_at = time.time()
        self.dump_count = 0
        self._lock = threading.Lock()
        self._once = {}            # once-key -> path (crash idempotence)
        self._last_path = None

    # -- recording (hot path: no lock, deque append is atomic) ------------
    def append(self, kind: str, name: str, args=None):
        # wall/monotonic pair (mxtpu.events/2 discipline): cross-process
        # merges order within a process by mono so NTP steps can't
        # reorder the ring
        ev = {"ts": time.time(), "mono": time.monotonic(),
              "kind": kind, "name": name}
        if args:
            ev["args"] = args
        self.events.append(ev)

    def op_event(self, name):
        """Minimal per-dispatch event (installed as ndarray._flight_hook)."""
        self.events.append({"ts": time.time(),
                            "mono": time.monotonic(), "kind": "op",
                            "name": name or "op"})

    # -- dumping -----------------------------------------------------------
    def default_path(self) -> str:
        return os.path.join(self.dump_dir,
                            f"mxtpu_flight_{os.getpid()}.json")

    def dump(self, reason: str = "manual", path: str | None = None,
             exc=None, once_key: str | None = None,
             best_effort: bool = False) -> str:
        """Write the ring to disk. With `once_key` (the crash path), the
        first call wins and later calls return the same path untouched.
        `best_effort` (signal-handler context) never blocks on a lock the
        interrupted thread might hold: it bounds the lock wait and falls
        back to lock-free snapshots — a slightly torn dump beats a
        process that hangs inside its own SIGTERM handler."""
        locked = self._lock.acquire(timeout=2.0) if best_effort \
            else self._lock.acquire()
        try:
            if once_key is not None and once_key in self._once:
                return self._once[once_key]
            path = path or self.default_path()
            counters, kinds = _snapshot_registry(best_effort)
            events = sorted(self.events, key=lambda e: e["ts"])
            payload = {
                "schema": SCHEMA,
                "dumped_at": time.time(),
                "started_at": self.started_at,
                "reason": reason,
                "env": self.env,
                "config": self.config,
                "counters": counters,
                "counter_kinds": kinds,
                "n_events": len(events),
                "capacity": self.capacity,
                "events": events,
            }
            if exc is not None:
                tp, val = exc[0], exc[1]
                payload["exception"] = {
                    "type": getattr(tp, "__name__", str(tp)),
                    "message": str(val)[:2000],
                }
                if len(exc) > 2 and exc[2] is not None:
                    import traceback
                    payload["exception"]["traceback"] = \
                        traceback.format_tb(exc[2])[-20:]
            # unique tmp name: an unlocked best-effort dump must not race
            # another dumper over the same staging file
            tmp = f"{path}.{os.getpid()}.{threading.get_ident()}.tmp"
            with open(tmp, "w") as f:
                json.dump(payload, f)
            os.replace(tmp, path)     # crash dumps must never be half-files
            self.dump_count += 1
            self._last_path = path
            if once_key is not None:
                self._once[once_key] = path
            return path
        finally:
            if locked:
                self._lock.release()


# ---------------------------------------------------------------------------
# module surface
# ---------------------------------------------------------------------------

def record(kind: str, name: str, args=None):
    """Append one event if the recorder is on (cheap no-op otherwise).
    Subsystems on genuinely hot paths should guard with
    ``if flight._REC is not None:`` to skip even this call."""
    rec = _REC
    if rec is not None:
        rec.append(kind, name, args)


def flight_enabled() -> bool:
    return _REC is not None


def last_dump_path():
    rec = _REC
    return rec._last_path if rec is not None else None


def _crash_excepthook(tp, val, tb):
    try:
        crash_dump((tp, val, tb), reason=f"uncaught:{tp.__name__}")
    except Exception:
        pass                       # the crash path must never mask the crash
    prev = _prev_excepthook or sys.__excepthook__
    prev(tp, val, tb)


def _sigterm_handler(signum, frame):
    try:
        crash_dump(None, reason="SIGTERM", best_effort=True)
    except Exception:
        pass
    prev = _prev_sigterm
    if prev is signal.SIG_IGN:
        return                     # the process chose to survive SIGTERM;
                                   # dumping must not change that
    if callable(prev):
        prev(signum, frame)
    else:
        signal.signal(signum, signal.SIG_DFL)
        os.kill(os.getpid(), signum)


def enable_flight_recorder(capacity: int = 4096, dump_on_crash: bool = True,
                           dump_dir: str | None = None,
                           record_ops: bool = True) -> FlightRecorder:
    """Arm the recorder. Installs the ndarray dispatch hook (unless
    `record_ops=False`) and, with `dump_on_crash`, the excepthook +
    SIGTERM chain. Idempotent-ish: re-enabling replaces the ring."""
    global _REC, _prev_excepthook, _prev_sigterm
    rec = FlightRecorder(capacity=capacity, dump_dir=dump_dir)
    rec.config["dump_on_crash"] = bool(dump_on_crash)
    rec.config["record_ops"] = bool(record_ops)
    _REC = rec
    if record_ops:
        from .. import ndarray as _nd
        _nd._flight_hook = rec.op_event
    rec.append("lifecycle", "flight_recorder.enable")
    if dump_on_crash:
        if sys.excepthook is not _crash_excepthook:
            _prev_excepthook = sys.excepthook
            sys.excepthook = _crash_excepthook
        try:
            if threading.current_thread() is threading.main_thread():
                prev = signal.signal(signal.SIGTERM, _sigterm_handler)
                if prev is not _sigterm_handler:
                    _prev_sigterm = prev
        except (ValueError, OSError):
            pass                   # non-main thread / restricted env
    return rec


def disable_flight_recorder():
    """Stop recording and unhook (the excepthook chain stays installed but
    becomes a pass-through once `_REC` is None)."""
    global _REC
    _REC = None
    try:
        from .. import ndarray as _nd
        _nd._flight_hook = None
    except Exception:
        pass


def dump(reason: str = "manual", path: str | None = None) -> str | None:
    """Manually flush the ring to disk; returns the path (None if off)."""
    rec = _REC
    if rec is None:
        return None
    return rec.dump(reason=reason, path=path)


def crash_dump(exc=None, reason: str = "crash",
               best_effort: bool = False) -> str | None:
    """The crash-path dump: one per process, idempotent — repeated calls
    (excepthook then signal handler then atexit cascades) return the same
    already-written path."""
    rec = _REC
    if rec is None:
        return None
    return rec.dump(reason=reason, exc=exc, once_key="crash",
                    best_effort=best_effort)
