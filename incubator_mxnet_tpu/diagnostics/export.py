"""Metrics export: sampler thread + Prometheus / newline-JSON backends.

The always-live counters registry (profiler.counters) plus the memory
ledger are the framework's time-series surface; this module makes them
scrapeable:

* :func:`sample` — one consistent snapshot: wall timestamp, every
  registered counter/gauge (with its kind), and the memory ledger
  headline numbers.
* :func:`prometheus_text` — the snapshot in Prometheus text exposition
  format (`# TYPE` lines from counter kinds, `_bytes` gauges labeled by
  context/block), servable from a file (textfile collector) or the
  built-in HTTP endpoint.
* :class:`MetricsSampler` — a daemon thread that snapshots every
  `interval_ms`, appends newline-JSON to `jsonl_path` and atomically
  rewrites `prom_path`. Counters are monotonic across samples by the
  registry contract, which `tools/trace_check.py` validates.
* :func:`start_http` — stdlib HTTP server exposing `/metrics`
  (Prometheus), `/json` (latest sample), `/memory` (full
  memory_summary) and `/events?n=N` (the tail of this process's open
  ``mxtpu.events`` log plus which scopes are armed — the fleetscope
  collector's per-process pull surface), for pull-based scraping
  during live runs.

The reference stack's counterpart is MXBoard/monitoring riding on
mx.profiler counters; the pull/push split follows Prometheus practice.
"""
from __future__ import annotations

import atexit
import json
import os
import re
import threading
import time

from ..profiler.counters import registry_snapshot as _registry_snapshot
from . import memory as _memory

__all__ = ["sample", "prometheus_text", "MetricsSampler", "start_sampler",
           "stop_sampler", "sampler_running", "start_http", "stop_http"]

_SAMPLER = None
_HTTP = None

_NAME_RE = re.compile(r"[^a-zA-Z0-9_]")


def _prom_name(name: str) -> str:
    n = _NAME_RE.sub("_", name)
    if n and n[0].isdigit():
        n = "_" + n
    return n


def _prom_label(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def sample() -> dict:
    """One snapshot of everything scrapeable: counters (+kinds) and the
    memory ledger headline."""
    snap = _registry_snapshot()
    mem = _memory.memory_summary(include_reconcile=False) \
        if _memory.memory_enabled() else None
    out = {
        "ts": time.time(),
        # wall/monotonic pair, same discipline as mxtpu.events/2: a
        # puller estimating clock offset from "ts" can detect an NTP
        # step between two pulls by comparing the deltas
        "mono": time.monotonic(),
        "counters": {k: v for k, (v, _) in snap.items()},
        "kinds": {k: kind for k, (_, kind) in snap.items()},
    }
    if mem is not None:
        out["memory"] = {"current_bytes": mem["current_bytes"],
                         "peak_bytes": mem["peak_bytes"],
                         "live_arrays": mem["live_arrays"],
                         "by_context": mem["by_context"]}
    return out


def prometheus_text(snapshot: dict | None = None) -> str:
    """Render a snapshot (default: a fresh one) as Prometheus text
    exposition format."""
    s = snapshot or sample()
    lines = []
    for name in sorted(s["counters"]):
        v = s["counters"][name]
        pn = _prom_name(name)
        kind = s["kinds"].get(name, "gauge")
        if kind == "histogram" and isinstance(v, dict):
            # full exposition-format histogram family: cumulative
            # `_bucket{le=...}` series + `_sum` + `_count`
            lines.append(f"# TYPE {pn} histogram")
            for le, c in (v.get("buckets") or {}).items():
                lines.append(f'{pn}_bucket{{le="{_prom_label(le)}"}} '
                             f"{float(c)!r}")
            lines.append(f"{pn}_sum {float(v.get('sum', 0.0))!r}")
            lines.append(f"{pn}_count {float(v.get('count', 0))!r}")
            continue
        if not isinstance(v, (int, float)) or isinstance(v, bool):
            continue               # non-numeric gauges are not scrapeable
        lines.append(f"# TYPE {pn} "
                     f"{'counter' if kind == 'counter' else 'gauge'}")
        # shortest round-trip repr: %g's 6 significant digits would
        # flatten large byte counters into identical consecutive scrapes
        lines.append(f"{pn} {float(v)!r}")
    mem = s.get("memory")
    if mem:
        by_ctx = sorted(mem.get("by_context", {}).items())
        # one contiguous sample group per metric family (exposition-format
        # rule; strict parsers reject a reopened family)
        lines.append("# TYPE mxtpu_memory_current_bytes gauge")
        for ctx, e in by_ctx:
            lines.append(f'mxtpu_memory_current_bytes'
                         f'{{context="{_prom_label(ctx)}"}} '
                         f"{float(e['current_bytes'])!r}")
        lines.append("# TYPE mxtpu_memory_peak_bytes gauge")
        for ctx, e in by_ctx:
            lines.append(f'mxtpu_memory_peak_bytes'
                         f'{{context="{_prom_label(ctx)}"}} '
                         f"{float(e['peak_bytes'])!r}")
        lines.append("# TYPE mxtpu_memory_live_arrays gauge")
        lines.append(f"mxtpu_memory_live_arrays "
                     f"{float(mem['live_arrays'])!r}")
    return "\n".join(lines) + "\n"


class MetricsSampler(threading.Thread):
    """Daemon sampling loop. `samples` keeps the last `keep` snapshots in
    memory for tests/inspection; files are optional."""

    def __init__(self, interval_ms: int = 1000, jsonl_path: str | None = None,
                 prom_path: str | None = None, keep: int = 512,
                 truncate: bool = True):
        super().__init__(name="mxtpu-metrics-sampler", daemon=True)
        self.interval_s = max(0.001, interval_ms / 1000.0)
        self.jsonl_path = jsonl_path
        if truncate and jsonl_path and os.path.exists(jsonl_path):
            # a fresh sampler means a fresh series: counters restart at 0
            # in a new process, and appending across runs would make the
            # file fail the monotonic-counter validation it must satisfy
            os.remove(jsonl_path)
        self.prom_path = prom_path
        import collections
        self.samples = collections.deque(maxlen=keep)
        self._stop_ev = threading.Event()
        self.ticks = 0

    def tick(self):
        s = sample()
        self.samples.append(s)
        self.ticks += 1
        if self.jsonl_path:
            with open(self.jsonl_path, "a") as f:
                f.write(json.dumps(s) + "\n")
        if self.prom_path:
            tmp = self.prom_path + ".tmp"
            with open(tmp, "w") as f:
                f.write(prometheus_text(s))
            os.replace(tmp, self.prom_path)

    def run(self):
        while not self._stop_ev.is_set():
            try:
                self.tick()
            except Exception:
                pass               # sampling must never kill the host run
            self._stop_ev.wait(self.interval_s)

    def stop(self, final_tick: bool = True):
        self._stop_ev.set()
        self.join(timeout=10)
        if final_tick:
            try:
                self.tick()        # always leave a closing sample on disk
            except Exception:
                pass


def start_sampler(interval_ms: int = 1000, jsonl_path: str | None = None,
                  prom_path: str | None = None, keep: int = 512,
                  truncate: bool = True) -> MetricsSampler:
    """Start (or restart) the module-level sampler thread. `truncate`
    (default) starts a fresh jsonl series; pass False to append to an
    existing same-process series."""
    global _SAMPLER
    if _SAMPLER is not None:
        _SAMPLER.stop(final_tick=False)
    _SAMPLER = MetricsSampler(interval_ms, jsonl_path, prom_path, keep,
                              truncate)
    _SAMPLER.start()
    return _SAMPLER


def stop_sampler():
    global _SAMPLER
    if _SAMPLER is not None:
        _SAMPLER.stop()
        _SAMPLER = None


def sampler_running() -> bool:
    return _SAMPLER is not None and _SAMPLER.is_alive()


def _atexit_stop_sampler():
    """Join the sampler before interpreter teardown. Without this, a
    still-running tick can race module teardown (open() on a half-torn
    interpreter → noisy ignored exceptions on exit). No final tick: at
    atexit the priority is a clean join, not one more sample."""
    global _SAMPLER
    s = _SAMPLER
    if s is not None:
        try:
            s.stop(final_tick=False)
        except Exception:
            pass
        _SAMPLER = None


atexit.register(_atexit_stop_sampler)


# ---------------------------------------------------------------------------
# HTTP endpoint (pull-based scraping)
# ---------------------------------------------------------------------------

def _events_doc(query: str) -> dict:
    """The ``/events`` body: this process's open ``mxtpu.events`` log
    tail (bounded, ``?n=N`` capped at 256) plus which scopes are armed
    — everything the fleetscope collector needs from one pull."""
    n = 64
    for part in query.split("&"):
        if part.startswith("n="):
            try:
                n = max(1, min(256, int(part[2:])))
            except ValueError:
                pass
    from ..healthmon import events as _hm_events
    log = _hm_events.current_log()
    path = log.path if log is not None else None
    tail = []
    if path is not None:
        from ..fleetscope.collector import events_tail
        tail = events_tail(path, n=n)
    armed = {}
    try:
        import incubator_mxnet_tpu as _mx
        for scope in ("healthmon", "servescope", "fleetscope",
                      "devicescope", "memscope"):
            mod = getattr(_mx, scope, None)
            fn = getattr(mod, "enabled", None)
            if callable(fn):
                armed[scope] = bool(fn())
    except Exception:  # noqa: BLE001 — armed flags are context, not truth
        pass
    return {"ts": time.time(), "mono": time.monotonic(),
            "path": path, "tail": tail, "health": armed}


def start_http(port: int = 0, host: str = "127.0.0.1"):
    """Serve /metrics (Prometheus), /json (latest sample), /memory
    (memory_summary), /events (events tail + armed scopes). Returns
    (server, bound_port); port 0 picks a free one. The server runs in a
    daemon thread."""
    global _HTTP
    stop_http()        # a forgotten prior server must not leak its port
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    class _Handler(BaseHTTPRequestHandler):
        def do_GET(self):
            try:
                if self.path.startswith("/metrics"):
                    body = prometheus_text().encode()
                    ctype = "text/plain; version=0.0.4"
                elif self.path.startswith("/json"):
                    body = json.dumps(sample()).encode()
                    ctype = "application/json"
                elif self.path.startswith("/memory"):
                    body = json.dumps(_memory.memory_summary()).encode()
                    ctype = "application/json"
                elif self.path.startswith("/events"):
                    _, _, query = self.path.partition("?")
                    body = json.dumps(_events_doc(query)).encode()
                    ctype = "application/json"
                else:
                    self.send_response(404)
                    self.end_headers()
                    return
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
            except Exception:
                try:
                    self.send_response(500)
                    self.end_headers()
                except Exception:
                    pass

        def log_message(self, *a):   # stay quiet on stderr
            pass

    server = ThreadingHTTPServer((host, int(port)), _Handler)
    t = threading.Thread(target=server.serve_forever,
                         name="mxtpu-metrics-http", daemon=True)
    t.start()
    _HTTP = server
    return server, server.server_address[1]


def stop_http():
    global _HTTP
    if _HTTP is not None:
        _HTTP.shutdown()
        _HTTP.server_close()
        _HTTP = None
