"""mxtpu.diagnostics — always-on observability for production runs.

The monitoring counterpart to :mod:`incubator_mxnet_tpu.profiler` (which
is on-demand tracing): cheap always-live telemetry in the
Dapper/Prometheus mold, three pillars —

* **device-memory accounting** (:mod:`.memory`) — a per-Context
  allocation ledger hooked into NDArray creation/free and the bulk
  deferred paths, with per-layer attribution via Gluon Block scopes and
  reconciliation against the XLA allocator:
  ``diagnostics.memory_summary()``;
* **metrics export** (:mod:`.export`) — a sampler thread snapshotting
  the counters/gauges registry + memory stats at a configurable
  interval, exported as Prometheus text (HTTP endpoint or textfile) and
  newline-JSON, so ``trainer.dispatches_per_step``, ``bulk.*``, jit
  cache hit-rates and KVStore bytes become scrapeable time series;
* **flight recorder** (:mod:`.flight`) — a bounded ring of recent
  events (op dispatches, bulk flushes, collective launches, compile
  spans, env/config snapshot) flushed to disk by an excepthook/SIGTERM
  handler on crash; pretty-print dumps with ``tools/mxdiag.py``.

Quick start::

    from incubator_mxnet_tpu import diagnostics as diag
    diag.enable()                      # ledger + flight recorder
    diag.start_sampler(interval_ms=100, jsonl_path="metrics.jsonl",
                       prom_path="metrics.prom")
    ...train...
    print(diag.format_memory_summary())
    diag.dump_flight("end_of_run.json")

Env knobs (see docs/diagnostics.md): ``MXTPU_DIAG=1`` auto-enables at
import; ``MXTPU_DIAG_DIR`` (dump/export directory), ``MXTPU_DIAG_SAMPLE_MS``
(sampler interval; 0 = no sampler), ``MXTPU_FLIGHT_CAPACITY`` (ring size).
"""
from __future__ import annotations

import os

from .memory import (enable_memory, disable_memory, memory_enabled,
                     reset_memory, memory_summary, format_memory_summary,
                     reconcile)
from .flight import (FlightRecorder, enable_flight_recorder,
                     disable_flight_recorder, flight_enabled, record,
                     crash_dump, last_dump_path)
from .flight import dump as dump_flight
from .export import (sample, prometheus_text, MetricsSampler, start_sampler,
                     stop_sampler, sampler_running, start_http, stop_http)

__all__ = [
    "enable", "disable", "enabled", "enable_from_env",
    # memory
    "enable_memory", "disable_memory", "memory_enabled", "reset_memory",
    "memory_summary", "format_memory_summary", "reconcile",
    # flight
    "FlightRecorder", "enable_flight_recorder", "disable_flight_recorder",
    "flight_enabled", "record", "dump_flight", "crash_dump",
    "last_dump_path",
    # export
    "sample", "prometheus_text", "MetricsSampler", "start_sampler",
    "stop_sampler", "sampler_running", "start_http", "stop_http",
]


def enable(memory: bool = True, flight: bool = True,
           dump_on_crash: bool = True, flight_capacity: int = 4096,
           sampler_interval_ms: int = 0, diag_dir: str | None = None):
    """One-call arming of the always-on layer: the memory ledger, the
    flight recorder (with crash dumps), and — when
    ``sampler_interval_ms > 0`` — the metrics sampler writing
    ``metrics.jsonl`` / ``metrics.prom`` under ``diag_dir``."""
    from ..autotune.knobs import env_str
    diag_dir = diag_dir or env_str("MXTPU_DIAG_DIR", "/tmp")
    if memory:
        enable_memory()
    if flight:
        enable_flight_recorder(capacity=flight_capacity,
                               dump_on_crash=dump_on_crash,
                               dump_dir=diag_dir)
    if sampler_interval_ms > 0:
        os.makedirs(diag_dir, exist_ok=True)
        start_sampler(
            interval_ms=sampler_interval_ms,
            jsonl_path=os.path.join(diag_dir, "metrics.jsonl"),
            prom_path=os.path.join(diag_dir, "metrics.prom"))


def disable():
    """Tear down everything this module turned on."""
    stop_sampler()
    stop_http()
    disable_flight_recorder()
    disable_memory()


def enabled() -> bool:
    return memory_enabled() or flight_enabled() or sampler_running()


def enable_from_env():
    """Honor MXTPU_DIAG=1 (called from package import)."""
    if os.environ.get("MXTPU_DIAG", "0") in ("1", "true", "on"):
        from ..autotune.knobs import env_int
        enable(
            flight_capacity=env_int("MXTPU_FLIGHT_CAPACITY", 4096),
            sampler_interval_ms=env_int("MXTPU_DIAG_SAMPLE_MS", 0))
