"""Device-memory accounting: a per-Context allocation ledger.

The reference MXNet's GPU memory profiler attributes allocations to the
operator/layer that requested them (src/storage/ + the gpu_memory_profiler
env knobs). Rebuilt TPU-native: NDArray creation funnels through one hook
(`ndarray._mem_hook`, installed only while the ledger is enabled) that
registers every wrapper with this ledger; a `weakref.finalize` on the
wrapper retires the same bytes when it dies, so the ledger is balanced by
construction — whatever enters must leave, and `current_bytes` returning
to baseline after `del model` is the no-leak invariant the tests assert.

Accounting semantics (documented contract, see docs/diagnostics.md):

* **unit** — logical NDArray storage: shape x itemsize at registration.
  Buffers shared by several wrappers (detach/copyto aliases) are deduped
  by buffer identity with a refcount, so an alias costs nothing until the
  last wrapper dies.
* **attribution** — three axes, all at creation time: the owning Context
  (`cpu(0)` / `tpu(0)`), the dtype, and the innermost live Gluon Block
  scope (`Block.__call__` pushes its name while the ledger is active), so
  `memory_summary()` can answer "which layer holds the bytes".
* **approximation** — in-place mutation (`x[...] = v`) swaps the backing
  buffer but keeps the wrapper's registered size (shapes are preserved by
  the mutation ops, so the byte count stays truthful); deferred bulk
  outputs are attributed to the current default Context at defer time.
  Physical truth lives in the XLA allocator — `memory_summary()` carries
  a `reconcile` section from `jax.Device.memory_stats()` and
  `jax.live_arrays()` where the backend exposes them.

Off-path cost: one module-global check in `NDArray.__init__` and one in
`Block.__call__` (`_ACTIVE`), same discipline as the profiler hooks.

This module must not import `ndarray` at module scope (it is imported
from gluon/bulk layers during package init); the hook is installed
lazily in :func:`enable_memory`.
"""
from __future__ import annotations

import threading
import weakref

from ..context import current_context
from ..profiler.counters import set_gauge as _set_gauge

__all__ = ["enable_memory", "disable_memory", "memory_enabled",
           "reset_memory", "memory_summary", "format_memory_summary",
           "push_block", "pop_block", "reconcile", "logical_nbytes",
           "shard_bytes_by_device"]


def logical_nbytes(raw) -> int:
    """Logical storage bytes of an array-like (shape x itemsize) — THE
    byte formula for every accounting surface (ledger, kvstore payload
    counters), so dtype/packing changes have one place to land."""
    n = getattr(raw.dtype, "itemsize", 4)
    for s in raw.shape:
        n *= int(s)
    return n


def shard_bytes_by_device(arrays) -> dict:
    """{device: bytes} each device PHYSICALLY holds for these arrays —
    a replicated array costs its full size on every device, a dp/mp
    shard only its slice. THE shard-walking formula for both the
    reconcile census and the sharding.*_bytes_per_device gauges
    (parallel/sharding.py), so the FSDP memory evidence can't diverge
    between the two surfaces. Arrays without addressable shards (plain
    host/numpy buffers) are accounted under the key None."""
    out = {}
    for a in arrays:
        shards = getattr(a, "addressable_shards", None)
        if shards is None:
            out[None] = out.get(None, 0) + int(getattr(a, "nbytes", 0) or 0)
            continue
        try:
            for s in shards:
                out[s.device] = out.get(s.device, 0) + int(s.data.nbytes)
        except Exception:
            continue
    return out

# fast-path predicate: read by Block.__call__ on every forward
_ACTIVE = False

_lock = threading.Lock()
_tls = threading.local()


def _block_stack():
    st = getattr(_tls, "blocks", None)
    if st is None:
        st = _tls.blocks = []
    return st


def push_block(name: str):
    """Enter a Block attribution scope (called by Block.__call__ while
    the ledger is active)."""
    _block_stack().append(name)


def pop_block():
    st = _block_stack()
    if st:
        st.pop()


class _Ledger:
    """The accounting state. All mutation under the module lock."""

    def __init__(self):
        self.reset()

    def reset(self):
        self.current = {}        # ctx -> live bytes
        self.peak = {}           # ctx -> high-water bytes
        self.by_dtype = {}       # (ctx, dtype) -> live bytes
        self.by_block = {}       # block name -> live bytes
        self.total_bytes = 0     # live bytes across contexts
        self.peak_total = 0      # high-water of total_bytes
        self.live_arrays = 0
        self.total_registered = 0
        # buffer dedup: entries are keyed by an opaque token (finalizers
        # hold the token), with a secondary id(raw) -> token map for alias
        # lookup. The entry carries a weakref to the raw buffer so a
        # RECYCLED id (CPython reuses addresses the moment a buffer is
        # freed, e.g. after an in-place __setitem__ swaps NDArray._data)
        # is detected as "not the same buffer" instead of silently
        # swallowing the new allocation as an alias of a dead one.
        self._entries = {}       # token -> [count, nbytes, ctx, dt, blk, wref]
        self._by_id = {}         # id(raw) -> token
        self._next_tok = 0

    # -- registration ------------------------------------------------------
    def register(self, nd):
        """Account one NDArray wrapper; pairs with a weakref finalizer."""
        raw = nd._data
        tname = type(raw).__name__
        if tname == "DeferredArray":
            ctx = str(current_context())
        else:
            import jax
            if isinstance(raw, jax.core.Tracer):
                return                       # inside a jit trace: no storage
            try:
                dev = next(iter(raw.devices()))
            except Exception:
                return                       # exotic backing, don't account
            from ..context import ctx_from_device
            ctx = str(ctx_from_device(dev))
        nbytes = logical_nbytes(raw)
        dt_s = str(raw.dtype)
        st = getattr(_tls, "blocks", None)
        blk = st[-1] if st else "<unscoped>"
        key = id(raw)
        with _lock:
            self.total_registered += 1
            self.live_arrays += 1
            tok = self._by_id.get(key)
            ent = self._entries.get(tok) if tok is not None else None
            same = ent is not None and \
                (ent[5]() is raw if ent[5] is not None else True)
            if same:
                ent[0] += 1                  # aliased buffer: refcount only
            else:
                try:
                    wref = weakref.ref(raw)
                except TypeError:
                    wref = None
                self._next_tok += 1
                tok = self._next_tok
                self._entries[tok] = [1, nbytes, ctx, dt_s, blk, wref]
                self._by_id[key] = tok       # dead entry keeps its token
                self._add(ctx, dt_s, blk, nbytes)
        weakref.finalize(nd, self._unregister, tok, key)

    def _add(self, ctx, dt_s, blk, nbytes):
        self.current[ctx] = self.current.get(ctx, 0) + nbytes
        if self.current[ctx] > self.peak.get(ctx, 0):
            self.peak[ctx] = self.current[ctx]
        self.total_bytes += nbytes
        if self.total_bytes > self.peak_total:
            self.peak_total = self.total_bytes
        k = (ctx, dt_s)
        self.by_dtype[k] = self.by_dtype.get(k, 0) + nbytes
        self.by_block[blk] = self.by_block.get(blk, 0) + nbytes

    def _unregister(self, tok, key):
        with _lock:
            ent = self._entries.get(tok)
            if ent is None:
                return                       # ledger reset since register
            self.live_arrays -= 1
            ent[0] -= 1
            if ent[0] > 0:
                return
            del self._entries[tok]
            if self._by_id.get(key) == tok:
                del self._by_id[key]
            _, nbytes, ctx, dt_s, blk, _ = ent
            self.current[ctx] = self.current.get(ctx, 0) - nbytes
            self.total_bytes -= nbytes
            k = (ctx, dt_s)
            self.by_dtype[k] = self.by_dtype.get(k, 0) - nbytes
            self.by_block[blk] = self.by_block.get(blk, 0) - nbytes

    # -- reporting ---------------------------------------------------------
    def summary(self) -> dict:
        with _lock:
            by_dtype = {}
            for (ctx, dt_s), b in self.by_dtype.items():
                by_dtype.setdefault(ctx, {})[dt_s] = b
            return {
                "current_bytes": self.total_bytes,
                "peak_bytes": self.peak_total,
                "live_arrays": self.live_arrays,
                "total_registered": self.total_registered,
                "by_context": {c: {"current_bytes": b,
                                   "peak_bytes": self.peak.get(c, 0)}
                               for c, b in self.current.items()},
                "by_dtype": by_dtype,
                "by_block": {b: n for b, n in self.by_block.items()
                             if n != 0},
            }


_ledger = _Ledger()


def enable_memory(reset: bool = False) -> None:
    """Turn the allocation ledger on: installs the NDArray creation hook
    and arms Block-scope attribution. Idempotent."""
    global _ACTIVE
    if reset:
        _ledger.reset()
    from .. import ndarray as _nd
    _nd._mem_hook = _ledger.register
    _ACTIVE = True
    _publish_gauges()


def disable_memory() -> None:
    """Stop accounting new arrays (already-registered finalizers keep
    retiring their bytes so the ledger stays balanced)."""
    global _ACTIVE
    _ACTIVE = False
    try:
        from .. import ndarray as _nd
        _nd._mem_hook = None
    except Exception:
        pass


def memory_enabled() -> bool:
    return _ACTIVE


def reset_memory() -> None:
    _ledger.reset()


def reconcile() -> dict:
    """Ground truth from the runtime: per-device XLA allocator stats and
    the jax live-array census, for checking the ledger against physical
    reality. Empty dict entries where the backend exposes nothing (CPU)."""
    out = {"devices": {}, "jax_live_arrays": None,
           "jax_live_bytes": None}
    try:
        import jax
        for d in jax.local_devices():
            try:
                stats = d.memory_stats()
            except Exception:
                stats = None
            if stats:
                out["devices"][str(d)] = {
                    k: stats[k] for k in ("bytes_in_use",
                                          "peak_bytes_in_use",
                                          "bytes_limit")
                    if k in stats}
        try:
            live = jax.live_arrays()
            out["jax_live_arrays"] = len(live)
            out["jax_live_bytes"] = int(sum(
                getattr(a, "nbytes", 0) or 0 for a in live))
            # sharding-aware census: what each device PHYSICALLY holds.
            # This is the ledger evidence that an FSDP layout actually
            # reduced per-device bytes — `nbytes` above is logical/
            # global and cannot show it.
            out["per_device_live_bytes"] = {
                str(d): v
                for d, v in shard_bytes_by_device(live).items()
                if d is not None}
        except Exception:
            pass
    except Exception:
        pass
    return out


def _publish_gauges(s: dict | None = None):
    """Mirror the headline numbers into the always-live counters registry
    so the sampler/Prometheus exporter picks them up with everything else."""
    s = s or _ledger.summary()
    _set_gauge("current_bytes", s["current_bytes"], "memory")
    _set_gauge("peak_bytes", s["peak_bytes"], "memory")
    _set_gauge("live_arrays", s["live_arrays"], "memory")


def memory_summary(include_reconcile: bool = True) -> dict:
    """The memory report: current/peak bytes overall, per Context, per
    dtype, per Gluon Block, plus the XLA-side reconciliation."""
    s = _ledger.summary()
    _publish_gauges(s)
    if include_reconcile:
        s["reconcile"] = reconcile()
    return s


def _fmt_bytes(n: int) -> str:
    for unit in ("B", "KiB", "MiB", "GiB"):
        if abs(n) < 1024 or unit == "GiB":
            return f"{n:.1f} {unit}" if unit != "B" else f"{n} B"
        n /= 1024.0
    return f"{n} B"


def format_memory_summary(s: dict | None = None) -> str:
    """Human-readable rendering of :func:`memory_summary`."""
    s = s or memory_summary()
    lines = [f"current {_fmt_bytes(s['current_bytes'])}   "
             f"peak {_fmt_bytes(s['peak_bytes'])}   "
             f"live arrays {s['live_arrays']}"]
    for ctx, e in sorted(s["by_context"].items()):
        lines.append(f"  {ctx:<12} current {_fmt_bytes(e['current_bytes']):>12}"
                     f"  peak {_fmt_bytes(e['peak_bytes']):>12}")
        for dt, b in sorted(s["by_dtype"].get(ctx, {}).items()):
            if b:
                lines.append(f"    {dt:<12} {_fmt_bytes(b):>12}")
    blocks = sorted(s["by_block"].items(), key=lambda kv: -kv[1])
    if blocks:
        lines.append("  by block:")
        for b, n in blocks[:20]:
            lines.append(f"    {b:<28} {_fmt_bytes(n):>12}")
    rec = s.get("reconcile") or {}
    for dev, st in (rec.get("devices") or {}).items():
        lines.append(f"  xla {dev}: in_use "
                     f"{_fmt_bytes(st.get('bytes_in_use', 0))} peak "
                     f"{_fmt_bytes(st.get('peak_bytes_in_use', 0))}")
    if rec.get("jax_live_arrays") is not None:
        lines.append(f"  jax.live_arrays: {rec['jax_live_arrays']} "
                     f"({_fmt_bytes(rec.get('jax_live_bytes') or 0)})")
    return "\n".join(lines)
