"""mxtpu.resilience — elastic, self-healing training.

The subsystem that ACTS on healthmon's verdicts (docs/resilience.md;
the "who acts on which verdict" column in docs/observability.md).
healthmon (PR 5) + devicescope (PR 10) made every distributed failure
mode visible — named straggler, NaN within one step, stall post-mortem
with the measured device timeline attached — and then the job died
anyway, making every verdict an obituary. Four pieces close the loop,
MegaScale-style (recovery as an ops-cost multiplier):

* **periodic async sharded checkpoints**
  (:class:`~.checkpoint.CheckpointManager`) — params + optimizer state
  + lr/step counter + RNG key + data cursor every N steps; the training
  thread pays one device→host copy at a step boundary, a worker thread
  does sha256 manifests + orbax serialization + ATOMIC rename (a torn
  write is never a valid checkpoint), bounded last-K rotation;
* **restart-from-last-good** (:class:`~.policy.Supervisor`) —
  in-process rollback on NaN (restore last-good, skip/re-read the
  poison batch, bounded retries with backoff, then escalate), process-
  level resume from the manifest (data cursor included — consumed
  batches are not replayed), stall → supervised restart via
  :data:`~.policy.RESTART_EXIT_CODE`;
* **elastic rank leave/join** (:class:`~.elastic.ElasticGroup`) — a
  membership layer over the existing rank-0 TCP wire + coordination
  KV: a preempted rank is evicted at the round deadline and the
  survivors re-form at the smaller world size and roll back to
  last-good instead of dying; re-join is admitted at the next
  checkpoint boundary;
* **a chaos harness that proves it** (tools/chaos_cluster.py,
  tools/resilience_smoke.sh) — NaN injection, mid-step rank kill,
  torn checkpoint, frozen rank: training must converge THROUGH each
  fault with the recovery visible on all three surfaces (counters,
  flight breadcrumbs, ``mxtpu.events/1`` records — rendered by
  ``tools/mxdiag.py recover``).

Cost contract: with resilience disarmed nothing here runs — the only
hot-path residue is one ``is None`` predicate in healthmon's alert
fan-out and the optional ``resilience=`` argument on
``TrainLoop.fit``; zero ``resilience.*`` counters exist. Armed, the
steady-state cost is one loss fetch per chunk (fault detection) and
one device→host copy per checkpoint cadence.

Env knobs: ``MXTPU_RESILIENCE_EVERY`` (checkpoint cadence in steps,
default 50), ``MXTPU_RESILIENCE_KEEP`` (rotation, default 3),
``MXTPU_RESILIENCE_ON_STALL`` (``none`` | ``exit``),
``MXTPU_ELASTIC_SYNC_TIMEOUT`` (round deadline s, default 10),
``MXTPU_ELASTIC_ADDR`` (member rendezvous, ``host:port``).
"""
from __future__ import annotations

from ..profiler.counters import (counter as _counter,
                                 counters as _counters_snap)
from .checkpoint import CheckpointManager, _breadcrumb, _emit
from .elastic import ElasticGroup, GroupClosed
from .policy import RESTART_EXIT_CODE, RecoveryEscalated, Supervisor

__all__ = ["CheckpointManager", "Supervisor", "ElasticGroup",
           "GroupClosed", "RecoveryEscalated", "RESTART_EXIT_CODE",
           "supervised", "current", "status", "bench_extra",
           "record_recovery", "on_health_alert"]

# module global: None = no supervisor armed (THE fast-path predicate —
# healthmon's alert fan-out guards its one call here with it)
_RS = None


def _register(sup):
    global _RS
    _RS = sup


def _unregister(sup):
    global _RS
    if _RS is sup:
        _RS = None


def supervised() -> bool:
    return _RS is not None


def current():
    return _RS


def on_health_alert(name, args, step=None):
    """healthmon's verdict → recovery-policy routing (called from
    HealthMonitor._alert when a supervisor is registered)."""
    sup = _RS
    if sup is not None:
        sup.on_health_alert(name, args, step=step)


def record_recovery(action, args=None, step=None):
    """Three-surface recovery record for policies outside
    :class:`Supervisor` (the elastic chaos worker's departure rollback,
    a custom loop's resume): ``resilience.recoveries_total`` counter +
    flight breadcrumb + ``resilience.<action>`` event."""
    _counter("resilience.recoveries_total",
                      "resilience").increment()
    args = dict(args or {})
    _breadcrumb(action, args)
    _emit("resilience", "resilience." + action, step=step, args=args)


def _snap(prefix="resilience/"):
    return {k[len(prefix):]: v for k, v in _counters_snap().items()
            if k.startswith(prefix)}


def status():
    """Operator-facing summary for deep ``/healthz`` and healthmon's
    status block: checkpoint freshness, recovery totals, and whether a
    rollback is mid-flight. Cheap (one counters snapshot)."""
    c = _snap()
    return {
        "supervised": _RS is not None,
        "last_checkpoint_step": c.get("resilience.last_checkpoint_step"),
        "recoveries_total": c.get("resilience.recoveries_total", 0),
        "rollback_in_progress":
            bool(c.get("resilience.rollback_in_progress", 0)),
        "rollbacks": c.get("resilience.rollbacks", 0),
        "resumes": c.get("resilience.resumes", 0),
        "corrupt_checkpoints": c.get("resilience.corrupt_checkpoints", 0),
        "rank_departures": c.get("resilience.rank_departures", 0),
        "steps_lost_last": c.get("resilience.steps_lost_last", 0),
    }


def bench_extra(manager=None):
    """The ``extra.resilience`` block for training BENCH json
    (validated by tools/trace_check.py check_resilience_extra):
    checkpoint cadence + save cost percentiles + recovery accounting."""
    c = _snap()
    if not c and manager is None:
        return None

    def _hist(name):
        h = c.get(name)
        if not isinstance(h, dict):
            return None
        return {"count": h.get("count", 0),
                "p50_ms": h.get("p50"), "p95_ms": h.get("p95")}

    out = {
        "enabled": True,
        "checkpoints_saved": c.get("resilience.checkpoints_saved", 0),
        "last_checkpoint_step": c.get("resilience.last_checkpoint_step"),
        "recoveries_total": c.get("resilience.recoveries_total", 0),
        "rollbacks": c.get("resilience.rollbacks", 0),
        "resumes": c.get("resilience.resumes", 0),
        "rank_departures": c.get("resilience.rank_departures", 0),
        "steps_lost_last": c.get("resilience.steps_lost_last", 0),
        "steps_lost_total": c.get("resilience.steps_lost_total", 0),
        "save": _hist("resilience.save_ms"),
        "copy": _hist("resilience.copy_ms"),
    }
    if manager is not None:
        out["every"] = manager.every
        out["keep"] = manager.keep
        out["dir"] = manager.directory
    return out
