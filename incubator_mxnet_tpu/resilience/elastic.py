"""Elastic rank membership over the rank-0 TCP wire — the
leave/join half of mxtpu.resilience (docs/resilience.md).

A fixed-world collective stack dies with its first preempted host: the
allgather blocks forever, the stall watchdog writes the obituary. This
module gives the run a MEMBERSHIP layer in front of its state exchange,
riding the same transport discipline as the dist_async parameter server
(kvstore/async_ps.py): rank 0 hosts a tiny TCP coordinator
(length-prefixed pickled frames — the existing wire's framing helpers
are imported, not reimplemented), the jax coordination KV (when a
cluster is formed) or an explicit address is used ONLY for rendezvous,
and every data-plane message is one request/response round trip.

The contract:

* **sync is the heartbeat** — members call :meth:`ElasticGroup.sync`
  once per step with their flat state/gradient vector; the coordinator
  holds each round open until every CURRENT member contributes or the
  round deadline passes.
* **leave = eviction at the deadline** — a member that missed the
  deadline (SIGKILLed, preempted, wedged) is evicted: the generation
  bumps, the round completes over the SURVIVORS, and every survivor
  sees ``membership_changed`` in its sync response — its cue to roll
  back to the last good checkpoint (so the survivors restart the step
  from identical state) and keep training at the smaller world size
  instead of dying.
* **join = admission at the checkpoint boundary** — a (re)joining rank
  polls :meth:`join`; it stays ``pending`` until the group reports its
  next completed checkpoint (:meth:`report_checkpoint`), then is
  admitted with the generation, the checkpoint path to restore from,
  and the step at which to start contributing. Mid-step admission is
  impossible by construction — a joiner can only enter with last-good
  state, which only exists at a checkpoint boundary.

The coordinator (rank 0) is the membership authority, exactly as the
ps-lite scheduler was; rank 0's own calls short-circuit in-process.
Telemetry: ``resilience.rank_departures`` / ``resilience.rank_joins``
counters on every member that observes the change, plus
``resilience.rank_departed`` / ``resilience.rank_joined`` events.

Fleetscope rides this wire for TRAINING runs (serving uses the
collector's HTTP pull instead): members push bounded telemetry
snapshots with :meth:`ElasticGroup.report_telemetry` — the coordinator
cannot initiate a connection to a member on this wire, so collection is
member-push — and each reply carries the coordinator's wall clock, from
which the member estimates its clock offset (NTP midpoint, ± rtt/2)
and includes it in its NEXT report. Rank 0 keeps per-rank bounded
rings; :meth:`pod_telemetry` returns the merged view.
"""
from __future__ import annotations

import pickle
import socket
import threading
import time

import numpy as np

from ..kvstore.async_ps import _recv_frame, _send_frame
from ..profiler.counters import counter as _counter
from .checkpoint import _breadcrumb, _emit

__all__ = ["ElasticGroup", "GroupClosed"]

_KV_KEY = "mxtpu_elastic/addr"


class GroupClosed(RuntimeError):
    """The coordinator is gone (rank 0 died or left) — process-level
    restart territory, not membership-level recovery."""


class ElasticGroup:
    """One rank's handle on the elastic membership group.

        g = ElasticGroup(rank=r, addr=addr)       # rank 0 hosts
        info = g.join()                           # admit (or wait)
        ...
        mean, info = g.sync(step, flat_vec)
        if info["membership_changed"]:
            ...roll back to last good, continue at new world size...
        g.report_checkpoint(step, path)           # admits pending joiners
        g.leave()

    addr: ``(host, port)`` of the coordinator. Rank 0 passes the port it
    wants (or 0 for ephemeral) via ``port=``; non-zero ranks pass
    ``addr=`` explicitly, or leave it None to read the coordination KV
    (a formed jax cluster) or ``MXTPU_ELASTIC_ADDR`` (``host:port``).
    sync_timeout_s: round deadline after which missing members are
    evicted (``MXTPU_ELASTIC_SYNC_TIMEOUT``, default 10).
    startup_grace_s: a member that has NEVER contributed (still
    compiling/restoring after join) cannot be evicted until this much
    time passed since its join (``MXTPU_ELASTIC_STARTUP_GRACE``,
    default 60) — first-round compile skew must not read as death."""

    def __init__(self, rank, addr=None, port=0, sync_timeout_s=None,
                 host="127.0.0.1", startup_grace_s=None):
        self.rank = int(rank)
        from ..autotune.knobs import env_float
        self.sync_timeout_s = float(env_float(
            "MXTPU_ELASTIC_SYNC_TIMEOUT", 10.0,
            call_site=sync_timeout_s))
        self.startup_grace_s = float(env_float(
            "MXTPU_ELASTIC_STARTUP_GRACE", 60.0,
            call_site=startup_grace_s))
        self._gen_seen = 0
        self._c_departures = _counter("resilience.rank_departures",
                                      "resilience")
        self._c_joins = _counter("resilience.rank_joins", "resilience")
        self._closed = False
        # fleetscope clock alignment: offset of the COORDINATOR's wall
        # clock relative to ours, refreshed by every telemetry report
        self._telem_offset = None
        self._telem_bound = None
        if self.rank == 0:
            self._listener = socket.socket(socket.AF_INET,
                                           socket.SOCK_STREAM)
            self._listener.setsockopt(socket.SOL_SOCKET,
                                      socket.SO_REUSEADDR, 1)
            self._listener.bind((host, int(port)))
            self._listener.listen(64)
            self._listener.settimeout(0.2)
            self.addr = self._listener.getsockname()
            self._co = _Coordinator(self.sync_timeout_s,
                                    self.startup_grace_s)
            self._stop = threading.Event()
            self._thread = threading.Thread(
                target=self._serve, daemon=True,
                name="mxtpu-elastic-coordinator")
            self._thread.start()
            self._publish_addr()
        else:
            self.addr = self._resolve_addr(addr)
            self._co = None

    # -- rendezvous -------------------------------------------------------
    def _publish_addr(self):
        try:
            from jax._src import distributed as _jd
            c = _jd.global_state.client
            if c is not None:
                c.key_value_set_bytes(_KV_KEY, pickle.dumps(self.addr),
                                      allow_overwrite=True)
        except Exception:   # noqa: BLE001 — KV rendezvous is optional
            pass

    @staticmethod
    def _resolve_addr(addr):
        if addr is not None:
            return tuple(addr) if not isinstance(addr, str) else \
                (addr.rsplit(":", 1)[0], int(addr.rsplit(":", 1)[1]))
        from ..autotune.knobs import env_str
        env = env_str("MXTPU_ELASTIC_ADDR")
        if env:
            host, port = env.rsplit(":", 1)
            return (host, int(port))
        try:
            from jax._src import distributed as _jd
            c = _jd.global_state.client
            if c is not None:
                return tuple(pickle.loads(
                    c.blocking_key_value_get_bytes(_KV_KEY, 60_000)))
        except Exception:   # noqa: BLE001
            pass
        raise ValueError("ElasticGroup needs addr= (or MXTPU_ELASTIC_ADDR,"
                         " or a formed jax cluster's coordination KV)")

    # -- member surface ---------------------------------------------------
    def join(self, poll_s=0.2, timeout_s=120.0):
        """Register with the group. Admission is immediate while the
        group has not started stepping; afterwards it waits for the next
        checkpoint boundary. Returns {generation, members, next_step,
        last_good} and records the join."""
        deadline = time.monotonic() + timeout_s
        while True:
            resp = self._call("join", self.rank)
            if resp["admitted"]:
                self._gen_seen = resp["generation"]
                info = {"rank": self.rank,
                        "generation": resp["generation"],
                        "members": resp["members"],
                        "next_step": resp["next_step"]}
                self._c_joins.increment()
                _breadcrumb("rank_joined", info)
                _emit("resilience", "resilience.rank_joined",
                      step=resp.get("next_step"), args=info)
                return resp
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"rank {self.rank}: join not admitted within "
                    f"{timeout_s}s (no checkpoint boundary reached?)")
            time.sleep(poll_s)

    def sync(self, step, vec):
        """Contribute this rank's flat float32 vector for `step` and
        block for the round mean over the CURRENT members. Returns
        ``(mean, info)``; ``info["membership_changed"]`` is True when the
        generation moved since this rank last looked — departures are in
        ``info["departed"]`` (roll back to ``info["last_good"]``),
        joiners in ``info["joined"]``."""
        vec = np.asarray(vec, np.float32)
        # the coordinator may legitimately hold a round open past the
        # eviction deadline while a just-admitted joiner is still inside
        # its startup grace (compiling/restoring) — the socket timeout
        # must outlast the longest such hold, or every healthy survivor
        # would misread the wait as a dead coordinator
        resp = self._call("sync", self.rank, self._gen_seen, int(step),
                          vec, timeout=(self.sync_timeout_s
                                        + self.startup_grace_s + 30.0))
        changed = resp["generation"] != self._gen_seen
        self._gen_seen = resp["generation"]
        info = {"generation": resp["generation"],
                "members": resp["members"],
                "membership_changed": changed,
                "departed": resp.get("departed", []),
                "left": resp.get("left", []),
                "joined": resp.get("joined", []),
                "last_good": resp.get("last_good")}
        if changed:
            if info["left"]:
                args = {"rank": self.rank, "left": info["left"],
                        "generation": info["generation"],
                        "members": info["members"]}
                _breadcrumb("rank_left", args)
                _emit("resilience", "resilience.rank_left",
                      step=int(step), args=args)
            if info["departed"]:
                self._c_departures.increment(len(info["departed"]))
                args = {"rank": self.rank, "departed": info["departed"],
                        "generation": info["generation"],
                        "members": info["members"]}
                _breadcrumb("rank_departed", args)
                _emit("resilience", "resilience.rank_departed",
                      step=int(step), args=args)
            if info["joined"]:
                args = {"rank": self.rank, "joined": info["joined"],
                        "generation": info["generation"],
                        "members": info["members"]}
                _breadcrumb("rank_joined", args)
                _emit("resilience", "resilience.rank_joined",
                      step=int(step), args=args)
        return resp["mean"], info

    def report_checkpoint(self, step, path):
        """Tell the coordinator a good checkpoint exists at `path` for
        `step` — the admission boundary for pending joiners."""
        return self._call("ckpt", self.rank, int(step), str(path))

    def members(self):
        return self._call("info")["members"]

    # -- fleetscope telemetry (member-push over the membership wire) ------
    def report_telemetry(self, counters=None, events_tail=None,
                         health=None):
        """Push one bounded telemetry snapshot to the coordinator and
        refresh this rank's clock-offset estimate from the reply's
        coordinator wall clock (NTP midpoint, error ≤ rtt/2). The
        offset rides along on the NEXT report so rank 0's merged view
        is clock-aligned without a second protocol. Never raises: a
        failed push is a counted ``fleetscope.telem_errors`` datum.
        Returns ``{"offset_s", "offset_bound_s"}`` or None."""
        from ..fleetscope.collector import estimate_offset
        payload = {"ts": time.time(), "mono": time.monotonic(),
                   "counters": counters, "events_tail": events_tail,
                   "health": health,
                   "offset_s": self._telem_offset,
                   "offset_bound_s": self._telem_bound}
        t_send = time.time()
        try:
            resp = self._call("telem", self.rank, payload)
        except Exception:   # noqa: BLE001 — telemetry never breaks a run
            _counter("fleetscope.telem_errors", "fleetscope").increment()
            return None
        t_recv = time.time()
        co_ts = resp.get("coordinator_ts")
        if isinstance(co_ts, (int, float)):
            self._telem_offset, self._telem_bound = estimate_offset(
                t_send, t_recv, float(co_ts))
        _counter("fleetscope.telem_reports", "fleetscope").increment()
        return {"offset_s": self._telem_offset,
                "offset_bound_s": self._telem_bound}

    def pod_telemetry(self):
        """The coordinator's per-rank telemetry rings: {rank: [reports,
        oldest first]} plus the per-rank clock offsets it last saw —
        the ``mxdiag.py pod`` input for training runs."""
        return self._call("telem_snap")

    def leave(self):
        """Graceful drain: this rank is removed without waiting out a
        round deadline, and survivors re-form WITHOUT rolling back (a
        drained rank completed its rounds — nothing was lost mid-step,
        unlike an eviction). Rank 0 leaving closes the whole group."""
        if self._closed:
            return
        self._closed = True
        if self.rank == 0:
            self._stop.set()
            self._thread.join(timeout=5)
            try:
                self._listener.close()
            except Exception:   # noqa: BLE001
                pass
        else:
            try:
                self._call("leave", self.rank)
            except Exception:   # noqa: BLE001 — leaving a dead group is
                pass            # already the goal

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.leave()
        return False

    # -- transport --------------------------------------------------------
    def _call(self, op, *args, timeout=30.0):
        if self.rank == 0:
            return self._co.handle(op, args)
        try:
            with socket.create_connection(self.addr,
                                          timeout=timeout) as s:
                _send_frame(s, (op,) + args)
                kind, payload = _recv_frame(s)
        except (OSError, ConnectionError) as e:
            raise GroupClosed(f"elastic coordinator unreachable: "
                              f"{type(e).__name__}: {e}") from e
        if kind == "err":
            raise RuntimeError(f"elastic coordinator: {payload}")
        return payload

    def _serve(self):
        while not self._stop.is_set():
            try:
                conn, _ = self._listener.accept()
            except socket.timeout:
                continue
            except Exception:
                if self._stop.is_set():
                    break
                time.sleep(0.05)
                continue
            threading.Thread(target=self._handle_conn, args=(conn,),
                             daemon=True).start()

    def _handle_conn(self, conn):
        try:
            with conn:
                msg = _recv_frame(conn)
                op, args = msg[0], tuple(msg[1:])
                try:
                    reply = ("ok", self._co.handle(op, args))
                except Exception as e:   # noqa: BLE001 — one bad request
                    reply = ("err", f"{type(e).__name__}: {e}")
                _send_frame(conn, reply)
        except Exception:
            pass                  # a dropped member must not kill rank 0


class _Coordinator:
    """Rank-0 membership + round state. Thread-safe; every op goes
    through :meth:`handle` (called from connection handler threads and
    rank 0's own in-process calls alike)."""

    def __init__(self, sync_timeout_s, startup_grace_s=60.0):
        self.sync_timeout_s = float(sync_timeout_s)
        self.startup_grace_s = float(startup_grace_s)
        self._joined_at = {}     # rank -> monotonic join time
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._gen = 1
        self._members = set()
        self._pending = set()
        self._rounds = {}        # step -> {rank: vec}
        self._active_from = {}   # rank -> first step it must sync at
        self._last_contrib = {}  # rank -> newest round it contributed to
        self._departed_log = []  # [(gen, [ranks])] — EVICTIONS only
        self._left_log = []      # [(gen, [ranks])] — graceful drains
        self._joined_log = []
        self._last_good = None   # (step, path)
        self._max_step = 0
        self._started = False
        # fleetscope: bounded per-rank telemetry rings (member-push)
        self._telem = {}         # rank -> deque of reports

    def handle(self, op, args):
        if op == "join":
            return self._join(int(args[0]))
        if op == "sync":
            rank, gen_seen, step, vec = args
            return self._sync(int(rank), int(gen_seen), int(step),
                              np.asarray(vec, np.float32))
        if op == "ckpt":
            rank, step, path = args
            return self._ckpt(int(step), str(path))
        if op == "leave":
            return self._leave(int(args[0]))
        if op == "info":
            with self._lock:
                return {"generation": self._gen,
                        "members": sorted(self._members),
                        "pending": sorted(self._pending),
                        "last_good": self._last_good,
                        "max_step": self._max_step}
        if op == "telem":
            rank, payload = args
            return self._telem_push(int(rank), payload)
        if op == "telem_snap":
            return self._telem_snapshot()
        raise ValueError(f"unknown elastic op {op!r}")

    def _telem_push(self, rank, payload):
        """Store one member telemetry report (bounded ring) and reply
        with the coordinator's wall clock — the member's offset
        estimate needs nothing more than this round trip."""
        rec = dict(payload) if isinstance(payload, dict) else {}
        rec["rank"] = rank
        rec["received_ts"] = time.time()
        with self._lock:
            import collections
            ring = self._telem.get(rank)
            if ring is None:
                ring = self._telem[rank] = collections.deque(maxlen=16)
            ring.append(rec)
        return {"coordinator_ts": time.time(), "generation": self._gen}

    def _telem_snapshot(self):
        with self._lock:
            reports = {r: list(ring) for r, ring in self._telem.items()}
        offsets = {}
        for r, ring in reports.items():
            if ring:
                off = ring[-1].get("offset_s")
                if isinstance(off, (int, float)):
                    offsets[r] = off
        return {"reports": reports, "offsets": offsets}

    def _admit(self, rank, active_from):
        """Shared admission bookkeeping. Dropping any stale
        _last_contrib entry is what re-arms the startup grace for a
        RE-joining rank (a relaunched SIGKILL victim): its pre-eviction
        contributions must not make its restore/compile silence read as
        death again."""
        self._members.add(rank)
        self._active_from[rank] = active_from
        self._joined_at[rank] = time.monotonic()
        self._last_contrib.pop(rank, None)

    def _join(self, rank):
        with self._cond:
            if rank in self._members:
                return self._admit_payload(rank)
            if not self._started:
                self._admit(rank, 1)
                return self._admit_payload(rank)
            if self._last_good is not None:
                # a checkpoint boundary has already passed: restorable
                # last-good state exists, so the joiner enters now
                # (effective from the step after the current round)
                self._admit(rank, self._max_step + 1)
                self._gen += 1
                self._joined_log.append((self._gen, [rank]))
                self._cond.notify_all()
                return self._admit_payload(rank)
            # mid-run with NO checkpoint yet: admission waits for the
            # next checkpoint boundary (the joiner needs state to
            # restore)
            self._pending.add(rank)
            return {"admitted": False, "generation": self._gen,
                    "members": sorted(self._members)}

    def _admit_payload(self, rank):
        lg = self._last_good
        return {"admitted": True, "generation": self._gen,
                "members": sorted(self._members),
                "next_step": self._max_step + 1,
                "last_good": ({"step": lg[0], "path": lg[1]}
                              if lg else None)}

    def _ckpt(self, step, path):
        with self._cond:
            if self._last_good is None or step >= self._last_good[0]:
                self._last_good = (step, path)
            admitted = []
            if self._pending:
                # the admission boundary: last-good state now exists for
                # joiners to restore from
                for r in sorted(self._pending):
                    self._admit(r, self._max_step + 1)
                    admitted.append(r)
                self._pending.clear()
                self._gen += 1
                self._joined_log.append((self._gen, admitted))
                self._cond.notify_all()
            return {"last_good": {"step": self._last_good[0],
                                  "path": self._last_good[1]},
                    "admitted": admitted, "generation": self._gen}

    def _leave(self, rank):
        with self._cond:
            if rank in self._members:
                # a graceful drain, NOT an eviction: the leaver finished
                # its rounds, so survivors re-form without rolling back
                self._members.discard(rank)
                self._gen += 1
                self._left_log.append((self._gen, [rank]))
                self._cond.notify_all()
            self._pending.discard(rank)
            return {"generation": self._gen,
                    "members": sorted(self._members)}

    def _sync(self, rank, gen_seen, step, vec):
        with self._cond:
            self._started = True
            self._max_step = max(self._max_step, step)
            if rank not in self._members:
                # an evicted rank syncing again (it was only slow, not
                # dead, and missed the round): it must re-join through
                # the checkpoint boundary like any other joiner
                raise RuntimeError(
                    f"rank {rank} is not a member (evicted or never "
                    f"joined) — call join() to re-enter at the next "
                    f"checkpoint boundary")
            rnd = self._rounds.setdefault(step, {})
            rnd[rank] = vec
            self._last_contrib[rank] = max(
                self._last_contrib.get(rank, 0), step)
            self._cond.notify_all()
            deadline = time.monotonic() + self.sync_timeout_s
            while True:
                # a joiner admitted at a checkpoint boundary is only
                # REQUIRED from the step it was told to start at — a
                # survivor mid-round must not wait on a contribution
                # the joiner was never asked for
                current = {r for r in self._members
                           if self._active_from.get(r, 1) <= step}
                missing = current - set(rnd)
                if not missing:
                    break
                # a member already syncing LATER rounds is alive and
                # will never come back to this one (a lagging re-joiner
                # replaying a stale round must neither wait for it nor
                # evict it) — complete over whoever is here
                ahead = {r for r in missing
                         if self._last_contrib.get(r, -1) > step}
                if missing == ahead:
                    break
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    now = time.monotonic()
                    # a member that has NEVER contributed is still in
                    # startup (compiling, restoring): inside its grace
                    # window its silence is expected, not death
                    graced = {r for r in missing - ahead
                              if r not in self._last_contrib
                              and now - self._joined_at.get(r, now)
                              < self.startup_grace_s}
                    dead = sorted(missing - ahead - graced)
                    if dead:
                        # eviction: the departed rank's contribution is
                        # never coming; the survivors' round completes
                        # without it
                        for r in dead:
                            self._members.discard(r)
                        self._gen += 1
                        self._departed_log.append((self._gen, dead))
                        # survivors will roll back to last-good and
                        # REPLAY rounds ≤ this one: stale buffered
                        # contributions must not mix into the replayed
                        # means, stale _last_contrib must not make the
                        # "ahead" rule complete a replayed round over a
                        # partial set, and the restore-from-last-good
                        # pause must not itself read as death — so the
                        # round state resets and every survivor gets a
                        # fresh startup-grace window
                        self._rounds.clear()
                        self._last_contrib.clear()
                        now_m = time.monotonic()
                        for r in self._members:
                            self._joined_at[r] = now_m
                        self._cond.notify_all()
                        break
                    if not graced:
                        break
                    deadline = now + 0.5   # re-check as grace expires
                self._cond.wait(min(max(remaining, 0.05), 0.2))
            contrib = [v for r, v in rnd.items() if r in self._members]
            mean = (np.mean(contrib, axis=0) if contrib
                    else np.asarray(vec, np.float32))
            resp = {"mean": mean, "generation": self._gen,
                    "members": sorted(self._members), "step": step}
            if self._gen != gen_seen:
                resp["departed"] = sorted(
                    r for g, rs in self._departed_log if g > gen_seen
                    for r in rs)
                resp["left"] = sorted(
                    r for g, rs in self._left_log if g > gen_seen
                    for r in rs)
                resp["joined"] = sorted(
                    r for g, rs in self._joined_log if g > gen_seen
                    for r in rs)
                lg = self._last_good
                resp["last_good"] = ({"step": lg[0], "path": lg[1]}
                                     if lg else None)
            # bounded round memory: everything older than a few steps
            # is settled
            for s in [s for s in self._rounds if s < step - 4]:
                self._rounds.pop(s, None)
            return resp
