"""The supervised recovery policy — the act-on-verdicts half of
mxtpu.resilience (docs/resilience.md has the full state machine).

healthmon (PR 5) detects: the NaN sentinel fires within one step, the
stall watchdog dumps the flight ring, the EWMA flags the regression —
and then the job dies anyway. :class:`Supervisor` closes that loop for
a :class:`~..trainloop.TrainLoop`:

* **in-process rollback** — a non-finite loss in a chunk rolls params/
  optimizer state/lr step/rng back to the last GOOD checkpoint
  (draining any in-flight save first), skips the poison batch (or
  re-reads it under ``skip_poison=False`` for transient faults), and
  retries with backoff; ``max_retries`` consecutive faults escalate to
  :class:`RecoveryEscalated` — bounded, never an infinite rollback
  loop burning the reservation.
* **process-level resume** — ``drive()`` on a directory that already
  holds checkpoints restores the last good one (falling back past torn
  ones — parallel/checkpoint.py), reads the data cursor from its
  manifest, and skips the already-consumed batches, so a restarted
  process continues instead of replaying.
* **stall → restart** — the stall watchdog's alert routes here (one
  predicate in healthmon's fan-out): the request is counted + evented,
  and under ``on_stall='exit'`` (``MXTPU_RESILIENCE_ON_STALL``) the
  process exits with :data:`RESTART_EXIT_CODE` so a launcher/chaos
  harness restarts it into the resume path above. An in-process
  "un-wedge" does not exist — a stuck collective is stuck; the honest
  action is a clean restart from last-good.

Every recovery lands on all three surfaces at once: ``resilience.*``
counters, a flight breadcrumb, and an ``mxtpu.events/1`` record —
``tools/mxdiag.py recover`` renders the timeline.

Detection cost: supervised mode fetches each chunk's losses to host
(the NaN check needs scalars), i.e. one device sync per chunk — the
same sync the un-supervised loop pays only at fit() end. That is THE
overhead of arming resilience; disabled, nothing here runs.
"""
from __future__ import annotations

import os
import time

import numpy as np

from ..profiler.counters import (counter as _counter,
                                 set_gauge as _set_gauge)
from .checkpoint import CheckpointManager, _breadcrumb, _emit

__all__ = ["Supervisor", "RecoveryEscalated", "RESTART_EXIT_CODE"]

# exit status a stall-escalated process dies with: distinguishable from
# a crash (nonzero) and from success, so a supervising launcher knows
# "restart me into the resume path" (tools/chaos_cluster.py's freeze
# scenario watches for it)
RESTART_EXIT_CODE = 96


class RecoveryEscalated(RuntimeError):
    """Bounded retries exhausted — the fault is not transient and not a
    single poison batch; a human (or a higher-level scheduler) owns the
    next move."""


class Supervisor:
    """Resilient driver for a TrainLoop.

        loop = TrainLoop(net, loss, trainer)
        sup = Supervisor("/ckpts/run1", every=50, keep=3)
        losses = sup.drive(loop, train_iter, steps=500)

    or, equivalently, ``loop.fit(train_iter, steps=500,
    resilience="/ckpts/run1")``.

    Parameters: ``every``/``keep`` forward to
    :class:`~.checkpoint.CheckpointManager`; ``max_retries`` bounds
    CONSECUTIVE faults before escalation; ``backoff_s`` is the base of
    the exponential retry backoff; ``skip_poison=True`` advances past
    the faulting chunk's batches (a poison batch), ``False`` re-reads
    the same chunk (a transient fault); ``on_stall`` is ``'none'``
    (record only) or ``'exit'`` (die with RESTART_EXIT_CODE for the
    launcher to restart)."""

    def __init__(self, ckpt_dir, every=None, keep=None, max_retries=2,
                 backoff_s=0.05, skip_poison=True, on_stall=None):
        self.ckpt_dir = os.path.abspath(ckpt_dir)
        self.every = every
        self.keep = keep
        self.max_retries = int(max_retries)
        self.backoff_s = float(backoff_s)
        self.skip_poison = bool(skip_poison)
        from ..autotune.knobs import env_str
        self.on_stall = (on_stall or env_str(
            "MXTPU_RESILIENCE_ON_STALL", "none")).lower()
        if self.on_stall not in ("none", "exit"):
            raise ValueError(f"on_stall must be 'none' or 'exit', "
                             f"got {self.on_stall!r}")
        self.manager = None
        self._c_recoveries = _counter(
            "resilience.recoveries_total", "resilience")
        self._c_rollbacks = _counter("resilience.rollbacks",
                                              "resilience")
        self._c_resumes = _counter("resilience.resumes",
                                            "resilience")
        self._c_steps_lost = _counter(
            "resilience.steps_lost_total", "resilience")
        self._c_escalations = _counter(
            "resilience.retries_exhausted", "resilience")
        self._c_restarts = _counter(
            "resilience.restarts_requested", "resilience")

    # -- healthmon verdict routing ---------------------------------------
    def on_health_alert(self, name, args, step=None):
        """Called by healthmon's alert fan-out while this supervisor is
        registered. NaN verdicts are acted on by the drive loop itself
        (it sees the loss first); the stall watchdog's verdict is acted
        on HERE — the loop thread is the thing that is stuck."""
        if name != "stall":
            return
        self._c_restarts.increment()
        info = {"age_s": args.get("age_s"), "on_stall": self.on_stall,
                "last_checkpoint_step":
                    self.manager.last_saved_step if self.manager else None}
        _breadcrumb("restart_requested", info)
        _emit("resilience", "resilience.restart_requested", step=step,
              args=info)
        if self.on_stall == "exit":
            # the loop thread is wedged (that is what a stall IS) — a
            # graceful unwind cannot run. Die with the restart code so
            # the launcher restarts into the resume path — but from a
            # SEPARATE thread after a beat, so healthmon's own stall
            # handler (which called us) finishes writing the flight
            # post-mortem first.
            import threading

            def _die():
                time.sleep(1.0)
                try:
                    from ..healthmon import events as _events
                    log = _events.current_log()
                    if log is not None:
                        log.close()
                except Exception:   # noqa: BLE001
                    pass
                os._exit(RESTART_EXIT_CODE)

            threading.Thread(target=_die, daemon=True,
                             name="mxtpu-resilience-restart").start()

    # -- the drive loop ---------------------------------------------------
    def drive(self, loop, data, steps=None, cycle=True):
        """Run ``loop`` to a TARGET of ``steps`` total optimizer updates
        (a resumed run counts its restored updates toward the target),
        checkpointing every N and recovering per the policy. Returns the
        per-step losses of the chunks that SURVIVED (rolled-back chunks'
        losses are discarded with their updates)."""
        from .. import resilience as _rs
        from ..io.prefetch import DevicePrefetcher

        if steps is None:
            raise ValueError("resilient fit is steps-driven: pass steps=")
        k = loop.chunk
        if steps < k:
            raise ValueError(f"steps={steps} is less than one chunk "
                             f"of {k}")

        self.manager = CheckpointManager(self.ckpt_dir, loop.step,
                                         every=self.every, keep=self.keep)
        _rs._register(self)
        try:
            return self._drive(loop, data, int(steps), cycle,
                               DevicePrefetcher)
        finally:
            _rs._unregister(self)
            self.manager.close()

    def _build_from_probe(self, loop, data):
        """Compile the step from the source's first batch WITHOUT
        consuming an update (restore needs a built step). Returns the
        source to keep feeding from: the probe batch is given back by
        reset()/re-iteration where the source supports it, and CHAINED
        back in front of a one-shot iterator/generator (which has no
        rewind — dropping the probe there would silently lose the first
        unconsumed batch of a cursor resume)."""
        import itertools

        from ..io.prefetch import _split_batch
        from ..ndarray import NDArray
        it = None
        if hasattr(data, "next"):
            first = data.next()
        else:
            it = iter(data)
            first = next(it)
        x, y = _split_batch(first)
        if y is None:
            raise ValueError("resilient fit needs labeled batches")
        as_nd = (lambda a: a if isinstance(a, NDArray)
                 else NDArray(np.asarray(a)))
        loop.step.ensure_built(as_nd(x), as_nd(y))
        if hasattr(data, "reset"):
            data.reset()
            return data
        if it is None or it is data:
            # .next()-style source without reset(), or a one-shot
            # iterator: the probe consumed a real batch with no way to
            # rewind — chain it back in front
            return itertools.chain([first], data if it is None else it)
        return data

    def _drive(self, loop, data, target, cycle, DevicePrefetcher):
        from ..parallel import checkpoint as _ckpt
        k = loop.chunk
        # same steps= semantics as the un-supervised fit: whole chunks
        # only, remainder dropped — arming resilience must not change
        # how many updates fit(steps=N) performs
        target = (target // k) * k
        cursor = 0
        if _ckpt.list_steps(self.ckpt_dir):
            # process-level resume: restart-from-last-good
            data = self._build_from_probe(loop, data)
            n, cur = self.manager.restore_last_good()
            cursor = int(cur or 0)
            self._c_resumes.increment()
            self._c_recoveries.increment()
            info = {"restored_step": n, "cursor": cursor,
                    "dir": self.ckpt_dir}
            _breadcrumb("resume", info)
            _emit("resilience", "resilience.resume", step=n, args=info)
            self._beat_watchdog()
            # restore_last_good just full-digest-verified the newest
            # checkpoint, the probe built the step, and the watchdog is
            # fresh — the first-chunk guard below would only repeat all
            # three (for a multi-GB sharded checkpoint, last_good()'s
            # re-hash doubles resume-time disk I/O)
            resumed = True
        else:
            resumed = False
        history = []            # [(first_step, losses_np)]
        faults = 0
        pending = None          # re-read chunk under skip_poison=False
        with DevicePrefetcher(
                data, depth=loop.prefetch_depth, chunk=k,
                sharding=lambda: loop.step._stacked_sharding,
                cycle=cycle, skip=cursor) as pf:
            guarded = resumed
            while loop.step._num_update < target:
                if pending is not None:
                    xs, ys = pending
                    pending = None
                else:
                    try:
                        xs, ys = next(pf)
                    except StopIteration:
                        raise ValueError(
                            f"data source exhausted at update "
                            f"{loop.step._num_update} of {target} and "
                            f"cannot be rewound") from None
                    cursor += k
                if not guarded:
                    # a pre-flight checkpoint of the CURRENT state (step
                    # 0, or the resumed step if its save was pruned):
                    # rollback is then ALWAYS possible, even for a fault
                    # in the very first chunk
                    guarded = True
                    loop.step.ensure_built(_first_micro(xs),
                                           _first_micro(ys))
                    if self.manager.last_good() is None:
                        self.manager.save_now(cursor=cursor - k,
                                              block=True)
                    self._beat_watchdog()
                start = loop.step._num_update + 1
                losses = loop.run_chunk(xs, ys).asnumpy()
                if np.isfinite(losses).all():
                    faults = 0
                    history.append((start, losses))
                    self.manager.maybe_save(cursor=cursor)
                    self._mark_healthmon(float(losses[-1]))
                    continue
                # ---- fault: non-finite loss inside this chunk --------
                # the verdict surface first: healthmon's NaN sentinel
                # fires (counter + flight + event) so the timeline shows
                # FAULT -> ACTION, not an unexplained rollback; its
                # on_nan='raise' is subsumed by supervision (rollback IS
                # the raise handler here)
                bad = losses[~np.isfinite(losses)]
                self._observe_nan(float(bad[0]) if bad.size else
                                  float("nan"),
                                  step=loop.step._num_update)
                faults += 1
                if faults > self.max_retries:
                    self._escalate(loop.step._num_update, faults)
                to_step, history = self._rollback(
                    loop, history, reason="nan_loss",
                    fault_step=loop.step._num_update, attempt=faults)
                if not self.skip_poison:
                    pending = (xs, ys)   # transient fault: re-read
        # run end: final checkpoint so a later process resumes from here
        self.manager.save_now(cursor=cursor, block=True)
        if not history:
            return np.zeros((0,), np.float32)
        return np.concatenate([h for _, h in history])

    def _beat_watchdog(self):
        """Recovery progress is not a stall: a restore, shape-probe
        compile, or guard save legitimately outlasts a tight stall
        deadline, and firing mid-recovery would restart a process that
        is already recovering. Re-arm the deadline when one completes."""
        try:
            from .. import healthmon as _hm
            hm = _hm.current()
            if hm is not None and hm.watchdog is not None:
                hm.watchdog.beat()
        except Exception:   # noqa: BLE001 — telemetry only
            pass

    def _observe_nan(self, value, step=None):
        try:
            from .. import healthmon as _hm
            _hm.observe_loss(value, step=step)
        except FloatingPointError:
            pass
        except Exception:   # noqa: BLE001 — telemetry only
            pass

    def _mark_healthmon(self, value):
        """One healthmon mark per survived chunk: beats the stall
        watchdog (a healthy supervised loop must not look stalled),
        feeds the step-time EWMA/event stream, and ticks the NaN
        sentinel with the already-fetched loss scalar. Under
        supervision a non-finite value triggers ROLLBACK, not the
        sentinel's on_nan='raise'."""
        try:
            from .. import healthmon as _hm
            hm = _hm.current()
            if hm is not None:
                hm.step_end(loss=value)
        except FloatingPointError:
            pass
        except Exception:   # noqa: BLE001 — telemetry only
            pass

    def _rollback(self, loop, history, reason, fault_step, attempt):
        _set_gauge("resilience.rollback_in_progress", 1,
                            "resilience")
        try:
            to_step, _cur = self.manager.restore_last_good()
            self._beat_watchdog()
            steps_lost = max(0, fault_step - to_step)
            self._c_rollbacks.increment()
            self._c_recoveries.increment()
            self._c_steps_lost.increment(steps_lost)
            _set_gauge("resilience.steps_lost_last", steps_lost,
                                "resilience")
            args = {"reason": reason, "from_step": fault_step,
                    "to_step": to_step, "steps_lost": steps_lost,
                    "attempt": attempt,
                    "skip_poison": self.skip_poison}
            _breadcrumb("rollback", args)
            _emit("resilience", "resilience.rollback", step=fault_step,
                  args=args)
            # rolled-back updates take their losses with them: the
            # returned history is the trajectory that SURVIVED
            history = [(s, l) for s, l in history
                       if s + len(l) - 1 <= to_step]
            if attempt > 1 and self.backoff_s > 0:
                time.sleep(self.backoff_s * (2 ** (attempt - 2)))
            return to_step, history
        finally:
            _set_gauge("resilience.rollback_in_progress", 0,
                                "resilience")

    def _escalate(self, at_step, faults):
        self._c_escalations.increment()
        args = {"step": at_step, "consecutive_faults": faults,
                "max_retries": self.max_retries}
        _breadcrumb("escalation", args)
        _emit("alert", "resilience.escalation", step=at_step, args=args)
        raise RecoveryEscalated(
            f"resilience: {faults} consecutive faults at step {at_step} "
            f"exceeded max_retries={self.max_retries} — not a transient "
            f"or single poison batch; escalating")


def _first_micro(stacked):
    """First micro-batch of a stacked (k, batch, ...) chunk as an
    NDArray (for ensure_built's shape probe)."""
    from ..ndarray import NDArray
    if isinstance(stacked, NDArray):
        return NDArray(stacked._data[0])
    return NDArray(np.asarray(stacked)[0])
