"""Periodic async sharded checkpoints — the save half of
mxtpu.resilience (docs/resilience.md).

The training thread's cost per due boundary is ONE device→host copy
(`parallel.checkpoint._host_tree` — jax.device_get at a step boundary,
where the donated buffers are between programs and safe to read); the
sha256 digesting, orbax serialization, manifest, atomic rename, and
rotation all run on a single worker thread. A save still in flight when
the next boundary comes due is SKIPPED (counted), so the queue depth is
bounded at one and a slow disk degrades checkpoint cadence, never step
time — the save-is-async contract tests/test_resilience.py pins.

Telemetry (domain ``resilience``): ``checkpoints_saved`` /
``checkpoints_pruned`` / ``saves_skipped`` / ``save_errors`` counters,
``last_checkpoint_step`` gauge, ``copy_ms`` / ``save_ms`` histograms
(boundary copy vs worker serialization — the BENCH ``extra.resilience``
save p50/p95 read the latter), plus a ``resilience.checkpoint_saved``
event per completed save.
"""
from __future__ import annotations

import os
import queue as _queue
import threading
import time

from ..parallel import checkpoint as _ckpt
from ..profiler.counters import (counter as _counter,
                                 histogram as _histogram,
                                 set_gauge as _set_gauge)

__all__ = ["CheckpointManager"]


def _emit(kind, name, step=None, args=None):
    """Structured event, if a healthmon event log is open (no-op
    otherwise — resilience works with or without healthmon)."""
    try:
        from ..healthmon import events as _events
        _events.emit(kind, name, step=step, args=args)
    except Exception:   # noqa: BLE001 — telemetry must not block saving
        pass


def _breadcrumb(name, args):
    try:
        from ..diagnostics import flight as _flight
        if _flight._REC is not None:
            _flight.record("resilience", name, args)
    except Exception:   # noqa: BLE001
        pass


class CheckpointManager:
    """Bounded-rotation async checkpointer for a FusedTrainStep (or a
    TrainLoop — anything exposing ``.step``/being a step).

        mgr = CheckpointManager(dir, step, every=50, keep=3)
        ...
        loss = step(x, y)
        mgr.maybe_save(cursor=batches_consumed)    # due? copy + enqueue
        ...
        mgr.close()                                # drain + final state

    every : checkpoint cadence in optimizer steps
            (``MXTPU_RESILIENCE_EVERY``, default 50; 0 disables periodic
            saves — ``save_now`` still works).
    keep  : bounded rotation of last-K GOOD checkpoints
            (``MXTPU_RESILIENCE_KEEP``, default 3).
    """

    def __init__(self, directory, step, every=None, keep=None):
        step = getattr(step, "step", step)   # accept a TrainLoop
        self._step = step
        self.directory = os.path.abspath(directory)
        from ..autotune.knobs import env_float
        self.every = int(env_float("MXTPU_RESILIENCE_EVERY", 50.0,
                                   call_site=every))
        self.keep = int(env_float("MXTPU_RESILIENCE_KEEP", 3.0,
                                  call_site=keep))
        if self.keep < 1:
            raise ValueError(f"keep must be >= 1, got {self.keep}")
        os.makedirs(self.directory, exist_ok=True)
        self._c_saved = _counter("resilience.checkpoints_saved",
                                          "resilience")
        self._c_pruned = _counter("resilience.checkpoints_pruned",
                                           "resilience")
        self._c_skipped = _counter("resilience.saves_skipped",
                                            "resilience")
        self._c_errors = _counter("resilience.save_errors",
                                           "resilience")
        self._h_copy = _histogram("resilience.copy_ms",
                                           "resilience")
        self._h_save = _histogram("resilience.save_ms",
                                           "resilience")
        self._q = _queue.Queue(maxsize=1)    # bounded: at most 1 in flight
        self._idle = threading.Event()
        self._idle.set()
        self._stop = False
        self.last_saved_step = None
        self._last_enqueued = None
        self._last_error = None
        self._thread = threading.Thread(target=self._worker, daemon=True,
                                        name="mxtpu-resilience-ckpt")
        self._thread.start()

    # -- training-thread side ---------------------------------------------
    def due(self, step_num=None):
        """True when the step count CROSSED a cadence boundary since the
        last enqueued save — not just when it lands exactly on one: a
        chunked loop advances num_update by k per call, and requiring
        divisibility would stretch the effective cadence to
        lcm(every, k)."""
        n = self._step._num_update if step_num is None else int(step_num)
        if self.every <= 0 or n <= 0:
            return False
        ref = self._last_enqueued or 0
        return n // self.every > ref // self.every

    def maybe_save(self, cursor=None, step_num=None):
        """Call once per completed optimizer step (or chunk boundary).
        If the step count crossed the cadence, snapshot and enqueue.
        Returns True when a save was enqueued."""
        n = self._step._num_update if step_num is None else int(step_num)
        if not self.due(n):
            return False
        if n == self._last_enqueued:
            return False           # chunk boundaries can land on the same n
        return self.save_now(cursor=cursor, step_num=n, block=False)

    def save_now(self, cursor=None, step_num=None, block=True):
        """Snapshot (boundary device→host copy, the only blocking part)
        and hand the host tree to the worker. With ``block=False`` an
        in-flight save makes this a counted skip instead of a wait."""
        n = self._step._num_update if step_num is None else int(step_num)
        if not block and not self._idle.is_set():
            self._c_skipped.increment()
            return False
        if block:
            self.wait()
        t0 = time.perf_counter()
        tree = _ckpt._host_tree(self._step)
        self._h_copy.observe((time.perf_counter() - t0) * 1e3)
        meta = {"num_update": int(n)}
        if cursor is not None:
            meta["cursor"] = int(cursor)
        self._idle.clear()
        self._last_enqueued = n
        self._q.put((n, tree, meta))
        return True

    def wait(self, timeout=None):
        """Block until no save is in flight (tests / shutdown / before a
        rollback reads last-good)."""
        return self._idle.wait(timeout)

    # -- worker side ------------------------------------------------------
    def _worker(self):
        while True:
            item = self._q.get()
            if item is None:
                return
            n, tree, meta = item
            t0 = time.perf_counter()
            try:
                path = self._gated_save(n, tree, meta)
                ms = (time.perf_counter() - t0) * 1e3
                self._h_save.observe(ms)
                self._c_saved.increment()
                self.last_saved_step = n
                _set_gauge("resilience.last_checkpoint_step", n,
                                    "resilience")
                args = {"path": path, "save_ms": round(ms, 3),
                        "cursor": meta.get("cursor")}
                _breadcrumb("checkpoint_saved", dict(args, step=n))
                _emit("resilience", "resilience.checkpoint_saved",
                      step=n, args=args)
                self._prune()
            except Exception as e:   # noqa: BLE001 — a failed save must
                # degrade durability, not kill training; but loudly
                self._c_errors.increment()
                self._last_error = f"{type(e).__name__}: {e}"
                _breadcrumb("save_error",
                            {"step": n, "error": self._last_error[:300]})
                _emit("alert", "resilience.save_error", step=n,
                      args={"error": self._last_error[:300]})
            finally:
                self._idle.set()

    def _gated_save(self, n, tree, meta):
        """On the XLA:CPU client, hold the process-wide transfer gate for
        the whole orbax serialization: that client is unsafe against
        concurrent client work (io/pipeline.py's safety model), and the
        donating-dispatch window on the training thread is also inside
        the gate there — so the save window and every XLA window are
        mutually excluded. The tree is already host numpy; training only
        stalls if a put/dispatch collides with an in-flight save, so the
        save stays async in the common case. Other backends save
        ungated (concurrency is the point of the worker thread)."""
        from ..io.pipeline import TRANSFER_GATE, _defer_put_needed
        if _defer_put_needed():
            with TRANSFER_GATE:
                return _ckpt.save_tree(self.directory, n, tree, meta=meta)
        return _ckpt.save_tree(self.directory, n, tree, meta=meta)

    def _prune(self):
        import shutil
        steps = _ckpt.list_steps(self.directory)
        for n in steps[:-self.keep] if len(steps) > self.keep else []:
            shutil.rmtree(_ckpt._step_path(self.directory, n),
                          ignore_errors=True)
            self._c_pruned.increment()

    # -- restore side -----------------------------------------------------
    def last_good(self):
        """Newest step number whose checkpoint verifies (None if none).
        Does NOT drain in-flight saves — call wait() first when that
        matters (the rollback path does)."""
        for n in reversed(_ckpt.list_steps(self.directory)):
            status, _ = _ckpt.verify_checkpoint(
                _ckpt._step_path(self.directory, n))
            if status in ("ok", "legacy"):
                return n
        return None

    def restore_last_good(self):
        """Drain in-flight saves, then restore the newest good
        checkpoint into the live step (falling back past corrupt ones —
        parallel/checkpoint.py owns that policy). Returns
        ``(restored_step, cursor)``; raises if nothing restorable."""
        self.wait()
        n = _ckpt.restore_train_step(self.directory, self._step)
        # a rollback moves num_update BELOW the save high-water mark:
        # re-anchor the cadence there so replayed steps checkpoint on
        # schedule instead of waiting to re-cross the old mark
        self._last_enqueued = n
        man = _ckpt.read_manifest(_ckpt._step_path(self.directory, n))
        cursor = None
        if man and isinstance(man.get("meta"), dict):
            c = man["meta"].get("cursor")
            cursor = int(c) if isinstance(c, int) else None
        return n, cursor

    # -- lifecycle --------------------------------------------------------
    def close(self):
        """Drain pending saves and stop the worker. Idempotent."""
        if self._stop:
            return
        self._stop = True
        self.wait()
        self._q.put(None)
        self._thread.join(timeout=30)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
