"""mx.model namespace shim (parity: python/mxnet/model.py).

The reference keeps `save_checkpoint`/`load_checkpoint` in mx.model (the
Module docs and many downstream scripts call them there). The
implementations live in `module/`; this re-export keeps those call sites
working. The deprecated FeedForward trainer is intentionally absent — use
`mx.mod.Module` (same `fit` surface).
"""
from .module import save_checkpoint, load_checkpoint  # noqa: F401

__all__ = ["save_checkpoint", "load_checkpoint"]
