"""Token embeddings (reference: python/mxnet/contrib/text/embedding.py).

File-backed pretrained vectors (GloVe/fastText text formats) load into a
host matrix and become a device ``NDArray`` ready for
``gluon.nn.Embedding.weight.set_data`` — the TPU path is one
embedding-table gather, no per-token host work. This image has no egress,
so the auto-download path of the reference raises a documented error;
``pretrained_file_path`` pointing at a local vector file works fully.
"""
import io
import logging
import os

import numpy as np

from ... import ndarray as nd
from ...base import _Registry
from .vocab import TokenIndexMixin

__all__ = ["register", "create", "get_pretrained_file_names",
           "TokenEmbedding", "CustomEmbedding", "CompositeEmbedding",
           "GloVe", "FastText"]

_REG = _Registry("token_embedding")


def register(cls):
    _REG.register(cls.__name__.lower())(cls)
    return cls


def create(embedding_name, **kwargs):
    """Create by registered name, e.g. ``create('glove',
    pretrained_file_name=..., pretrained_file_path=...)``."""
    return _REG.create(embedding_name.lower(), **kwargs)


def get_pretrained_file_names(embedding_name=None):
    """Known pretrained vector files per registered embedding (names
    only; files must be provided locally, this image has no egress).
    User classes added via @register appear here too."""
    known = {name: list(getattr(cls, "pretrained_file_names", ()))
             for name, cls in _REG._map.items()}
    if embedding_name is None:
        return known
    name = embedding_name.lower()
    if name not in known:
        raise KeyError(f"unknown embedding {embedding_name!r}; choose from "
                       f"{sorted(known)}")
    return known[name]


def _gather_rows(rows, tok2idx, tokens):
    """(len(tokens), D) matrix of each token's vector from ``rows``
    (unknown → row 0). The one definition of token→row lookup shared by
    vocabulary re-indexing and CompositeEmbedding."""
    out = np.zeros((len(tokens), rows.shape[1]), np.float32)
    for i, tok in enumerate(tokens):
        out[i] = rows[tok2idx.get(tok, 0)]
    return out


class TokenEmbedding(TokenIndexMixin):
    """Base: an index of tokens with a dense vector per token.

    ``vocabulary`` (optional) re-indexes the loaded vectors against a
    :class:`~.vocab.Vocabulary`; otherwise tokens index in file order
    with index 0 = unknown."""

    def __init__(self, unknown_token="<unk>",
                 init_unknown_vec=np.zeros):
        self._unknown_token = unknown_token
        self._init_unknown_vec = init_unknown_vec
        self._idx_to_token = [unknown_token]
        self._token_to_idx = {unknown_token: 0}
        self._idx_to_vec = None      # (N, D) NDArray after load
        self._idx_to_vec_np = None   # host mirror (row gathers stay cheap)

    def _set_idx_to_vec(self, matrix_np):
        """Install the vector table: device NDArray + cached host mirror
        (a per-lookup asnumpy() of a 2M x 300 table would be a multi-GB
        device→host copy per call)."""
        self._idx_to_vec_np = np.asarray(matrix_np, np.float32)
        self._idx_to_vec = nd.array(self._idx_to_vec_np)

    # -- loading ---------------------------------------------------------
    def _load_embedding_txt(self, path, elem_delim=" ", encoding="utf8"):
        """Parse a GloVe/fastText-style text file: `token v1 v2 ... vD`
        per line. Malformed lines are skipped with a warning (reference
        behavior)."""
        if not os.path.isfile(path):
            raise OSError(
                f"pretrained embedding file {path!r} not found. This "
                "environment has no network egress; download is not "
                "supported — place the vector file locally and pass "
                "pretrained_file_path.")
        vecs = []
        dim = None
        log = logging.getLogger("incubator_mxnet_tpu.text")
        with io.open(path, "r", encoding=encoding) as f:
            for ln_no, line in enumerate(f, 1):
                parts = line.rstrip().split(elem_delim)
                if ln_no == 1 and len(parts) == 2:
                    continue  # fastText header line: "<count> <dim>"
                token, elems = parts[0], parts[1:]
                if not elems:
                    # blank/vector-less line: must not commit dim=0
                    log.warning("%s:%d skipped (no vector)", path, ln_no)
                    continue
                if dim is not None and len(elems) != dim:
                    log.warning("%s:%d skipped (bad length)", path, ln_no)
                    continue
                if token in self._token_to_idx:
                    log.warning("%s:%d skipped (dup token)", path, ln_no)
                    continue
                try:
                    vec = np.asarray([float(e) for e in elems], np.float32)
                except ValueError:
                    log.warning("%s:%d skipped (non-float element)",
                                path, ln_no)
                    continue
                # dim commits only after a line fully parses, so a
                # malformed first line can't poison the expected length
                if dim is None:
                    if len(elems) == 1:
                        raise ValueError(
                            f"{path}:{ln_no}: unexpected vector length 1 — "
                            f"wrong elem_delim?")
                    dim = len(elems)
                self._token_to_idx[token] = len(self._idx_to_token)
                self._idx_to_token.append(token)
                vecs.append(vec)
        if dim is None:
            raise ValueError(f"{path}: no vectors parsed")
        unk = np.asarray(self._init_unknown_vec((dim,)), np.float32)
        self._set_idx_to_vec(np.vstack([unk[None, :]] + vecs))

    def _reindex_to_vocabulary(self, vocabulary):
        rows = _gather_rows(self._idx_to_vec_np, self._token_to_idx,
                            vocabulary.idx_to_token)
        self._idx_to_token = list(vocabulary.idx_to_token)
        self._token_to_idx = dict(vocabulary.token_to_idx)
        self._set_idx_to_vec(rows)

    # -- the reference API ----------------------------------------------
    def __len__(self):
        return len(self._idx_to_token)

    @property
    def vec_len(self):
        return 0 if self._idx_to_vec is None else self._idx_to_vec.shape[1]

    @property
    def idx_to_vec(self):
        return self._idx_to_vec

    @property
    def idx_to_token(self):
        return self._idx_to_token

    @property
    def token_to_idx(self):
        return self._token_to_idx

    @property
    def unknown_token(self):
        return self._unknown_token

    def get_vecs_by_tokens(self, tokens, lower_case_backup=False):
        """Vectors for token(s); unknown tokens get the unknown vector."""
        single = isinstance(tokens, str)
        toks = [tokens] if single else tokens

        def idx_of(t):
            if t in self._token_to_idx:
                return self._token_to_idx[t]
            if lower_case_backup:
                return self._token_to_idx.get(t.lower(), 0)
            return 0

        rows = self._idx_to_vec_np[[idx_of(t) for t in toks]]
        out = nd.array(rows)
        return out[0] if single else out

    def update_token_vectors(self, tokens, new_vectors):
        """Overwrite vectors for known tokens (reference semantics:
        unknown tokens raise)."""
        single = isinstance(tokens, str)
        toks = [tokens] if single else list(tokens)
        vecs = new_vectors.asnumpy()
        if vecs.ndim == 1:
            vecs = vecs[None, :]
        if len(toks) != vecs.shape[0]:
            raise ValueError("tokens / new_vectors length mismatch")
        data = np.array(self._idx_to_vec_np)  # host mirror is read-only
        for t, v in zip(toks, vecs):
            if t not in self._token_to_idx:
                raise ValueError(f"token {t!r} is not indexed")
            data[self._token_to_idx[t]] = v
        self._set_idx_to_vec(data)


@register
class CustomEmbedding(TokenEmbedding):
    """Vectors from a user-supplied text file: `token v1 ... vD` lines
    (reference CustomEmbedding)."""

    def __init__(self, pretrained_file_path, elem_delim=" ",
                 encoding="utf8", vocabulary=None, **kwargs):
        super().__init__(**kwargs)
        self._load_embedding_txt(pretrained_file_path, elem_delim, encoding)
        if vocabulary is not None:
            self._reindex_to_vocabulary(vocabulary)


class _PretrainedEmbedding(CustomEmbedding):
    pretrained_file_names = ()

    def __init__(self, pretrained_file_name=None, pretrained_file_path=None,
                 vocabulary=None, **kwargs):
        if pretrained_file_path is None:
            raise OSError(
                f"{type(self).__name__}: automatic download of "
                f"{pretrained_file_name!r} is not supported in this "
                "no-egress environment. Pass pretrained_file_path= to a "
                "locally available vector file (same text format).")
        super().__init__(pretrained_file_path, vocabulary=vocabulary,
                         **kwargs)


@register
class GloVe(_PretrainedEmbedding):
    """GloVe vectors (reference class; local-file-backed here)."""

    pretrained_file_names = (
        "glove.42B.300d.txt", "glove.6B.50d.txt", "glove.6B.100d.txt",
        "glove.6B.200d.txt", "glove.6B.300d.txt", "glove.840B.300d.txt",
        "glove.twitter.27B.25d.txt", "glove.twitter.27B.50d.txt",
        "glove.twitter.27B.100d.txt", "glove.twitter.27B.200d.txt")


@register
class FastText(_PretrainedEmbedding):
    """fastText vectors (reference class; local-file-backed here)."""

    pretrained_file_names = (
        "wiki.simple.vec", "wiki.en.vec", "crawl-300d-2M.vec")


class CompositeEmbedding(TokenEmbedding):
    """Concatenate several embeddings over one vocabulary (reference
    CompositeEmbedding)."""

    def __init__(self, vocabulary, token_embeddings, **kwargs):
        super().__init__(**kwargs)
        embs = (token_embeddings if isinstance(token_embeddings, list)
                else [token_embeddings])
        self._idx_to_token = list(vocabulary.idx_to_token)
        self._token_to_idx = dict(vocabulary.token_to_idx)
        parts = [_gather_rows(emb._idx_to_vec_np, emb.token_to_idx,
                              self._idx_to_token) for emb in embs]
        self._set_idx_to_vec(np.concatenate(parts, axis=1))
