"""mx.contrib.text (reference: python/mxnet/contrib/text/): vocabulary,
token counting, and file-backed token embeddings."""
from . import embedding  # noqa: F401
from . import utils  # noqa: F401
from . import vocab  # noqa: F401
from .utils import count_tokens_from_str  # noqa: F401
from .vocab import Vocabulary  # noqa: F401
