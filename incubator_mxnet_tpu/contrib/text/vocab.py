"""Indexed vocabulary (reference: python/mxnet/contrib/text/vocab.py).

Pure-host data structure: token↔index maps feed Embedding layers /
one_hot on device; nothing here touches the chip.
"""
import collections

__all__ = ["Vocabulary", "TokenIndexMixin"]


class TokenIndexMixin:
    """Shared token↔index semantics for Vocabulary and TokenEmbedding:
    requires ``self._token_to_idx`` / ``self._idx_to_token``; unknown
    tokens map to index 0."""

    def to_indices(self, tokens):
        """Token(s) → index/indices; unknown tokens map to index 0."""
        single = isinstance(tokens, str)
        toks = [tokens] if single else tokens
        idx = [self._token_to_idx.get(t, 0) for t in toks]
        return idx[0] if single else idx

    def to_tokens(self, indices):
        """Index/indices → token(s); raises on out-of-range."""
        single = isinstance(indices, int)
        idxs = [indices] if single else indices
        toks = []
        for i in idxs:
            if not 0 <= i < len(self._idx_to_token):
                raise ValueError(f"token index {i} out of range "
                                 f"[0, {len(self._idx_to_token)})")
            toks.append(self._idx_to_token[i])
        return toks[0] if single else toks


class Vocabulary(TokenIndexMixin):
    """Token index built from a ``collections.Counter``.

    Index 0 is ``unknown_token``; ``reserved_tokens`` (e.g. <pad>, <bos>,
    <eos>) follow, then counted tokens by frequency (ties broken
    alphabetically — the reference's ordering), capped at
    ``most_freq_count`` and filtered by ``min_freq``."""

    def __init__(self, counter=None, most_freq_count=None, min_freq=1,
                 unknown_token="<unk>", reserved_tokens=None):
        if min_freq < 1:
            raise ValueError("min_freq must be >= 1")
        if reserved_tokens is not None:
            if unknown_token in reserved_tokens:
                raise ValueError("unknown_token must not be in "
                                 "reserved_tokens")
            if len(set(reserved_tokens)) != len(reserved_tokens):
                raise ValueError("reserved_tokens must be unique")
        self._unknown_token = unknown_token
        self._reserved_tokens = (list(reserved_tokens) if reserved_tokens
                                 else None)
        self._idx_to_token = [unknown_token] + (self._reserved_tokens or [])
        self._token_to_idx = {t: i for i, t in enumerate(self._idx_to_token)}
        if counter is not None:
            self._index_counter_keys(counter, most_freq_count, min_freq)

    def _index_counter_keys(self, counter, most_freq_count, min_freq):
        assert isinstance(counter, collections.Counter)
        unknown_and_reserved = set(self._idx_to_token)
        pairs = sorted(counter.items(), key=lambda kv: (-kv[1], kv[0]))
        budget = (len(pairs) if most_freq_count is None
                  else most_freq_count)
        for token, freq in pairs:
            if budget <= 0:
                break
            if freq < min_freq:
                break  # sorted by freq: nothing later qualifies
            if token in unknown_and_reserved:
                continue
            self._token_to_idx[token] = len(self._idx_to_token)
            self._idx_to_token.append(token)
            budget -= 1

    def __len__(self):
        return len(self._idx_to_token)

    @property
    def token_to_idx(self):
        return self._token_to_idx

    @property
    def idx_to_token(self):
        return self._idx_to_token

    @property
    def unknown_token(self):
        return self._unknown_token

    @property
    def reserved_tokens(self):
        return self._reserved_tokens
