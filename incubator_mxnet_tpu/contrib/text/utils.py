"""Text token-counting utilities (reference:
python/mxnet/contrib/text/utils.py)."""
import collections
import re

__all__ = ["count_tokens_from_str"]


def count_tokens_from_str(source_str, token_delim=" ", seq_delim="\n",
                          to_lower=False, counter_to_update=None):
    """Count tokens in ``source_str``, splitting on ``token_delim`` and
    ``seq_delim``. Returns (or updates) a ``collections.Counter``."""
    source_str = re.split(
        f"({re.escape(token_delim)})|({re.escape(seq_delim)})", source_str)
    tokens = [t for t in source_str
              if t is not None and t not in (token_delim, seq_delim)
              and t.strip()]
    if to_lower:
        tokens = [t.lower() for t in tokens]
    counter = (counter_to_update if counter_to_update is not None
               else collections.Counter())
    counter.update(tokens)
    return counter
