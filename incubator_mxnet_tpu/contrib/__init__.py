"""mx.contrib namespace (reference parity: python/mxnet/contrib/__init__.py).

Routes to the contrib op families that live with their subsystems:
`contrib.ndarray`/`nd` (box/SSD ops, control flow, attention) and
`contrib.symbol`/`sym` (their symbolic mirrors).
"""
from ..ndarray import contrib as ndarray
from ..ndarray import contrib as nd
from ..symbol import contrib as symbol
from ..symbol import contrib as sym
from . import quantization
from . import text

__all__ = ["ndarray", "nd", "symbol", "sym", "quantization", "text"]
