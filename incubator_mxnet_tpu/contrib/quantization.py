"""Int8 inference quantization (parity: python/mxnet/contrib/quantization.py,
src/operator/quantization/ — quantize/dequantize ops, quantized FC/conv,
min-max calibration, `quantize_model`).

TPU-native design: symmetric int8 with zero-free scales (per-tensor for
activations, per-output-channel for weights), so the
matmul/conv stays a pure integer op the MXU consumes directly
(`lax.dot_general` / `conv_general_dilated` with
`preferred_element_type=int32`) and the single fp rescale at the end fuses
into neighbouring elementwise work. The reference's asymmetric uint8 path
(zero-points, per-op requantize kernels) targets x86 VNNI; on TPU the
symmetric form is both simpler and faster, and calibration only has to
find one |max| per tensor.

Modes, mirroring the reference's `quantize_model` API surface:
- no calibration: activation ranges computed per batch on device (dynamic);
- 'naive' calibration: run calib batches through the fp32 net, record each
  quantized layer's input |max|, bake static scales (no per-batch reduce);
- 'entropy' calibration: per-layer KL-optimal clip thresholds over the
  observed |activation| distribution (the reference's
  _get_optimal_threshold), clipping rare outliers for finer in-range
  resolution.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..gluon.parameter import DeferredInitializationError
from ..ndarray import NDArray, _apply
from ..ops import _raw as _rawops
from .. import gluon

__all__ = ["quantize", "dequantize", "quantize_v2",
           "QuantizedDense", "QuantizedConv2D",
           "quantize_net", "quantize_model"]


_QTYPES = {"int8": (jnp.int8, 127.0), "uint8": (jnp.uint8, 255.0)}


def _q_raw(x, min_r, max_r, qtype):
    dt, qmax = _QTYPES[qtype]
    if qtype == "int8":
        # eps guard: a constant-zero tensor quantizes to zeros, not NaN
        scale = qmax / jnp.maximum(jnp.maximum(jnp.abs(min_r),
                                               jnp.abs(max_r)), 1e-12)
        q = jnp.clip(jnp.rint(x * scale), -qmax, qmax).astype(dt)
    else:
        scale = qmax / jnp.maximum(max_r - min_r, 1e-12)
        q = jnp.clip(jnp.rint((x - min_r) * scale), 0, qmax).astype(dt)
    return q


def _dq_raw(q, min_r, max_r):
    if q.dtype == jnp.uint8:
        return q.astype(jnp.float32) * ((max_r - min_r) / 255.0) + min_r
    scale = jnp.maximum(jnp.maximum(jnp.abs(min_r), jnp.abs(max_r)),
                        1e-12) / 127.0
    return q.astype(jnp.float32) * scale


def quantize(data, min_range, max_range, out_type="int8"):
    """(q, min, max) = contrib.quantize(data, min, max) — reference
    src/operator/quantization/quantize.cc. int8 is symmetric (scale =
    127/|max|), uint8 affine."""
    if out_type not in _QTYPES:
        raise ValueError(f"out_type must be int8/uint8, got {out_type!r}")
    q = _apply(lambda x, lo, hi: _q_raw(x, lo, hi, out_type),
               [data, _as_nd(min_range), _as_nd(max_range)],
               name="quantize")
    return q, _as_nd(min_range), _as_nd(max_range)


def dequantize(data, min_range, max_range):
    """Reference src/operator/quantization/dequantize.cc."""
    return _apply(_dq_raw, [data, _as_nd(min_range), _as_nd(max_range)],
                  name="dequantize")


def quantize_v2(data, min_calib_range=None, max_calib_range=None,
                out_type="int8"):
    """Quantize with auto range when no calibration is given (reference
    quantize_v2.cc). Returns (q, min, max)."""
    if min_calib_range is None or max_calib_range is None:
        mn = float(jnp.min(data._data))
        mx_ = float(jnp.max(data._data))
    else:
        mn, mx_ = float(min_calib_range), float(max_calib_range)
    return quantize(data, mn, mx_, out_type)


def _as_nd(v):
    if isinstance(v, NDArray):
        return v
    return NDArray(jnp.asarray(v, jnp.float32))


# ---------------------------------------------------------------------------
# quantized layers
# ---------------------------------------------------------------------------

def _int8_pair(x_f32, a_scale):
    """fp32 -> int8 with the given symmetric scale (jax-level)."""
    return jnp.clip(jnp.rint(x_f32 * a_scale), -127, 127).astype(jnp.int8)


class QuantizedDense(gluon.nn.HybridBlock):
    """Int8 Dense (reference quantized_fully_connected.cc): weights are
    quantized ONCE at wrap time with PER-OUTPUT-CHANNEL scales (reference
    channel-wise quantization), activations per batch (dynamic) or with a
    baked calib scale. Accumulates in int32 on the MXU, one fp rescale."""

    def __init__(self, dense, prefix=None, params=None):
        super().__init__(prefix, params)
        # device-resident from the start (no per-forward host->device copy);
        # the fp32 source layer is deliberately NOT kept — int8 replaces it
        w = dense.weight.data()._data.astype(jnp.float32)   # (out, in)
        amax = jnp.maximum(jnp.max(jnp.abs(w), axis=1), 1e-12)
        self._w_scale = (127.0 / amax).astype(jnp.float32)       # (out,)
        self._qw = _int8_pair(w, self._w_scale[:, None])
        self._bias = (None if dense.bias is None
                      else dense.bias.data()._data.astype(jnp.float32))
        self._flatten = dense._flatten
        self._act = dense.act
        self.calib_max = None            # set by calibration

    def forward(self, x):
        qw, w_scale = self._qw, self._w_scale
        bias, act, flatten = self._bias, self._act, self._flatten
        calib = self.calib_max

        def fn(xr):
            xf = xr.astype(jnp.float32)
            if flatten and xf.ndim > 2:
                xf = xf.reshape(xf.shape[0], -1)
            amax = (jnp.float32(calib) if calib is not None
                    else jnp.maximum(jnp.max(jnp.abs(xf)), 1e-12))
            a_scale = 127.0 / amax
            qx = _int8_pair(xf, a_scale)
            acc = jax.lax.dot_general(
                qx, qw, (((qx.ndim - 1,), (1,)), ((), ())),
                preferred_element_type=jnp.int32)
            y = acc.astype(jnp.float32) / (a_scale * w_scale)
            if bias is not None:
                y = y + bias
            if act:
                y = _rawops.activation(y, act)
            return y

        return _apply(fn, [x], name="quantized_dense")


class QuantizedConv2D(gluon.nn.HybridBlock):
    """Int8 2D convolution (reference quantized_conv.cc): int8×int8→int32
    via conv_general_dilated, symmetric per-tensor scales."""

    def __init__(self, conv, prefix=None, params=None):
        super().__init__(prefix, params)
        w = conv.weight.data()._data.astype(jnp.float32)
        # per-output-channel scales; O axis is 0 for OIHW (NCHW layouts),
        # last for HWIO (channels-last layouts)
        o_axis = 0 if conv._layout.startswith("NC") else w.ndim - 1
        red = tuple(a for a in range(w.ndim) if a != o_axis)
        amax = jnp.maximum(jnp.max(jnp.abs(w), axis=red), 1e-12)
        self._w_scale = (127.0 / amax).astype(jnp.float32)     # (O,)
        bshape = [1] * w.ndim
        bshape[o_axis] = w.shape[o_axis]
        self._qw = _int8_pair(w, self._w_scale.reshape(bshape))
        self._bias = (None if conv.bias is None
                      else conv.bias.data()._data.astype(jnp.float32))
        self._stride = conv._stride
        self._pad = conv._pad
        self._dilate = conv._dilate
        self._groups = conv._groups
        self._layout = conv._layout
        self._act = conv.act
        self.calib_max = None

    def forward(self, x):
        qw, w_scale = self._qw, self._w_scale
        bias, act = self._bias, self._act
        stride, pad, dilate = self._stride, self._pad, self._dilate
        groups, layout = self._groups, self._layout
        calib = self.calib_max

        def fn(xr):
            xf = xr.astype(jnp.float32)
            amax = (jnp.float32(calib) if calib is not None
                    else jnp.maximum(jnp.max(jnp.abs(xf)), 1e-12))
            a_scale = 127.0 / amax
            qx = _int8_pair(xf, a_scale)
            dn = _rawops._conv_dn(qx.ndim, layout)
            acc = jax.lax.conv_general_dilated(
                qx, qw,
                window_strides=tuple(stride),
                padding=[(p, p) for p in pad],
                rhs_dilation=tuple(dilate),
                dimension_numbers=dn,
                feature_group_count=groups,
                preferred_element_type=jnp.int32)
            ws = w_scale
            if not layout.endswith("C"):           # NCHW...: C is axis 1
                ws = ws.reshape((1, -1) + (1,) * (acc.ndim - 2))
            y = acc.astype(jnp.float32) / (a_scale * ws)
            if bias is not None:
                if layout.endswith("C"):
                    y = y + bias
                else:
                    y = y + bias.reshape((1, -1) + (1,) * (y.ndim - 2))
            if act:
                y = _rawops.activation(y, act)
            return y

        return _apply(fn, [x], name="quantized_conv2d")


# ---------------------------------------------------------------------------
# net transformation + calibration
# ---------------------------------------------------------------------------

def _wrap(block):
    if isinstance(block, gluon.nn.Dense):
        return QuantizedDense(block)
    if isinstance(block, gluon.nn.Conv2D):
        return QuantizedConv2D(block)
    return None


def _entropy_threshold(samples, num_bins=2048, num_quantized_bins=255):
    """KL-divergence-optimal clip threshold over |activation| samples
    (reference _get_optimal_threshold, python/mxnet/contrib/quantization.py):
    slide the clip point, compare the clipped distribution P against its
    num_quantized_bins quantization Q, keep the threshold minimizing
    KL(P||Q). Clips rare outliers so the int8 grid spends its codes where
    the mass is."""
    import numpy as _np
    samples = _np.abs(_np.asarray(samples, _np.float64).ravel())
    amax = float(samples.max()) if samples.size else 0.0
    if amax <= 0.0:
        return 1e-12
    hist, edges = _np.histogram(samples, bins=num_bins, range=(0.0, amax))
    hist = hist.astype(_np.float64)

    def smooth(d, eps=1e-4):
        """Reference _smooth_distribution: move eps mass onto zero bins so
        KL stays finite without hard skip rules."""
        is_zero = d == 0
        n_zero = int(is_zero.sum())
        n_nonzero = d.size - n_zero
        if n_nonzero == 0 or n_zero == 0:
            return d
        out = d.copy()
        out[is_zero] = eps
        out[~is_zero] -= eps * n_zero / n_nonzero
        return out

    best_kl, best_i = _np.inf, num_bins
    for i in range(num_quantized_bins, num_bins + 1):
        sliced = hist[:i]
        p = sliced.copy()
        p[i - 1] += hist[i:].sum()            # outliers clip into the edge
        if p.sum() == 0:
            continue
        # q: the SLICED (pre-clip) distribution quantized to
        # num_quantized_bins and expanded back — clipped outlier mass
        # lives in p but not q, so aggressive clipping raises KL (the
        # reference's construction). Vectorized: per-chunk sums and
        # nonzero counts via reduceat, expanded with repeat.
        bounds = (_np.arange(num_quantized_bins) * i) // num_quantized_bins
        bounds = _np.unique(bounds)
        sizes = _np.diff(_np.append(bounds, i))
        nzmask = sliced > 0
        sums = _np.add.reduceat(sliced, bounds)
        nzcnt = _np.add.reduceat(nzmask.astype(_np.float64), bounds)
        avg = _np.where(nzcnt > 0, sums / _np.maximum(nzcnt, 1.0), 0.0)
        q = _np.repeat(avg, sizes) * nzmask
        if q.sum() == 0:
            continue
        p_s = smooth(p)                       # smooth raw counts, like ref
        q_s = smooth(q)
        p_n = p_s / p_s.sum()
        q_n = q_s / q_s.sum()
        kl = float(_np.sum(p_n * _np.log(p_n / q_n)))
        if _np.isfinite(kl) and kl < best_kl:
            best_kl, best_i = kl, i
    return float(edges[best_i])


def _clear_hybrid_caches(block):
    """Drop every HybridBlock's traced-graph cache in the tree: a cached
    fp32 CachedOp would otherwise keep serving the OLD graph after layers
    are swapped (and would bypass calibration pre-hooks)."""
    if hasattr(block, "_cache"):
        block._cache = {}
    for child in block._children.values():
        _clear_hybrid_caches(child)


def quantize_net(net, calib_data=None, exclude=(), calib_mode=None):
    """Replace every Dense/Conv2D in `net` (in place, recursively) with its
    int8 twin; with `calib_data` (an iterable of input batches) run a
    calibration pass first so activation scales are baked static.
    calib_mode='naive' records each layer's |max| (reference
    `quantize_model(..., calib_mode='naive')`); 'entropy' collects
    |activation| samples and picks the KL-optimal clip threshold per layer
    (reference calib_mode='entropy'), trading rare-outlier fidelity for
    finer resolution where the mass is. Blocks in `exclude` (by
    reference) are left fp32. Returns `net`.

    Works on hybridized nets too: traced-graph caches are cleared so both
    the calibration pass and the quantized net retrace. Deferred-shape
    nets must have run one forward (or provide calib_data, whose first
    batch completes the deferred init).
    """
    targets = []            # (parent, name, child)

    def collect(parent):
        for name, child in list(parent._children.items()):
            if child in exclude:
                continue
            if isinstance(child, (QuantizedDense, QuantizedConv2D)):
                continue                       # idempotent re-entry
            if isinstance(child, (gluon.nn.Dense, gluon.nn.Conv2D)):
                targets.append((parent, name, child))
            else:
                collect(child)

    if calib_mode is not None and calib_data is None:
        raise ValueError(
            f"calib_mode={calib_mode!r} needs calib_data; omit both for "
            f"dynamic per-batch ranges")
    if calib_mode is None:
        calib_mode = "naive"
    if calib_mode not in ("naive", "entropy"):
        raise ValueError(f"calib_mode must be 'naive' or 'entropy', "
                         f"got {calib_mode!r}")
    collect(net)
    if not targets:
        raise ValueError("no quantizable (Dense/Conv2D) layers found")
    # validate BEFORE any mutation so a failure cannot leave the net
    # half-quantized
    for _, _, child in targets:
        try:
            child.weight.data()
        except DeferredInitializationError:
            raise ValueError(
                f"layer {child!r} has uninitialized (deferred) shapes; run "
                f"one forward pass (or pass calib_data through the full "
                f"net) before quantize_net")
    _clear_hybrid_caches(net)   # hooks must fire; fp32 trace is stale soon

    ranges = None
    if calib_data is not None:
        import numpy as _np
        ranges = {id(c): 0.0 for _, _, c in targets}
        samples = {id(c): [] for _, _, c in targets}
        hooked = []
        # calibration must run EAGERLY: a hybridized (traced) forward would
        # hand the hooks abstract tracers with no values to record
        deactivated = []

        def deactivate(b):
            if getattr(b, "_active", False):
                deactivated.append(b)
                b._active = False
            for c in b._children.values():
                deactivate(c)

        deactivate(net)
        try:
            for _, _, child in targets:
                def mk(cid):
                    def pre_hook(block, inputs):
                        x = inputs[0]
                        m = float(jnp.max(jnp.abs(x._data)))
                        ranges[cid] = max(ranges[cid], m)
                        if calib_mode == "entropy":
                            held = sum(c.size for c in samples[cid])
                            if held >= 512 * 1024:
                                return      # per-layer TOTAL cap: histogram
                            flat = _np.abs(_np.asarray(x._data).ravel())
                            if flat.size > 65536:   # per-batch cap
                                flat = flat[_np.random.RandomState(0)
                                            .choice(flat.size, 65536,
                                                    replace=False)]
                            samples[cid].append(flat.astype(_np.float32))
                    return pre_hook
                child.register_forward_pre_hook(mk(id(child)))
                hooked.append(child)
            for batch in calib_data:
                net(batch if isinstance(batch, NDArray) else NDArray(batch))
        finally:
            for child in hooked:            # calibration hooks are one-shot
                child._forward_pre_hooks.pop()
            for b in deactivated:
                b._active = True
        if calib_mode == "entropy":
            for cid, chunks in samples.items():
                if chunks and ranges[cid] > 0.0:
                    ranges[cid] = _entropy_threshold(
                        _np.concatenate(chunks))

    for parent, name, child in targets:
        wrapped = _wrap(child)
        if ranges is not None:
            if ranges[id(child)] > 0.0:
                wrapped.calib_max = ranges[id(child)]
            else:
                # layer never saw calibration data (conditional branch /
                # aux head): fall back to dynamic ranges rather than bake
                # a garbage scale
                import logging
                logging.getLogger(__name__).warning(
                    "quantize_net: %r received no calibration data; using "
                    "dynamic per-batch activation ranges for it", child)
        parent._children[name] = wrapped
        if getattr(parent, name, None) is child:
            object.__setattr__(parent, name, wrapped)
    _clear_hybrid_caches(net)   # force retrace onto the int8 graph
    return net


def quantize_model(sym_or_net, calib_data=None, **kwargs):
    """Reference-name alias: upstream `contrib.quantization.quantize_model`
    takes a Symbol+params triple; the gluon-first equivalent here takes a
    net (see MIGRATION.md). A dict where calib_data belongs means the call
    site still passes the reference's arg_params — fail fast with
    guidance instead of iterating parameter names as batches."""
    if isinstance(calib_data, dict):
        raise TypeError(
            "quantize_model(net, arg_params, ...) is the reference Symbol "
            "signature; here pass a gluon net and calib_data=[batches] — "
            "see MIGRATION.md 'Int8 quantization'")
    return quantize_net(sym_or_net, calib_data=calib_data, **kwargs)
