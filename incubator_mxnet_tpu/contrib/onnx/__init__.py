"""ONNX model interchange: export (mx2onnx) and import (onnx2mx).

Reference parity: ``python/mxnet/contrib/onnx/`` — ``mx2onnx/export_model.py``
(symbol+params -> ModelProto) and ``onnx2mx/import_model.py``
(ModelProto -> symbol, arg_params, aux_params). The reference serializes
through the pip ``onnx`` package; that package is not in this image, so this
module carries the public ONNX IR schema (``onnx_ir.proto``, field numbers
identical to upstream onnx.proto3) and uses protoc-generated bindings.
Files written here load in stock onnx/onnxruntime and vice versa.

TPU-first note: the exporter works on the *symbol graph*, which in this
framework is the single serialization format for every frontend (Gluon
HybridBlock export, Module checkpoints) — so one graph walker covers all
model families. Layout must be NCHW (ONNX's convention); NHWC graphs
(the TPU-preferred layout of the model zoo) are rejected with a clear
error rather than silently transposed.

Supported op surface (opset 13): Convolution/Deconvolution, Pooling
(incl. global), BatchNorm, FullyConnected, Activation, LeakyReLU/ELU/SELU,
Dropout, Flatten, Reshape, transpose, expand_dims, squeeze, slice_axis,
Concat, add_n, Embedding, softmax/log_softmax/SoftmaxOutput, elementwise
and broadcast arithmetic, scalar arithmetic, clip, sum/mean/max/min
reductions, and the common unary math ops — enough for every CNN in the
model zoo plus MLP/embedding models.
"""
import json
import os
import sys

import numpy as np

_HERE = os.path.dirname(__file__)
if _HERE not in sys.path:  # protoc gencode does a top-level sibling import
    sys.path.insert(0, _HERE)
from . import onnx_ir_pb2 as P  # noqa: E402

__all__ = ["export_model", "import_model", "get_model_metadata",
           "import_to_gluon"]

_ONNX_OPSET = 13
_IR_VERSION = 8

# numpy dtype -> TensorProto.DataType
_NP2ONNX = {
    np.dtype("float32"): P.TensorProto.FLOAT,
    np.dtype("float64"): P.TensorProto.DOUBLE,
    np.dtype("float16"): P.TensorProto.FLOAT16,
    np.dtype("uint8"): P.TensorProto.UINT8,
    np.dtype("int8"): P.TensorProto.INT8,
    np.dtype("int32"): P.TensorProto.INT32,
    np.dtype("int64"): P.TensorProto.INT64,
    np.dtype("bool"): P.TensorProto.BOOL,
}
_ONNX2NP = {v: k for k, v in _NP2ONNX.items()}


def _np_to_tensor(name, arr):
    arr = np.ascontiguousarray(arr)
    if arr.dtype not in _NP2ONNX:  # bfloat16 etc. -> float32
        arr = arr.astype(np.float32)
    t = P.TensorProto(name=name, data_type=_NP2ONNX[arr.dtype])
    t.dims.extend(arr.shape)
    t.raw_data = arr.astype(arr.dtype.newbyteorder("<")).tobytes()
    return t


def _tensor_to_np(t):
    if t.data_type not in _ONNX2NP:
        raise ValueError("unsupported ONNX tensor dtype %d" % t.data_type)
    dt = _ONNX2NP[t.data_type].newbyteorder("<")
    shape = tuple(t.dims)
    if t.raw_data:
        return np.frombuffer(t.raw_data, dtype=dt).reshape(shape).copy()
    if t.data_type == P.TensorProto.FLOAT:
        return np.asarray(t.float_data, np.float32).reshape(shape)
    if t.data_type == P.TensorProto.DOUBLE:
        return np.asarray(t.double_data, np.float64).reshape(shape)
    if t.data_type == P.TensorProto.INT64:
        return np.asarray(t.int64_data, np.int64).reshape(shape)
    if t.data_type in (P.TensorProto.INT32, P.TensorProto.INT8,
                       P.TensorProto.UINT8, P.TensorProto.BOOL,
                       P.TensorProto.FLOAT16):
        raw = np.asarray(t.int32_data, np.int32)
        return raw.astype(_ONNX2NP[t.data_type]).reshape(shape)
    raise ValueError("empty tensor %r" % t.name)


def _vi(name, shape, elem_type=P.TensorProto.FLOAT):
    v = P.ValueInfoProto(name=name)
    tt = v.type.tensor_type
    tt.elem_type = elem_type
    if shape is not None:
        for d in shape:
            dim = tt.shape.dim.add()
            dim.dim_value = int(d)
    # unknown shape: leave the shape field unset (unknown rank); an empty
    # TensorShapeProto would wrongly claim a scalar
    return v


# ---------------------------------------------------------------------------
# Export (mx2onnx)
# ---------------------------------------------------------------------------

class _Exporter:
    def __init__(self, graph_json, params, opset, np_dtype=np.float32,
                 input_shapes=None):
        self.nodes = graph_json["nodes"]
        self.heads = graph_json["heads"]
        self.params = params
        self.opset = opset
        self.np_dtype = np.dtype(np_dtype)  # the graph's tensor dtype
        self.g = P.GraphProto()
        self.names = {}          # (node_idx, out_idx) -> tensor name
        self.emitted_inits = set()
        self.used_inputs = []    # graph-input var names in consumption order
        self.shapes = {}         # (node_idx, out_idx) -> tuple | missing
        if input_shapes is not None:
            self._annotate_shapes(list(input_shapes))

    def _annotate_shapes(self, input_shapes):
        """Static per-node output shapes via jax.eval_shape over the
        registered op runtimes (abstract — nothing computes). Shape-
        dependent exporters (attention decomposition, Slice ends,
        Transpose perms) read self.shapes; ops eval_shape can't handle
        (e.g. pure_callback customs) just leave gaps."""
        import jax

        from ...symbol import _OPS, _Runtime

        rt = _Runtime(False, jax.random.PRNGKey(0))
        specs = {}
        # input_shape entries map to DATA inputs; label-like variables
        # (dropped by exporters like SoftmaxOutput) must not steal a
        # shape from a later real input
        null_names = [n["name"] for n in self.nodes
                      if n["op"] == "null" and n["name"] not in self.params]
        data_names = [n for n in null_names
                      if not (n == "label" or n.endswith("_label"))]
        if len(input_shapes) < len(data_names):
            raise ValueError(
                "model has %d data inputs %r but input_shape has %d "
                "entries" % (len(data_names), data_names,
                             len(input_shapes)))
        assign = dict(zip(data_names, input_shapes))
        # canonical name->shape map (declaration order, matching the
        # documented input_shape contract); export_model reads this so the
        # emitted input value_infos can never disagree with the shape pass
        # on multi-input graphs whose consumption order differs
        self.input_shape_assign = {k: tuple(v) for k, v in assign.items()}
        for idx, node in enumerate(self.nodes):
            try:
                if node["op"] == "null":
                    name = node["name"]
                    if name in self.params:
                        arr = self.params[name]
                        a_np = (arr.asnumpy() if hasattr(arr, "asnumpy")
                                else np.asarray(arr))
                        spec = jax.ShapeDtypeStruct(a_np.shape, a_np.dtype)
                    elif name in assign:
                        spec = jax.ShapeDtypeStruct(tuple(assign[name]),
                                                    self.np_dtype)
                    else:
                        continue  # label/unknown input: no shape
                    specs[(idx, 0)] = spec
                    continue
                od = _OPS[node["op"]]
                ins = [specs[(i, o)] for i, o in node["inputs"]]
                attrs = node.get("attrs") or {}
                out = jax.eval_shape(
                    lambda *raws: od.fn(rt, attrs, *raws), *ins)
                outs = out if isinstance(out, (list, tuple)) else (out,)
                for o, s in enumerate(outs):
                    specs[(idx, o)] = s
            except Exception:  # noqa: BLE001 — gaps are allowed
                continue
        self.shapes = {k: tuple(v.shape) for k, v in specs.items()}

    # -- helpers ------------------------------------------------------------
    def shape_of(self, node_idx, out_idx=0):
        s = self.shapes.get((node_idx, out_idx))
        if s is None:
            raise NotImplementedError(
                "ONNX export of %r needs static shape inference for node "
                "%r, which was unavailable (pass input_shape to "
                "export_model, and check the op's runtime is "
                "eval_shape-able)" % (self.nodes[node_idx]["op"],
                                      self.nodes[node_idx]["name"]))
        return s

    def name_of(self, node_idx, out_idx=0):
        return self.names[(node_idx, out_idx)]

    def in_names(self, node):
        return [self.name_of(i, o) for i, o in node["inputs"]]

    def add_node(self, op_type, inputs, outputs, name, **attrs):
        n = self.g.node.add(op_type=op_type, name=name)
        n.input.extend(inputs)
        n.output.extend(outputs)
        for k, v in attrs.items():
            if v is None:
                continue
            a = n.attribute.add(name=k)
            if isinstance(v, float):
                a.type = P.AttributeProto.FLOAT
                a.f = v
            elif isinstance(v, bool) or isinstance(v, int):
                a.type = P.AttributeProto.INT
                a.i = int(v)
            elif isinstance(v, str):
                a.type = P.AttributeProto.STRING
                a.s = v.encode()
            elif isinstance(v, (list, tuple)):
                if v and isinstance(v[0], float):
                    a.type = P.AttributeProto.FLOATS
                    a.floats.extend(v)
                else:
                    a.type = P.AttributeProto.INTS
                    a.ints.extend(int(x) for x in v)
            else:
                raise TypeError("attr %s=%r" % (k, v))
        return n

    def add_init(self, name, arr):
        if name not in self.emitted_inits:
            self.g.initializer.append(_np_to_tensor(name, np.asarray(arr)))
            self.emitted_inits.add(name)
        return name

    def var_used(self, node_idx):
        """Mark a null node as consumed: param -> initializer, else input."""
        node = self.nodes[node_idx]
        name = node["name"]
        if name in self.params:
            self.add_init(name, self.params[name].asnumpy()
                          if hasattr(self.params[name], "asnumpy")
                          else self.params[name])
        elif name not in self.used_inputs:
            self.used_inputs.append(name)
        return name

    # -- conversion ---------------------------------------------------------
    def run(self):
        for idx, node in enumerate(self.nodes):
            op = node["op"]
            name = node["name"]
            if op == "null":
                self.names[(idx, 0)] = name
                continue
            fn = _EXPORTERS.get(op)
            if fn is None:
                raise NotImplementedError(
                    "ONNX export: unsupported op %r (node %r). Supported: %s"
                    % (op, name, ", ".join(sorted(_EXPORTERS))))
            # mark consumed variable inputs (the handler may drop some,
            # e.g. SoftmaxOutput's label — handlers call var_used themselves
            # via resolve())
            fn(self, idx, node)
        return self.g

    def resolve(self, node, positions=None):
        """Tensor names for a node's inputs, registering consumed vars."""
        ins = node["inputs"]
        if positions is not None:
            ins = [ins[p] for p in positions if p < len(ins)]
        out = []
        for i, o in ins:
            if self.nodes[i]["op"] == "null":
                out.append(self.var_used(i))
            else:
                out.append(self.name_of(i, o))
        return out


_EXPORTERS = {}


def _export(*ops):
    def deco(fn):
        for op in ops:
            _EXPORTERS[op] = fn
        return fn
    return deco


def _sym_pads(pad, ndim):
    pad = tuple(pad or (0,) * ndim)
    return list(pad) + list(pad)


@_export("Convolution")
def _exp_conv(ex, idx, node):
    a = node["attrs"]
    if (a.get("layout") or "NCHW") not in ("NCHW", "NCW", "NCDHW"):
        raise NotImplementedError(
            "ONNX export requires NCHW layout (got %s); rebuild the model "
            "with layout='NCHW'" % a["layout"])
    k = tuple(a["kernel"])
    ex.add_node("Conv", ex.resolve(node), [node["name"]], node["name"],
                kernel_shape=list(k),
                strides=list(a.get("stride") or (1,) * len(k)),
                dilations=list(a.get("dilate") or (1,) * len(k)),
                pads=_sym_pads(a.get("pad"), len(k)),
                group=int(a.get("num_group", 1)))
    ex.names[(idx, 0)] = node["name"]


@_export("Deconvolution")
def _exp_deconv(ex, idx, node):
    a = node["attrs"]
    if (a.get("layout") or "NCHW") != "NCHW":
        raise NotImplementedError("ONNX export requires NCHW layout")
    k = tuple(a["kernel"])
    kw = dict(kernel_shape=list(k),
              strides=list(a.get("stride") or (1,) * len(k)),
              dilations=list(a.get("dilate") or (1,) * len(k)),
              pads=_sym_pads(a.get("pad"), len(k)),
              group=int(a.get("num_group", 1)))
    if a.get("adj"):
        kw["output_padding"] = list(a["adj"])
    ex.add_node("ConvTranspose", ex.resolve(node), [node["name"]],
                node["name"], **kw)
    ex.names[(idx, 0)] = node["name"]


@_export("FullyConnected")
def _exp_fc(ex, idx, node):
    a = node["attrs"]
    ins = ex.resolve(node)
    data = ins[0]
    if a.get("flatten", True):
        flat = node["name"] + "_flat"
        ex.add_node("Flatten", [data], [flat], flat, axis=1)
        data = flat
    ex.add_node("Gemm", [data] + ins[1:], [node["name"]], node["name"],
                alpha=1.0, beta=1.0, transA=0, transB=1)
    ex.names[(idx, 0)] = node["name"]


@_export("Pooling")
def _exp_pool(ex, idx, node):
    a = node["attrs"]
    if (a.get("layout") or "NCHW") not in ("NCHW", "NCW", "NCDHW"):
        raise NotImplementedError("ONNX export requires NCHW layout")
    ptype = a.get("pool_type", "max")
    ins = ex.resolve(node)
    if a.get("global_pool"):
        op = {"max": "GlobalMaxPool", "avg": "GlobalAveragePool"}.get(ptype)
        if op is None:
            raise NotImplementedError("global %s pooling" % ptype)
        ex.add_node(op, ins, [node["name"]], node["name"])
    else:
        k = tuple(a.get("kernel", (2, 2)))
        kw = dict(kernel_shape=list(k),
                  strides=list(a.get("stride") or k),
                  pads=_sym_pads(a.get("pad"), len(k)),
                  ceil_mode=int(bool(a.get("ceil_mode", False))))
        if ptype == "max":
            op = "MaxPool"
        elif ptype == "avg":
            op = "AveragePool"
            kw["count_include_pad"] = int(bool(a.get("count_include_pad",
                                                     True)))
        else:
            raise NotImplementedError("pool_type=%s" % ptype)
        ex.add_node(op, ins, [node["name"]], node["name"], **kw)
    ex.names[(idx, 0)] = node["name"]


_ACT2ONNX = {"relu": "Relu", "sigmoid": "Sigmoid", "tanh": "Tanh",
             "softrelu": "Softplus", "softsign": "Softsign"}


@_export("Activation")
def _exp_act(ex, idx, node):
    act = node["attrs"].get("act_type", "relu")
    if act not in _ACT2ONNX:
        raise NotImplementedError("Activation act_type=%s" % act)
    ex.add_node(_ACT2ONNX[act], ex.resolve(node), [node["name"]],
                node["name"])
    ex.names[(idx, 0)] = node["name"]


@_export("LeakyReLU")
def _exp_leaky(ex, idx, node):
    a = node["attrs"]
    act = a.get("act_type", "leaky")
    slope = float(a.get("slope", 0.25))
    ins = ex.resolve(node)
    if act == "leaky":
        ex.add_node("LeakyRelu", ins, [node["name"]], node["name"],
                    alpha=slope)
    elif act == "elu":
        ex.add_node("Elu", ins, [node["name"]], node["name"], alpha=slope)
    elif act == "selu":
        ex.add_node("Selu", ins, [node["name"]], node["name"])
    else:
        raise NotImplementedError("LeakyReLU act_type=%s" % act)
    ex.names[(idx, 0)] = node["name"]


@_export("BatchNorm")
def _exp_bn(ex, idx, node):
    a = node["attrs"]
    ins = ex.resolve(node)
    if a.get("fix_gamma", False):
        gname = ins[1]
        if gname in ex.params:
            gamma = ex.params[gname]
            gamma = gamma.asnumpy() if hasattr(gamma, "asnumpy") else gamma
            # fix_gamma pins gamma to 1 at run time (reference batch_norm.cc
            # semantics); bake that into the exported initializer
            for t in ex.g.initializer:
                if t.name == gname:
                    t.CopyFrom(_np_to_tensor(gname, np.ones_like(gamma)))
    ex.add_node("BatchNormalization", ins, [node["name"]], node["name"],
                epsilon=float(a.get("eps", 1e-5)),
                momentum=float(a.get("momentum", 0.9)))
    ex.names[(idx, 0)] = node["name"]
    # outputs 1/2 (updated moving stats) exist only in training graphs;
    # consuming them in an exported inference graph is an error caught by
    # name_of raising KeyError.


@_export("Flatten")
def _exp_flatten(ex, idx, node):
    ex.add_node("Flatten", ex.resolve(node), [node["name"]], node["name"],
                axis=1)
    ex.names[(idx, 0)] = node["name"]


@_export("Reshape")
def _exp_reshape(ex, idx, node):
    shape = tuple(node["attrs"]["shape"])
    if any(s in (-2, -3, -4) for s in shape):
        raise NotImplementedError("Reshape specials -2/-3/-4 have no ONNX "
                                  "equivalent")
    sname = ex.add_init(node["name"] + "_shape",
                        np.asarray(shape, np.int64))
    ex.add_node("Reshape", ex.resolve(node) + [sname], [node["name"]],
                node["name"])
    ex.names[(idx, 0)] = node["name"]


@_export("transpose")
def _exp_transpose(ex, idx, node):
    axes = node["attrs"].get("axes")
    kw = {"perm": list(axes)} if axes else {}
    ex.add_node("Transpose", ex.resolve(node), [node["name"]], node["name"],
                **kw)
    ex.names[(idx, 0)] = node["name"]


@_export("expand_dims")
def _exp_expand(ex, idx, node):
    aname = ex.add_init(node["name"] + "_axes",
                        np.asarray([node["attrs"]["axis"]], np.int64))
    ex.add_node("Unsqueeze", ex.resolve(node) + [aname], [node["name"]],
                node["name"])
    ex.names[(idx, 0)] = node["name"]


@_export("squeeze")
def _exp_squeeze(ex, idx, node):
    axis = node["attrs"].get("axis")
    ins = ex.resolve(node)
    if axis is not None:
        axes = [axis] if isinstance(axis, int) else list(axis)
        ins = ins + [ex.add_init(node["name"] + "_axes",
                                 np.asarray(axes, np.int64))]
    ex.add_node("Squeeze", ins, [node["name"]], node["name"])
    ex.names[(idx, 0)] = node["name"]


@_export("slice_axis")
def _exp_slice(ex, idx, node):
    a = node["attrs"]
    end = a.get("end")
    end = np.iinfo(np.int64).max if end is None else end
    ins = ex.resolve(node) + [
        ex.add_init(node["name"] + "_starts",
                    np.asarray([a["begin"]], np.int64)),
        ex.add_init(node["name"] + "_ends", np.asarray([end], np.int64)),
        ex.add_init(node["name"] + "_axes",
                    np.asarray([a["axis"]], np.int64))]
    ex.add_node("Slice", ins, [node["name"]], node["name"])
    ex.names[(idx, 0)] = node["name"]


@_export("Concat")
def _exp_concat(ex, idx, node):
    ex.add_node("Concat", ex.resolve(node), [node["name"]], node["name"],
                axis=int(node["attrs"].get("dim", 1)))
    ex.names[(idx, 0)] = node["name"]


@_export("add_n")
def _exp_addn(ex, idx, node):
    ex.add_node("Sum", ex.resolve(node), [node["name"]], node["name"])
    ex.names[(idx, 0)] = node["name"]


@_export("Embedding")
def _exp_embedding(ex, idx, node):
    ins = ex.resolve(node)  # (indices, weight)
    cast = node["name"] + "_idx64"
    ex.add_node("Cast", [ins[0]], [cast], cast, to=int(P.TensorProto.INT64))
    ex.add_node("Gather", [ins[1], cast], [node["name"]], node["name"],
                axis=0)
    ex.names[(idx, 0)] = node["name"]


@_export("softmax")
def _exp_softmax(ex, idx, node):
    ex.add_node("Softmax", ex.resolve(node), [node["name"]], node["name"],
                axis=int(node["attrs"].get("axis", -1)))
    ex.names[(idx, 0)] = node["name"]


@_export("log_softmax")
def _exp_log_softmax(ex, idx, node):
    ex.add_node("LogSoftmax", ex.resolve(node), [node["name"]],
                node["name"], axis=int(node["attrs"].get("axis", -1)))
    ex.names[(idx, 0)] = node["name"]


@_export("SoftmaxOutput")
def _exp_softmax_output(ex, idx, node):
    # inference export: softmax over the class axis; the label input is
    # dropped (reference mx2onnx does the same)
    ins = ex.resolve(node, positions=[0])
    ex.add_node("Softmax", ins, [node["name"]], node["name"], axis=1)
    ex.names[(idx, 0)] = node["name"]


@_export("Dropout")
def _exp_dropout(ex, idx, node):
    # inference graph: identity (ONNX Dropout in eval mode is identity too)
    ex.add_node("Identity", ex.resolve(node, positions=[0]),
                [node["name"]], node["name"])
    ex.names[(idx, 0)] = node["name"]


_BINOP = {"_plus": "Add", "elemwise_add": "Add", "broadcast_add": "Add",
          "_minus": "Sub", "elemwise_sub": "Sub", "broadcast_sub": "Sub",
          "_mul": "Mul", "elemwise_mul": "Mul", "broadcast_mul": "Mul",
          "_div": "Div", "elemwise_div": "Div", "broadcast_div": "Div",
          "_power": "Pow", "broadcast_power": "Pow",
          "broadcast_maximum": "Max", "broadcast_minimum": "Min",
          "dot": "MatMul"}


@_export("batch_dot")
def _exp_batch_dot(ex, idx, node):
    """batch_dot == numpy-matmul semantics == ONNX MatMul; transpose
    flags become Transpose of the last two axes (rank from the shape
    pass)."""
    a = node.get("attrs") or {}
    ins = ex.resolve(node)
    n = node["name"]
    for flag, pos in (("transpose_a", 0), ("transpose_b", 1)):
        if not a.get(flag):
            continue
        rank = len(ex.shape_of(node["inputs"][pos][0],
                               node["inputs"][pos][1]))
        perm = list(range(rank))
        perm[-1], perm[-2] = perm[-2], perm[-1]
        tname = f"{n}_{flag}"
        ex.add_node("Transpose", [ins[pos]], [tname], tname, perm=perm)
        ins[pos] = tname
    ex.add_node("MatMul", ins, [n], n)
    ex.names[(idx, 0)] = n


@_export(*_BINOP)
def _exp_binop(ex, idx, node):
    ins = ex.resolve(node)
    if node["op"] == "dot":
        # MatMul only matches dot's tensordot semantics up to rank 2;
        # a rank>2 stacked dot would silently change numerics
        rhs_shape = ex.shapes.get((node["inputs"][1][0],
                                   node["inputs"][1][1]))
        if rhs_shape is not None and len(rhs_shape) > 2:
            # MatMul broadcasts the lhs over rhs leading dims; dot's
            # tensordot contracts lhs-last with rhs-FIRST — different
            # result whenever rhs rank > 2, whatever the lhs rank
            raise NotImplementedError(
                "ONNX export: dot with a rank>2 rhs contracts "
                "differently from MatMul; use batch_dot for batched "
                "matmul semantics")
        # dot may carry transpose flags (sym.dot(transpose_b=True), the
        # weight-tied LM head); MatMul alone would silently drop them
        a = node.get("attrs") or {}
        for flag, pos, swap_last in (("transpose_a", 0, True),
                                     ("transpose_b", 1, False)):
            if not a.get(flag):
                continue
            src_node = ex.nodes[node["inputs"][pos][0]]
            param = ex.params.get(src_node["name"])
            if param is not None:
                rank = len(param.shape)
            else:
                # activations have a static rank too whenever the shape
                # pass covered them (input_shape given) — only raise when
                # the pass has a genuine gap
                shp = ex.shapes.get(tuple(node["inputs"][pos]))
                if shp is None:
                    raise NotImplementedError(
                        "ONNX export: dot with %s on a non-parameter input "
                        "whose shape the annotation pass could not infer; "
                        "pass input_shape or restructure with an explicit "
                        "transpose" % flag)
                rank = len(shp)
            if rank < 2:
                continue  # dot_mx treats transpose on 1-D as a no-op
            perm = list(range(rank))
            if swap_last:      # lhs: swap last two (nd.dot semantics)
                perm[-1], perm[-2] = perm[-2], perm[-1]
            else:              # rhs: swap first two
                perm[0], perm[1] = perm[1], perm[0]
            tname = node["name"] + "_" + flag
            ex.add_node("Transpose", [ins[pos]], [tname], tname,
                        perm=perm)
            ins[pos] = tname
    ex.add_node(_BINOP[node["op"]], ins, [node["name"]], node["name"])
    ex.names[(idx, 0)] = node["name"]


_SCALAR_OP = {"_plus_scalar": ("Add", False), "_minus_scalar": ("Sub", False),
              "_rminus_scalar": ("Sub", True), "_mul_scalar": ("Mul", False),
              "_div_scalar": ("Div", False), "_rdiv_scalar": ("Div", True),
              "_power_scalar": ("Pow", False)}


@_export(*_SCALAR_OP)
def _exp_scalar(ex, idx, node):
    op, reverse = _SCALAR_OP[node["op"]]
    s = ex.add_init(node["name"] + "_scalar",
                    np.asarray(node["attrs"]["scalar"], np.float32))
    ins = ex.resolve(node)
    ins = [s, ins[0]] if reverse else [ins[0], s]
    ex.add_node(op, ins, [node["name"]], node["name"])
    ex.names[(idx, 0)] = node["name"]


@_export("clip")
def _exp_clip(ex, idx, node):
    a = node["attrs"]
    ins = ex.resolve(node) + [
        ex.add_init(node["name"] + "_min", np.asarray(a["a_min"], np.float32)),
        ex.add_init(node["name"] + "_max", np.asarray(a["a_max"], np.float32))]
    ex.add_node("Clip", ins, [node["name"]], node["name"])
    ex.names[(idx, 0)] = node["name"]


_REDUCE = {"mean": "ReduceMean", "max": "ReduceMax", "min": "ReduceMin",
           "prod": "ReduceProd"}


@_export("mean", "max", "min", "prod")
def _exp_reduce(ex, idx, node):
    a = node["attrs"]
    axis = a.get("axis")
    kw = {"keepdims": int(bool(a.get("keepdims", False)))}
    if axis is not None:
        kw["axes"] = [axis] if isinstance(axis, int) else list(axis)
    ex.add_node(_REDUCE[node["op"]], ex.resolve(node), [node["name"]],
                node["name"], **kw)
    ex.names[(idx, 0)] = node["name"]


@_export("sum")
def _exp_sum(ex, idx, node):
    # ReduceSum moved axes to an input at opset 13
    a = node["attrs"]
    axis = a.get("axis")
    ins = ex.resolve(node)
    if axis is not None:
        axes = [axis] if isinstance(axis, int) else list(axis)
        ins = ins + [ex.add_init(node["name"] + "_axes",
                                 np.asarray(axes, np.int64))]
    ex.add_node("ReduceSum", ins, [node["name"]], node["name"],
                keepdims=int(bool(a.get("keepdims", False))))
    ex.names[(idx, 0)] = node["name"]


_UNARY = {"exp": "Exp", "log": "Log", "sqrt": "Sqrt", "abs": "Abs",
          "negative": "Neg", "sigmoid": "Sigmoid", "tanh": "Tanh",
          "relu": "Relu", "floor": "Floor", "ceil": "Ceil", "erf": "Erf",
          "sin": "Sin", "cos": "Cos"}


@_export(*_UNARY)
def _exp_unary(ex, idx, node):
    ex.add_node(_UNARY[node["op"]], ex.resolve(node), [node["name"]],
                node["name"])
    ex.names[(idx, 0)] = node["name"]


@_export("Pad", "pad")
def _exp_pad(ex, idx, node):
    a = node["attrs"]
    pw = tuple(a["pad_width"])
    ndim = len(pw) // 2
    # ONNX pads layout: all begins then all ends
    pads = [pw[2 * i] for i in range(ndim)] + [pw[2 * i + 1]
                                               for i in range(ndim)]
    mode = {"constant": "constant", "edge": "edge",
            "reflect": "reflect"}[a.get("mode", "constant")]
    ins = ex.resolve(node) + [
        ex.add_init(node["name"] + "_pads", np.asarray(pads, np.int64)),
        # constant_value must share the data tensor's type T (ONNX spec)
        ex.add_init(node["name"] + "_cval",
                    np.asarray(a.get("constant_value", 0), ex.np_dtype))]
    ex.add_node("Pad", ins, [node["name"]], node["name"], mode=mode)
    ex.names[(idx, 0)] = node["name"]


@_export("swapaxes")
def _exp_swapaxes(ex, idx, node):
    a = node["attrs"]
    rank = len(ex.shape_of(node["inputs"][0][0], node["inputs"][0][1]))
    perm = list(range(rank))
    i1, i2 = int(a["a1"]) % rank, int(a["a2"]) % rank
    perm[i1], perm[i2] = perm[i2], perm[i1]
    ex.add_node("Transpose", ex.resolve(node), [node["name"]],
                node["name"], perm=perm)
    ex.names[(idx, 0)] = node["name"]


@_export("slice_like")
def _exp_slice_like(ex, idx, node):
    # output shape is static: emit a plain Slice of `data` to it
    out_shape = ex.shape_of(idx)
    a = node.get("attrs") or {}
    axes = a.get("axes")
    axes = (list(range(len(out_shape))) if axes is None
            else [int(x) % len(out_shape) for x in axes])
    name = node["name"]
    ins = ex.resolve(node, positions=[0]) + [
        ex.add_init(name + "_starts",
                    np.zeros(len(axes), np.int64)),
        ex.add_init(name + "_ends",
                    np.asarray([out_shape[ax] for ax in axes], np.int64)),
        ex.add_init(name + "_axes", np.asarray(axes, np.int64))]
    ex.add_node("Slice", ins, [name], name)
    ex.names[(idx, 0)] = name


@_export("LayerNorm")
def _exp_layer_norm(ex, idx, node):
    # opset-13 decomposition (LayerNormalization itself is opset 17):
    # (x - mean) / sqrt(var + eps) * gamma + beta over the last axis
    a = node.get("attrs") or {}
    axis = int(a.get("axis", -1))
    rank = len(ex.shape_of(node["inputs"][0][0], node["inputs"][0][1]))
    if axis % rank != rank - 1:
        raise NotImplementedError(
            "ONNX LayerNorm export supports last-axis normalization only "
            "(got axis=%d)" % axis)
    x, gamma, beta = ex.resolve(node)
    n = node["name"]
    eps = ex.add_init(n + "_eps",
                      np.asarray(a.get("eps", 1e-5), np.float32))
    ex.add_node("ReduceMean", [x], [n + "_mean"], n + "_mean",
                axes=[-1], keepdims=1)
    ex.add_node("Sub", [x, n + "_mean"], [n + "_xmu"], n + "_xmu")
    ex.add_node("Mul", [n + "_xmu", n + "_xmu"], [n + "_sq"], n + "_sq")
    ex.add_node("ReduceMean", [n + "_sq"], [n + "_var"], n + "_var",
                axes=[-1], keepdims=1)
    ex.add_node("Add", [n + "_var", eps], [n + "_vareps"], n + "_vareps")
    ex.add_node("Sqrt", [n + "_vareps"], [n + "_std"], n + "_std")
    ex.add_node("Div", [n + "_xmu", n + "_std"], [n + "_norm"], n + "_norm")
    ex.add_node("Mul", [n + "_norm", gamma], [n + "_scaled"], n + "_scaled")
    ex.add_node("Add", [n + "_scaled", beta], [n], n)
    ex.names[(idx, 0)] = n


@_export("SliceChannel")
def _exp_slice_channel(ex, idx, node):
    a = node["attrs"]
    num = int(a["num_outputs"])
    axis = int(a.get("axis", 1))
    n = node["name"]
    part_names = [f"{n}_part{k}" for k in range(num)]
    ex.add_node("Split", ex.resolve(node), part_names, n, axis=axis)
    for k in range(num):
        if a.get("squeeze_axis"):
            sq = f"{n}_out{k}"
            ex.add_node("Squeeze", [part_names[k],
                                    ex.add_init(n + "_sqax",
                                                np.asarray([axis],
                                                           np.int64))],
                        [sq], sq)
            ex.names[(idx, k)] = sq
        else:
            ex.names[(idx, k)] = part_names[k]


@_export("multihead_attention")
def _exp_multihead_attention(ex, idx, node):
    """Decomposition of the symbol attention op: split heads ->
    QK^T*scale (+causal/mask) -> Softmax -> AV -> merge heads. Shapes
    are static at export, so the causal mask is a constant and the
    reshapes use concrete dims."""
    a = node.get("attrs") or {}
    heads = int(a["num_heads"])
    qi, qo = node["inputs"][0]
    ki, ko = node["inputs"][1]
    b_, lq, d = ex.shape_of(qi, qo)
    lk = ex.shape_of(ki, ko)[1]
    hd = d // heads
    scale = a.get("scale")
    scale = float(scale) if scale is not None else 1.0 / (hd ** 0.5)
    n = node["name"]
    ins = ex.resolve(node)
    q, k, v = ins[0], ins[1], ins[2]
    mask = ins[3] if a.get("has_mask") else None

    def split_heads(src, tag, length):
        ex.add_node("Reshape", [src, ex.add_init(
            f"{n}_{tag}_shape", np.asarray([b_, length, heads, hd],
                                           np.int64))],
            [f"{n}_{tag}_r"], f"{n}_{tag}_r")
        ex.add_node("Transpose", [f"{n}_{tag}_r"], [f"{n}_{tag}_h"],
                    f"{n}_{tag}_h", perm=[0, 2, 1, 3])
        return f"{n}_{tag}_h"

    qh, kh, vh = (split_heads(q, "q", lq), split_heads(k, "k", lk),
                  split_heads(v, "v", lk))
    ex.add_node("Transpose", [kh], [n + "_kt"], n + "_kt",
                perm=[0, 1, 3, 2])
    ex.add_node("MatMul", [qh, n + "_kt"], [n + "_scores"], n + "_scores")
    ex.add_node("Mul", [n + "_scores",
                        ex.add_init(n + "_scale",
                                    np.asarray(scale, np.float32))],
                [n + "_scaled"], n + "_scaled")
    cur = n + "_scaled"

    def neg():
        return ex.add_init(n + "_neg", np.asarray(-1e9, np.float32))

    if a.get("causal"):
        tri = np.tril(np.ones((lq, lk), bool), k=lk - lq)
        cond = ex.add_init(n + "_tri", tri)
        ex.add_node("Where", [cond, cur, neg()], [n + "_causal"],
                    n + "_causal")
        cur = n + "_causal"
    if mask is not None:
        ex.add_node("Cast", [mask], [n + "_maskb"], n + "_maskb",
                    to=P.TensorProto.BOOL)
        ex.add_node("Where", [n + "_maskb", cur, neg()], [n + "_masked"],
                    n + "_masked")
        cur = n + "_masked"
    ex.add_node("Softmax", [cur], [n + "_w"], n + "_w", axis=-1)
    ex.add_node("MatMul", [n + "_w", vh], [n + "_ctx"], n + "_ctx")
    ex.add_node("Transpose", [n + "_ctx"], [n + "_ctxT"], n + "_ctxT",
                perm=[0, 2, 1, 3])
    ex.add_node("Reshape", [n + "_ctxT", ex.add_init(
        n + "_out_shape", np.asarray([b_, lq, d], np.int64))], [n], n)
    ex.names[(idx, 0)] = n


@_export("where")
def _exp_where(ex, idx, node):
    ins = ex.resolve(node)
    n = node["name"]
    # ONNX Where requires a BOOL condition; our where accepts numeric
    ex.add_node("Cast", [ins[0]], [n + "_cond"], n + "_cond",
                to=P.TensorProto.BOOL)
    ex.add_node("Where", [n + "_cond", ins[1], ins[2]], [n], n)
    ex.names[(idx, 0)] = n


@_export("cast")
def _exp_cast(ex, idx, node):
    dt = np.dtype(node["attrs"]["dtype"])
    ex.add_node("Cast", ex.resolve(node), [node["name"]], node["name"],
                to=_NP2ONNX[dt])
    ex.names[(idx, 0)] = node["name"]


@_export("gelu")
def _exp_gelu(ex, idx, node):
    # opset 13 has no Gelu; emit the exact erf form
    # 0.5 * x * (1 + erf(x / sqrt(2))). A tanh-approximate gelu exports to
    # the same erf form (divergence < 1e-2, documented).
    x = ex.resolve(node)[0]
    n = node["name"]
    inv = ex.add_init(n + "_rsqrt2", np.asarray(1.0 / np.sqrt(2.0),
                                                np.float32))
    half = ex.add_init(n + "_half", np.asarray(0.5, np.float32))
    one = ex.add_init(n + "_one", np.asarray(1.0, np.float32))
    ex.add_node("Mul", [x, inv], [n + "_s"], n + "_s")
    ex.add_node("Erf", [n + "_s"], [n + "_e"], n + "_e")
    ex.add_node("Add", [n + "_e", one], [n + "_a"], n + "_a")
    ex.add_node("Mul", [x, n + "_a"], [n + "_m"], n + "_m")
    ex.add_node("Mul", [n + "_m", half], [n], n)
    ex.names[(idx, 0)] = n


@_export("silu")
def _exp_silu(ex, idx, node):
    x = ex.resolve(node)[0]
    n = node["name"]
    ex.add_node("Sigmoid", [x], [n + "_sig"], n + "_sig")
    ex.add_node("Mul", [x, n + "_sig"], [n], n)
    ex.names[(idx, 0)] = n


@_export("square")
def _exp_square(ex, idx, node):
    x = ex.resolve(node)[0]
    ex.add_node("Mul", [x, x], [node["name"]], node["name"])
    ex.names[(idx, 0)] = node["name"]


def export_model(sym, params, input_shape, input_type="float32",
                 onnx_file_path=None, model_name="incubator_mxnet_tpu_model",
                 opset=_ONNX_OPSET):
    """Symbol + params -> serialized ONNX ModelProto bytes.

    Mirrors the reference ``onnx_mxnet.export_model`` signature
    (mx2onnx/export_model.py): ``params`` maps arg/aux names to arrays
    (NDArray or numpy; ``arg:``/``aux:`` name prefixes are stripped);
    ``input_shape`` is a list of shapes for the graph's data inputs in
    ``list_inputs()`` order. Writes ``onnx_file_path`` if given and always
    returns the serialized bytes.
    """
    params = {k.split(":", 1)[-1]: v for k, v in dict(params).items()}
    graph_json = json.loads(sym.tojson())
    if any(n["op"] in ("_foreach", "_while_loop", "_cond")
           for n in graph_json["nodes"]):
        raise NotImplementedError("control-flow subgraphs cannot be "
                                  "exported to ONNX")
    in_np = np.dtype(input_type)
    if isinstance(input_shape, tuple):
        input_shape = [input_shape]
    ex = _Exporter(graph_json, params, opset,
                   np_dtype=in_np if in_np in _NP2ONNX else np.float32,
                   input_shapes=input_shape)
    g = ex.run()
    g.name = model_name

    elem = _NP2ONNX.get(in_np, P.TensorProto.FLOAT)
    data_inputs = ex.used_inputs
    if len(input_shape) < len(data_inputs):
        raise ValueError("model has %d data inputs %r but input_shape has %d"
                         % (len(data_inputs), data_inputs, len(input_shape)))
    # one canonical name->shape assignment (declaration order, built by the
    # shape pass) so the emitted input value_infos can never disagree with
    # the shapes the exporters decomposed against. Consumed inputs the
    # pass skipped (label-heuristic names like *_label that a real op
    # reads) take the SPARE input_shape entries in consumption order —
    # the legacy contract — and only a genuine shortfall raises.
    canonical = dict(getattr(ex, "input_shape_assign", None)
                     or zip(data_inputs, input_shape))
    shape_of = dict(canonical)
    spare = list(input_shape[len(canonical):])
    for n in data_inputs:
        if n not in shape_of:
            if not spare:
                raise ValueError(
                    "graph consumes input %r which the shape pass "
                    "skipped (label-heuristic name) and no spare "
                    "input_shape entry remains; append its shape to "
                    "input_shape (declared data inputs: %r)"
                    % (n, sorted(canonical)))
            shape_of[n] = spare.pop(0)
    for name in data_inputs:
        g.input.append(_vi(name, shape_of[name], elem))

    # output value infos via the symbol's own shape inference
    try:
        kw = dict(shape_of)
        for k, v in params.items():
            kw.setdefault(k, tuple(np.shape(
                v.asnumpy() if hasattr(v, "asnumpy") else v)))
        _, out_shapes, _ = sym.infer_shape(**kw)
    except Exception:
        out_shapes = [None] * len(graph_json["heads"])
    for (hidx, hout), oshape in zip(graph_json["heads"], out_shapes):
        g.output.append(_vi(ex.name_of(hidx, hout), oshape, elem))

    m = P.ModelProto(ir_version=_IR_VERSION,
                     producer_name="incubator-mxnet-tpu",
                     producer_version="0.4", graph=g)
    m.opset_import.add(domain="", version=opset)
    data = m.SerializeToString()
    if onnx_file_path:
        with open(onnx_file_path, "wb") as f:
            f.write(data)
    return data


# ---------------------------------------------------------------------------
# Import (onnx2mx)
# ---------------------------------------------------------------------------

def _load_model_proto(model):
    if isinstance(model, P.ModelProto):
        return model
    if isinstance(model, (bytes, bytearray)):
        data = bytes(model)
    else:
        with open(model, "rb") as f:
            data = f.read()
    m = P.ModelProto()
    m.ParseFromString(data)
    return m


class _Importer:
    def __init__(self, m):
        from ... import symbol as S
        from ... import ndarray as nd
        self.S, self.nd = S, nd
        self.g = m.graph
        self.inits = {t.name: _tensor_to_np(t) for t in self.g.initializer}
        self.tensors = {}     # onnx tensor name -> Symbol
        self.aux_names = set()

    def sym_of(self, name):
        if name not in self.tensors:
            if name not in self.inits:
                raise ValueError("ONNX import: undefined tensor %r" % name)
            self.tensors[name] = self.S.Variable(name)
        return self.tensors[name]

    def run(self):
        for v in self.g.input:
            if v.name not in self.inits:
                self.tensors[v.name] = self.S.Variable(v.name)
        for node in self.g.node:
            fn = _IMPORTERS.get(node.op_type)
            if fn is None:
                raise NotImplementedError(
                    "ONNX import: unsupported op %r. Supported: %s"
                    % (node.op_type, ", ".join(sorted(_IMPORTERS))))
            fn(self, node, _attr_dict(node))
        outs = [self.tensors[v.name] for v in self.g.output]
        sym = outs[0] if len(outs) == 1 else self.S.Group(outs)
        used = set(sym.list_arguments()) | set(sym.list_auxiliary_states())
        arg_params, aux_params = {}, {}
        for name, arr in self.inits.items():
            if name not in used:
                continue
            dst = aux_params if name in self.aux_names else arg_params
            dst[name] = self.nd.array(arr)
        return sym, arg_params, aux_params


def _attr_dict(node):
    out = {}
    for a in node.attribute:
        if a.type == P.AttributeProto.FLOAT:
            out[a.name] = a.f
        elif a.type == P.AttributeProto.INT:
            out[a.name] = a.i
        elif a.type == P.AttributeProto.STRING:
            out[a.name] = a.s.decode()
        elif a.type == P.AttributeProto.INTS:
            out[a.name] = tuple(a.ints)
        elif a.type == P.AttributeProto.FLOATS:
            out[a.name] = tuple(a.floats)
        else:
            out[a.name] = a
    return out


_IMPORTERS = {}


def _import(*ops):
    def deco(fn):
        for op in ops:
            _IMPORTERS[op] = fn
        return fn
    return deco


def _onnx_pads(attrs, ndim, allow_asymmetric=False):
    """Symmetric pads -> per-dim tuple; asymmetric -> (begin, end) pair
    when the caller can emit an explicit Pad node, else a clear error."""
    auto = attrs.get("auto_pad", "")
    if auto not in ("", "NOTSET", "VALID"):
        raise NotImplementedError(
            "auto_pad=%s is unsupported; re-export the model with explicit "
            "pads" % auto)
    pads = attrs.get("pads")
    if not pads:
        return (0,) * ndim
    begin, end = tuple(pads[:ndim]), tuple(pads[ndim:])
    if begin != end:
        if allow_asymmetric:
            return (begin, end)
        raise NotImplementedError("asymmetric ONNX pads %r" % (pads,))
    return begin


def _maybe_prepad(im, node, data_sym, a, ndim):
    """Asymmetric Conv pads: insert an explicit zero Pad on the spatial
    dims and zero out the op's own padding. Conv ONLY — ConvTranspose
    pads crop the OUTPUT in ONNX semantics, so pre-padding the input
    would be wrong there (Deconvolution keeps its symmetric-only
    error)."""
    pads = _onnx_pads(a, ndim, allow_asymmetric=True)
    if not (pads and isinstance(pads[0], tuple)):
        return data_sym, pads
    begin, end = pads
    # NCHW: batch and channel dims unpadded, then per-spatial begin/end
    pw = [0, 0, 0, 0]
    for b, e in zip(begin, end):
        pw += [int(b), int(e)]
    padded = im.S.Pad(data_sym, mode="constant", pad_width=tuple(pw),
                      constant_value=0,
                      name=(node.name + "_prepad") if node.name else None)
    return padded, (0,) * ndim


@_import("Conv")
def _imp_conv(im, node, a):
    k = tuple(a["kernel_shape"])
    w = im.inits.get(node.input[1])
    nf = a.get("num_filter") or (w.shape[0] if w is not None else None)
    if nf is None:
        raise ValueError("Conv %s: weight initializer required to recover "
                         "num_filter" % node.name)
    data, pad = _maybe_prepad(im, node, im.sym_of(node.input[0]), a,
                              len(k))
    im.tensors[node.output[0]] = im.S.Convolution(
        data=data, weight=im.sym_of(node.input[1]),
        bias=im.sym_of(node.input[2]) if len(node.input) > 2 else None,
        no_bias=len(node.input) <= 2, kernel=k,
        stride=tuple(a.get("strides", (1,) * len(k))),
        dilate=tuple(a.get("dilations", (1,) * len(k))),
        pad=pad, num_filter=int(nf),
        num_group=int(a.get("group", 1)), name=node.name or None)


@_import("ConvTranspose")
def _imp_deconv(im, node, a):
    k = tuple(a["kernel_shape"])
    w = im.inits.get(node.input[1])
    if w is None:
        raise ValueError("ConvTranspose %s: weight initializer required to "
                         "recover num_filter" % node.name)
    nf = w.shape[1] * int(a.get("group", 1))
    kw = {}
    if a.get("output_padding"):
        kw["adj"] = tuple(a["output_padding"])
    im.tensors[node.output[0]] = im.S.Deconvolution(
        data=im.sym_of(node.input[0]), weight=im.sym_of(node.input[1]),
        bias=im.sym_of(node.input[2]) if len(node.input) > 2 else None,
        no_bias=len(node.input) <= 2, kernel=k,
        stride=tuple(a.get("strides", (1,) * len(k))),
        dilate=tuple(a.get("dilations", (1,) * len(k))),
        pad=_onnx_pads(a, len(k)), num_filter=int(nf),
        num_group=int(a.get("group", 1)), name=node.name or None)


@_import("Gemm")
def _imp_gemm(im, node, a):
    if (a.get("alpha", 1.0), a.get("beta", 1.0)) != (1.0, 1.0) \
            or a.get("transA", 0):
        raise NotImplementedError("Gemm with alpha/beta/transA != defaults")
    if not a.get("transB", 0):
        raise NotImplementedError("Gemm transB=0 (use MatMul)")
    w = im.inits.get(node.input[1])
    if w is None:
        raise ValueError("Gemm %s: weight initializer required" % node.name)
    im.tensors[node.output[0]] = im.S.FullyConnected(
        data=im.sym_of(node.input[0]), weight=im.sym_of(node.input[1]),
        bias=im.sym_of(node.input[2]) if len(node.input) > 2 else None,
        no_bias=len(node.input) <= 2, num_hidden=int(w.shape[0]),
        flatten=False, name=node.name or None)


@_import("MatMul")
def _imp_matmul(im, node, a):
    # ONNX MatMul is numpy-matmul semantics (batched over leading dims,
    # broadcasting) — that is batch_dot's jnp.matmul runtime, NOT dot's
    # tensordot (which mis-contracts rank>2 stacks)
    im.tensors[node.output[0]] = im.S.batch_dot(
        im.sym_of(node.input[0]), im.sym_of(node.input[1]),
        name=node.name or None)


@_import("BatchNormalization")
def _imp_bn(im, node, a):
    im.aux_names.update(node.input[3:5])
    im.tensors[node.output[0]] = im.S.BatchNorm(
        data=im.sym_of(node.input[0]), gamma=im.sym_of(node.input[1]),
        beta=im.sym_of(node.input[2]), moving_mean=im.sym_of(node.input[3]),
        moving_var=im.sym_of(node.input[4]),
        eps=float(a.get("epsilon", 1e-5)),
        momentum=float(a.get("momentum", 0.9)),
        use_global_stats=True, name=node.name or None)


_ONNX2ACT = {v: k for k, v in _ACT2ONNX.items()}


@_import("Relu", "Sigmoid", "Tanh", "Softplus", "Softsign")
def _imp_act(im, node, a):
    im.tensors[node.output[0]] = im.S.Activation(
        im.sym_of(node.input[0]), act_type=_ONNX2ACT[node.op_type],
        name=node.name or None)


@_import("LeakyRelu")
def _imp_leaky(im, node, a):
    im.tensors[node.output[0]] = im.S.LeakyReLU(
        im.sym_of(node.input[0]), act_type="leaky",
        slope=float(a.get("alpha", 0.01)), name=node.name or None)


@_import("Elu")
def _imp_elu(im, node, a):
    im.tensors[node.output[0]] = im.S.LeakyReLU(
        im.sym_of(node.input[0]), act_type="elu",
        slope=float(a.get("alpha", 1.0)), name=node.name or None)


@_import("Selu")
def _imp_selu(im, node, a):
    im.tensors[node.output[0]] = im.S.LeakyReLU(
        im.sym_of(node.input[0]), act_type="selu", name=node.name or None)


@_import("MaxPool", "AveragePool", "GlobalMaxPool", "GlobalAveragePool")
def _imp_pool(im, node, a):
    is_global = node.op_type.startswith("Global")
    ptype = "max" if "Max" in node.op_type else "avg"
    kw = dict(pool_type=ptype, global_pool=is_global,
              name=node.name or None)
    if not is_global:
        k = tuple(a["kernel_shape"])
        kw.update(kernel=k, stride=tuple(a.get("strides", k)),
                  pad=_onnx_pads(a, len(k)),
                  ceil_mode=bool(a.get("ceil_mode", 0)))
        if ptype == "avg":
            kw["count_include_pad"] = bool(a.get("count_include_pad", 1))
    else:
        kw["kernel"] = (1, 1)
    im.tensors[node.output[0]] = im.S.Pooling(im.sym_of(node.input[0]), **kw)


@_import("Flatten")
def _imp_flatten(im, node, a):
    if a.get("axis", 1) != 1:
        raise NotImplementedError("Flatten axis != 1")
    im.tensors[node.output[0]] = im.S.Flatten(im.sym_of(node.input[0]),
                                              name=node.name or None)


@_import("Reshape")
def _imp_reshape(im, node, a):
    # NOTE (here and below): constant inputs (shape/axes/bounds) are READ,
    # never popped — legal ONNX graphs share one initializer between nodes.
    # Unconsumed initializers are pruned from params by the `used` filter
    # in _Importer.run.
    shape = im.inits.get(node.input[1])
    if shape is None:
        raise NotImplementedError("Reshape with dynamic shape input")
    im.tensors[node.output[0]] = im.S.Reshape(
        im.sym_of(node.input[0]), shape=tuple(int(s) for s in shape),
        name=node.name or None)


@_import("Transpose")
def _imp_transpose(im, node, a):
    im.tensors[node.output[0]] = im.S.transpose(
        im.sym_of(node.input[0]), axes=tuple(a["perm"]) if "perm" in a
        else None, name=node.name or None)


@_import("Unsqueeze")
def _imp_unsqueeze(im, node, a):
    axes = (tuple(a["axes"]) if "axes" in a
            else tuple(int(x) for x in im.inits[node.input[1]]))
    s = im.sym_of(node.input[0])
    for ax in axes:
        s = im.S.expand_dims(s, axis=int(ax))
    im.tensors[node.output[0]] = s


@_import("Squeeze")
def _imp_squeeze(im, node, a):
    axes = (tuple(a["axes"]) if "axes" in a
            else tuple(int(x) for x in im.inits[node.input[1]])
            if len(node.input) > 1 else None)
    im.tensors[node.output[0]] = im.S.squeeze(
        im.sym_of(node.input[0]),
        axis=axes if axes is None or len(axes) > 1 else axes[0])


@_import("Slice")
def _imp_slice(im, node, a):
    if len(node.input) < 4:
        raise NotImplementedError("Slice without explicit axes input")
    starts = [int(x) for x in im.inits[node.input[1]]]
    ends = [int(x) for x in im.inits[node.input[2]]]
    axes = [int(x) for x in im.inits[node.input[3]]]
    s = im.sym_of(node.input[0])
    imax = np.iinfo(np.int64).max
    for b, e, ax in zip(starts, ends, axes):
        s = im.S.slice_axis(s, axis=ax, begin=b,
                            end=None if e >= imax else e)
    im.tensors[node.output[0]] = s


@_import("Split")
def _imp_split(im, node, a):
    if len(node.input) > 1:
        raise NotImplementedError(
            "ONNX Split with explicit split lengths is unsupported "
            "(equal-parts Split only)")
    parts = im.S.split(im.sym_of(node.input[0]),
                       num_outputs=len(node.output),
                       axis=int(a.get("axis", 0)))
    for k, out in enumerate(node.output):
        im.tensors[out] = parts[k]


@_import("Where")
def _imp_where(im, node, a):
    im.tensors[node.output[0]] = im.S.where(
        im.sym_of(node.input[0]), im.sym_of(node.input[1]),
        im.sym_of(node.input[2]))


@_import("Concat")
def _imp_concat(im, node, a):
    im.tensors[node.output[0]] = im.S.Concat(
        *[im.sym_of(i) for i in node.input], dim=int(a.get("axis", 0)),
        name=node.name or None)


@_import("Sum")
def _imp_sum(im, node, a):
    syms = [im.sym_of(i) for i in node.input]
    im.tensors[node.output[0]] = (syms[0] if len(syms) == 1
                                  else im.S.add_n(*syms,
                                                  name=node.name or None))


@_import("Cast")
def _imp_cast(im, node, a):
    im.tensors[node.output[0]] = im.S.cast(
        im.sym_of(node.input[0]),
        dtype=_ONNX2NP[a["to"]].name) if hasattr(im.S, "cast") \
        else im.sym_of(node.input[0])


@_import("Gather")
def _imp_gather(im, node, a):
    if int(a.get("axis", 0)) != 0:
        raise NotImplementedError("Gather axis != 0")
    w = im.inits.get(node.input[0])
    if w is None:
        raise NotImplementedError("Gather from non-initializer")
    im.tensors[node.output[0]] = im.S.Embedding(
        data=im.sym_of(node.input[1]), weight=im.sym_of(node.input[0]),
        input_dim=int(w.shape[0]), output_dim=int(w.shape[1]),
        name=node.name or None)


@_import("Softmax")
def _imp_softmax(im, node, a):
    im.tensors[node.output[0]] = im.S.softmax(
        im.sym_of(node.input[0]), axis=int(a.get("axis", -1)),
        name=node.name or None)


@_import("LogSoftmax")
def _imp_log_softmax(im, node, a):
    im.tensors[node.output[0]] = im.S.log_softmax(
        im.sym_of(node.input[0]), axis=int(a.get("axis", -1)),
        name=node.name or None)


@_import("Identity", "Dropout")
def _imp_identity(im, node, a):
    im.tensors[node.output[0]] = im.sym_of(node.input[0])


@_import("Pad")
def _imp_pad(im, node, a):
    mode = a.get("mode", "constant")
    if mode not in ("constant", "edge", "reflect"):
        raise NotImplementedError("Pad mode=%r is unsupported" % mode)
    if "pads" in a:  # opset < 11: attribute form
        pads = [int(p) for p in a["pads"]]
        cval = float(a.get("value", 0.0))
    else:
        pads = [int(p) for p in im.inits[node.input[1]]]
        if len(node.input) > 2 and node.input[2]:
            cval = _scalar_init(im, node.input[2])
            if cval is None:
                raise NotImplementedError(
                    "Pad %s: constant_value must be a scalar initializer "
                    "(computed values are unsupported)" % node.name)
        else:
            cval = 0.0
    if any(p < 0 for p in pads):
        raise NotImplementedError("negative ONNX pads (crop) %r" % (pads,))
    ndim = len(pads) // 2
    pw = []
    for i in range(ndim):
        pw += [pads[i], pads[ndim + i]]
    im.tensors[node.output[0]] = im.S.Pad(
        im.sym_of(node.input[0]), mode=mode, pad_width=tuple(pw),
        constant_value=cval, name=node.name or None)


def _scalar_init(im, name):
    arr = im.inits.get(name)
    if arr is not None and arr.size == 1:
        return float(arr.reshape(()))
    return None


_ONNX_BIN = {"Add": "broadcast_add", "Sub": "broadcast_sub",
             "Mul": "broadcast_mul", "Div": "broadcast_div",
             "Pow": "broadcast_power", "Max": "broadcast_maximum",
             "Min": "broadcast_minimum"}
_SCALAR_FWD = {"Add": "_plus_scalar", "Sub": "_minus_scalar",
               "Mul": "_mul_scalar", "Div": "_div_scalar",
               "Pow": "_power_scalar"}
_SCALAR_REV = {"Add": "_plus_scalar", "Sub": "_rminus_scalar",
               "Mul": "_mul_scalar", "Div": "_rdiv_scalar"}


@_import(*_ONNX_BIN)
def _imp_binop(im, node, a):
    # scalar initializer operand -> scalar op (keeps round-trip exact and
    # the constant out of arg_params)
    op = node.op_type
    s1 = _scalar_init(im, node.input[1])
    if s1 is not None and op in _SCALAR_FWD and node.input[1] not in im.tensors:
        from ...symbol import _register as _R
        im.tensors[node.output[0]] = _R._make_op(
            _SCALAR_FWD[op], [im.sym_of(node.input[0])], {"scalar": s1},
            node.name or None)
        return
    s0 = _scalar_init(im, node.input[0])
    if s0 is not None and op in _SCALAR_REV and node.input[0] not in im.tensors:
        from ...symbol import _register as _R
        im.tensors[node.output[0]] = _R._make_op(
            _SCALAR_REV[op], [im.sym_of(node.input[1])], {"scalar": s0},
            node.name or None)
        return
    if op in ("Max", "Min") and len(node.input) != 2:
        raise NotImplementedError("%s with != 2 inputs" % op)
    im.tensors[node.output[0]] = getattr(im.S, _ONNX_BIN[op])(
        im.sym_of(node.input[0]), im.sym_of(node.input[1]),
        name=node.name or None)


@_import("Clip")
def _imp_clip(im, node, a):
    if len(node.input) > 1:
        amin = (_scalar_init(im, node.input[1]) if node.input[1]
                else -np.inf)
        amax = (_scalar_init(im, node.input[2])
                if len(node.input) > 2 and node.input[2] else np.inf)
        if amin is None or amax is None:
            raise NotImplementedError(
                "Clip %s: min/max must be scalar initializers (computed "
                "bounds are unsupported)" % node.name)
    else:
        amin, amax = a.get("min", -np.inf), a.get("max", np.inf)
    im.tensors[node.output[0]] = im.S.clip(
        im.sym_of(node.input[0]), a_min=float(amin), a_max=float(amax),
        name=node.name or None)


@_import("ReduceMean", "ReduceMax", "ReduceMin", "ReduceProd")
def _imp_reduce(im, node, a):
    mxop = {"ReduceMean": "mean", "ReduceMax": "max", "ReduceMin": "min",
            "ReduceProd": "prod"}[node.op_type]
    axes = a.get("axes")
    im.tensors[node.output[0]] = getattr(im.S, mxop)(
        im.sym_of(node.input[0]),
        axis=tuple(axes) if axes is not None else None,
        keepdims=bool(a.get("keepdims", 1)))


@_import("ReduceSum")
def _imp_reduce_sum(im, node, a):
    axes = a.get("axes")
    if axes is None and len(node.input) > 1:
        axes = tuple(int(x) for x in im.inits[node.input[1]])
    im.tensors[node.output[0]] = im.S.sum(
        im.sym_of(node.input[0]),
        axis=tuple(axes) if axes is not None else None,
        keepdims=bool(a.get("keepdims", 1)))


_ONNX_UNARY = {v: k for k, v in _UNARY.items()}


@_import(*_ONNX_UNARY)
def _imp_unary(im, node, a):
    im.tensors[node.output[0]] = getattr(im.S, _ONNX_UNARY[node.op_type])(
        im.sym_of(node.input[0]))


def import_model(model):
    """ONNX file path / bytes / ModelProto -> (sym, arg_params, aux_params).

    Mirrors the reference ``onnx_mxnet.import_model``
    (onnx2mx/import_model.py). BatchNormalization running stats land in
    ``aux_params``; every other initializer consumed by the graph lands in
    ``arg_params`` as NDArray.
    """
    return _Importer(_load_model_proto(model)).run()


def import_to_gluon(model, ctx=None):
    """ONNX model -> gluon SymbolBlock with parameters set (reference
    onnx2mx/import_to_gluon.py)."""
    from ...gluon import SymbolBlock
    sym, arg_params, aux_params = import_model(model)
    inputs = [n for n in sym.list_inputs()
              if n not in arg_params and n not in aux_params]
    from ... import symbol as S
    net = SymbolBlock(sym, [S.Variable(n) for n in inputs])
    params = dict(arg_params)
    params.update(aux_params)
    net.load_dict(params, ctx=ctx) if hasattr(net, "load_dict") else \
        net.collect_params().load_dict(params, ctx=ctx)
    return net


def get_model_metadata(model):
    """Input/output names+shapes of an ONNX model (reference
    onnx2mx/import_model.py:get_model_metadata)."""
    m = _load_model_proto(model)
    inits = {t.name for t in m.graph.initializer}

    def shape_of(v):
        return tuple(d.dim_value if d.dim_value else d.dim_param
                     for d in v.type.tensor_type.shape.dim)
    return {
        "input_tensor_data": [(v.name, shape_of(v)) for v in m.graph.input
                              if v.name not in inits],
        "output_tensor_data": [(v.name, shape_of(v))
                               for v in m.graph.output],
    }
