"""Symbol-API RNN cells (reference parity: python/mxnet/rnn/rnn_cell.py).

The reference's pre-Gluon recurrent API: cells that compose Symbol ops and
`unroll` into a static graph, used with `Module`/`BucketingModule` plus
`io.BucketSentenceIter`.

TPU-first design notes:
- `unroll()` emits a T-step static graph; the symbol executor jit-compiles
  it into ONE XLA program, so the whole unrolled loop fuses (no per-step
  kernel launches to amortize, unlike the reference's imperative path).
- `FusedRNNCell` emits the single fused `RNN` op — one `lax.scan` on
  device, the analogue of the reference's cuDNN fused kernel
  (src/operator/rnn.cc) — preferred for long sequences where an unrolled
  graph would blow up compile time.
- Cell math matches gluon.rnn (LSTM gates i,f,g,o; GRU r,z,n with the
  reset gate applied to the h2h candidate), so fused/unfused/gluon paths
  are numerically interchangeable.
"""
from __future__ import annotations

from .. import symbol as sym

__all__ = ["RNNParams", "BaseRNNCell", "RNNCell", "LSTMCell", "GRUCell",
           "FusedRNNCell", "SequentialRNNCell", "BidirectionalCell",
           "DropoutCell", "ModifierCell", "ResidualCell"]


class RNNParams:
    """Container for cell weights: creates (and caches) prefixed symbol
    Variables on demand (reference rnn_cell.RNNParams)."""

    def __init__(self, prefix=""):
        self._prefix = prefix
        self._params = {}

    def get(self, name, **kwargs):
        name = self._prefix + name
        if name not in self._params:
            self._params[name] = sym.Variable(name, **kwargs)
        return self._params[name]


class BaseRNNCell:
    """Abstract cell: one-step `__call__(inputs, states)` plus `unroll`."""

    def __init__(self, prefix="", params=None):
        if params is None:
            params = RNNParams(prefix)
            self._own_params = True
        else:
            self._own_params = False
        self._prefix = prefix
        self._params = params
        self._modified = False
        self.reset()

    @property
    def params(self):
        self._own_params = False
        return self._params

    @property
    def prefix(self):
        return self._prefix

    def reset(self):
        """Reset the step counter before building a fresh graph."""
        self._init_counter = -1
        self._counter = -1

    def __call__(self, inputs, states):
        """One timestep -> (output, new_states)."""
        raise NotImplementedError

    def state_info(self, batch_size=0):
        raise NotImplementedError

    @property
    def state_shape(self):
        return [info["shape"] for info in self.state_info()]

    def begin_state(self, func=None, batch_size=0, **kwargs):
        """Initial states. With batch_size > 0 returns concrete
        `sym.zeros`; with batch_size == 0 returns named Variables the
        caller binds (the reference defers via shape inference; binding
        is this executor's explicit equivalent)."""
        assert not self._modified, (
            "After applying modifier cells (e.g. DropoutCell), call "
            "begin_state on the base cell instead")
        states = []
        for info in self.state_info(batch_size):
            self._init_counter += 1
            name = f"{self._prefix}begin_state_{self._init_counter}"
            if func is not None:
                states.append(func(name=name, **kwargs))
            elif batch_size > 0:
                states.append(sym.zeros(shape=info["shape"], name=name))
            else:
                states.append(sym.Variable(name, **kwargs))
        return states

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None):
        """Unroll for `length` steps -> (outputs, states).

        inputs: merged Symbol ((N,T,C) for NTC / (T,N,C) for TNC) or a
        list of `length` step Symbols. merge_outputs=True stacks step
        outputs back into one Symbol on the time axis."""
        self.reset()
        inputs, _ = _normalize_sequence(length, inputs, layout, False)
        if begin_state is None:
            begin_state = self.begin_state()
        states = begin_state
        outputs = []
        for i in range(length):
            output, states = self(inputs[i], states)
            outputs.append(output)
        outputs, _ = _normalize_sequence(length, outputs, layout,
                                         merge_outputs)
        return outputs, states


def _normalize_sequence(length, inputs, layout, merge, in_layout=None):
    """list <-> merged Symbol on the layout's time axis."""
    assert layout in ("NTC", "TNC"), f"unsupported layout {layout}"
    axis = layout.find("T")
    in_axis = in_layout.find("T") if in_layout else axis
    if isinstance(inputs, sym.Symbol):
        if merge is False:
            inputs = list(sym.SliceChannel(inputs, num_outputs=length,
                                           axis=in_axis, squeeze_axis=True))
    else:
        assert length is None or len(inputs) == length
        if merge is True:
            inputs = sym.stack(*inputs, axis=axis)
    return inputs, axis


class RNNCell(BaseRNNCell):
    """Vanilla cell: h' = act(W_i x + b_i + W_h h + b_h)."""

    def __init__(self, num_hidden, activation="tanh", prefix="rnn_",
                 params=None):
        super().__init__(prefix, params)
        self._num_hidden = num_hidden
        self._activation = activation
        self._iW = self.params.get("i2h_weight")
        self._iB = self.params.get("i2h_bias")
        self._hW = self.params.get("h2h_weight")
        self._hB = self.params.get("h2h_bias")

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._num_hidden),
                 "__layout__": "NC"}]

    @property
    def _gate_names(self):
        return ("",)

    def __call__(self, inputs, states):
        self._counter += 1
        name = f"{self._prefix}t{self._counter}_"
        i2h = sym.FullyConnected(data=inputs, weight=self._iW, bias=self._iB,
                                 num_hidden=self._num_hidden,
                                 name=name + "i2h")
        h2h = sym.FullyConnected(data=states[0], weight=self._hW,
                                 bias=self._hB,
                                 num_hidden=self._num_hidden,
                                 name=name + "h2h")
        output = sym.Activation(i2h + h2h, act_type=self._activation,
                                name=name + "out")
        return output, [output]


class LSTMCell(BaseRNNCell):
    """LSTM (gates i,f,g,o — reference rnn_cell.LSTMCell)."""

    def __init__(self, num_hidden, prefix="lstm_", params=None,
                 forget_bias=1.0):
        super().__init__(prefix, params)
        self._num_hidden = num_hidden
        self._iW = self.params.get("i2h_weight")
        # forget_bias rides the bias initializer, like the reference's
        # LSTMBias init (Module.init_params honors the __init__ attr)
        from .. import initializer as _init
        self._iB = self.params.get(
            "i2h_bias", init=_init.LSTMBias(forget_bias))
        self._hW = self.params.get("h2h_weight")
        self._hB = self.params.get("h2h_bias")

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._num_hidden), "__layout__": "NC"},
                {"shape": (batch_size, self._num_hidden), "__layout__": "NC"}]

    @property
    def _gate_names(self):
        return ("_i", "_f", "_c", "_o")

    def __call__(self, inputs, states):
        self._counter += 1
        name = f"{self._prefix}t{self._counter}_"
        i2h = sym.FullyConnected(data=inputs, weight=self._iW, bias=self._iB,
                                 num_hidden=self._num_hidden * 4,
                                 name=name + "i2h")
        h2h = sym.FullyConnected(data=states[0], weight=self._hW,
                                 bias=self._hB,
                                 num_hidden=self._num_hidden * 4,
                                 name=name + "h2h")
        gates = i2h + h2h
        i, f, g, o = sym.SliceChannel(gates, num_outputs=4, axis=-1,
                                      name=name + "slice")
        in_gate = sym.Activation(i, act_type="sigmoid")
        forget_gate = sym.Activation(f, act_type="sigmoid")
        in_transform = sym.Activation(g, act_type="tanh")
        out_gate = sym.Activation(o, act_type="sigmoid")
        next_c = forget_gate * states[1] + in_gate * in_transform
        next_h = out_gate * sym.Activation(next_c, act_type="tanh")
        return next_h, [next_h, next_c]


class GRUCell(BaseRNNCell):
    """GRU (gates r,z,n; reset applied to the h2h candidate — reference
    rnn_cell.GRUCell)."""

    def __init__(self, num_hidden, prefix="gru_", params=None):
        super().__init__(prefix, params)
        self._num_hidden = num_hidden
        self._iW = self.params.get("i2h_weight")
        self._iB = self.params.get("i2h_bias")
        self._hW = self.params.get("h2h_weight")
        self._hB = self.params.get("h2h_bias")

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._num_hidden),
                 "__layout__": "NC"}]

    @property
    def _gate_names(self):
        return ("_r", "_z", "_o")

    def __call__(self, inputs, states):
        self._counter += 1
        name = f"{self._prefix}t{self._counter}_"
        prev_h = states[0]
        i2h = sym.FullyConnected(data=inputs, weight=self._iW, bias=self._iB,
                                 num_hidden=self._num_hidden * 3,
                                 name=name + "i2h")
        h2h = sym.FullyConnected(data=prev_h, weight=self._hW, bias=self._hB,
                                 num_hidden=self._num_hidden * 3,
                                 name=name + "h2h")
        i2h_r, i2h_z, i2h_n = sym.SliceChannel(
            i2h, num_outputs=3, axis=-1, name=name + "i2h_slice")
        h2h_r, h2h_z, h2h_n = sym.SliceChannel(
            h2h, num_outputs=3, axis=-1, name=name + "h2h_slice")
        reset = sym.Activation(i2h_r + h2h_r, act_type="sigmoid")
        update = sym.Activation(i2h_z + h2h_z, act_type="sigmoid")
        cand = sym.Activation(i2h_n + reset * h2h_n, act_type="tanh")
        ones = update * 0 + 1  # symbolic 1 with update's shape
        next_h = (ones - update) * cand + update * prev_h
        return next_h, [next_h]


class FusedRNNCell(BaseRNNCell):
    """Fused multi-layer RNN over one packed parameter vector — emits the
    `RNN` op (one lax.scan on device; reference: cuDNN path of
    src/operator/rnn.cc). Only `unroll` is supported, like the reference."""

    def __init__(self, num_hidden, num_layers=1, mode="lstm",
                 bidirectional=False, dropout=0.0, get_next_state=False,
                 forget_bias=1.0, prefix=None, params=None):
        if prefix is None:
            prefix = f"{mode}_"
        super().__init__(prefix, params)
        self._num_hidden = num_hidden
        self._num_layers = num_layers
        self._mode = mode
        self._bidirectional = bidirectional
        self._dropout = dropout
        self._get_next_state = get_next_state
        self._forget_bias = forget_bias
        # forget_bias rides the packed-parameter initializer (reference
        # init.FusedRNN), so fused init matches unfuse()'s LSTMCells
        from .. import initializer as _init
        self._param = self.params.get(
            "parameters",
            init=_init.FusedRNN(None, num_hidden, num_layers, mode,
                                bidirectional, forget_bias)
            if mode == "lstm" else None)

    @property
    def _num_gates(self):
        from ..ops._rnn import GATES
        return GATES[self._mode]

    def state_info(self, batch_size=0):
        b = self._num_layers * (2 if self._bidirectional else 1)
        info = [{"shape": (b, batch_size, self._num_hidden),
                 "__layout__": "LNC"}]
        if self._mode == "lstm":
            info.append({"shape": (b, batch_size, self._num_hidden),
                         "__layout__": "LNC"})
        return info

    def param_size(self, input_size):
        """Length of the packed parameter vector (rnn-inl.h layout —
        shared helper with the RNN op's shape-inference hint)."""
        from ..ops._rnn import packed_param_size
        return packed_param_size(self._mode, self._num_layers,
                                 self._bidirectional, input_size,
                                 self._num_hidden)

    def __call__(self, inputs, states):
        raise NotImplementedError(
            "FusedRNNCell cannot be stepped; use unroll()")

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None):
        self.reset()
        inputs, _ = _normalize_sequence(length, inputs, layout, True)
        if layout == "NTC":
            inputs = sym.transpose(inputs, axes=(1, 0, 2))
        if begin_state is None:
            begin_state = self.begin_state()
        states = list(begin_state)
        rnn = sym.RNN(data=inputs, parameters=self._param,
                      state=states[0],
                      state_cell=states[1] if self._mode == "lstm" else None,
                      mode=self._mode, state_size=self._num_hidden,
                      num_layers=self._num_layers,
                      bidirectional=self._bidirectional, p=self._dropout,
                      state_outputs=self._get_next_state,
                      name=self._prefix + "rnn")
        if self._get_next_state:
            outputs = rnn[0]
            next_states = [rnn[i] for i in range(1, len(self.state_info()) + 1)]
        else:
            outputs, next_states = rnn, []
        if layout == "NTC":
            outputs = sym.transpose(outputs, axes=(1, 0, 2))
        if merge_outputs is False:
            outputs = list(sym.SliceChannel(
                outputs, num_outputs=length, axis=layout.find("T"),
                squeeze_axis=True))
        return outputs, next_states

    def unfuse(self):
        """Equivalent stack of unfused cells (reference
        FusedRNNCell.unfuse) — same math, stepping-capable."""
        stack = SequentialRNNCell()
        get_cell = {
            "rnn_relu": lambda p: RNNCell(self._num_hidden, "relu", p),
            "rnn_tanh": lambda p: RNNCell(self._num_hidden, "tanh", p),
            "lstm": lambda p: LSTMCell(self._num_hidden, p,
                                       forget_bias=self._forget_bias),
            "gru": lambda p: GRUCell(self._num_hidden, p),
        }[self._mode]
        for i in range(self._num_layers):
            if self._bidirectional:
                stack.add(BidirectionalCell(
                    get_cell(f"{self._prefix}l{i}_"),
                    get_cell(f"{self._prefix}r{i}_"),
                    output_prefix=f"{self._prefix}bi_l{i}_"))
            else:
                stack.add(get_cell(f"{self._prefix}l{i}_"))
            if self._dropout > 0 and i != self._num_layers - 1:
                stack.add(DropoutCell(self._dropout,
                                      prefix=f"{self._prefix}_dropout{i}_"))
        return stack


class SequentialRNNCell(BaseRNNCell):
    """Stack cells vertically (reference rnn_cell.SequentialRNNCell)."""

    def __init__(self, params=None):
        super().__init__(prefix="", params=params)
        self._cells = []

    def add(self, cell):
        self._cells.append(cell)

    def state_info(self, batch_size=0):
        infos = []
        for c in self._cells:
            infos.extend(c.state_info(batch_size))
        return infos

    def begin_state(self, **kwargs):
        assert not self._modified
        states = []
        for c in self._cells:
            states.extend(c.begin_state(**kwargs))
        return states

    def __call__(self, inputs, states):
        next_states = []
        p = 0
        for cell in self._cells:
            assert not isinstance(cell, FusedRNNCell)
            n = len(cell.state_info())
            inputs, cstates = cell(inputs, states[p:p + n])
            next_states.extend(cstates)
            p += n
        return inputs, next_states

    def reset(self):
        super().reset()
        for c in getattr(self, "_cells", ()):
            c.reset()


class BidirectionalCell(BaseRNNCell):
    """Run two cells over the sequence in opposite directions and concat
    their step outputs (reference rnn_cell.BidirectionalCell). Only
    `unroll` is defined, as in the reference."""

    def __init__(self, l_cell, r_cell, params=None, output_prefix="bi_"):
        super().__init__("", params)
        self._l_cell = l_cell
        self._r_cell = r_cell
        self._output_prefix = output_prefix

    def state_info(self, batch_size=0):
        return (self._l_cell.state_info(batch_size)
                + self._r_cell.state_info(batch_size))

    def begin_state(self, **kwargs):
        return (self._l_cell.begin_state(**kwargs)
                + self._r_cell.begin_state(**kwargs))

    def __call__(self, inputs, states):
        raise NotImplementedError("BidirectionalCell cannot be stepped; "
                                  "use unroll()")

    def reset(self):
        super().reset()
        for c in (getattr(self, "_l_cell", None),
                  getattr(self, "_r_cell", None)):
            if c is not None:
                c.reset()

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None):
        self.reset()
        inputs, axis = _normalize_sequence(length, inputs, layout, False)
        if begin_state is None:
            begin_state = self.begin_state()
        nl = len(self._l_cell.state_info())
        l_out, l_states = self._l_cell.unroll(
            length, inputs, begin_state[:nl], layout, merge_outputs=False)
        r_out, r_states = self._r_cell.unroll(
            length, list(reversed(inputs)), begin_state[nl:], layout,
            merge_outputs=False)
        outputs = [
            sym.Concat(l, r, dim=1,
                       name=f"{self._output_prefix}t{i}")
            for i, (l, r) in enumerate(zip(l_out, reversed(r_out)))]
        outputs, _ = _normalize_sequence(length, outputs, layout,
                                         merge_outputs)
        return outputs, l_states + r_states


class ModifierCell(BaseRNNCell):
    """Wrap a cell, reusing its params/states (reference
    rnn_cell.ModifierCell)."""

    def __init__(self, base_cell):
        super().__init__()
        base_cell._modified = True
        self.base_cell = base_cell

    @property
    def params(self):
        self._own_params = False
        return self.base_cell.params

    def state_info(self, batch_size=0):
        return self.base_cell.state_info(batch_size)

    def begin_state(self, **kwargs):
        assert not self._modified
        self.base_cell._modified = False
        begin = self.base_cell.begin_state(**kwargs)
        self.base_cell._modified = True
        return begin

    def reset(self):
        super().reset()
        if hasattr(self, "base_cell"):
            self.base_cell.reset()


class DropoutCell(BaseRNNCell):
    """Apply dropout on the input sequence (reference
    rnn_cell.DropoutCell). Stateless."""

    def __init__(self, dropout, prefix="dropout_", params=None):
        super().__init__(prefix, params)
        self.dropout = dropout

    def state_info(self, batch_size=0):
        return []

    def __call__(self, inputs, states):
        if self.dropout > 0:
            inputs = sym.Dropout(data=inputs, p=self.dropout)
        return inputs, states


class ResidualCell(ModifierCell):
    """output = base(inputs) + inputs (reference rnn_cell.ResidualCell)."""

    def __call__(self, inputs, states):
        output, states = self.base_cell(inputs, states)
        output = sym.elemwise_add(output, inputs,
                                  name=f"{output.name}_plus_residual")
        return output, states

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None):
        self.reset()
        self.base_cell._modified = False
        outputs, states = self.base_cell.unroll(
            length, inputs, begin_state, layout, merge_outputs=False)
        self.base_cell._modified = True
        inputs, _ = _normalize_sequence(length, inputs, layout, False)
        outputs = [sym.elemwise_add(o, i) for o, i in zip(outputs, inputs)]
        outputs, _ = _normalize_sequence(length, outputs, layout,
                                         merge_outputs)
        return outputs, states
