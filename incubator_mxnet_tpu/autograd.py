"""Imperative autograd: tape over per-op `jax.vjp`.

Reference parity: python/mxnet/autograd.py (record/pause/train_mode/
predict_mode, mark_variables, backward, grad, custom Function). The reference
records a tape in the C++ executor; here each eager op records a Node whose
`fn` is the pure jax function that produced it. backward() walks the tape in
reverse topological order calling `jax.vjp(fn, *saved_inputs)`. Because the
walk itself emits ops through the same recording machinery, `create_graph=True`
(higher-order grad) works by simply leaving recording on during the walk.

The hybridized/jitted path does NOT use this tape — `HybridBlock.hybridize`
differentiates the whole traced graph with `jax.grad` inside one XLA
computation (see gluon/block.py), which is the performance path on TPU.
"""
from __future__ import annotations

import threading
from typing import List, Optional, Sequence

import jax
import numpy as np

from . import profiler as _prof

__all__ = [
    "record", "pause", "train_mode", "predict_mode", "is_recording",
    "is_training", "mark_variables", "backward", "grad", "Function",
    "get_symbol",
]

_STATE = threading.local()


def _st():
    if not hasattr(_STATE, "recording"):
        _STATE.recording = False
        _STATE.training = False
    return _STATE


def is_recording() -> bool:
    return _st().recording


def is_training() -> bool:
    return _st().training


class _Scope:
    def __init__(self, recording, training):
        self._rec, self._train = recording, training

    def __enter__(self):
        st = _st()
        self._old = (st.recording, st.training)
        if self._rec is not None:
            st.recording = self._rec
        if self._train is not None:
            st.training = self._train
        return self

    def __exit__(self, *exc):
        st = _st()
        st.recording, st.training = self._old
        return False


def record(train_mode: bool = True) -> _Scope:
    return _Scope(True, train_mode)


def pause(train_mode: bool = False) -> _Scope:
    return _Scope(False, train_mode)


def train_mode() -> _Scope:
    return _Scope(None, True)


def predict_mode() -> _Scope:
    return _Scope(None, False)


class Node:
    """One recorded eager op.

    fn            : pure function raw-arrays -> raw array | tuple of raws
    input_values  : raw jax arrays at record time (immutable snapshot, so
                    later in-place NDArray mutation can't corrupt the tape)
    parents       : per input, (Node, out_index) | None
    leaf_refs     : per input, the producing NDArray if it was a leaf
    out_avals     : [(shape, dtype)] per output
    """

    __slots__ = ("fn", "fn_vjp", "input_values", "parents", "leaf_refs",
                 "out_avals", "n_out", "name")

    def __init__(self, fn, input_values, parents, leaf_refs, out_avals,
                 name=None, fn_vjp=None):
        self.fn = fn
        self.fn_vjp = fn_vjp  # optional precompiled pullback (CachedOp path)
        self.input_values = input_values
        self.parents = parents
        self.leaf_refs = leaf_refs
        self.out_avals = out_avals
        self.n_out = len(out_avals)
        self.name = name


def _record_op(fn, nd_inputs, raw_inputs, nd_outputs, name=None, fn_vjp=None):
    """Called by ndarray._apply for every eager op while recording."""
    parents, leaf_refs = [], []
    for x in nd_inputs:
        parents.append(x._node)
        leaf_refs.append(x if x._grad_req is not None else None)
    out_avals = [(tuple(o._data.shape), o._data.dtype) for o in nd_outputs]
    node = Node(fn, tuple(raw_inputs), parents, leaf_refs, out_avals, name, fn_vjp)
    for i, o in enumerate(nd_outputs):
        o._node = (node, i)
    return node


def mark_variables(variables, gradients, grad_reqs="write"):
    """Parity: autograd.mark_variables — associate grad buffers with vars."""
    if isinstance(grad_reqs, str):
        grad_reqs = [grad_reqs] * len(variables)
    for v, g, req in zip(variables, gradients, grad_reqs):
        v._grad_req = None if req == "null" else req
        v._grad = g
        v._node = None


# ---------------------------------------------------------------------------
# backward engine
# ---------------------------------------------------------------------------

def _toposort(roots: Sequence[Node]) -> List[Node]:
    order, state = [], {}

    def visit(n):
        stack = [(n, iter([p for p in n.parents if p is not None]))]
        state[id(n)] = 1
        while stack:
            node, it = stack[-1]
            advanced = False
            for (pnode, _pi) in it:
                s = state.get(id(pnode), 0)
                if s == 0:
                    state[id(pnode)] = 1
                    stack.append((pnode, iter([p for p in pnode.parents if p is not None])))
                    advanced = True
                    break
            if not advanced:
                state[id(node)] = 2
                order.append(node)
                stack.pop()

    for r in roots:
        if state.get(id(r), 0) == 0:
            visit(r)
    return order  # parents before children


def _make_vjp_fn(fn, n_in, single_out):
    """Pure function (inputs..., out_cotangents...) -> input cotangents tuple.
    Being pure jax, it is itself recordable → higher-order autograd."""

    def vjp_fn(*args):
        primals, cots = args[:n_in], args[n_in:]
        _, pullback = jax.vjp(lambda *p: fn(*p), *primals)
        in_cots = pullback(cots[0] if single_out else tuple(cots))
        return in_cots[0] if n_in == 1 else in_cots

    return vjp_fn


def _is_float(dt) -> bool:
    return np.issubdtype(np.dtype(dt), np.inexact) or dt == jax.dtypes.bfloat16


def backward(heads, head_grads=None, retain_graph=False, train_mode=True):
    """Compute gradients of `heads` w.r.t. all leaves with grads attached,
    accumulating into each leaf's `.grad` per its grad_req."""
    if _prof._ACTIVE:
        with _prof.Scope("autograd.backward", "autograd", sync=False):
            return _grad_impl(heads, head_grads, variables=None,
                              create_graph=False)
    grads = _grad_impl(heads, head_grads, variables=None, create_graph=False)
    return grads


def grad(heads, variables, head_grads=None, retain_graph=None,
         create_graph=False, train_mode=True):
    """Parity: autograd.grad — return grads w.r.t. `variables` instead of
    writing .grad. With create_graph=True the returned grads are themselves
    recorded, enabling grad-of-grad."""
    from . import ndarray as _nd
    single = not isinstance(variables, (list, tuple))
    varlist = [variables] if single else list(variables)
    if _prof._ACTIVE:
        with _prof.Scope("autograd.grad", "autograd", sync=False):
            out = _grad_impl(heads, head_grads, variables=varlist,
                             create_graph=create_graph)
    else:
        out = _grad_impl(heads, head_grads, variables=varlist, create_graph=create_graph)
    missing = [i for i, g in enumerate(out) if g is None]
    if missing:
        out = [g if g is not None else _nd.zeros_like(varlist[i])
               for i, g in enumerate(out)]
    return out[0] if single else out


def _grad_impl(heads, head_grads, variables, create_graph):
    from . import bulk as _bulk
    from . import ndarray as _nd

    # pending deferred segment: the tape's saved values and head data must
    # be concrete before the reverse walk reads them. Unconditional (not
    # gated on _bulk._ON): a segment may outlive its scope/auto-bulk mode
    # on another thread, and flush() is a cheap thread-local check.
    _bulk.flush("backward")
    if not isinstance(heads, (list, tuple)):
        heads = [heads]
    if head_grads is None:
        head_grads = [None] * len(heads)
    elif not isinstance(head_grads, (list, tuple)):
        head_grads = [head_grads]

    # Seed cotangents, keyed by (id(node), out_index).
    cot = {}
    node_by_id = {}
    roots = []
    # Pre-read variables index so a head that IS a variable seeds directly.
    pre_var_index = None
    if variables is not None:
        pre_var_index = {id(v): i for i, v in enumerate(variables)}
    pre_var_seeds = {}

    for h, hg in zip(heads, head_grads):
        if h._node is None:
            seed = hg if hg is not None else _nd.ones_like(h)
            if variables is None:
                # head is itself a leaf: d head/d head = head_grad
                if h._grad_req is not None:
                    _accumulate_leaf(h, seed)
            elif id(h) in pre_var_index:
                j = pre_var_index[id(h)]
                pre_var_seeds[j] = seed if j not in pre_var_seeds else pre_var_seeds[j] + seed
            continue
        node, oi = h._node
        seed = hg if hg is not None else _nd.ones_like(h)
        key = (id(node), oi)
        cot[key] = seed if key not in cot else cot[key] + seed
        node_by_id[id(node)] = node
        roots.append(node)

    if not roots and variables is None:
        return None

    order = _toposort(roots)

    # Collected grads for explicit `variables` mode.
    var_index = None
    var_grads = None
    if variables is not None:
        var_index = pre_var_index
        var_grads = [None] * len(variables)
        for j, seed in pre_var_seeds.items():
            var_grads[j] = seed
    # Per-leaf accumulation within this walk (grad_req only governs how the
    # final total is combined with any pre-existing .grad).
    leaf_acc = {}

    # Early finalization (backward mode): once the walk passes the LAST
    # node that can contribute to a leaf, that leaf's grad is final — write
    # it and fire its grad hook right there, mid-backward. This is the
    # readiness signal ByteScheduler-style overlapped communication needs
    # (reference: BytePS per-tensor ready callbacks); without it every
    # push would wait for the whole backward pass.
    rev_order = list(reversed(order))
    finalize_after = {}
    if variables is None:
        last_contrib = {}
        any_hook = False
        for idx, node in enumerate(rev_order):
            for leaf in node.leaf_refs:
                if leaf is not None:
                    last_contrib[id(leaf)] = idx
                    any_hook = any_hook or leaf._grad_hook is not None
        # no hooks registered -> skip the per-node finalize machinery
        # entirely (hot single-chip loops pay nothing; hooks then fire in
        # the end-of-walk loop, which is the no-overlap behavior anyway)
        if any_hook:
            for lid, idx in last_contrib.items():
                finalize_after.setdefault(idx, []).append(lid)

    def _finalize(lid):
        ent = leaf_acc.pop(lid, None)
        if ent is None:
            return
        leaf, g = ent
        _accumulate_leaf(leaf, g)
        if leaf._grad_hook is not None:
            leaf._grad_hook(leaf)

    rec_scope = record() if create_graph else pause()
    with rec_scope:
        for walk_idx, node in enumerate(rev_order):
            outs = []
            have_any = False
            for oi in range(node.n_out):
                c = cot.pop((id(node), oi), None)
                if c is None:
                    shape, dt = node.out_avals[oi]
                    c = _nd.zeros(shape, dtype=dt)
                else:
                    have_any = True
                outs.append(c)
            if not have_any:
                for lid in finalize_after.get(walk_idx, ()):
                    _finalize(lid)
                continue
            n_in = len(node.input_values)
            if isinstance(node.fn, _CustomFn):
                in_cots = node.fn.func.backward(*outs)
                if not isinstance(in_cots, (list, tuple)):
                    in_cots = (in_cots,)
            else:
                vjp = node.fn_vjp or _make_vjp_fn(node.fn, n_in, node.n_out == 1)
                in_shells = []
                for i in range(n_in):
                    leaf = node.leaf_refs[i]
                    if leaf is not None and leaf._data is node.input_values[i]:
                        # Reuse the original leaf so a create_graph walk
                        # records it (identity matters for grad routing).
                        in_shells.append(leaf)
                    else:
                        in_shells.append(
                            _nd.NDArray(node.input_values[i], _node=node.parents[i]))
                in_cots = _nd._apply(vjp, in_shells + outs, n_out=n_in,
                                     name=(node.name or "op") + "_backward")
                if n_in == 1:
                    in_cots = (in_cots,)
            for i, g in enumerate(in_cots):
                if not _is_float(node.input_values[i].dtype):
                    continue
                parent = node.parents[i]
                leaf = node.leaf_refs[i]
                if parent is not None:
                    pnode, pi = parent
                    key = (id(pnode), pi)
                    cot[key] = g if key not in cot else cot[key] + g
                elif leaf is not None:
                    if var_index is not None and id(leaf) in var_index:
                        j = var_index[id(leaf)]
                        var_grads[j] = g if var_grads[j] is None else var_grads[j] + g
                    elif var_index is None:
                        k = id(leaf)
                        if k in leaf_acc:
                            leaf_acc[k] = (leaf, leaf_acc[k][1] + g)
                        else:
                            leaf_acc[k] = (leaf, g)
                # else: constant input, discard
            for lid in finalize_after.get(walk_idx, ()):
                _finalize(lid)

        # backward mode without hooks lands every leaf here (the early
        # finalize machinery is skipped then); with hooks, the early pass
        # popped them all already. Explicit-variables mode never fills
        # leaf_acc, so grad() does not drive overlapped communication.
        for leaf, g in list(leaf_acc.values()):
            _accumulate_leaf(leaf, g)
        leaf_acc.clear()

    return var_grads


def _accumulate_leaf(leaf, g):
    from .ndarray import sparse as _sparse
    if isinstance(g, _sparse.RowSparseNDArray):
        # sparse embedding gradient: 'write' stores the RowSparse object
        # itself (the whole point — optimizers take the lazy-row path);
        # 'add' over an existing buffer merges sparsely or densifies.
        if leaf._grad_req == "add" and leaf._grad is not None:
            if isinstance(leaf._grad, _sparse.RowSparseNDArray):
                leaf._grad = leaf._grad + g
            else:
                leaf._grad._data = (leaf._grad._data
                                    + g.todense()._data.astype(leaf._grad._data.dtype))
        else:
            leaf._grad = g
        return
    if isinstance(leaf._grad, _sparse.RowSparseNDArray):
        # dense grad arriving over a sparse buffer from a previous step
        if leaf._grad_req == "add":
            from . import ndarray as _nd
            leaf._grad = _nd.NDArray(leaf._grad.todense()._data + g._data)
            return
        leaf._grad = None  # fall through to dense write below
    if leaf._grad_req == "add" and leaf._grad is not None:
        leaf._grad._data = (leaf._grad._data + g._data).astype(leaf._grad._data.dtype)
    else:  # 'write'
        if leaf._grad is None:
            from . import ndarray as _nd
            leaf._grad = _nd.zeros_like(leaf)
        leaf._grad._data = g._data.astype(leaf._grad._data.dtype)


# ---------------------------------------------------------------------------
# Custom differentiable Function (parity: mx.autograd.Function)
# ---------------------------------------------------------------------------

class Function:
    """User-defined op with explicit forward/backward.

    Subclass and implement ``forward(self, *inputs)`` and
    ``backward(self, *output_grads)`` operating on NDArrays; call the
    instance. Saved tensors go through ``self.save_for_backward``.
    """

    def __init__(self):
        self._saved = ()

    def save_for_backward(self, *arrs):
        self._saved = arrs

    def __call__(self, *inputs):
        from . import ndarray as _nd
        with pause():
            outputs = self.forward(*inputs)
        single = not isinstance(outputs, (list, tuple))
        outs = [outputs] if single else list(outputs)
        if is_recording():
            func = self
            # Record a node whose vjp is supplied by the user's backward().
            parents, leaf_refs = [], []
            for x in inputs:
                parents.append(x._node)
                leaf_refs.append(x if x._grad_req is not None else None)
            out_avals = [(tuple(o._data.shape), o._data.dtype) for o in outs]
            node = Node(None, tuple(x._data for x in inputs), parents,
                        leaf_refs, out_avals, type(self).__name__)
            node.fn = _CustomFn(func, len(inputs))
            for i, o in enumerate(outs):
                o._node = (node, i)
        return outputs

    def forward(self, *inputs):
        raise NotImplementedError

    def backward(self, *output_grads):
        raise NotImplementedError


class _CustomFn:
    """Adapter so the backward engine can vjp a user Function: jax.vjp is
    bypassed — the user's backward computes input cotangents directly."""

    def __init__(self, func, n_in):
        self.func = func
        self.n_in = n_in

    def __call__(self, *raws):  # only used if someone re-runs forward
        raise RuntimeError("custom Function cannot be re-executed from the tape")


def get_symbol(x):
    """Parity: mx.autograd.get_symbol (python/mxnet/autograd.py) — lift the
    recorded tape that produced NDArray `x` into a Symbol graph.

    Each tape node becomes a graph node that replays the same pure jax
    function (so bind/forward/backward give identical numerics and
    gradients to the tape); grad-attached leaf arrays become Variables
    named var0, var1, ... in the order a depth-first walk over inputs
    from the output first reaches them (still read
    `result.list_arguments()` for the binding order rather than assuming
    trace order); constants captured mid-graph are baked in. The result
    composes/binds like any Symbol but is runtime-only (tojson raises —
    the fns are closures); for serializable graphs use HybridBlock.export
    + SymbolBlock (MIGRATION.md). Custom autograd.Function nodes cannot
    be lifted (their forward is not re-runnable) and raise here."""
    from .ndarray import NDArray
    from .symbol import Symbol, Variable, _make_op
    from .symbol import _auto_name as _sym_auto_name
    if not isinstance(x, NDArray):
        raise TypeError(f"get_symbol expects an NDArray, got {type(x)}")
    if x._node is None:
        raise ValueError(
            "array carries no recorded graph; compute it under "
            "autograd.record() first")

    memo = {}        # id(tape Node) -> Symbol with all its outputs
    leaf_syms = {}   # id(leaf NDArray) -> Variable
    counter = [0]

    def lift(node):
        """Build this node's Symbol; every parent is already in memo."""
        if isinstance(node.fn, _CustomFn):
            raise ValueError(
                f"tape contains a custom autograd.Function "
                f"({node.fn.func and type(node.fn.func).__name__}); its "
                f"forward cannot be re-executed, so this graph cannot be "
                f"lifted to a Symbol")
        in_syms = []
        for i, parent in enumerate(node.parents):
            if parent is not None:
                pnode, pidx = parent
                in_syms.append(Symbol([memo[id(pnode)]._entries[pidx]]))
            else:
                leaf = node.leaf_refs[i]
                if leaf is not None:
                    if id(leaf) not in leaf_syms:
                        leaf_syms[id(leaf)] = Variable(f"var{counter[0]}")
                        counter[0] += 1
                    in_syms.append(leaf_syms[id(leaf)])
                else:
                    in_syms.append(_make_op(
                        "_traced_const", [],
                        {"__value__": node.input_values[i]}))
        return _make_op("_traced_fn", in_syms,
                        {"__fn__": node.fn, "n_out": node.n_out},
                        name=_sym_auto_name(node.name or "traced_fn"))

    root, idx = x._node

    # pre-pass: name leaves in EXACT depth-first first-reach order from
    # the output (the documented var0/var1/... rule) — the lift below runs
    # post-order, which would number them differently
    visited = set()
    walk = [root]
    while walk:
        item = walk.pop()
        if isinstance(item, tuple):               # ("leaf", ndarray)
            leaf = item[1]
            if id(leaf) not in leaf_syms:
                from .symbol import Variable as _Var
                leaf_syms[id(leaf)] = _Var(f"var{counter[0]}")
                counter[0] += 1
            continue
        if id(item) in visited:
            continue
        visited.add(id(item))
        entries = []
        for i, parent in enumerate(item.parents):
            if parent is None:
                if item.leaf_refs[i] is not None:
                    entries.append(("leaf", item.leaf_refs[i]))
            else:
                entries.append(parent[0])
        walk.extend(reversed(entries))            # input 0 reached first

    # iterative post-order: eager-loop tapes run thousands of ops deep,
    # past Python's recursion limit (the backward engine walks its
    # toposort iteratively for the same reason)
    stack = [root]
    while stack:
        node = stack[-1]
        if id(node) in memo:
            stack.pop()
            continue
        pending = [p[0] for p in node.parents
                   if p is not None and id(p[0]) not in memo]
        if pending:
            stack.extend(reversed(pending))   # input 0's subtree lifts first
            continue
        stack.pop()
        memo[id(node)] = lift(node)

    return Symbol([memo[id(root)]._entries[idx]])
