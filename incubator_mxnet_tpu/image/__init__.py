"""Image API (parity: reference python/mxnet/image/image.py + the augmenter
stack of src/io/image_aug_default.cc): decode, resize, crop, color jitter,
and composable Augmenters feeding the training input pipeline.

TPU-first design: everything here is the HOST side of the input pipeline —
decode (PIL's C JPEG codec replacing the reference's OpenCV), numpy
augmentation, batch assembly — and runs on DataLoader/ImageRecordIter
worker threads under the native C++ prefetch runtime so the chip never
waits on input. Per-image work never touches the device; only assembled
batches are transferred (one host->device copy per batch).

Functions accept and return `NDArray` (HWC, like the reference) but carry a
numpy fast path internally (`_as_np`) so per-image augmentation costs no
device round-trips.
"""
from __future__ import annotations

import io as _io
import random as _pyrandom

import numpy as np

from .. import ndarray as nd
from ..ndarray import NDArray

__all__ = [
    "imdecode", "imread", "imresize", "resize_short", "fixed_crop",
    "copyMakeBorder",
    "random_crop", "center_crop", "random_size_crop", "color_normalize",
    "Augmenter", "SequentialAug", "ResizeAug", "ForceResizeAug",
    "RandomCropAug", "CenterCropAug", "RandomSizedCropAug",
    "HorizontalFlipAug", "CastAug", "ColorNormalizeAug",
    "BrightnessJitterAug", "ContrastJitterAug", "SaturationJitterAug",
    "HueJitterAug", "ColorJitterAug", "LightingAug", "RandomGrayAug",
    "RandomOrderAug", "CreateAugmenter", "ImageIter",
    "DetAugmenter", "DetBorderAug", "DetHorizontalFlipAug",
    "DetRandomCropAug", "CreateDetAugmenter", "ImageDetIter",
]

_GRAY = np.array([0.299, 0.587, 0.114], dtype=np.float32)


def _as_np(img):
    if isinstance(img, NDArray):
        return img.asnumpy()
    return np.asarray(img)


def _wrap(arr, like):
    return nd.array(arr) if isinstance(like, NDArray) or like is None else arr


# ---------------------------------------------------------------------------
# decode / geometric ops
# ---------------------------------------------------------------------------

def imdecode(buf, flag=1, to_rgb=True):
    """Decode an encoded (JPEG/PNG/...) buffer to an HWC uint8 NDArray
    (reference mx.image.imdecode; flag=0 -> grayscale HW1).

    JPEG streams take the native libjpeg path (runtime.decode_jpeg,
    GIL-free — the rebuild of the reference's opencv decode in
    src/io/iter_image_recordio_2.cc); anything else, or a native-path
    failure, decodes via PIL."""
    if isinstance(buf, NDArray):
        buf = buf.asnumpy().tobytes()
    buf = bytes(buf)
    arr = None
    if buf[:2] == b"\xff\xd8":          # JPEG magic
        from .. import runtime as _runtime
        arr = _runtime.decode_jpeg(buf, channels=3)
    if arr is None:
        from PIL import Image
        img = Image.open(_io.BytesIO(buf))
        img = img.convert("RGB")
        arr = np.asarray(img, dtype=np.uint8)
    if flag == 0:
        # PIL's exact ITU-R 601 integer luma ((19595R+38470G+7471B+2^15)
        # >> 16), applied to the RGB decode on BOTH paths so grayscale
        # output is identical whether or not the native decoder built
        a32 = arr.astype(np.uint32)
        arr = ((19595 * a32[..., 0] + 38470 * a32[..., 1]
                + 7471 * a32[..., 2] + 32768) >> 16).astype(np.uint8)
    if not to_rgb and flag != 0:
        arr = arr[..., ::-1]  # reference BGR default when to_rgb=False
    if arr.ndim == 2:
        arr = arr[:, :, None]
    return nd.array(arr)


def imread(filename, flag=1, to_rgb=True):
    """Read an image file -> HWC uint8 NDArray (reference mx.image.imread)."""
    with open(filename, "rb") as f:
        return imdecode(f.read(), flag=flag, to_rgb=to_rgb)


def idx_path_for(path_imgrec):
    """The reference's .rec → .idx naming convention (one place)."""
    return (path_imgrec[:-4] + ".idx" if path_imgrec.endswith(".rec")
            else path_imgrec + ".idx")


def next_padded_indices(order, cursor, batch_size):
    """Shared batching tail for the image iterators: the index window at
    `cursor`, wrap-padded to a full batch (repeating from the start as
    many times as needed when the dataset is smaller than one batch).
    Returns (indices, n_pad); raises StopIteration at the end."""
    if cursor >= len(order):
        raise StopIteration
    idx = list(order[cursor:cursor + batch_size])
    pad = batch_size - len(idx)
    while len(idx) < batch_size:
        idx.extend(order[:batch_size - len(idx)])
    return idx, pad


def finalize_image(img, auglist, hw):
    """Shared tail of the sample pipeline: augment → float32 → fix any
    augmenter that left the wrong spatial size (reference iterators resize
    as a last resort). Returns HWC float32 at exactly (h, w)."""
    for aug in auglist:
        img = aug(img)
    img = _as_np(img).astype(np.float32, copy=False)
    h, w = hw
    if img.shape[:2] != (h, w):
        img = _pil_resize(img.astype(np.uint8), w, h, 2).astype(np.float32)
    return img


def _pil_resize(arr, w, h, interp):
    from PIL import Image

    resamples = {0: Image.NEAREST, 1: Image.BILINEAR, 2: Image.BICUBIC,
                 3: Image.LANCZOS, 4: Image.LANCZOS}
    squeeze = arr.shape[-1] == 1
    pil = Image.fromarray(arr[..., 0] if squeeze else arr)
    out = np.asarray(pil.resize((int(w), int(h)),
                                resamples.get(interp, Image.BILINEAR)))
    if squeeze:
        out = out[:, :, None]
    return out


def imresize(src, w, h, interp=1):
    """Resize to exactly (w, h) (reference mx.image.imresize)."""
    arr = _as_np(src)
    return _wrap(_pil_resize(arr, w, h, interp), src)


def resize_short(src, size, interp=2):
    """Resize so the SHORT side equals `size`, preserving aspect
    (reference mx.image.resize_short)."""
    arr = _as_np(src)
    h, w = arr.shape[:2]
    if h > w:
        new_w, new_h = size, int(h * size / w)
    else:
        new_w, new_h = int(w * size / h), size
    return _wrap(_pil_resize(arr, new_w, new_h, interp), src)


def fixed_crop(src, x0, y0, w, h, size=None, interp=2):
    """Crop the (x0, y0, w, h) window, optionally resize to `size` (w, h)."""
    arr = _as_np(src)[y0:y0 + h, x0:x0 + w]
    if size is not None and (w, h) != size:
        arr = _pil_resize(arr, size[0], size[1], interp)
    return _wrap(arr, src)


def random_crop(src, size, interp=2):
    """Random crop of exactly `size`=(w, h) (pre-resized up if smaller);
    returns (cropped, (x0, y0, w, h)) like the reference."""
    arr = _as_np(src)
    h, w = arr.shape[:2]
    new_w, new_h = min(size[0], w), min(size[1], h)
    x0 = _pyrandom.randint(0, w - new_w)
    y0 = _pyrandom.randint(0, h - new_h)
    out = fixed_crop(_wrap(arr, src), x0, y0, new_w, new_h, size, interp)
    return out, (x0, y0, new_w, new_h)


def center_crop(src, size, interp=2):
    """Center crop to `size`=(w, h); returns (cropped, window)."""
    arr = _as_np(src)
    h, w = arr.shape[:2]
    new_w, new_h = min(size[0], w), min(size[1], h)
    x0 = (w - new_w) // 2
    y0 = (h - new_h) // 2
    out = fixed_crop(_wrap(arr, src), x0, y0, new_w, new_h, size, interp)
    return out, (x0, y0, new_w, new_h)


def random_size_crop(src, size, area, ratio, interp=2, max_attempts=10):
    """RandomResizedCrop: crop a random area/aspect window, resize to `size`
    (reference mx.image.random_size_crop; the Inception-style augmenter)."""
    arr = _as_np(src)
    h, w = arr.shape[:2]
    src_area = h * w
    if isinstance(area, (int, float)):
        area = (area, 1.0)
    for _ in range(max_attempts):
        target_area = _pyrandom.uniform(area[0], area[1]) * src_area
        log_ratio = (np.log(ratio[0]), np.log(ratio[1]))
        aspect = np.exp(_pyrandom.uniform(*log_ratio))
        new_w = int(round(np.sqrt(target_area * aspect)))
        new_h = int(round(np.sqrt(target_area / aspect)))
        if new_w <= w and new_h <= h:
            x0 = _pyrandom.randint(0, w - new_w)
            y0 = _pyrandom.randint(0, h - new_h)
            out = fixed_crop(_wrap(arr, src), x0, y0, new_w, new_h, size,
                             interp)
            return out, (x0, y0, new_w, new_h)
    return center_crop(src, size, interp)


def color_normalize(src, mean, std=None):
    """(src - mean) / std, float32 (reference mx.image.color_normalize)."""
    arr = _as_np(src).astype(np.float32)
    mean_arr = _as_np(mean).astype(np.float32) if mean is not None else None
    if mean_arr is not None:
        arr = arr - mean_arr
    if std is not None:
        arr = arr / _as_np(std).astype(np.float32)
    return _wrap(arr, src)


# ---------------------------------------------------------------------------
# augmenters (reference Augmenter class hierarchy)
# ---------------------------------------------------------------------------

class Augmenter:
    """Composable image augmenter; __call__(img HWC NDArray) -> NDArray."""

    def __init__(self, **kwargs):
        self._kwargs = kwargs

    def dumps(self):
        import json
        return json.dumps([self.__class__.__name__, self._kwargs])

    def __call__(self, src):
        raise NotImplementedError


class SequentialAug(Augmenter):
    def __init__(self, ts):
        super().__init__()
        self.ts = list(ts)

    def __call__(self, src):
        for t in self.ts:
            src = t(src)
        return src


class RandomOrderAug(Augmenter):
    def __init__(self, ts):
        super().__init__()
        self.ts = list(ts)

    def __call__(self, src):
        order = list(range(len(self.ts)))
        _pyrandom.shuffle(order)
        for i in order:
            src = self.ts[i](src)
        return src


class ResizeAug(Augmenter):
    """resize_short to `size`."""

    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size, self.interp = size, interp

    def __call__(self, src):
        return resize_short(src, self.size, self.interp)


class ForceResizeAug(Augmenter):
    """Resize to exactly (w, h) ignoring aspect."""

    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size, self.interp = size, interp

    def __call__(self, src):
        return imresize(src, self.size[0], self.size[1], self.interp)


class RandomCropAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size, self.interp = size, interp

    def __call__(self, src):
        return random_crop(src, self.size, self.interp)[0]


class CenterCropAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size, self.interp = size, interp

    def __call__(self, src):
        return center_crop(src, self.size, self.interp)[0]


class RandomSizedCropAug(Augmenter):
    def __init__(self, size, area, ratio, interp=2):
        super().__init__(size=size, area=area, ratio=ratio, interp=interp)
        self.size, self.area, self.ratio, self.interp = size, area, ratio, \
            interp

    def __call__(self, src):
        return random_size_crop(src, self.size, self.area, self.ratio,
                                self.interp)[0]


class HorizontalFlipAug(Augmenter):
    def __init__(self, p=0.5):
        super().__init__(p=p)
        self.p = p

    def __call__(self, src):
        if _pyrandom.random() < self.p:
            return _wrap(_as_np(src)[:, ::-1], src)
        return src


class CastAug(Augmenter):
    def __init__(self, typ="float32"):
        super().__init__(typ=typ)
        self.typ = typ

    def __call__(self, src):
        return _wrap(_as_np(src).astype(self.typ), src)


class ColorNormalizeAug(Augmenter):
    def __init__(self, mean, std):
        super().__init__()
        self.mean = None if mean is None else np.asarray(mean, np.float32)
        self.std = None if std is None else np.asarray(std, np.float32)

    def __call__(self, src):
        return color_normalize(src, self.mean, self.std)


class BrightnessJitterAug(Augmenter):
    def __init__(self, brightness):
        super().__init__(brightness=brightness)
        self.brightness = brightness

    def __call__(self, src):
        alpha = 1.0 + _pyrandom.uniform(-self.brightness, self.brightness)
        return _wrap(_as_np(src).astype(np.float32) * alpha, src)


class ContrastJitterAug(Augmenter):
    def __init__(self, contrast):
        super().__init__(contrast=contrast)
        self.contrast = contrast

    def __call__(self, src):
        arr = _as_np(src).astype(np.float32)
        alpha = 1.0 + _pyrandom.uniform(-self.contrast, self.contrast)
        gray_mean = (arr * _GRAY).sum() / (arr.shape[0] * arr.shape[1])
        return _wrap(arr * alpha + gray_mean * (1.0 - alpha), src)


class SaturationJitterAug(Augmenter):
    def __init__(self, saturation):
        super().__init__(saturation=saturation)
        self.saturation = saturation

    def __call__(self, src):
        arr = _as_np(src).astype(np.float32)
        alpha = 1.0 + _pyrandom.uniform(-self.saturation, self.saturation)
        gray = (arr * _GRAY).sum(axis=2, keepdims=True)
        return _wrap(arr * alpha + gray * (1.0 - alpha), src)


class HueJitterAug(Augmenter):
    """Hue rotation in YIQ space (reference HueJitterAug's Gray-world
    approximation with the tyiq/ityiq matrices)."""

    _TYIQ = np.array([[0.299, 0.587, 0.114],
                      [0.596, -0.274, -0.321],
                      [0.211, -0.523, 0.311]], np.float32)
    _ITYIQ = np.array([[1.0, 0.956, 0.621],
                       [1.0, -0.272, -0.647],
                       [1.0, -1.107, 1.705]], np.float32)

    def __init__(self, hue):
        super().__init__(hue=hue)
        self.hue = hue

    def __call__(self, src):
        arr = _as_np(src).astype(np.float32)
        alpha = _pyrandom.uniform(-self.hue, self.hue)
        u, w_ = np.cos(alpha * np.pi), np.sin(alpha * np.pi)
        bt = np.array([[1.0, 0.0, 0.0],
                       [0.0, u, -w_],
                       [0.0, w_, u]], np.float32)
        t = self._ITYIQ @ bt @ self._TYIQ
        return _wrap(arr @ t.T, src)


class ColorJitterAug(RandomOrderAug):
    def __init__(self, brightness, contrast, saturation):
        ts = []
        if brightness > 0:
            ts.append(BrightnessJitterAug(brightness))
        if contrast > 0:
            ts.append(ContrastJitterAug(contrast))
        if saturation > 0:
            ts.append(SaturationJitterAug(saturation))
        super().__init__(ts)


class LightingAug(Augmenter):
    """AlexNet-style PCA lighting noise."""

    def __init__(self, alphastd, eigval, eigvec):
        super().__init__()
        self.alphastd = alphastd
        self.eigval = np.asarray(eigval, np.float32)
        self.eigvec = np.asarray(eigvec, np.float32)

    def __call__(self, src):
        alpha = np.random.normal(0, self.alphastd, size=(3,)).astype(
            np.float32)
        rgb = self.eigvec @ (alpha * self.eigval)
        return _wrap(_as_np(src).astype(np.float32) + rgb, src)


class RandomGrayAug(Augmenter):
    def __init__(self, p=0.5):
        super().__init__(p=p)
        self.p = p

    def __call__(self, src):
        if _pyrandom.random() < self.p:
            arr = _as_np(src).astype(np.float32)
            gray = (arr * _GRAY).sum(axis=2, keepdims=True)
            return _wrap(np.broadcast_to(gray, arr.shape).copy(), src)
        return src


def CreateAugmenter(data_shape, resize=0, rand_crop=False, rand_resize=False,
                    rand_mirror=False, mean=None, std=None, brightness=0,
                    contrast=0, saturation=0, hue=0, pca_noise=0,
                    rand_gray=0, inter_method=2):
    """Standard training augmenter stack (reference mx.image.CreateAugmenter).
    data_shape is CHW like the reference."""
    auglist = []
    if resize > 0:
        auglist.append(ResizeAug(resize, inter_method))
    crop_size = (data_shape[2], data_shape[1])
    if rand_resize:
        assert rand_crop
        auglist.append(RandomSizedCropAug(crop_size, (0.08, 1.0),
                                          (3.0 / 4.0, 4.0 / 3.0),
                                          inter_method))
    elif rand_crop:
        auglist.append(RandomCropAug(crop_size, inter_method))
    else:
        auglist.append(CenterCropAug(crop_size, inter_method))
    if rand_mirror:
        auglist.append(HorizontalFlipAug(0.5))
    auglist.append(CastAug())
    if brightness or contrast or saturation:
        auglist.append(ColorJitterAug(brightness, contrast, saturation))
    if hue:
        auglist.append(HueJitterAug(hue))
    if pca_noise > 0:
        auglist.append(LightingAug(
            pca_noise,
            [55.46, 4.794, 1.148],
            [[-0.5675, 0.7192, 0.4009],
             [-0.5808, -0.0045, -0.8140],
             [-0.5836, -0.6948, 0.4203]]))
    if rand_gray > 0:
        auglist.append(RandomGrayAug(rand_gray))
    if mean is True:
        mean = np.array([123.68, 116.28, 103.53], np.float32)
    if std is True:
        std = np.array([58.395, 57.12, 57.375], np.float32)
    if mean is not None or std is not None:
        auglist.append(ColorNormalizeAug(mean, std))
    return auglist


# ---------------------------------------------------------------------------
# ImageIter (reference mx.image.ImageIter: .rec or .lst/raw-file driven)
# ---------------------------------------------------------------------------

class ImageIter:
    """Image iterator over a record file (path_imgrec) or an index list
    (imglist) of raw image files, with augmentation. DataIter protocol:
    next() -> DataBatch of CHW float32 data + label.

    The hot path (decode + augment, numpy) runs on the caller thread here;
    `io.ImageRecordIter` wraps this dataset shape with the native prefetch
    pipeline for throughput (reference iter_image_recordio_2.cc).
    """

    def __init__(self, batch_size, data_shape, label_width=1,
                 path_imgrec=None, path_imglist=None, path_root="",
                 shuffle=False, aug_list=None, imglist=None,
                 data_name="data", label_name="softmax_label",
                 last_batch_handle="pad", **aug_kwargs):
        from ..io import DataDesc
        if len(data_shape) != 3:
            raise ValueError("data_shape must be CHW")
        self.batch_size = batch_size
        self.data_shape = tuple(data_shape)
        self.label_width = label_width
        self._shuffle = shuffle
        self._last = last_batch_handle
        self._rec = None
        self._samples = None
        if path_imgrec is not None:
            from ..recordio import MXIndexedRecordIO
            self._rec = MXIndexedRecordIO(idx_path_for(path_imgrec),
                                          path_imgrec, "r")
            self._order = list(self._rec.keys) if self._rec.keys else None
            if self._order is None:
                raise ValueError(f"no index found for {path_imgrec}")
        elif imglist is not None or path_imglist is not None:
            import os
            if imglist is None:
                imglist = []
                with open(path_imglist) as f:
                    for line in f:
                        parts = line.strip().split("\t")
                        imglist.append([float(x) for x in parts[1:-1]]
                                       + [parts[-1]])
            self._samples = [(np.asarray(entry[:-1], np.float32),
                              os.path.join(path_root, entry[-1]))
                             for entry in imglist]
            self._order = list(range(len(self._samples)))
        else:
            raise ValueError("need path_imgrec, path_imglist or imglist")
        if aug_list is None:
            aug_list = CreateAugmenter(data_shape, **aug_kwargs)
        self.auglist = aug_list
        self.data_name, self.label_name = data_name, label_name
        self._desc = DataDesc
        self.reset()

    def __len__(self):
        return len(self._order)

    @property
    def provide_data(self):
        return [self._desc(self.data_name,
                           (self.batch_size,) + self.data_shape, np.float32)]

    @property
    def provide_label(self):
        shape = (self.batch_size,) if self.label_width == 1 else \
            (self.batch_size, self.label_width)
        return [self._desc(self.label_name, shape, np.float32)]

    def reset(self):
        if self._shuffle:
            _pyrandom.shuffle(self._order)
        self._cursor = 0

    def read_sample(self, i):
        """(label float32 array, HWC uint8 image) for sample key/index i."""
        from ..recordio import unpack
        if self._rec is not None:
            header, img_bytes = unpack(self._rec.read_idx(i))
            label = np.atleast_1d(np.asarray(header.label, np.float32))
            img = imdecode(img_bytes).asnumpy()
        else:
            label, path = self._samples[i]
            img = imread(path).asnumpy()
        return label, img

    def _augment(self, img):
        c, h, w = self.data_shape
        return finalize_image(img, self.auglist, (h, w))

    def next(self):
        from ..io import DataBatch
        c, h, w = self.data_shape
        idx, pad = next_padded_indices(self._order, self._cursor,
                                       self.batch_size)
        if pad and self._last == "discard":
            self._cursor = len(self._order)
            raise StopIteration
        self._cursor += self.batch_size
        data = np.empty((self.batch_size, c, h, w), np.float32)
        label = np.empty((self.batch_size, self.label_width), np.float32)
        for n, i in enumerate(idx):
            lab, img = self.read_sample(i)
            img = self._augment(img)
            data[n] = np.transpose(img, (2, 0, 1))
            label[n] = lab[:self.label_width]
        lab_out = label[:, 0] if self.label_width == 1 else label
        return DataBatch([nd.array(data)], [nd.array(lab_out)], pad=pad,
                         provide_data=self.provide_data,
                         provide_label=self.provide_label)

    def __iter__(self):
        return self

    def __next__(self):
        return self.next()


# ---------------------------------------------------------------------------
# Detection augmenters + ImageDetIter (parity: python/mxnet/image/detection.py)
# ---------------------------------------------------------------------------

class DetAugmenter:
    """Detection augmenter: __call__(img HWC, label (N,5) [cls,x0,y0,x1,y1]
    normalized) -> (img, label)."""

    def __call__(self, src, label):
        raise NotImplementedError


class DetBorderAug(DetAugmenter):
    """Resize to exactly (w, h); normalized boxes are size-invariant."""

    def __init__(self, size, interp=2):
        self.size, self.interp = size, interp

    def __call__(self, src, label):
        return imresize(src, self.size[0], self.size[1], self.interp), label


class DetHorizontalFlipAug(DetAugmenter):
    """Mirror image AND boxes with probability p (reference
    DetHorizontalFlipAug)."""

    def __init__(self, p=0.5):
        self.p = p

    def __call__(self, src, label):
        if _pyrandom.random() < self.p:
            src = _as_np(src)[:, ::-1]
            label = label.copy()
            valid = label[:, 0] >= 0
            x0 = label[valid, 1].copy()
            label[valid, 1] = 1.0 - label[valid, 3]
            label[valid, 3] = 1.0 - x0
        return src, label


class DetRandomCropAug(DetAugmenter):
    """Random crop keeping box overlap >= min_object_covered (simplified
    reference sampler: tries `max_attempts` crops, falls back to identity).
    Boxes are clipped to the crop and dropped when their center is out."""

    def __init__(self, min_object_covered=0.3, aspect_ratio_range=(0.75, 1.33),
                 area_range=(0.3, 1.0), max_attempts=20):
        self.min_cov = min_object_covered
        self.ar_range = aspect_ratio_range
        self.area_range = area_range
        self.max_attempts = max_attempts

    def __call__(self, src, label):
        img = _as_np(src)
        h, w = img.shape[:2]
        valid = label[:, 0] >= 0
        boxes = label[valid, 1:5]
        for _ in range(self.max_attempts):
            area = _pyrandom.uniform(*self.area_range)
            ar = _pyrandom.uniform(*self.ar_range)
            cw = min(1.0, np.sqrt(area * ar))
            ch = min(1.0, np.sqrt(area / ar))
            cx = _pyrandom.uniform(0, 1.0 - cw)
            cy = _pyrandom.uniform(0, 1.0 - ch)
            if len(boxes) == 0:
                keep = np.zeros((0,), bool)
            else:
                centers = (boxes[:, :2] + boxes[:, 2:]) / 2.0
                keep = ((centers[:, 0] >= cx) & (centers[:, 0] <= cx + cw)
                        & (centers[:, 1] >= cy) & (centers[:, 1] <= cy + ch))
                if keep.sum() == 0:
                    continue
                ix0 = np.maximum(boxes[:, 0], cx)
                iy0 = np.maximum(boxes[:, 1], cy)
                ix1 = np.minimum(boxes[:, 2], cx + cw)
                iy1 = np.minimum(boxes[:, 3], cy + ch)
                inter = (np.clip(ix1 - ix0, 0, None)
                         * np.clip(iy1 - iy0, 0, None))
                barea = ((boxes[:, 2] - boxes[:, 0])
                         * (boxes[:, 3] - boxes[:, 1]))
                cov = inter / np.maximum(barea, 1e-12)
                if (cov[keep] < self.min_cov).any():
                    continue
            # accept: crop pixels, remap surviving boxes to crop coords
            px0, py0 = int(cx * w), int(cy * h)
            px1, py1 = int((cx + cw) * w), int((cy + ch) * h)
            out_img = img[py0:max(py1, py0 + 1), px0:max(px1, px0 + 1)]
            new_label = np.full_like(label, -1.0)
            n = 0
            for i, k in enumerate(np.nonzero(valid)[0]):
                if not keep[i]:
                    continue
                b = boxes[i]
                nb = [(max(b[0], cx) - cx) / cw, (max(b[1], cy) - cy) / ch,
                      (min(b[2], cx + cw) - cx) / cw,
                      (min(b[3], cy + ch) - cy) / ch]
                new_label[n, 0] = label[k, 0]
                new_label[n, 1:5] = np.clip(nb, 0, 1)
                n += 1
            return out_img, new_label
        return img, label


def CreateDetAugmenter(data_shape, resize=0, rand_crop=0, rand_mirror=False,
                       mean=None, std=None, brightness=0, contrast=0,
                       saturation=0, inter_method=2, min_object_covered=0.3,
                       area_range=(0.3, 3.0)):
    """Detection augmenter stack (reference mx.image.CreateDetAugmenter).
    data_shape CHW; pixel augmenters wrap the plain image augmenters.
    Unknown options raise (no silent **kwargs swallow); mean=True/std=True
    expand to the ImageNet constants like CreateAugmenter."""
    auglist = []
    if resize > 0:
        class _DetResizeShort(DetAugmenter):
            def __call__(self, src, label):
                # normalized boxes are invariant under aspect-preserving
                # resize
                return resize_short(src, resize, inter_method), label
        auglist.append(_DetResizeShort())
    if rand_crop > 0:
        auglist.append(DetRandomCropAug(
            min_object_covered=min_object_covered,
            area_range=(min(area_range[0], 1.0), min(area_range[1], 1.0))))
    if rand_mirror:
        auglist.append(DetHorizontalFlipAug(0.5))
    auglist.append(DetBorderAug((data_shape[2], data_shape[1]), inter_method))

    if mean is True:
        mean = np.array([123.68, 116.28, 103.53], np.float32)
    if std is True:
        std = np.array([58.395, 57.12, 57.375], np.float32)
    pixel = []
    if brightness or contrast or saturation:
        pixel.append(ColorJitterAug(brightness, contrast, saturation))
    pixel.append(CastAug())
    if mean is not None or std is not None:
        pixel.append(ColorNormalizeAug(
            mean if mean is not None else np.zeros(3, np.float32),
            std if std is not None else np.ones(3, np.float32)))

    class _Pixel(DetAugmenter):
        def __init__(self, ts):
            self.ts = ts

        def __call__(self, src, label):
            for t in self.ts:
                src = t(src)
            return src, label

    auglist.append(_Pixel(pixel))
    return auglist


class ImageDetIter:
    """Detection iterator (parity: mx.image.ImageDetIter): yields DataBatch
    with data (B,C,H,W) float32 and label (B, max_objs, 5) normalized
    [cls, x0, y0, x1, y1], padding rows = -1 — exactly what
    ops.MultiBoxTarget / SSD.targets consume."""

    def __init__(self, batch_size, data_shape, path_imgrec=None, imglist=None,
                 path_root="", shuffle=False, aug_list=None,
                 data_name="data", label_name="label", max_objs=None,
                 **aug_kwargs):
        from ..io import DataDesc
        self.batch_size = batch_size
        self.data_shape = tuple(data_shape)
        self._shuffle = shuffle
        self.data_name, self.label_name = data_name, label_name
        self._samples = []      # (img source, label (N,5))
        if path_imgrec is not None:
            from ..recordio import MXIndexedRecordIO, unpack
            self._rec = MXIndexedRecordIO(idx_path_for(path_imgrec),
                                          path_imgrec, "r")
            for k in self._rec.keys:
                header, _ = unpack(self._rec.read_idx(k))
                lab = np.asarray(header.label, np.float32)
                self._samples.append((("rec", k), self._parse_label(lab)))
        elif imglist is not None:
            import os
            self._rec = None
            for entry in imglist:
                lab = np.asarray(entry[:-1], np.float32)
                path = os.path.join(path_root, entry[-1])
                self._samples.append((("file", path), self._parse_label(lab)))
        else:
            raise ValueError("need path_imgrec or imglist")
        widest = max((len(l) for _, l in self._samples), default=1)
        if max_objs is not None and widest > max_objs:
            raise ValueError(
                f"max_objs={max_objs} but a record has {widest} objects; "
                f"raise max_objs/label_pad_width (the reference errors on "
                f"insufficient label_pad_width rather than dropping boxes)")
        self._max_objs = max_objs or widest
        if aug_list is None:
            aug_list = CreateDetAugmenter(self.data_shape, **aug_kwargs)
        self.auglist = aug_list
        self._desc = DataDesc
        self.reset()

    @staticmethod
    def _parse_label(lab):
        """Reference det-record label: [header_width A, obj_width B,
        (extra header...), (cls, x0, y0, x1, y1, extra...)*]."""
        if lab.ndim > 1:
            return lab.astype(np.float32)
        a, b = int(lab[0]), int(lab[1])
        body = lab[a:]
        n = len(body) // b
        out = body[:n * b].reshape(n, b)[:, :5]
        return out.astype(np.float32)

    def __len__(self):
        return len(self._samples)

    @property
    def provide_data(self):
        c, h, w = self.data_shape
        return [self._desc(self.data_name, (self.batch_size, c, h, w),
                           np.float32)]

    @property
    def provide_label(self):
        return [self._desc(self.label_name,
                           (self.batch_size, self._max_objs, 5), np.float32)]

    def reset(self):
        self._order = list(range(len(self._samples)))
        if self._shuffle:
            _pyrandom.shuffle(self._order)
        self._cursor = 0

    def __iter__(self):
        return self

    def _read_img(self, source):
        kind, ref = source
        if kind == "rec":
            from ..recordio import unpack
            _, img_bytes = unpack(self._rec.read_idx(ref))
            return imdecode(img_bytes).asnumpy()
        return imread(ref).asnumpy()

    def next(self):
        from ..io import DataBatch
        idx, npad = next_padded_indices(self._order, self._cursor,
                                        self.batch_size)
        self._cursor += self.batch_size
        c, h, w = self.data_shape
        data = np.empty((self.batch_size, c, h, w), np.float32)
        labels = np.full((self.batch_size, self._max_objs, 5), -1.0,
                         np.float32)
        for n, i in enumerate(idx):
            src, lab = self._samples[i]
            img = self._read_img(src)
            lab = lab.copy()
            pad = np.full((self._max_objs, 5), -1.0, np.float32)
            pad[:len(lab)] = lab[:self._max_objs]

            def det_tail(im):
                nonlocal pad
                for aug in self.auglist:
                    im, pad = aug(im, pad)
                return im

            img = finalize_image(img, [det_tail], (h, w))
            data[n] = np.transpose(img, (2, 0, 1))
            labels[n] = pad
        from ..ndarray import NDArray
        import jax.numpy as jnp
        return DataBatch([NDArray(jnp.asarray(data))],
                         [NDArray(jnp.asarray(labels))], pad=npad,
                         provide_data=self.provide_data,
                         provide_label=self.provide_label)

    def __next__(self):
        return self.next()


def copyMakeBorder(src, top, bot, left, right, border_type=0, values=0.0):
    """Pad an HWC image with a border (parity: mx.image.copyMakeBorder /
    cv2.copyMakeBorder). border_type 0 = constant (`values`), 1 =
    replicate edge pixels."""
    import jax.numpy as jnp
    from ..ndarray import NDArray, _apply

    def f(img):
        pads = ((top, bot), (left, right)) + ((0, 0),) * (img.ndim - 2)
        if border_type == 1:
            return jnp.pad(img, pads, mode="edge")
        if border_type != 0:
            raise ValueError(f"unsupported border_type {border_type}; "
                             "0 (constant) and 1 (replicate) are supported")
        if jnp.ndim(jnp.asarray(values)) == 0:
            return jnp.pad(img, pads, mode="constant",
                           constant_values=values)
        # sequence `values` = per-CHANNEL border color (the cv2 contract),
        # not numpy's per-axis pad constants
        vals = jnp.asarray(values, img.dtype)
        if img.ndim != 3 or vals.shape != (img.shape[-1],):
            raise ValueError(
                f"per-channel values needs an HWC image with "
                f"{vals.shape[0]} channels, got image shape {img.shape}")
        padded = jnp.pad(img, pads, mode="constant")
        h, w = img.shape[:2]
        row = jnp.arange(padded.shape[0])[:, None]
        col = jnp.arange(padded.shape[1])[None, :]
        border = ((row < top) | (row >= top + h)
                  | (col < left) | (col >= left + w))
        return jnp.where(border[..., None], vals, padded)

    return _apply(f, [src if isinstance(src, NDArray) else NDArray(src)],
                  name="copyMakeBorder")
