"""Weight initializers (parity: python/mxnet/initializer.py).

Each initializer produces a raw jax array for a (shape, dtype) given a PRNG
key — pure, so deferred initialization can run inside or outside jit. The
string registry mirrors mx.init.* names (`initializer.create("xavier")`).
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from .base import _Registry, normalize_dtype

registry = _Registry("initializer")
register = registry.register
create = registry.create


class Initializer:
    """Base class. Subclasses implement _init(key, shape, dtype)."""

    def to_attr_str(self):
        """Serialize for the Variable __init__ attr (json name+params form
        that Module.init_params re-creates; reference dumps initializers
        the same way for InitDesc dispatch). Values are coerced where
        possible (numpy scalars, tuples); only individually unserializable
        values are dropped."""
        import json

        def coerce(v):
            if isinstance(v, (np.floating, np.integer, np.bool_)):
                return v.item()
            if isinstance(v, np.ndarray):
                return v.tolist()
            if isinstance(v, (tuple, list)):
                return [coerce(e) for e in v]
            if isinstance(v, Initializer):   # nested (e.g. FusedRNN inner)
                return json.loads(v.to_attr_str())
            return v

        params = {}
        for k, v in vars(self).items():
            if k.startswith("_"):
                continue
            v = coerce(v)
            try:
                json.dumps(v)
            except TypeError:
                continue
            params[k] = v
        return json.dumps({"name": type(self).__name__.lower(),
                           "params": params})

    def __call__(self, key, shape, dtype="float32"):
        return self._init(key, tuple(shape), normalize_dtype(dtype))

    def _init(self, key, shape, dtype):
        raise NotImplementedError

    def __repr__(self):
        return type(self).__name__


@register("zeros")
@register("zero")
class Zero(Initializer):
    def _init(self, key, shape, dtype):
        return jnp.zeros(shape, dtype)


@register("ones")
@register("one")
class One(Initializer):
    def _init(self, key, shape, dtype):
        return jnp.ones(shape, dtype)


@register()
class Constant(Initializer):
    def __init__(self, value=0.0):
        self.value = value

    def _init(self, key, shape, dtype):
        return jnp.full(shape, self.value, dtype)


@register()
class Uniform(Initializer):
    def __init__(self, scale=0.07):
        self.scale = scale

    def _init(self, key, shape, dtype):
        return jax.random.uniform(key, shape, dtype, -self.scale, self.scale)


@register()
class Normal(Initializer):
    def __init__(self, sigma=0.01):
        self.sigma = sigma

    def _init(self, key, shape, dtype):
        return self.sigma * jax.random.normal(key, shape, dtype)


def _fans(shape, factor_type):
    # Convention (matches reference mxnet Xavier): shape[0]=out, shape[1:]=in
    hw = int(np.prod(shape[2:])) if len(shape) > 2 else 1
    fan_in = (shape[1] if len(shape) > 1 else shape[0]) * hw
    fan_out = shape[0] * hw
    if factor_type == "avg":
        return (fan_in + fan_out) / 2.0
    if factor_type == "in":
        return float(fan_in)
    if factor_type == "out":
        return float(fan_out)
    raise ValueError(f"bad factor_type {factor_type}")


@register()
class Xavier(Initializer):
    def __init__(self, rnd_type="uniform", factor_type="avg", magnitude=3):
        self.rnd_type = rnd_type
        self.factor_type = factor_type
        self.magnitude = magnitude

    def _init(self, key, shape, dtype):
        factor = _fans(shape, self.factor_type)
        scale = math.sqrt(self.magnitude / factor)
        if self.rnd_type == "uniform":
            return jax.random.uniform(key, shape, dtype, -scale, scale)
        if self.rnd_type == "gaussian":
            return scale * jax.random.normal(key, shape, dtype)
        raise ValueError(f"bad rnd_type {self.rnd_type}")


@register("msraprelu")
class MSRAPrelu(Xavier):
    def __init__(self, factor_type="avg", slope=0.25):
        magnitude = 2.0 / (1 + slope ** 2)
        super().__init__("gaussian", factor_type, magnitude)


@register()
class Orthogonal(Initializer):
    def __init__(self, scale=1.414, rand_type="uniform"):
        self.scale = scale
        self.rand_type = rand_type

    def _init(self, key, shape, dtype):
        nout = shape[0]
        nin = int(np.prod(shape[1:])) if len(shape) > 1 else 1
        if self.rand_type == "uniform":
            tmp = jax.random.uniform(key, (nout, nin), jnp.float32, -1.0, 1.0)
        else:
            tmp = jax.random.normal(key, (nout, nin), jnp.float32)
        u, _, v = jnp.linalg.svd(tmp, full_matrices=False)
        q = u if u.shape == (nout, nin) else v
        return (self.scale * q.reshape(shape)).astype(dtype)


@register()
class Bilinear(Initializer):
    """Upsampling deconv weights (parity: mx.init.Bilinear)."""

    def _init(self, key, shape, dtype):
        weight = np.zeros(shape, dtype=np.float32)
        f = math.ceil(shape[3] / 2.0)
        c = (2 * f - 1 - f % 2) / (2.0 * f)
        for i in range(int(np.prod(shape))):
            x = i % shape[3]
            y = (i // shape[3]) % shape[2]
            weight.flat[i] = (1 - abs(x / f - c)) * (1 - abs(y / f - c))
        return jnp.asarray(weight, dtype)


@register("lstmbias")
class LSTMBias(Initializer):
    """Forget-gate bias = 1 (gate order i, f, g, o)."""

    def __init__(self, forget_bias=1.0):
        self.forget_bias = forget_bias

    def _init(self, key, shape, dtype):
        b = jnp.zeros(shape, dtype)
        n = shape[0] // 4
        return b.at[n:2 * n].set(self.forget_bias)


@register("fusedrnn")
class FusedRNN(Initializer):
    """Parity: mx.init.FusedRNN (python/mxnet/initializer.py) — initialize
    a FusedRNNCell's flat packed parameter vector with `init`, then set the
    LSTM forget-gate biases (gate order i, f, g, o) so fused and unfused
    cells start from the same effective math: i2h forget bias =
    forget_bias, h2h forget bias = 0 (the cell step sums bi + bh). Bias
    offsets need only the vector length: the bias block is the fixed-size
    tail of the rnn-inl.h packing, independent of the input size."""

    def __init__(self, init=None, num_hidden=0, num_layers=1, mode="lstm",
                 bidirectional=False, forget_bias=1.0):
        if isinstance(init, str):
            init = create(init)
        elif isinstance(init, dict):    # nested to_attr_str round-trip form
            init = create(init["name"], **init.get("params", {}))
        # init=None means DEFERRED: Module.init_params fills it in via
        # with_inner() with the user's initializer, so attaching a default
        # FusedRNN attr never overrides an explicit init.Xavier() etc.
        self.init = init
        self.num_hidden = int(num_hidden)
        self.num_layers = int(num_layers)
        self.mode = mode
        self.bidirectional = bool(bidirectional)
        self.forget_bias = float(forget_bias)

    def with_inner(self, inner):
        """Copy with the deferred inner initializer filled in."""
        import copy
        c = copy.copy(self)
        c.init = inner
        return c

    def _init(self, key, shape, dtype):
        from .ops._rnn import GATES
        inner = self.init if self.init is not None else Uniform(0.07)
        arr = inner(key, shape, dtype)
        if self.mode != "lstm":
            return arr
        G, H = GATES[self.mode], self.num_hidden
        L = self.num_layers
        D = 2 if self.bidirectional else 1
        bias_size = L * D * 2 * G * H
        weights_total = shape[0] - bias_size
        for k in range(L * D):
            bi_off = weights_total + k * 2 * G * H
            bh_off = bi_off + G * H
            arr = arr.at[bi_off + H:bi_off + 2 * H].set(self.forget_bias)
            arr = arr.at[bh_off + H:bh_off + 2 * H].set(0.0)
        return arr


@register()
class Mixed(Initializer):
    """Pattern-matched initializer selection by parameter name."""

    def __init__(self, patterns, initializers):
        import re
        self.map = [(re.compile(p), init) for p, init in zip(patterns, initializers)]

    def init_for(self, name):
        for pat, init in self.map:
            if pat.search(name):
                return init
        raise ValueError(f"no initializer pattern matches {name!r}")

    def _init(self, key, shape, dtype):
        raise RuntimeError("Mixed must be resolved per-parameter via init_for()")
