"""incubator_mxnet_tpu — a TPU-native deep learning framework with the
capabilities of Apache MXNet (reference: ymjiang/incubator-mxnet), rebuilt
from scratch on JAX/XLA/Pallas.

Import surface mirrors `mxnet`:

    import incubator_mxnet_tpu as mx        # or: import mxtpu as mx
    x = mx.nd.ones((2, 3), ctx=mx.tpu(0))
    with mx.autograd.record():
        y = (x * 2).sum()
    y.backward()
"""
import sys as _sys

from . import base, context
from .context import Context, cpu, gpu, tpu, current_context, num_gpus, num_tpus
from . import profiler
from . import ndarray
from . import ndarray as nd
from . import autograd
from . import ops
from . import initializer
from . import initializer as init
from . import optimizer
from .optimizer import lr_scheduler
from . import kvstore
from . import kvstore as kv
from . import gluon
from . import symbol
from . import symbol as sym
from .symbol import AttrScope
from .symbol import executor
from . import attribute
from . import contrib
from . import registry
from . import util
from . import rnn
from . import module
from . import module as mod
from . import model
from . import metric
from . import io
from . import operator
from . import callback
from . import monitor
from .monitor import Monitor
from . import visualization
from . import visualization as viz
from . import distributed
from . import recordio
from . import image
from . import amp
from . import runtime
from . import engine
from . import diagnostics
from . import healthmon
from . import perfscope
from . import commscope
from . import devicescope
from . import memscope
from . import servescope
from . import serving
from . import resilience
from . import autotune
from . import mxlint
from . import embedding
from . import trainloop
from .trainloop import TrainLoop
from . import test_utils
from . import utils

from .ndarray import NDArray
from .ndarray import random as _ndrandom

# `mx.random` surface (seed + samplers)
random = _ndrandom

__version__ = "0.1.0"

# Short import alias, torch-style: `import mxtpu as mx`.
_sys.modules.setdefault("mxtpu", _sys.modules[__name__])

# MXTPU_DIAG=1: arm the always-on observability layer (memory ledger,
# flight recorder, optional sampler — see docs/diagnostics.md) at import.
diagnostics.enable_from_env()
# MXTPU_HEALTHMON=1: arm cross-rank training health (watchdogs, skew
# timeline, structured event log — see docs/observability.md) at import.
healthmon.enable_from_env()
# MXTPU_PERFSCOPE=1: arm roofline-aware cost capture at compile sites
# (per-program FLOPs/bytes + verdicts — see docs/perfscope.md) at import.
perfscope.enable_from_env()
# MXTPU_COMMSCOPE=1: arm collective/resharding extraction at the same
# compile sites (per-program inventory + estimates — docs/commscope.md).
commscope.enable_from_env()
# MXTPU_DEVICESCOPE=1: arm measured device-timeline capture (windowed
# jax-profiler trace + ingestion + analytic-vs-measured reconciliation
# — see docs/devicescope.md).
devicescope.enable_from_env()
# MXTPU_MEMSCOPE=1: arm memory observability (static per-program
# footprints at the compile sites, the watermark ring at the step
# marks, OOM forensics — see docs/memscope.md).
memscope.enable_from_env()
# MXTPU_SERVESCOPE=1: arm request-lifecycle tracing + tail-latency
# attribution on the serving path (sampled via MXTPU_SERVESCOPE_SAMPLE
# — see docs/servescope.md).
servescope.enable_from_env()
# MXTPU_STRICT=1: arm the mxlint strict-mode jit-program auditor
# (host-sync / recompile-storm / donation-violation detection over the
# steady loop — see docs/mxlint.md).
mxlint.runtime.enable_from_env()
