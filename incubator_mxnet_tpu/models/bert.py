"""BERT (parity: GluonNLP scripts/bert + reference src/operator/contrib/
transformer.cc interleaved_matmul ops; model API mirrors
gluonnlp.model.bert.BERTModel / get_bert_model).

TPU-first design decisions:
- QKV projection is ONE fused (D, 3D) matmul (the reference's
  interleaved_matmul_selfatt_qk trick, done here at the layer level) so the
  MXU sees a single large GEMM per attention block.
- The attention core dispatches to the pallas flash-attention kernel when no
  padding mask is needed (ops/pallas/flash_attention.py): O(L) memory,
  scores never hit HBM. With a valid_length mask it falls back to the fused
  XLA softmax path.
- Everything is a HybridBlock: `hybridize()` compiles the whole encoder into
  one XLA computation; FusedTrainStep fuses fwd+bwd+AdamW into one program.
- Long sequences: two exact sequence-parallel cores via
  ring=(mesh, axis[, scheme]): scheme "ring" (KV rotation,
  parallel/ring_attention.py, O(L/n) memory) or "ulysses" (all-to-all
  head sharding, parallel/ulysses.py, needs num_heads % n == 0).
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..ndarray import NDArray, _apply
from .. import ndarray as nd
from .. import ops
from ..gluon import nn
from ..gluon.block import HybridBlock
from ..gluon.loss import Loss

__all__ = ["BERTModel", "BERTEncoder", "BERTEncoderCell", "PositionwiseFFN",
           "MultiHeadAttentionCell", "BERTForPretrain", "BERTPretrainLoss",
           "get_bert_model", "bert_12_768_12", "bert_24_1024_16"]


class MultiHeadAttentionCell(HybridBlock):
    """Self-attention with fused QKV projection.

    One (D,3D) GEMM -> split heads -> flash attention (pallas) or masked
    softmax -> output projection. Mirrors gluonnlp.model.attention_cell.
    MultiHeadAttentionCell but restructured for the MXU.
    """

    def __init__(self, units, num_heads, dropout=0.0, use_bias=True,
                 weight_initializer=None, ring=None, prefix=None,
                 params=None):
        super().__init__(prefix, params)
        assert units % num_heads == 0
        self._units = units
        self._num_heads = num_heads
        self._dropout = dropout
        # (mesh, axis) or (mesh, axis, "ring"|"ulysses"):
        # sequence-parallel attention core scheme
        self._ring = ring
        if ring is not None:
            scheme = ring[2] if len(ring) > 2 else "ring"
            if scheme not in ("ring", "ulysses"):
                raise ValueError(f"unknown sequence-parallel scheme "
                                 f"{scheme!r}; choose 'ring' or 'ulysses'")
            if scheme == "ulysses":
                n = ring[0].shape[ring[1]]
                if num_heads % n:
                    raise ValueError(
                        f"ulysses shards heads: num_heads={num_heads} must "
                        f"divide by mesh axis {ring[1]}={n} (use 'ring' "
                        f"otherwise)")
        if ring is not None and dropout > 0.0:
            import warnings
            warnings.warn(
                "ring attention applies no attention-weight dropout (flash-"
                "style kernels keep weights in registers); residual/FFN "
                "dropout still applies", stacklevel=3)
        self.qkv = nn.Dense(3 * units, flatten=False, in_units=units,
                            use_bias=use_bias,
                            weight_initializer=weight_initializer)
        self.proj = nn.Dense(units, flatten=False, in_units=units,
                             use_bias=use_bias,
                             weight_initializer=weight_initializer)

    def forward(self, x, mask=None):
        q, k, v = nd.split(self.qkv(x), 3, axis=-1)
        if self._ring is not None:
            if mask is not None:
                raise ValueError("ring attention path needs full sequences "
                                 "(valid_length mask unsupported); pad to "
                                 "max_length instead")
            out = self._ring_core(q, k, v)
        else:
            out = ops.multihead_attention(q, k, v, self._num_heads, mask,
                                          self._dropout)
        return self.proj(out)

    def _ring_core(self, q, k, v, causal=False):
        """Long-context core: sequence dim sharded over the mesh 'sp' axis.
        scheme "ring" rotates KV blocks over ICI
        (parallel/ring_attention.py); "ulysses" trades the sequence shard
        for a head shard with two all-to-alls (parallel/ulysses.py). Both
        cores are position-aware, so causal masking stays exact across
        sequence shards (the causal-LM subclass passes causal=True)."""
        from ..parallel import ring_attention, ulysses_attention
        mesh, axis = self._ring[0], self._ring[1]
        scheme = self._ring[2] if len(self._ring) > 2 else "ring"
        core = {"ring": ring_attention,
                "ulysses": ulysses_attention}[scheme]
        heads = self._num_heads

        def f(qr, kr, vr):
            b, L, d = qr.shape
            hd = d // heads

            def split(t):
                return t.reshape(b, L, heads, hd).transpose(0, 2, 1, 3)

            o = core(split(qr), split(kr), split(vr), mesh, axis,
                     causal=causal)
            return o.transpose(0, 2, 1, 3).reshape(b, L, d)
        return _apply(f, [q, k, v], name=scheme + "_self_attention")


class PositionwiseFFN(HybridBlock):
    """FFN sublayer (gluonnlp.model.transformer.PositionwiseFFN)."""

    def __init__(self, units, hidden_size, dropout=0.0, activation="gelu",
                 weight_initializer=None, prefix=None, params=None):
        super().__init__(prefix, params)
        self.ffn_1 = nn.Dense(hidden_size, flatten=False, in_units=units,
                              weight_initializer=weight_initializer)
        self.activation = nn.GELU()if activation == "gelu" else \
            nn.Activation(activation)
        self.ffn_2 = nn.Dense(units, flatten=False, in_units=hidden_size,
                              weight_initializer=weight_initializer)
        self.dropout = nn.Dropout(dropout)

    def forward(self, x):
        return self.dropout(self.ffn_2(self.activation(self.ffn_1(x))))


class BERTEncoderCell(HybridBlock):
    """One transformer layer: MHA + Add&LN, FFN + Add&LN.

    `pre_norm=False` is BERT's post-LN (reference default); True gives the
    pre-LN variant used for deep/stable training.
    """

    def __init__(self, units, hidden_size, num_heads, dropout=0.0,
                 pre_norm=False, layer_norm_eps=1e-12,
                 weight_initializer=None, ring=None, prefix=None,
                 params=None):
        super().__init__(prefix, params)
        self._pre_norm = pre_norm
        self.attention = MultiHeadAttentionCell(
            units, num_heads, dropout, weight_initializer=weight_initializer,
            ring=ring)
        self.ffn = PositionwiseFFN(units, hidden_size, dropout,
                                   weight_initializer=weight_initializer)
        self.dropout = nn.Dropout(dropout)
        self.ln1 = nn.LayerNorm(epsilon=layer_norm_eps, in_channels=units)
        self.ln2 = nn.LayerNorm(epsilon=layer_norm_eps, in_channels=units)

    def forward(self, x, mask=None):
        if self._pre_norm:
            x = x + self.dropout(self.attention(self.ln1(x), mask))
            return x + self.ffn(self.ln2(x))
        x = self.ln1(x + self.dropout(self.attention(x, mask)))
        return self.ln2(x + self.ffn(x))


class BERTEncoder(HybridBlock):
    """Stack of BERTEncoderCells (gluonnlp.model.BERTEncoder)."""

    def __init__(self, num_layers, units, hidden_size, num_heads,
                 max_length=512, dropout=0.0, pre_norm=False,
                 layer_norm_eps=1e-12, weight_initializer=None, ring=None,
                 prefix=None, params=None):
        super().__init__(prefix, params)
        self._units = units
        self._max_length = max_length
        self.position_weight = self.params.get(
            "position_weight", shape=(max_length, units), init="normal")
        self.dropout = nn.Dropout(dropout)
        self.ln = nn.LayerNorm(epsilon=layer_norm_eps, in_channels=units)
        self.cells = nn.HybridSequential()
        for _ in range(num_layers):
            self.cells.add(BERTEncoderCell(
                units, hidden_size, num_heads, dropout, pre_norm,
                layer_norm_eps, weight_initializer, ring=ring))

    def forward(self, x, mask=None):
        from ..ndarray import _symbolic
        pos = self.position_weight.data()
        if _symbolic(x):
            # symbol trace has no python shape: the first L rows of the
            # table are the positional embeddings; slice_like ties the
            # length to the input and an over-length bind fails the
            # broadcast instead of silently clamping
            x = x + nd.slice_like(pos, nd.swapaxes(x, 0, 1), axes=(0,))
        else:
            # eager/hybridized: static row slice (no transposed copy of
            # the activations just to read a shape)
            seq_len = x.shape[1]
            x = _apply(lambda xr, pr: xr + pr[:seq_len][None, :, :],
                       [x, pos], name="add_position_embed")
        x = self.dropout(self.ln(x))
        for cell in self.cells:
            x = cell(x, mask)
        return x


def _length_mask(valid_length, seq_len):
    """(B,) valid lengths -> (B, 1, 1, L) boolean attention mask."""
    def f(vl):
        ar = jnp.arange(seq_len)
        return (ar[None, :] < vl[:, None].astype(jnp.int32))[:, None, None, :]
    return _apply(f, [valid_length], name="length_mask")


class BERTModel(HybridBlock):
    """Embeddings + encoder + pooler (gluonnlp.model.bert.BERTModel).

    forward(inputs, token_types, valid_length=None) ->
        (sequence_output (B,L,D), pooled_output (B,D))
    """

    def __init__(self, num_layers=12, units=768, hidden_size=3072,
                 num_heads=12, max_length=512, vocab_size=30522,
                 token_type_vocab_size=2, dropout=0.1, pre_norm=False,
                 use_pooler=True, layer_norm_eps=1e-12, ring=None,
                 prefix=None, params=None):
        """ring=(mesh, 'sp') switches every attention core to sequence-
        parallel ring attention for long-context training: activations stay
        sharded (B, L/sp, D) per device, only KV blocks move over ICI."""
        super().__init__(prefix, params)
        self._units = units
        self.word_embed = nn.Embedding(vocab_size, units)
        self.token_type_embed = nn.Embedding(token_type_vocab_size, units)
        self.encoder = BERTEncoder(num_layers, units, hidden_size, num_heads,
                                   max_length, dropout, pre_norm,
                                   layer_norm_eps, ring=ring)
        self.pooler = (nn.Dense(units, flatten=False, in_units=units,
                                activation="tanh") if use_pooler else None)

    def forward(self, inputs, token_types=None, valid_length=None):
        x = self.word_embed(inputs)
        if token_types is not None:
            x = x + self.token_type_embed(token_types)
        mask = None
        if valid_length is not None:
            from ..ndarray import _symbolic
            if _symbolic(inputs):
                raise ValueError(
                    "symbol tracing of BERTModel does not support "
                    "valid_length (the mask needs a static length); pad "
                    "to max_length and trace without it")
            mask = _length_mask(valid_length, inputs.shape[1])
        seq = self.encoder(x, mask)
        if self.pooler is None:
            return seq
        pooled = self.pooler(seq[:, 0, :])
        return seq, pooled


class BERTForPretrain(HybridBlock):
    """MLM + NSP heads on a BERTModel (gluonnlp scripts/bert/pretraining).

    forward(inputs, token_types, valid_length, masked_positions) ->
        (mlm_scores (B,M,V), nsp_scores (B,2))
    The MLM decoder ties the word-embedding matrix (reference behaviour).
    """

    def __init__(self, bert: BERTModel, vocab_size, prefix=None, params=None):
        super().__init__(prefix, params)
        if bert.pooler is None:
            raise ValueError("BERTForPretrain needs a BERTModel built with "
                             "use_pooler=True (the NSP head reads the pooled "
                             "[CLS] output)")
        self.bert = bert
        self._vocab_size = vocab_size
        units = bert._units
        self.mlm_transform = nn.Dense(units, flatten=False, in_units=units)
        self.mlm_ln = nn.LayerNorm(epsilon=1e-12, in_channels=units)
        self.mlm_bias = self.params.get("mlm_bias", shape=(vocab_size,),
                                        init="zeros")
        self.nsp_classifier = nn.Dense(2, in_units=units)

    def forward(self, inputs, token_types, valid_length, masked_positions):
        seq, pooled = self.bert(inputs, token_types, valid_length)
        # gather the masked positions: (B, L, D) -> (B, M, D)
        h = _apply(lambda s, p: jnp.take_along_axis(
            s, p.astype(jnp.int32)[:, :, None], axis=1),
            [seq, masked_positions], name="gather_masked")
        h = self.mlm_ln(nd.gelu(self.mlm_transform(h)))
        embed_w = self.bert.word_embed.weight.data()
        mlm = _apply(lambda hr, wr, br: hr @ wr.T + br,
                     [h, embed_w, self.mlm_bias.data()], name="mlm_decoder")
        nsp = self.nsp_classifier(pooled)
        return mlm, nsp


class BERTPretrainLoss(Loss):
    """MLM CE (over masked positions, ignoring pads labelled -1) + NSP CE."""

    def forward(self, mlm_scores, nsp_scores, masked_labels, nsp_labels,
                sample_weight=None):
        import jax

        def f(ms, ml, ns, nl):
            valid = (ml >= 0)
            labels = jnp.maximum(ml, 0)
            logp = jax.nn.log_softmax(ms.astype(jnp.float32), axis=-1)
            mlm_nll = -jnp.take_along_axis(
                logp, labels.astype(jnp.int32)[..., None], axis=-1)[..., 0]
            denom = jnp.maximum(valid.sum(), 1)
            mlm_loss = jnp.where(valid, mlm_nll, 0.0).sum() / denom
            nlogp = jax.nn.log_softmax(ns.astype(jnp.float32), axis=-1)
            nsp_loss = -jnp.take_along_axis(
                nlogp, nl.astype(jnp.int32)[:, None], axis=-1).mean()
            return mlm_loss + nsp_loss
        return _apply(f, [mlm_scores, masked_labels, nsp_scores, nsp_labels],
                      name="bert_pretrain_loss")


_BERT_CONFIGS = {
    # name: (num_layers, units, hidden_size, num_heads)
    "bert_12_768_12": (12, 768, 3072, 12),     # BERT-base
    "bert_24_1024_16": (24, 1024, 4096, 16),   # BERT-large
}


def get_bert_model(model_name="bert_12_768_12", vocab_size=30522,
                   max_length=512, dropout=0.1, pre_norm=False,
                   use_pooler=True, **kwargs):
    num_layers, units, hidden, heads = _BERT_CONFIGS[model_name]
    return BERTModel(num_layers, units, hidden, heads, max_length,
                     vocab_size, dropout=dropout, pre_norm=pre_norm,
                     use_pooler=use_pooler, **kwargs)


def bert_12_768_12(**kwargs):
    return get_bert_model("bert_12_768_12", **kwargs)


def bert_24_1024_16(**kwargs):
    return get_bert_model("bert_24_1024_16", **kwargs)
