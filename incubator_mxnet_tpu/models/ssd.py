"""SSD single-shot detector (parity: reference example/ssd — symbol_builder,
multibox targets, SmoothL1+CE with hard negative mining; gluoncv-style
model API).

TPU-first shape discipline: the anchor set, target matching, loss masking and
NMS are all static-shape (ops/box.py), so the entire train step — backbone,
multi-scale heads, MultiBoxTarget, hard-negative mining, loss — jits into one
XLA computation. NHWC layout by default (MXU-friendly convs).
"""
from __future__ import annotations

from ..gluon import nn
from ..gluon.block import HybridBlock
from ..gluon.loss import Loss
from ..ndarray import _apply
from .. import ndarray as nd
from .. import ops
from . import resnet as _resnet

__all__ = ["SSD", "SSDLoss", "ssd_512_resnet18_v1", "ssd_512_resnet50_v1",
           "ssd_300_resnet18_v1"]


class _PredHead(HybridBlock):
    """3x3 conv predictor; emits (B, HW*K, E) rows from an NHWC/NCHW map."""

    def __init__(self, num_anchors, entries, layout, **kw):
        super().__init__(**kw)
        self._entries = entries
        self._layout = layout
        self.conv = nn.Conv2D(num_anchors * entries, 3, padding=1,
                              layout=layout)

    def forward(self, x):
        y = self.conv(x)
        if self._layout == "NCHW":
            y = y.transpose((0, 2, 3, 1))
        b = y.shape[0]
        return y.reshape((b, -1, self._entries))


class SSD(HybridBlock):
    """Generic SSD: backbone feature extractor + extra downsampling stages +
    per-scale class/box heads + MultiBoxPrior anchors.

    forward(x) -> (anchors (1, A, 4), cls_preds (B, A, C+1),
                   box_preds (B, A*4))
    """

    def __init__(self, backbone_features, num_classes, sizes, ratios,
                 extra_channels=(512, 256, 256, 256), layout="NHWC",
                 **kwargs):
        super().__init__(**kwargs)
        assert len(sizes) == len(ratios)
        self._num_classes = num_classes
        self._layout = layout
        self._sizes = sizes
        self._ratios = ratios
        self.features = backbone_features
        self.extras = nn.HybridSequential()
        for ch in extra_channels:
            stage = nn.HybridSequential()
            stage.add(nn.Conv2D(ch // 2, 1, layout=layout, activation="relu"))
            stage.add(nn.Conv2D(ch, 3, strides=2, padding=1, layout=layout,
                                activation="relu"))
            self.extras.add(stage)
        n_scales = 1 + len(extra_channels)
        assert len(sizes) == n_scales, (len(sizes), n_scales)
        self.cls_heads = nn.HybridSequential()
        self.box_heads = nn.HybridSequential()
        for s, r in zip(sizes, ratios):
            k = len(s) + len(r) - 1
            self.cls_heads.add(_PredHead(k, num_classes + 1, layout))
            self.box_heads.add(_PredHead(k, 4, layout))

    def forward(self, x):
        feats = [self.features(x)]
        for stage in self.extras:
            feats.append(stage(feats[-1]))
        anchors, cls_preds, box_preds = [], [], []
        for i, f in enumerate(feats):
            anchors.append(ops.MultiBoxPrior(
                f, sizes=self._sizes[i], ratios=self._ratios[i],
                layout=self._layout))
            cls_preds.append(self.cls_heads[i](f))
            box_preds.append(self.box_heads[i](f))
        anchor = nd.concat(*anchors, dim=1)
        cls_pred = nd.concat(*cls_preds, dim=1)             # (B, A, C+1)
        box_pred = nd.concat(*box_preds, dim=1)             # (B, A, 4)
        b = box_pred.shape[0]
        return anchor, cls_pred, box_pred.reshape((b, -1))

    # -- inference ---------------------------------------------------------
    def detect(self, x, threshold=0.01, nms_threshold=0.45, nms_topk=400):
        """(B, A, 6) detections [cls, score, x0, y0, x1, y1]; rows with
        cls = -1 are suppressed (reference MultiBoxDetection output)."""
        anchor, cls_pred, box_pred = self(x)
        cls_prob = nd.softmax(cls_pred, axis=-1).transpose((0, 2, 1))
        return ops.MultiBoxDetection(cls_prob, box_pred, anchor,
                                     threshold=threshold,
                                     nms_threshold=nms_threshold,
                                     nms_topk=nms_topk)

    def targets(self, anchor, cls_pred, label, negative_mining_ratio=3):
        """MultiBoxTarget with hard negative mining (cls_pred-aware)."""
        return ops.MultiBoxTarget(
            anchor, label, cls_pred.transpose((0, 2, 1)),
            overlap_threshold=0.5,
            negative_mining_ratio=negative_mining_ratio,
            negative_mining_thresh=0.5)


class SSDLoss(Loss):
    """CE over mined anchors (cls_target = -1 ignored) + SmoothL1 on
    positives, each image normalized by its positive count (reference
    example/ssd MultiBoxLoss). Returns per-sample losses (B,) per the gluon
    Loss contract; `weight` scales them."""

    def __init__(self, lambd=1.0, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._lambd = lambd

    def forward(self, cls_pred, box_pred, cls_target, box_target, box_mask):
        import jax
        import jax.numpy as jnp
        w = self._weight if self._weight is not None else 1.0

        def f(cp, bp, ct, bt, bm):
            logp = jax.nn.log_softmax(cp.astype(jnp.float32), axis=-1)
            ctc = jnp.maximum(ct, 0).astype(jnp.int32)
            nll = -jnp.take_along_axis(logp, ctc[..., None], -1)[..., 0]
            cls_loss = jnp.where(ct >= 0, nll, 0.0).sum(axis=-1)    # (B,)
            n_pos = jnp.maximum((ct > 0).sum(axis=-1), 1)           # (B,)
            diff = jnp.abs((bp - bt) * bm)
            sl1 = jnp.where(diff < 1.0, 0.5 * diff * diff,
                            diff - 0.5).sum(axis=-1)                # (B,)
            return w * (cls_loss + self._lambd * sl1) / n_pos
        return _apply(f, [cls_pred, box_pred, cls_target, box_target,
                          box_mask], name="ssd_loss")


def _resnet_features(num_layers, layout):
    """Backbone = ResNet stages through conv4 (stride 16), like the
    reference's resnet50 SSD feature map 1."""
    net = _resnet.get_resnet(1, num_layers, layout=layout)
    feats = nn.HybridSequential()
    # keep conv1..stage3 (drop stage4, pool, flatten, output)
    for child in list(net.features._children.values())[:-3]:
        feats.add(child)
    return feats


# Anchor configs follow the reference example/ssd defaults: 300-input uses
# 5 scales here (backbone + 4 extras), 512-input adds a 6th coarser scale.
_SSD_300 = dict(
    sizes=[[0.1, 0.141], [0.2, 0.272], [0.37, 0.447], [0.54, 0.619],
           [0.71, 0.79]],
    ratios=[[1, 2, 0.5]] * 2 + [[1, 2, 0.5, 3, 1.0 / 3]] * 3,
    extra_channels=(512, 256, 256, 256))
_SSD_512 = dict(
    sizes=[[0.07, 0.1], [0.15, 0.222], [0.3, 0.367], [0.45, 0.519],
           [0.6, 0.67], [0.75, 0.82]],
    ratios=[[1, 2, 0.5]] * 2 + [[1, 2, 0.5, 3, 1.0 / 3]] * 4,
    extra_channels=(512, 256, 256, 256, 256))


def _make_ssd(num_layers, classes, layout, cfg, **kwargs):
    return SSD(_resnet_features(num_layers, layout), classes,
               layout=layout, **cfg, **kwargs)


def ssd_512_resnet18_v1(classes=20, layout="NHWC", **kwargs):
    return _make_ssd(18, classes, layout, _SSD_512, **kwargs)


def ssd_512_resnet50_v1(classes=20, layout="NHWC", **kwargs):
    return _make_ssd(50, classes, layout, _SSD_512, **kwargs)


def ssd_300_resnet18_v1(classes=20, layout="NHWC", **kwargs):
    return _make_ssd(18, classes, layout, _SSD_300, **kwargs)
