"""Decoder-only transformer language model with KV-cache generation
(parity: the GluonNLP language-model family — gluonnlp.model.train lm
scripts — re-shaped as the modern causal-LM architecture).

TPU-first design decisions:
- Training forward is one causal pass: fused (D,3D) QKV GEMM per layer
  and the causal pallas flash-attention kernel (ops/pallas/
  flash_attention.py) — O(L) memory, no (L,L) score tensor in HBM.
- Pre-LN blocks + final LN (the stable deep-transformer variant); the
  output head can tie to the input embedding table (tie_weights) — one
  (D,V) GEMM either way, MXU-friendly.
- Generation keeps per-layer KV caches at a STATIC max_length so the
  one-token decode step has a fixed shape: it compiles once under
  hybridize()/jit and replays for every position (the reference's
  bucketing trick, collapsed to a single bucket). Cache positions beyond
  the current step are masked, mirroring how the flash kernel's decode
  path is exercised in tests/test_pallas.py::test_flash_decode_step.
"""
from __future__ import annotations

import numpy as np

from .. import ndarray as nd
from .. import ops
from ..gluon import nn
from ..gluon.block import HybridBlock
from ..gluon.loss import SoftmaxCrossEntropyLoss
from .bert import MultiHeadAttentionCell, PositionwiseFFN

__all__ = ["TransformerLM", "TransformerLMCell", "CausalSelfAttention",
           "transformer_lm_small", "transformer_lm_base", "lm_loss"]


class CausalSelfAttention(MultiHeadAttentionCell):
    """bert.MultiHeadAttentionCell with causal masking and a KV-cache
    decode path.

    Training: full-sequence causal attention (pallas flash kernel when
    available) through the shared fused-QKV cell. Decode: ONE qkv GEMM
    per step — the new token's K/V are written into the fixed-size cache
    and its Q attends over valid (<= current) positions."""

    def forward(self, x, mask=None):
        if mask is not None:
            raise ValueError("causal attention builds its own mask")
        q, k, v = nd.split(self.qkv(x), 3, axis=-1)
        if self._ring is not None:
            # sequence-parallel long-context training: ring / ulysses
            # cores are position-aware, so causality is exact across
            # sequence shards
            out = self._ring_core(q, k, v, causal=True)
        else:
            out = ops.multihead_attention(q, k, v, self._num_heads,
                                          dropout_rate=self._dropout,
                                          causal=True)
        return self.proj(out)

    def forward_step(self, x_t, k_cache, v_cache, pos, pos_mask):
        """One decode step: x_t (B,1,D) already layer-normed; caches
        (B,max_len,D); pos the write index; pos_mask (1,1,1,max_len)
        marking positions <= pos. Returns (out (B,1,D), k_cache,
        v_cache)."""
        q, k_t, v_t = nd.split(self.qkv(x_t), 3, axis=-1)
        k_cache[:, pos:pos + 1] = k_t
        v_cache[:, pos:pos + 1] = v_t
        out = ops.multihead_attention(q, k_cache, v_cache, self._num_heads,
                                      mask=pos_mask)
        return self.proj(out), k_cache, v_cache

    def project_kv(self, x_t):
        """K,V for prefill token(s) (B,L,D) -> two (B,L,D)."""
        _, k, v = nd.split(self.qkv(x_t), 3, axis=-1)
        return k, v


class TransformerLMCell(HybridBlock):
    """Pre-LN decoder block: LN→causal-MHA→residual, LN→FFN→residual."""

    def __init__(self, units, hidden_size, num_heads, dropout=0.0,
                 weight_initializer=None, ring=None, prefix=None,
                 params=None):
        super().__init__(prefix, params)
        self.attention = CausalSelfAttention(
            units, num_heads, dropout, weight_initializer=weight_initializer,
            ring=ring)
        self.ffn = PositionwiseFFN(units, hidden_size, dropout,
                                   weight_initializer=weight_initializer)
        self.dropout = nn.Dropout(dropout)
        self.ln1 = nn.LayerNorm(in_channels=units)
        self.ln2 = nn.LayerNorm(in_channels=units)

    def forward(self, x):
        x = x + self.dropout(self.attention(self.ln1(x)))
        return x + self.ffn(self.ln2(x))

    def forward_step(self, x_t, k_cache, v_cache, pos, pos_mask):
        a, k_cache, v_cache = self.attention.forward_step(
            self.ln1(x_t), k_cache, v_cache, pos, pos_mask)
        x_t = x_t + a
        return x_t + self.ffn(self.ln2(x_t)), k_cache, v_cache


class TransformerLM(HybridBlock):
    """Decoder-only LM: token + learned position embeddings, N pre-LN
    causal blocks, final LN, vocab head (optionally weight-tied).

    forward(inputs): (B, L) int token ids -> (B, L, vocab) logits.
    generate(...): greedy/temperature sampling with per-layer KV caches.
    """

    def __init__(self, vocab_size, num_layers=2, units=128,
                 hidden_size=512, num_heads=4, max_length=512, dropout=0.0,
                 tie_weights=True, ring=None, prefix=None, params=None):
        super().__init__(prefix, params)
        self._units = units
        self._max_length = max_length
        self._vocab_size = vocab_size
        self._tie = tie_weights
        self._ring = ring
        self.embedding = nn.Embedding(vocab_size, units)
        self.pos_embedding = nn.Embedding(max_length, units)
        self.layers = []
        for i in range(num_layers):
            cell = TransformerLMCell(units, hidden_size, num_heads, dropout,
                                     ring=ring)
            self.register_child(cell, f"layer{i}")
            self.layers.append(cell)
        self.ln_f = nn.LayerNorm(in_channels=units)
        if not tie_weights:
            self.head = nn.Dense(vocab_size, flatten=False, in_units=units)
        self.dropout = nn.Dropout(dropout)

    def _logits(self, h):
        if self._tie:
            # transpose_b (not .data().T): keeps the weight itself as the
            # op input, so symbol tracing maps it to its parameter
            # Variable and eager mode avoids materializing the transpose
            return nd.dot(h, self.embedding.weight.data(),
                          transpose_b=True)
        return self.head(h)

    def _embed(self, inputs, position_offset=0):
        if not isinstance(inputs, nd.NDArray):
            # symbol trace: positions are 0..L-1, so the first L rows of
            # the table ARE the positional embeddings — slice_like keeps
            # the length tied to the input, and an L > max_length bind
            # fails the broadcast add (a gather would silently clamp)
            if position_offset:
                raise ValueError("symbolic trace supports "
                                 "position_offset=0 only")
            pos_emb = nd.slice_like(self.pos_embedding.weight.data(),
                                    nd.swapaxes(inputs, 0, 1), axes=(0,))
            h = (self.embedding(inputs) * float(np.sqrt(self._units))
                 + pos_emb)
            return self.dropout(h)
        L = inputs.shape[1]
        if position_offset + L > self._max_length:
            raise ValueError(
                f"sequence length {position_offset + L} exceeds "
                f"max_length {self._max_length}")
        pos = nd.arange(position_offset, position_offset + L)
        h = (self.embedding(inputs) * float(np.sqrt(self._units))
             + self.pos_embedding(pos))
        return self.dropout(h)

    def forward(self, inputs):
        h = self._embed(inputs)
        for layer in self.layers:
            h = layer(h)
        return self._logits(self.ln_f(h))

    # -- KV-cache generation ---------------------------------------------
    def init_cache(self, batch_size):
        """Per-layer (k, v) caches, (B, max_length, D) zeros."""
        return [(nd.zeros((batch_size, self._max_length, self._units)),
                 nd.zeros((batch_size, self._max_length, self._units)))
                for _ in self.layers]

    def _write_cache(self, caches, h_stack, start):
        """Project K/V for positions [start, start+L) of each layer's
        INPUT activations h_stack[i] and write them into the caches."""
        new = []
        for (k_c, v_c), layer, h in zip(caches, self.layers, h_stack):
            k_t, v_t = layer.attention.project_kv(layer.ln1(h))
            k_c[:, start:start + h.shape[1]] = k_t
            v_c[:, start:start + h.shape[1]] = v_t
            new.append((k_c, v_c))
        return new

    def _step_with_cache(self, token, pos, caches):
        """Decode one token at `pos` given caches filled for [0, pos).
        Returns (logits (B, vocab), updated caches)."""
        h = self._embed(token, position_offset=pos)
        mask = (nd.arange(self._max_length) <= float(pos)).reshape(
            1, 1, 1, self._max_length)
        for i, layer in enumerate(self.layers):
            k_c, v_c = caches[i]
            h, k_c, v_c = layer.forward_step(h, k_c, v_c, pos, mask)
            caches[i] = (k_c, v_c)
        return self._logits(self.ln_f(h))[:, 0], caches

    def generate(self, prompt, max_new_tokens, temperature=0.0, seed=None):
        """Continue `prompt` (B, Lp) by max_new_tokens.

        temperature=0 is greedy argmax; >0 samples softmax(logits/T).
        Prefill runs ONE full causal pass (flash path) and fills the
        caches; each subsequent token is a fixed-shape one-step call.
        Returns (B, Lp + max_new_tokens) token ids."""
        if self._ring is not None:
            raise ValueError(
                "generate() decodes single-device; build the model without "
                "ring= for inference (sequence parallelism is a training "
                "configuration — load the same parameters into a dense "
                "model)")
        prompt = nd.array(prompt) if not isinstance(prompt, nd.NDArray) \
            else prompt
        b, lp = prompt.shape
        if lp + max_new_tokens > self._max_length:
            raise ValueError("prompt + max_new_tokens exceeds max_length")
        rng = np.random.RandomState(seed)

        # prefill: full causal pass, keeping each layer's INPUT activations
        # so the caches hold exactly what forward_step's attention sees
        h = self._embed(prompt)
        h_stack = []
        for layer in self.layers:
            h_stack.append(h)
            h = layer(h)
        logits_last = self._logits(self.ln_f(h))[:, -1]
        caches = self._write_cache(self.init_cache(b), h_stack, 0)

        out = [prompt]
        for i in range(max_new_tokens):
            if temperature > 0.0:
                p = nd.softmax(logits_last / temperature, axis=-1).asnumpy()
                p = p / p.sum(-1, keepdims=True)  # exact simplex for choice
                nxt = np.array([rng.choice(self._vocab_size, p=p[j])
                                for j in range(b)], np.int32)
            else:
                nxt = logits_last.asnumpy().argmax(-1).astype(np.int32)
            tok = nd.array(nxt[:, None])
            out.append(tok)
            if i == max_new_tokens - 1:
                break
            logits_last, caches = self._step_with_cache(
                tok, lp + i, caches)
        return nd.concat(*out, dim=1)


def transformer_lm_small(vocab_size=10000, **kwargs):
    """4-layer, 256-unit causal LM (toy/bench scale)."""
    kwargs.setdefault("num_layers", 4)
    kwargs.setdefault("units", 256)
    kwargs.setdefault("hidden_size", 1024)
    kwargs.setdefault("num_heads", 4)
    return TransformerLM(vocab_size, **kwargs)


def transformer_lm_base(vocab_size=50257, **kwargs):
    """12-layer, 768-unit causal LM (GPT-2-base scale)."""
    kwargs.setdefault("num_layers", 12)
    kwargs.setdefault("units", 768)
    kwargs.setdefault("hidden_size", 3072)
    kwargs.setdefault("num_heads", 12)
    kwargs.setdefault("max_length", 1024)
    return TransformerLM(vocab_size, **kwargs)


def lm_loss(logits, targets):
    """Shifted causal-LM loss: per-position CE of logits[:, :-1] vs
    targets[:, 1:], shape (B*(L-1),) — gluon loss convention; call
    .mean() for the scalar."""
    ce = SoftmaxCrossEntropyLoss()
    v = logits.shape[-1]
    return ce(logits[:, :-1].reshape(-1, v), targets[:, 1:].reshape(-1))
