"""AlexNet (parity: python/mxnet/gluon/model_zoo/vision/alexnet.py)."""
from __future__ import annotations

from ..gluon import nn
from ..gluon.block import HybridBlock

__all__ = ["AlexNet", "alexnet"]


class AlexNet(HybridBlock):
    def __init__(self, classes=1000, layout="NHWC", **kwargs):
        super().__init__(**kwargs)
        self.features = nn.HybridSequential()
        self.features.add(nn.Conv2D(64, 11, strides=4, padding=2,
                                    activation="relu", layout=layout))
        self.features.add(nn.MaxPool2D(3, 2, layout=layout))
        self.features.add(nn.Conv2D(192, 5, padding=2, activation="relu",
                                    layout=layout))
        self.features.add(nn.MaxPool2D(3, 2, layout=layout))
        self.features.add(nn.Conv2D(384, 3, padding=1, activation="relu",
                                    layout=layout))
        self.features.add(nn.Conv2D(256, 3, padding=1, activation="relu",
                                    layout=layout))
        self.features.add(nn.Conv2D(256, 3, padding=1, activation="relu",
                                    layout=layout))
        self.features.add(nn.MaxPool2D(3, 2, layout=layout))
        self.features.add(nn.Flatten())
        self.features.add(nn.Dense(4096, activation="relu"))
        self.features.add(nn.Dropout(0.5))
        self.features.add(nn.Dense(4096, activation="relu"))
        self.features.add(nn.Dropout(0.5))
        self.output = nn.Dense(classes)

    def forward(self, x):
        return self.output(self.features(x))


def alexnet(classes=1000, layout="NHWC", **kwargs):
    return AlexNet(classes=classes, layout=layout, **kwargs)
