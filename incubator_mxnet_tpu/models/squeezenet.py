"""SqueezeNet 1.0/1.1 (parity: python/mxnet/gluon/model_zoo/vision/
squeezenet.py)."""
from __future__ import annotations

from ..gluon import nn
from ..gluon.block import HybridBlock
from .. import ndarray as nd
from .common import bn_axis

__all__ = ["SqueezeNet", "squeezenet1_0", "squeezenet1_1"]


class _Fire(HybridBlock):
    def __init__(self, squeeze, expand1x1, expand3x3, layout, **kwargs):
        super().__init__(**kwargs)
        self._axis = bn_axis(layout)
        self.squeeze = nn.Conv2D(squeeze, 1, activation="relu", layout=layout)
        self.expand1x1 = nn.Conv2D(expand1x1, 1, activation="relu",
                                   layout=layout)
        self.expand3x3 = nn.Conv2D(expand3x3, 3, padding=1, activation="relu",
                                   layout=layout)

    def forward(self, x):
        x = self.squeeze(x)
        return nd.concat(self.expand1x1(x), self.expand3x3(x), dim=self._axis)


class SqueezeNet(HybridBlock):
    def __init__(self, version="1.0", classes=1000, layout="NHWC", **kwargs):
        super().__init__(**kwargs)
        assert version in ("1.0", "1.1")
        self.features = nn.HybridSequential()
        if version == "1.0":
            self.features.add(nn.Conv2D(96, 7, strides=2, activation="relu",
                                        layout=layout))
            self.features.add(nn.MaxPool2D(3, 2, ceil_mode=True, layout=layout))
            for sq, e1, e3 in [(16, 64, 64), (16, 64, 64), (32, 128, 128)]:
                self.features.add(_Fire(sq, e1, e3, layout))
            self.features.add(nn.MaxPool2D(3, 2, ceil_mode=True, layout=layout))
            for sq, e1, e3 in [(32, 128, 128), (48, 192, 192), (48, 192, 192),
                               (64, 256, 256)]:
                self.features.add(_Fire(sq, e1, e3, layout))
            self.features.add(nn.MaxPool2D(3, 2, ceil_mode=True, layout=layout))
            self.features.add(_Fire(64, 256, 256, layout))
        else:
            self.features.add(nn.Conv2D(64, 3, strides=2, activation="relu",
                                        layout=layout))
            self.features.add(nn.MaxPool2D(3, 2, ceil_mode=True, layout=layout))
            for sq, e1, e3 in [(16, 64, 64), (16, 64, 64)]:
                self.features.add(_Fire(sq, e1, e3, layout))
            self.features.add(nn.MaxPool2D(3, 2, ceil_mode=True, layout=layout))
            for sq, e1, e3 in [(32, 128, 128), (32, 128, 128)]:
                self.features.add(_Fire(sq, e1, e3, layout))
            self.features.add(nn.MaxPool2D(3, 2, ceil_mode=True, layout=layout))
            for sq, e1, e3 in [(48, 192, 192), (48, 192, 192),
                               (64, 256, 256), (64, 256, 256)]:
                self.features.add(_Fire(sq, e1, e3, layout))
        self.features.add(nn.Dropout(0.5))
        self.output = nn.HybridSequential()
        self.output.add(nn.Conv2D(classes, 1, activation="relu",
                                  layout=layout))
        self.output.add(nn.GlobalAvgPool2D(layout=layout))
        self.output.add(nn.Flatten())

    def forward(self, x):
        return self.output(self.features(x))


def squeezenet1_0(classes=1000, layout="NHWC", **kwargs):
    return SqueezeNet("1.0", classes=classes, layout=layout, **kwargs)


def squeezenet1_1(classes=1000, layout="NHWC", **kwargs):
    return SqueezeNet("1.1", classes=classes, layout=layout, **kwargs)
