"""LeNet-5 (reference: example/image-classification/symbols/lenet.py)."""
from __future__ import annotations

from ..gluon import nn
from ..gluon.block import HybridBlock

__all__ = ["LeNet", "lenet"]


class LeNet(HybridBlock):
    def __init__(self, classes=10, layout="NCHW", **kwargs):
        super().__init__(**kwargs)
        self.features = nn.HybridSequential()
        self.features.add(
            nn.Conv2D(20, kernel_size=5, activation="tanh", layout=layout),
            nn.MaxPool2D(2, 2, layout=layout),
            nn.Conv2D(50, kernel_size=5, activation="tanh", layout=layout),
            nn.MaxPool2D(2, 2, layout=layout),
            nn.Flatten(),
            nn.Dense(500, activation="tanh"),
        )
        self.output = nn.Dense(classes)

    def forward(self, x):
        return self.output(self.features(x))


def lenet(classes=10, **kwargs):
    return LeNet(classes=classes, **kwargs)
