"""DenseNet 121/161/169/201 (parity: python/mxnet/gluon/model_zoo/vision/
densenet.py)."""
from __future__ import annotations

from ..gluon import nn
from ..gluon.block import HybridBlock
from .. import ndarray as nd
from .common import bn_axis as _bn_axis

__all__ = ["DenseNet", "densenet121", "densenet161", "densenet169",
           "densenet201"]

# num_init_features, growth_rate, block_config
_SPEC = {121: (64, 32, [6, 12, 24, 16]),
         161: (96, 48, [6, 12, 36, 24]),
         169: (64, 32, [6, 12, 32, 32]),
         201: (64, 32, [6, 12, 48, 32])}


class _DenseLayer(HybridBlock):
    def __init__(self, growth_rate, bn_size, dropout, layout, **kwargs):
        super().__init__(**kwargs)
        self._axis = _bn_axis(layout)
        self.body = nn.HybridSequential()
        self.body.add(nn.BatchNorm(axis=self._axis))
        self.body.add(nn.Activation("relu"))
        self.body.add(nn.Conv2D(bn_size * growth_rate, 1, use_bias=False,
                                layout=layout))
        self.body.add(nn.BatchNorm(axis=self._axis))
        self.body.add(nn.Activation("relu"))
        self.body.add(nn.Conv2D(growth_rate, 3, padding=1, use_bias=False,
                                layout=layout))
        if dropout:
            self.body.add(nn.Dropout(dropout))

    def forward(self, x):
        return nd.concat(x, self.body(x), dim=self._axis)


class _Transition(HybridBlock):
    def __init__(self, channels, layout, **kwargs):
        super().__init__(**kwargs)
        self.body = nn.HybridSequential()
        self.body.add(nn.BatchNorm(axis=_bn_axis(layout)))
        self.body.add(nn.Activation("relu"))
        self.body.add(nn.Conv2D(channels, 1, use_bias=False, layout=layout))
        self.body.add(nn.AvgPool2D(2, 2, layout=layout))

    def forward(self, x):
        return self.body(x)


class DenseNet(HybridBlock):
    def __init__(self, num_init_features, growth_rate, block_config,
                 bn_size=4, dropout=0, classes=1000, layout="NHWC", **kwargs):
        super().__init__(**kwargs)
        axis = _bn_axis(layout)
        self.features = nn.HybridSequential()
        self.features.add(nn.Conv2D(num_init_features, 7, strides=2,
                                    padding=3, use_bias=False, layout=layout))
        self.features.add(nn.BatchNorm(axis=axis))
        self.features.add(nn.Activation("relu"))
        self.features.add(nn.MaxPool2D(3, 2, 1, layout=layout))
        num_features = num_init_features
        for i, num_layers in enumerate(block_config):
            for _ in range(num_layers):
                self.features.add(_DenseLayer(growth_rate, bn_size, dropout,
                                              layout))
            num_features += num_layers * growth_rate
            if i != len(block_config) - 1:
                num_features //= 2
                self.features.add(_Transition(num_features, layout))
        self.features.add(nn.BatchNorm(axis=axis))
        self.features.add(nn.Activation("relu"))
        self.features.add(nn.GlobalAvgPool2D(layout=layout))
        self.features.add(nn.Flatten())
        self.output = nn.Dense(classes)

    def forward(self, x):
        return self.output(self.features(x))


def _make(n):
    def f(classes=1000, layout="NHWC", **kwargs):
        ninit, growth, cfg = _SPEC[n]
        return DenseNet(ninit, growth, cfg, classes=classes, layout=layout,
                        **kwargs)
    f.__name__ = f"densenet{n}"
    return f


densenet121 = _make(121)
densenet161 = _make(161)
densenet169 = _make(169)
densenet201 = _make(201)
