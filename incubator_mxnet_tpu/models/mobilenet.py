"""MobileNet v1/v2 (parity: python/mxnet/gluon/model_zoo/vision/
mobilenet.py). Depthwise convs = grouped Conv2D with groups=channels; XLA:TPU
lowers these to efficient channel-tiled convolutions in NHWC."""
from __future__ import annotations

from ..gluon import nn
from ..gluon.block import HybridBlock
from .common import bn_axis

__all__ = ["MobileNet", "MobileNetV2", "mobilenet1_0", "mobilenet0_75",
           "mobilenet0_5", "mobilenet0_25", "mobilenet_v2_1_0",
           "mobilenet_v2_0_75", "mobilenet_v2_0_5", "mobilenet_v2_0_25"]


def _conv_block(out, channels, kernel, stride, pad, layout, groups=1,
                active=True, relu6=False):
    out.add(nn.Conv2D(channels, kernel, strides=stride, padding=pad,
                      groups=groups, use_bias=False, layout=layout))
    out.add(nn.BatchNorm(axis=bn_axis(layout)))
    if active:
        out.add(nn.Activation("relu6" if relu6 else "relu"))


class MobileNet(HybridBlock):
    """v1: depthwise-separable stacks."""

    def __init__(self, multiplier=1.0, classes=1000, layout="NHWC", **kwargs):
        super().__init__(**kwargs)
        dw_channels = [int(x * multiplier) for x in
                       [32, 64] + [128] * 2 + [256] * 2 + [512] * 6 + [1024]]
        channels = [int(x * multiplier) for x in
                    [64] + [128] * 2 + [256] * 2 + [512] * 6 + [1024] * 2]
        strides = [1, 2] * 3 + [1] * 5 + [2, 1]
        self.features = nn.HybridSequential()
        _conv_block(self.features, int(32 * multiplier), 3, 2, 1, layout)
        for dwc, c, s in zip(dw_channels, channels, strides):
            # depthwise
            _conv_block(self.features, dwc, 3, s, 1, layout, groups=dwc)
            # pointwise
            _conv_block(self.features, c, 1, 1, 0, layout)
        self.features.add(nn.GlobalAvgPool2D(layout=layout))
        self.features.add(nn.Flatten())
        self.output = nn.Dense(classes)

    def forward(self, x):
        return self.output(self.features(x))


class _InvertedResidual(HybridBlock):
    def __init__(self, in_ch, ch, t, stride, layout, **kwargs):
        super().__init__(**kwargs)
        self.use_shortcut = stride == 1 and in_ch == ch
        self.out = nn.HybridSequential()
        if t != 1:
            _conv_block(self.out, in_ch * t, 1, 1, 0, layout, relu6=True)
        _conv_block(self.out, in_ch * t, 3, stride, 1, layout,
                    groups=in_ch * t, relu6=True)
        _conv_block(self.out, ch, 1, 1, 0, layout, active=False)

    def forward(self, x):
        out = self.out(x)
        return out + x if self.use_shortcut else out


class MobileNetV2(HybridBlock):
    def __init__(self, multiplier=1.0, classes=1000, layout="NHWC", **kwargs):
        super().__init__(**kwargs)
        m = multiplier
        self.features = nn.HybridSequential()
        _conv_block(self.features, int(32 * m), 3, 2, 1, layout, relu6=True)
        # t, c, n, s (expansion, channels, repeats, first stride)
        spec = [(1, 16, 1, 1), (6, 24, 2, 2), (6, 32, 3, 2), (6, 64, 4, 2),
                (6, 96, 3, 1), (6, 160, 3, 2), (6, 320, 1, 1)]
        in_ch = int(32 * m)
        for t, c, n, s in spec:
            ch = int(c * m)
            for i in range(n):
                self.features.add(_InvertedResidual(
                    in_ch, ch, t, s if i == 0 else 1, layout))
                in_ch = ch
        last = int(1280 * m) if m > 1.0 else 1280
        _conv_block(self.features, last, 1, 1, 0, layout, relu6=True)
        self.features.add(nn.GlobalAvgPool2D(layout=layout))
        self.features.add(nn.Flatten())
        self.output = nn.Dense(classes)

    def forward(self, x):
        return self.output(self.features(x))


def _make(cls, mult, name):
    def f(classes=1000, layout="NHWC", **kwargs):
        return cls(mult, classes=classes, layout=layout, **kwargs)
    f.__name__ = name
    return f


mobilenet1_0 = _make(MobileNet, 1.0, "mobilenet1_0")
mobilenet0_75 = _make(MobileNet, 0.75, "mobilenet0_75")
mobilenet0_5 = _make(MobileNet, 0.5, "mobilenet0_5")
mobilenet0_25 = _make(MobileNet, 0.25, "mobilenet0_25")
mobilenet_v2_1_0 = _make(MobileNetV2, 1.0, "mobilenet_v2_1_0")
mobilenet_v2_0_75 = _make(MobileNetV2, 0.75, "mobilenet_v2_0_75")
mobilenet_v2_0_5 = _make(MobileNetV2, 0.5, "mobilenet_v2_0_5")
mobilenet_v2_0_25 = _make(MobileNetV2, 0.25, "mobilenet_v2_0_25")
