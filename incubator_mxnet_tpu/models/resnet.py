"""ResNet v1/v2 (parity: python/mxnet/gluon/model_zoo/vision/resnet.py;
reference example/image-classification resnet).

TPU-first defaults: layout='NHWC' (channels-last feeds the MXU without
relayout) and optional bf16 compute via net.cast('bfloat16') with f32 BN
statistics (handled inside _raw.batch_norm/layer_norm). Set layout='NCHW'
for bitwise API parity with the reference."""
from __future__ import annotations

from ..gluon import nn
from ..gluon.block import HybridBlock

__all__ = ["ResNetV1", "ResNetV2", "SpaceToDepthStem",
           "BasicBlockV1", "BottleneckV1",
           "BasicBlockV2", "BottleneckV2", "resnet18_v1", "resnet34_v1",
           "resnet50_v1", "resnet101_v1", "resnet152_v1", "resnet18_v2",
           "resnet34_v2", "resnet50_v2", "resnet101_v2", "resnet152_v2",
           "get_resnet"]


def _conv(channels, kernel, stride, pad, layout, in_channels=0):
    return nn.Conv2D(channels, kernel, strides=stride, padding=pad,
                     use_bias=False, layout=layout, in_channels=in_channels)


class SpaceToDepthStem(HybridBlock):
    """EXACT-equivalent replacement for the 7x7/stride-2 stem conv (NHWC).

    The standard stem feeds the MXU 3 input channels — 3 of 128 lanes do
    work. Space-to-depth (MLPerf ResNet's TPU trick) reshapes the image
    to (H/2, W/2, 4C) and runs the mathematically identical 4x4/stride-1
    conv with asymmetric (2,1) padding; the kernel is rearranged IN-GRAPH
    from the same (7,7,C,O) HWIO parameter, so checkpoints interchange
    with the standard stem bit-for-bit and XLA constant-folds the
    rearrangement.

    Derivation: y[p,q] = sum_{i,j} w[i,j] x[2p+i-3, 2q+j-3]; write
    i = 2*ai + di - 1 (di in {0,1}) and the sum becomes a 4-tap conv over
    the s2d image with channel index (di, dj, c)."""

    def __init__(self, channels, in_channels=3, prefix=None, params=None):
        super().__init__(prefix, params)
        self.weight = self.params.get(
            "weight", shape=(7, 7, in_channels, channels))

    def forward(self, x):
        from ..ndarray import _apply
        import jax
        import jax.numpy as jnp

        def fn(xr, w):
            N, H, W, C = xr.shape
            if C != w.shape[2]:
                raise ValueError(
                    f"SpaceToDepthStem was built for {w.shape[2]} input "
                    f"channels, got {C}; pass in_channels= to match")
            if H % 2 or W % 2:
                raise ValueError(
                    f"SpaceToDepthStem needs even H/W, got {(H, W)}")
            xs = (xr.reshape(N, H // 2, 2, W // 2, 2, C)
                  .transpose(0, 1, 3, 2, 4, 5)
                  .reshape(N, H // 2, W // 2, 4 * C))
            # kernel index i = 2*ai + di - 1  ->  pad one zero row/col at
            # the front so wp[2*ai + di] == w[i] (wp[0] is the i=-1 zero)
            wf = w.astype(jnp.float32)
            wp = jnp.pad(wf, ((1, 0), (1, 0), (0, 0), (0, 0)))
            O = wf.shape[-1]
            w2 = (wp.reshape(4, 2, 4, 2, C, O)
                  .transpose(0, 2, 1, 3, 4, 5)
                  .reshape(4, 4, 4 * C, O)).astype(xs.dtype)
            return jax.lax.conv_general_dilated(
                xs, w2, window_strides=(1, 1), padding=[(2, 1), (2, 1)],
                dimension_numbers=("NHWC", "HWIO", "NHWC"))

        return _apply(fn, [x, self.weight.data()], name="s2d_stem")


def _bn(layout, **kw):
    return nn.BatchNorm(axis=-1 if layout == "NHWC" else 1, **kw)


class BasicBlockV1(HybridBlock):
    def __init__(self, channels, stride, downsample=False, in_channels=0,
                 layout="NHWC", **kwargs):
        super().__init__(**kwargs)
        self.body = nn.HybridSequential()
        self.body.add(_conv(channels, 3, stride, 1, layout, in_channels))
        self.body.add(_bn(layout))
        self.body.add(nn.Activation("relu"))
        self.body.add(_conv(channels, 3, 1, 1, layout))
        self.body.add(_bn(layout))
        if downsample:
            self.downsample = nn.HybridSequential()
            self.downsample.add(_conv(channels, 1, stride, 0, layout, in_channels))
            self.downsample.add(_bn(layout))
        else:
            self.downsample = None

    def forward(self, x):
        residual = x if self.downsample is None else self.downsample(x)
        out = self.body(x)
        return (out + residual).relu()


class BottleneckV1(HybridBlock):
    def __init__(self, channels, stride, downsample=False, in_channels=0,
                 layout="NHWC", **kwargs):
        super().__init__(**kwargs)
        mid = channels // 4
        self.body = nn.HybridSequential()
        self.body.add(_conv(mid, 1, stride, 0, layout, in_channels))
        self.body.add(_bn(layout))
        self.body.add(nn.Activation("relu"))
        self.body.add(_conv(mid, 3, 1, 1, layout))
        self.body.add(_bn(layout))
        self.body.add(nn.Activation("relu"))
        self.body.add(_conv(channels, 1, 1, 0, layout))
        self.body.add(_bn(layout))
        if downsample:
            self.downsample = nn.HybridSequential()
            self.downsample.add(_conv(channels, 1, stride, 0, layout, in_channels))
            self.downsample.add(_bn(layout))
        else:
            self.downsample = None

    def forward(self, x):
        residual = x if self.downsample is None else self.downsample(x)
        out = self.body(x)
        return (out + residual).relu()


class BasicBlockV2(HybridBlock):
    def __init__(self, channels, stride, downsample=False, in_channels=0,
                 layout="NHWC", **kwargs):
        super().__init__(**kwargs)
        self.bn1 = _bn(layout)
        self.conv1 = _conv(channels, 3, stride, 1, layout, in_channels)
        self.bn2 = _bn(layout)
        self.conv2 = _conv(channels, 3, 1, 1, layout)
        if downsample:
            self.downsample = _conv(channels, 1, stride, 0, layout, in_channels)
        else:
            self.downsample = None

    def forward(self, x):
        bn1 = self.bn1(x).relu()
        residual = x if self.downsample is None else self.downsample(bn1)
        out = self.conv1(bn1)
        out = self.conv2(self.bn2(out).relu())
        return out + residual


class BottleneckV2(HybridBlock):
    def __init__(self, channels, stride, downsample=False, in_channels=0,
                 layout="NHWC", **kwargs):
        super().__init__(**kwargs)
        mid = channels // 4
        self.bn1 = _bn(layout)
        self.conv1 = _conv(mid, 1, 1, 0, layout, in_channels)
        self.bn2 = _bn(layout)
        self.conv2 = _conv(mid, 3, stride, 1, layout)
        self.bn3 = _bn(layout)
        self.conv3 = _conv(channels, 1, 1, 0, layout)
        if downsample:
            self.downsample = _conv(channels, 1, stride, 0, layout, in_channels)
        else:
            self.downsample = None

    def forward(self, x):
        bn1 = self.bn1(x).relu()
        residual = x if self.downsample is None else self.downsample(bn1)
        out = self.conv1(bn1)
        out = self.conv2(self.bn2(out).relu())
        out = self.conv3(self.bn3(out).relu())
        return out + residual


class _ResNetBase(HybridBlock):
    def __init__(self, block, layers, channels, classes=1000, layout="NHWC",
                 thumbnail=False, version=1, stem_s2d=False, **kwargs):
        super().__init__(**kwargs)
        self._layout = layout
        self.features = nn.HybridSequential()
        if version == 2:
            self.features.add(_bn(layout, scale=False, center=False))
        if thumbnail:
            self.features.add(_conv(channels[0], 3, 1, 1, layout))
        else:
            if stem_s2d:
                if layout != "NHWC":
                    raise ValueError("stem_s2d requires layout='NHWC'")
                self.features.add(SpaceToDepthStem(channels[0]))
            else:
                self.features.add(nn.Conv2D(channels[0], 7, strides=2,
                                            padding=3, use_bias=False,
                                            layout=layout))
            if version == 1:
                self.features.add(_bn(layout))
                self.features.add(nn.Activation("relu"))
            self.features.add(nn.MaxPool2D(3, 2, 1, layout=layout))
        in_ch = channels[0]
        for i, num_layer in enumerate(layers):
            stride = 1 if i == 0 else 2
            stage = nn.HybridSequential()
            stage.add(block(channels[i + 1], stride,
                            downsample=(channels[i + 1] != in_ch or stride != 1),
                            in_channels=in_ch, layout=layout))
            for _ in range(num_layer - 1):
                stage.add(block(channels[i + 1], 1, in_channels=channels[i + 1],
                                layout=layout))
            in_ch = channels[i + 1]
            self.features.add(stage)
        if version == 2:
            self.features.add(_bn(layout))
            self.features.add(nn.Activation("relu"))
        self.features.add(nn.GlobalAvgPool2D(
            layout=layout if layout == "NCHW" else "NHWC"))
        self.features.add(nn.Flatten())
        self.output = nn.Dense(classes, in_units=in_ch)

    def forward(self, x):
        return self.output(self.features(x))


class ResNetV1(_ResNetBase):
    def __init__(self, block, layers, channels, **kwargs):
        super().__init__(block, layers, channels, version=1, **kwargs)


class ResNetV2(_ResNetBase):
    def __init__(self, block, layers, channels, **kwargs):
        super().__init__(block, layers, channels, version=2, **kwargs)


_SPEC = {
    18: ("basic_block", [2, 2, 2, 2], [64, 64, 128, 256, 512]),
    34: ("basic_block", [3, 4, 6, 3], [64, 64, 128, 256, 512]),
    50: ("bottle_neck", [3, 4, 6, 3], [64, 256, 512, 1024, 2048]),
    101: ("bottle_neck", [3, 4, 23, 3], [64, 256, 512, 1024, 2048]),
    152: ("bottle_neck", [3, 8, 36, 3], [64, 256, 512, 1024, 2048]),
}
_BLOCKS = {1: {"basic_block": BasicBlockV1, "bottle_neck": BottleneckV1},
           2: {"basic_block": BasicBlockV2, "bottle_neck": BottleneckV2}}


def get_resnet(version, num_layers, classes=1000, layout="NHWC", **kwargs):
    btype, layers, channels = _SPEC[num_layers]
    cls = ResNetV1 if version == 1 else ResNetV2
    return cls(_BLOCKS[version][btype], layers, channels, classes=classes,
               layout=layout, **kwargs)


def _make(version, n):
    def f(classes=1000, layout="NHWC", **kwargs):
        return get_resnet(version, n, classes=classes, layout=layout, **kwargs)
    f.__name__ = f"resnet{n}_v{version}"
    return f


resnet18_v1 = _make(1, 18)
resnet34_v1 = _make(1, 34)
resnet50_v1 = _make(1, 50)
resnet101_v1 = _make(1, 101)
resnet152_v1 = _make(1, 152)
resnet18_v2 = _make(2, 18)
resnet34_v2 = _make(2, 34)
resnet50_v2 = _make(2, 50)
resnet101_v2 = _make(2, 101)
resnet152_v2 = _make(2, 152)
