"""Shared model-zoo helpers."""


def bn_axis(layout: str) -> int:
    """Channel axis for normalization layers: -1 for channels-last (NHWC,
    the TPU-preferred layout), 1 for channels-first (NCHW parity)."""
    return -1 if layout.endswith("C") else 1
