"""DLRM — the recsys model family (Naumov et al. 2019, arXiv:1906.00091).

Embedding bags + bottom/top MLP + pairwise dot-product feature
interaction — the canonical memory-bound, all-to-all-bound workload:
the (vocab, dim) tables dominate bytes (not FLOPs), so this is the
model family that makes the sharding/comms/memscope layers load-bearing
(docs/embedding.md).

Input convention (matches the `BENCH_MODEL=recsys` record stream): one
float32 matrix ``(batch, dense_dim + num_tables * bag_size)`` — dense
features first, then the categorical ids FLOAT-ENCODED (a record
stream's natural carrier; exact for any vocab < 2^24). The id policy
(embedding/lookup.normalize_ids) rounds them back to int32 — the
non-integer-index path `gluon.nn.Embedding` historically got wrong.

forward(x) -> (batch, 1) click logits; pair with
:func:`dlrm_loss` (sigmoid BCE).
"""
from __future__ import annotations

import numpy as np

from .. import ndarray as nd
from ..embedding import EmbeddingBag
from ..gluon import nn
from ..gluon.block import HybridBlock
from ..gluon.loss import SigmoidBinaryCrossEntropyLoss

__all__ = ["DLRM", "dlrm_loss", "dlrm_small", "dlrm_flops_per_sample",
           "dlrm_bytes_per_sample"]


class DLRM(HybridBlock):
    def __init__(self, num_tables=8, vocab_size=512, embed_dim=32,
                 dense_dim=13, bag_size=4, bottom_units=(64,),
                 top_units=(128, 64), dedup=True, dedup_capacity=None,
                 oor_policy="clip", prefix=None, params=None):
        super().__init__(prefix, params)
        self.num_tables = int(num_tables)
        self.vocab_size = int(vocab_size)
        self.embed_dim = int(embed_dim)
        self.dense_dim = int(dense_dim)
        self.bag_size = int(bag_size)
        self.embeddings = []
        for t in range(self.num_tables):
            bag = EmbeddingBag(vocab_size, embed_dim, mode="sum",
                               dedup=dedup, dedup_capacity=dedup_capacity,
                               oor_policy=oor_policy)
            setattr(self, f"embed{t}", bag)      # register as child
            self.embeddings.append(bag)
        self.bottom = nn.HybridSequential()
        for u in tuple(bottom_units) + (embed_dim,):
            self.bottom.add(nn.Dense(u, activation="relu"))
        self.top = nn.HybridSequential()
        for u in top_units:
            self.top.add(nn.Dense(u, activation="relu"))
        self.top.add(nn.Dense(1))
        # upper-triangle (i < j) flat indices of the (T+1, T+1) gram
        # matrix — the distinct pairwise interactions
        n = self.num_tables + 1
        self._tri = np.array([i * n + j for i in range(n)
                              for j in range(i + 1, n)], dtype=np.int32)

    def forward(self, x):
        b = x.shape[0]
        dense = nd.slice_axis(x, 1, 0, self.dense_dim)
        ids = nd.slice_axis(x, 1, self.dense_dim,
                            self.dense_dim
                            + self.num_tables * self.bag_size)
        ids = ids.reshape((b, self.num_tables, self.bag_size))
        bottom = self.bottom(dense)                       # (B, D)
        feats = [bottom]
        for t, bag in enumerate(self.embeddings):
            ids_t = nd.slice_axis(ids, 1, t, t + 1).reshape(
                (b, self.bag_size))
            feats.append(bag(ids_t))                      # (B, D)
        f = nd.stack(*feats, axis=1)                      # (B, T+1, D)
        z = nd.batch_dot(f, f, transpose_b=True)          # (B, T+1, T+1)
        n = self.num_tables + 1
        inter = nd.take(z.reshape((b, n * n)), nd.array(self._tri), axis=1)
        return self.top(nd.concat(bottom, inter, dim=1))  # (B, 1)


def dlrm_loss(logits, labels):
    """Per-sample sigmoid BCE of (B, 1) click logits vs (B,) labels —
    gluon loss convention; call .mean() for the scalar."""
    return SigmoidBinaryCrossEntropyLoss()(logits, labels.reshape(
        (labels.shape[0], 1)))


def dlrm_flops_per_sample(net: DLRM) -> float:
    """fwd+bwd MLP + interaction FLOPs per sample (3x fwd); the table
    gathers are excluded — they are bytes, not FLOPs (the roofline for
    this family is memory/comms-bound by design)."""
    d = net.embed_dim
    fwd = 0.0
    prev = net.dense_dim
    for layer in net.bottom._children.values():
        u = layer._units
        fwd += 2.0 * prev * u
        prev = u
    t1 = net.num_tables + 1
    fwd += 2.0 * t1 * t1 * d                      # pairwise gram
    prev = d + (t1 * (t1 - 1)) // 2
    for layer in net.top._children.values():
        u = layer._units
        fwd += 2.0 * prev * u
        prev = u
    return 3.0 * fwd


def dlrm_bytes_per_sample(net: DLRM, dedup_rate: float = 0.0) -> float:
    """Table bytes one sample moves: gather + backward scatter of
    ``bag*T`` rows, discounted by the measured dedup rate."""
    rows = net.num_tables * net.bag_size * (1.0 - dedup_rate)
    return 2.0 * rows * net.embed_dim * 4.0


def dlrm_small(**kwargs) -> DLRM:
    """The bench/default config: 8 tables x 512 rows x 32 dims, 4-hot
    bags, 13 dense features (a scaled-down Criteo shape)."""
    cfg = dict(num_tables=8, vocab_size=512, embed_dim=32, dense_dim=13,
               bag_size=4, bottom_units=(64,), top_units=(128, 64))
    cfg.update(kwargs)
    return DLRM(**cfg)
