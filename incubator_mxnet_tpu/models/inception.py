"""Inception-V3 (parity: python/mxnet/gluon/model_zoo/vision/inception.py)."""
from __future__ import annotations

from ..gluon import nn
from ..gluon.block import HybridBlock
from .common import bn_axis as _bn_axis

__all__ = ["Inception3", "inception_v3"]


def _conv(channels, kernel, stride=1, pad=0, layout="NHWC"):
    out = nn.HybridSequential()
    out.add(nn.Conv2D(channels, kernel, strides=stride, padding=pad,
                      use_bias=False, layout=layout))
    out.add(nn.BatchNorm(axis=_bn_axis(layout), epsilon=0.001))
    out.add(nn.Activation("relu"))
    return out


def _Branches(branches, layout):
    """Run branches on one input, concat along channels (nn.Concatenate)."""
    out = nn.Concatenate(axis=_bn_axis(layout))
    out.add(*branches)
    return out


def _seq(*blocks):
    s = nn.HybridSequential()
    for b in blocks:
        s.add(b)
    return s


def _make_A(pool_features, layout):
    return _Branches([
        _conv(64, 1, layout=layout),
        _seq(_conv(48, 1, layout=layout), _conv(64, 5, pad=2, layout=layout)),
        _seq(_conv(64, 1, layout=layout), _conv(96, 3, pad=1, layout=layout),
             _conv(96, 3, pad=1, layout=layout)),
        _seq(nn.AvgPool2D(3, 1, 1, layout=layout),
             _conv(pool_features, 1, layout=layout)),
    ], layout)


def _make_B(layout):
    return _Branches([
        _conv(384, 3, stride=2, layout=layout),
        _seq(_conv(64, 1, layout=layout), _conv(96, 3, pad=1, layout=layout),
             _conv(96, 3, stride=2, layout=layout)),
        nn.MaxPool2D(3, 2, layout=layout),
    ], layout)


def _make_C(channels_7x7, layout):
    c = channels_7x7
    return _Branches([
        _conv(192, 1, layout=layout),
        _seq(_conv(c, 1, layout=layout),
             _conv(c, (1, 7), pad=(0, 3), layout=layout),
             _conv(192, (7, 1), pad=(3, 0), layout=layout)),
        _seq(_conv(c, 1, layout=layout),
             _conv(c, (7, 1), pad=(3, 0), layout=layout),
             _conv(c, (1, 7), pad=(0, 3), layout=layout),
             _conv(c, (7, 1), pad=(3, 0), layout=layout),
             _conv(192, (1, 7), pad=(0, 3), layout=layout)),
        _seq(nn.AvgPool2D(3, 1, 1, layout=layout),
             _conv(192, 1, layout=layout)),
    ], layout)


def _make_D(layout):
    return _Branches([
        _seq(_conv(192, 1, layout=layout),
             _conv(320, 3, stride=2, layout=layout)),
        _seq(_conv(192, 1, layout=layout),
             _conv(192, (1, 7), pad=(0, 3), layout=layout),
             _conv(192, (7, 1), pad=(3, 0), layout=layout),
             _conv(192, 3, stride=2, layout=layout)),
        nn.MaxPool2D(3, 2, layout=layout),
    ], layout)


def _make_E(layout):
    return _Branches([
        _conv(320, 1, layout=layout),
        _seq(_conv(384, 1, layout=layout),
             _Branches([_conv(384, (1, 3), pad=(0, 1), layout=layout),
                        _conv(384, (3, 1), pad=(1, 0), layout=layout)],
                       layout)),
        _seq(_conv(448, 1, layout=layout),
             _conv(384, 3, pad=1, layout=layout),
             _Branches([_conv(384, (1, 3), pad=(0, 1), layout=layout),
                        _conv(384, (3, 1), pad=(1, 0), layout=layout)],
                       layout)),
        _seq(nn.AvgPool2D(3, 1, 1, layout=layout),
             _conv(192, 1, layout=layout)),
    ], layout)


class Inception3(HybridBlock):
    def __init__(self, classes=1000, layout="NHWC", **kwargs):
        super().__init__(**kwargs)
        self.features = nn.HybridSequential()
        self.features.add(_conv(32, 3, stride=2, layout=layout))
        self.features.add(_conv(32, 3, layout=layout))
        self.features.add(_conv(64, 3, pad=1, layout=layout))
        self.features.add(nn.MaxPool2D(3, 2, layout=layout))
        self.features.add(_conv(80, 1, layout=layout))
        self.features.add(_conv(192, 3, layout=layout))
        self.features.add(nn.MaxPool2D(3, 2, layout=layout))
        self.features.add(_make_A(32, layout))
        self.features.add(_make_A(64, layout))
        self.features.add(_make_A(64, layout))
        self.features.add(_make_B(layout))
        self.features.add(_make_C(128, layout))
        self.features.add(_make_C(160, layout))
        self.features.add(_make_C(160, layout))
        self.features.add(_make_C(192, layout))
        self.features.add(_make_D(layout))
        self.features.add(_make_E(layout))
        self.features.add(_make_E(layout))
        self.features.add(nn.AvgPool2D(8, layout=layout))
        self.features.add(nn.Dropout(0.5))
        self.features.add(nn.Flatten())
        self.output = nn.Dense(classes)

    def forward(self, x):
        return self.output(self.features(x))


def inception_v3(classes=1000, layout="NHWC", **kwargs):
    return Inception3(classes=classes, layout=layout, **kwargs)
