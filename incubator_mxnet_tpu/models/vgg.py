"""VGG 11/13/16/19 (+BN) (parity: python/mxnet/gluon/model_zoo/vision/vgg.py)."""
from __future__ import annotations

from ..gluon import nn
from ..gluon.block import HybridBlock
from .common import bn_axis

__all__ = ["VGG", "vgg11", "vgg13", "vgg16", "vgg19",
           "vgg11_bn", "vgg13_bn", "vgg16_bn", "vgg19_bn", "get_vgg"]

_SPEC = {11: ([1, 1, 2, 2, 2], [64, 128, 256, 512, 512]),
         13: ([2, 2, 2, 2, 2], [64, 128, 256, 512, 512]),
         16: ([2, 2, 3, 3, 3], [64, 128, 256, 512, 512]),
         19: ([2, 2, 4, 4, 4], [64, 128, 256, 512, 512])}


class VGG(HybridBlock):
    def __init__(self, layers, filters, classes=1000, batch_norm=False,
                 layout="NHWC", **kwargs):
        super().__init__(**kwargs)
        self.features = nn.HybridSequential()
        for num, ch in zip(layers, filters):
            for _ in range(num):
                self.features.add(nn.Conv2D(ch, 3, padding=1, layout=layout))
                if batch_norm:
                    self.features.add(nn.BatchNorm(axis=bn_axis(layout)))
                self.features.add(nn.Activation("relu"))
            self.features.add(nn.MaxPool2D(2, 2, layout=layout))
        self.features.add(nn.Flatten())
        self.features.add(nn.Dense(4096, activation="relu"))
        self.features.add(nn.Dropout(0.5))
        self.features.add(nn.Dense(4096, activation="relu"))
        self.features.add(nn.Dropout(0.5))
        self.output = nn.Dense(classes)

    def forward(self, x):
        return self.output(self.features(x))


def get_vgg(num_layers, classes=1000, batch_norm=False, layout="NHWC",
            **kwargs):
    layers, filters = _SPEC[num_layers]
    return VGG(layers, filters, classes=classes, batch_norm=batch_norm,
               layout=layout, **kwargs)


def _make(n, bn):
    def f(classes=1000, layout="NHWC", **kwargs):
        return get_vgg(n, classes=classes, batch_norm=bn, layout=layout,
                       **kwargs)
    f.__name__ = f"vgg{n}_bn" if bn else f"vgg{n}"
    return f


vgg11, vgg13, vgg16, vgg19 = (_make(n, False) for n in (11, 13, 16, 19))
vgg11_bn, vgg13_bn, vgg16_bn, vgg19_bn = (_make(n, True)
                                          for n in (11, 13, 16, 19))
