"""Model zoo (parity: python/mxnet/gluon/model_zoo/vision + the reference's
example/ networks). `get_model("resnet50_v1")` mirrors mx model_zoo."""
from .lenet import LeNet, lenet
from .resnet import (get_resnet, resnet18_v1, resnet34_v1, resnet50_v1,
                     resnet101_v1, resnet152_v1, resnet18_v2, resnet34_v2,
                     resnet50_v2, resnet101_v2, resnet152_v2)
from .alexnet import AlexNet, alexnet
from .vgg import (VGG, get_vgg, vgg11, vgg13, vgg16, vgg19,
                  vgg11_bn, vgg13_bn, vgg16_bn, vgg19_bn)
from .mobilenet import (MobileNet, MobileNetV2, mobilenet1_0, mobilenet0_75,
                        mobilenet0_5, mobilenet0_25, mobilenet_v2_1_0,
                        mobilenet_v2_0_75, mobilenet_v2_0_5,
                        mobilenet_v2_0_25)
from .squeezenet import SqueezeNet, squeezenet1_0, squeezenet1_1
from .densenet import (DenseNet, densenet121, densenet161, densenet169,
                       densenet201)
from .inception import Inception3, inception_v3
from .bert import (BERTModel, BERTForPretrain, BERTPretrainLoss,
                   get_bert_model, bert_12_768_12, bert_24_1024_16)
from .ssd import (SSD, SSDLoss, ssd_512_resnet18_v1, ssd_512_resnet50_v1,
                  ssd_300_resnet18_v1)
from .transformer_lm import (TransformerLM, lm_loss, transformer_lm_small,
                             transformer_lm_base)
from .dlrm import DLRM, dlrm_loss, dlrm_small

_MODELS = {}
for _name in ["resnet18_v1", "resnet34_v1", "resnet50_v1", "resnet101_v1",
              "resnet152_v1", "resnet18_v2", "resnet34_v2", "resnet50_v2",
              "resnet101_v2", "resnet152_v2", "lenet",
              "alexnet",
              "vgg11", "vgg13", "vgg16", "vgg19",
              "vgg11_bn", "vgg13_bn", "vgg16_bn", "vgg19_bn",
              "mobilenet1_0", "mobilenet0_75", "mobilenet0_5",
              "mobilenet0_25", "mobilenet_v2_1_0", "mobilenet_v2_0_75",
              "mobilenet_v2_0_5", "mobilenet_v2_0_25",
              "squeezenet1_0", "squeezenet1_1",
              "densenet121", "densenet161", "densenet169", "densenet201",
              "inception_v3",
              "bert_12_768_12", "bert_24_1024_16",
              "ssd_512_resnet18_v1", "ssd_512_resnet50_v1",
              "ssd_300_resnet18_v1",
              "transformer_lm_small", "transformer_lm_base",
              "dlrm_small"]:
    _MODELS[_name] = globals()[_name]


def get_model(name, **kwargs):
    name = name.lower()
    if name not in _MODELS:
        raise ValueError(f"unknown model {name!r}; available: {sorted(_MODELS)}")
    return _MODELS[name](**kwargs)


def register_model(name, fn):
    _MODELS[name.lower()] = fn
