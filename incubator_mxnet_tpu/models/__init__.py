"""Model zoo (parity: python/mxnet/gluon/model_zoo/vision + the reference's
example/ networks). `get_model("resnet50_v1")` mirrors mx model_zoo."""
from . import lenet as _lenet_mod
from . import resnet as _resnet_mod
from .lenet import LeNet, lenet
from .resnet import (get_resnet, resnet18_v1, resnet34_v1, resnet50_v1,
                     resnet101_v1, resnet152_v1, resnet18_v2, resnet34_v2,
                     resnet50_v2, resnet101_v2, resnet152_v2)
from .bert import (BERTModel, BERTForPretrain, BERTPretrainLoss,
                   get_bert_model, bert_12_768_12, bert_24_1024_16)
from .ssd import (SSD, SSDLoss, ssd_512_resnet18_v1, ssd_512_resnet50_v1,
                  ssd_300_resnet18_v1)

_MODELS = {}
for _name in ["resnet18_v1", "resnet34_v1", "resnet50_v1", "resnet101_v1",
              "resnet152_v1", "resnet18_v2", "resnet34_v2", "resnet50_v2",
              "resnet101_v2", "resnet152_v2", "lenet",
              "bert_12_768_12", "bert_24_1024_16",
              "ssd_512_resnet18_v1", "ssd_512_resnet50_v1",
              "ssd_300_resnet18_v1"]:
    _MODELS[_name] = globals()[_name]


def get_model(name, **kwargs):
    name = name.lower()
    if name not in _MODELS:
        raise ValueError(f"unknown model {name!r}; available: {sorted(_MODELS)}")
    return _MODELS[name](**kwargs)


def register_model(name, fn):
    _MODELS[name.lower()] = fn
