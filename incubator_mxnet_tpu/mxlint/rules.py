"""mxtpu.mxlint.rules — the framework-invariant rule set.

Each rule encodes an invariant a PR 6–13 review-hardening pass paid to
re-learn by hand (docs/mxlint.md cites the motivating PR per rule):

=============================  =========================================
rule id                        invariant
=============================  =========================================
``raw-env-read``               every MXTPU_*/BENCH_* knob read inside
                               the package routes through
                               ``autotune/knobs.py`` resolution (or the
                               documented allowlist below)
``unregistered-counter``       a metric in a governed family
                               (``mxlint/families.py``) must be
                               registered there before a producer may
                               emit it
``raise-in-never-raise``       modules documented never-raise
                               (commscope/devicescope ingest parsers)
                               may not leak an uncaught ``raise``
``unnormalized-device-kind``   device-kind strings are compared only
                               through ``normalize_device_kind`` (or an
                               explicit ``.lower()`` pipeline)
``thread-shared-mutation``     module-global rebinding inside the
                               threaded subsystems happens under a lock
``duplicated-default-table``   a literal default table must have ONE
                               home — a structurally equal copy in a
                               second module WILL drift
=============================  =========================================
"""
from __future__ import annotations

import ast

from . import families
from .engine import Rule

__all__ = ["RULES", "default_rules", "rule_by_id", "RAW_ENV_ALLOWLIST",
           "NEVER_RAISE_MODULES", "THREADED_MODULES",
           "RawEnvReadRule", "UnregisteredCounterRule",
           "RaiseInNeverRaiseRule", "UnnormalizedDeviceKindRule",
           "ThreadSharedMutationRule", "DuplicatedDefaultTableRule"]


# ---------------------------------------------------------------------------
# raw-env-read
# ---------------------------------------------------------------------------

# The documented allowlist: env name -> {reason, files}. ``files`` (path
# suffixes) pins WHERE the raw read is legal; None = anywhere in the
# package. Every entry needs a reason a reviewer can audit — that IS the
# policy (docs/mxlint.md).
RAW_ENV_ALLOWLIST = {
    "MXTPU_HEALTHMON": {
        "reason": "import-time arming knob, read once from "
                  "enable_from_env before the knob home is guaranteed "
                  "importable",
        "files": ("healthmon/__init__.py",)},
    "MXTPU_DIAG": {
        "reason": "import-time arming knob (diagnostics enable_from_env)",
        "files": ("diagnostics/__init__.py",)},
    "MXTPU_PERFSCOPE": {
        "reason": "import-time arming knob (perfscope enable_from_env; "
                  "carries the non-boolean 'jit0' spelling)",
        "files": ("perfscope/__init__.py",)},
    "MXTPU_COMMSCOPE": {
        "reason": "import-time arming knob (commscope enable_from_env)",
        "files": ("commscope/__init__.py",)},
    "MXTPU_DEVICESCOPE": {
        "reason": "import-time arming knob (devicescope enable_from_env)",
        "files": ("devicescope/__init__.py",)},
    "MXTPU_SERVESCOPE": {
        "reason": "import-time arming knob (servescope enable_from_env)",
        "files": ("servescope/__init__.py",)},
    "MXTPU_MEMSCOPE": {
        "reason": "import-time arming knob (memscope enable_from_env)",
        "files": ("memscope/__init__.py",)},
    "MXTPU_STRICT": {
        "reason": "import-time arming knob (mxlint.runtime "
                  "enable_from_env)",
        "files": ("mxlint/runtime.py",)},
    "MXTPU_AUTO_BULK": {
        "reason": "module-import-time read in the dispatch core, before "
                  "package init finishes — resolving through the knob "
                  "home mid-init would be an import-order bet",
        "files": ("bulk.py",)},
    "MXTPU_PROCESS_ID": {
        "reason": "crash/signal-dump path (flight recorder env snapshot) "
                  "— must stay import-free and never-raise",
        "files": ("diagnostics/flight.py",)},
    "MXTPU_DIAG_DIR": {
        "reason": "crash/signal-dump path (flight recorder dump dir) — "
                  "must stay import-free and never-raise",
        "files": ("diagnostics/flight.py",)},
}

_ENV_PREFIXES = ("MXTPU_", "BENCH_")

# the resolution home itself, plus this package (the rule engine and
# allowlist tables spell knob names as data)
_ENV_EXEMPT_SUFFIXES = ("autotune/knobs.py", "mxlint/rules.py",
                        "mxlint/engine.py", "mxlint/families.py")


def _path_matches(relpath: str, suffixes) -> bool:
    """Component-anchored suffix match: 'healthmon/__init__.py' matches
    .../healthmon/__init__.py but NOT .../myhealthmon/__init__.py — an
    unanchored endswith would let a suffix-colliding module escape the
    rule it is named in."""
    anchored = "/" + relpath
    return any(anchored.endswith("/" + s) for s in suffixes)


def _is_environ(node) -> bool:
    """``os.environ`` / bare ``environ`` reference."""
    if isinstance(node, ast.Attribute) and node.attr == "environ":
        return True
    return isinstance(node, ast.Name) and node.id == "environ"


def _is_getenv(func) -> bool:
    """``os.getenv`` / bare ``getenv`` reference."""
    if isinstance(func, ast.Attribute) and func.attr == "getenv":
        return True
    return isinstance(func, ast.Name) and func.id == "getenv"


class RawEnvReadRule(Rule):
    id = "raw-env-read"
    hint = ("resolve through autotune/knobs.py (KnobConfig/resolve for "
            "search-space knobs; knobs.env_str/env_int/env_float/"
            "env_flag for everything else), or add the knob to "
            "mxlint.rules.RAW_ENV_ALLOWLIST with a reason")

    def applies(self, relpath: str) -> bool:
        # the package only: bench.py and tools/ are the BENCH_* driver
        # layer — their own spelling by the documented precedence
        if "/incubator_mxnet_tpu/" not in f"/{relpath}":
            return False
        return not _path_matches(relpath, _ENV_EXEMPT_SUFFIXES)

    def _name_findings(self, ctx, node, name_node):
        if isinstance(name_node, ast.Constant) \
                and isinstance(name_node.value, str):
            name = name_node.value
            if not name.startswith(_ENV_PREFIXES):
                return []
            entry = RAW_ENV_ALLOWLIST.get(name)
            if entry is not None and (
                    entry["files"] is None
                    or _path_matches(ctx.relpath, entry["files"])):
                return []
            return [self.finding(
                ctx, node,
                f"raw environment read of knob {name!r} bypasses the "
                f"documented resolution order (call-site > BENCH_* > "
                f"MXTPU_* > cached winner > default)")]
        # dynamic name: local env helpers are exactly how the knob
        # spellings historically drifted — they must live in knobs.py
        return [self.finding(
            ctx, node,
            f"environment read with a dynamic name "
            f"({ctx.segment(name_node) or '<expr>'!s}) — local env "
            f"helpers are how knob spellings drift",
            hint="call the knobs.env_* accessors instead of wrapping "
                 "os.environ locally (allowlist the file if it truly "
                 "cannot import the knob home)")]

    def check(self, ctx):
        out = []
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Call):
                if _is_getenv(node.func) and node.args:
                    out += self._name_findings(ctx, node, node.args[0])
                elif isinstance(node.func, ast.Attribute) \
                        and node.func.attr in ("get", "setdefault", "pop") \
                        and _is_environ(node.func.value) and node.args:
                    out += self._name_findings(ctx, node, node.args[0])
            elif isinstance(node, ast.Subscript) \
                    and _is_environ(node.value) \
                    and isinstance(node.ctx, ast.Load):
                out += self._name_findings(ctx, node, node.slice)
            elif isinstance(node, ast.Compare) \
                    and any(isinstance(op, (ast.In, ast.NotIn))
                            for op in node.ops) \
                    and any(_is_environ(c) for c in node.comparators):
                out += self._name_findings(ctx, node, node.left)
        return out


# ---------------------------------------------------------------------------
# unregistered-counter
# ---------------------------------------------------------------------------

# registry entry points and where their (name, domain) arguments sit
_COUNTER_CALLS = {"counter": (0, 1), "histogram": (0, 1),
                  "observe": (0, 2), "set_gauge": (0, 2)}
# calls that REQUIRE the metric be histogram-kind in its family table
_HISTOGRAM_CALLS = {"histogram", "observe"}


class UnregisteredCounterRule(Rule):
    id = "unregistered-counter"
    hint = ("register the metric in mxlint/families.py (the ONE family "
            "home trace_check and mxlint both derive from), or fix the "
            "name/domain typo")

    def _call_name(self, func):
        if isinstance(func, ast.Name):
            return func.id.lstrip("_")
        if isinstance(func, ast.Attribute):
            return func.attr.lstrip("_")
        return None

    def _const_str(self, call, pos, kw):
        for k in call.keywords:
            if k.arg == kw:
                node = k.value
                break
        else:
            if pos >= len(call.args):
                return None, False
            node = call.args[pos]
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            return node.value, True
        return None, False       # dynamic: not statically resolvable

    def check(self, ctx):
        out = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            fname = self._call_name(node.func)
            if fname not in _COUNTER_CALLS:
                continue
            name_pos, dom_pos = _COUNTER_CALLS[fname]
            name, name_ok = self._const_str(node, name_pos, "name")
            domain, dom_ok = self._const_str(node, dom_pos, "domain")
            if not dom_ok or domain not in families.FAMILY_TABLES:
                continue          # ungoverned domain (or dynamic)
            if not name_ok:
                continue          # dynamic metric name: runtime's job
            full = f"{domain}/{name}"
            kind = families.metric_kind(full)
            if kind is None:
                out.append(self.finding(
                    ctx, node,
                    f"metric {full!r} is not registered in the "
                    f"{domain!r} family table"))
            elif fname in _HISTOGRAM_CALLS and kind != "histogram":
                out.append(self.finding(
                    ctx, node,
                    f"metric {full!r} is declared {kind!r} in its "
                    f"family table but emitted via {fname}() "
                    f"(histogram-kind)"))
            elif fname == "set_gauge" and kind != "gauge":
                out.append(self.finding(
                    ctx, node,
                    f"metric {full!r} is declared {kind!r} in its "
                    f"family table but written via set_gauge()"))
        return out


# ---------------------------------------------------------------------------
# raise-in-never-raise
# ---------------------------------------------------------------------------

# modules whose PUBLIC contract is never-raise (each docstring says so);
# a raise is legal only under a try whose handler catches Exception
NEVER_RAISE_MODULES = {
    "devicescope/ingest.py":
        "devicescope trace ingestion: 'Every entry point is never-raise "
        "by contract'",
    "commscope/hlo.py":
        "commscope HLO parser: unknown spellings bucket as 'other', "
        "never a raise",
}


def _handler_catches_all(handler) -> bool:
    if handler.type is None:
        return True
    names = []
    t = handler.type
    for n in (t.elts if isinstance(t, ast.Tuple) else [t]):
        if isinstance(n, ast.Name):
            names.append(n.id)
        elif isinstance(n, ast.Attribute):
            names.append(n.attr)
    return any(n in ("Exception", "BaseException") for n in names)


class RaiseInNeverRaiseRule(Rule):
    id = "raise-in-never-raise"
    hint = ("wrap the failing region in try/except Exception and degrade "
            "(count + return the empty shape), or move the raising "
            "helper out of the never-raise module")

    def applies(self, relpath: str) -> bool:
        return _path_matches(relpath, NEVER_RAISE_MODULES)

    def check(self, ctx):
        out = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Raise):
                continue
            guarded = False
            child = node
            for parent in ctx.parents(node):
                if isinstance(parent, ast.Try):
                    in_body = any(child is n or self._contains(n, child)
                                  for n in parent.body)
                    if in_body and any(_handler_catches_all(h)
                                       for h in parent.handlers):
                        guarded = True
                        break
                child = parent
            if not guarded:
                out.append(self.finding(
                    ctx, node,
                    "uncaught raise in a module documented never-raise"))
        return out

    @staticmethod
    def _contains(tree, node) -> bool:
        return any(n is node for n in ast.walk(tree))


# ---------------------------------------------------------------------------
# unnormalized-device-kind
# ---------------------------------------------------------------------------

# where the canonical spelling lives — comparisons inside it are the
# definition, not a violation
_DEVICE_KIND_HOME = ("autotune/cache.py",)


def _is_device_kind_ref(node) -> bool:
    """A RAW device-kind reference: a name / attribute / const-keyed
    subscript spelled *device_kind*, not wrapped in any normalizing
    call (a wrapped ref parses as a Call, so it never matches here)."""
    if isinstance(node, ast.Attribute):
        return "device_kind" in node.attr
    if isinstance(node, ast.Name):
        return "device_kind" in node.id
    if isinstance(node, ast.Subscript) \
            and isinstance(node.slice, ast.Constant) \
            and isinstance(node.slice.value, str):
        return "device_kind" in node.slice.value
    return False


def _is_stringy(node) -> bool:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return True
    if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
        return any(isinstance(e, ast.Constant)
                   and isinstance(e.value, str) for e in node.elts)
    return False


class UnnormalizedDeviceKindRule(Rule):
    id = "unnormalized-device-kind"
    hint = ("compare through autotune.cache.normalize_device_kind(...) "
            "— jax reports 'TPU v4' raw while perfscope/the tuning "
            "cache store lowercase, so a raw == is a silent never-match")

    def applies(self, relpath: str) -> bool:
        return not _path_matches(relpath, _DEVICE_KIND_HOME)

    def check(self, ctx):
        out = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Compare):
                continue
            sides = [node.left] + list(node.comparators)
            raw = [s for s in sides if _is_device_kind_ref(s)]
            lit = [s for s in sides if _is_stringy(s)]
            if raw and lit:
                out.append(self.finding(
                    ctx, node,
                    f"device-kind string compared against a literal "
                    f"without normalize_device_kind "
                    f"({ctx.segment(node)[:60]!r})"))
        return out


# ---------------------------------------------------------------------------
# thread-shared-mutation
# ---------------------------------------------------------------------------

# the subsystems where a worker thread and the training/serving loop
# share module state (each runs at least one daemon thread)
THREADED_MODULES = (
    "serving/batcher.py",
    "fleet/continuous.py",
    "fleet/router.py",
    "fleet/cache.py",
    "io/prefetch.py",
    "io/pipeline.py",
    "resilience/checkpoint.py",
    "resilience/elastic.py",
    "resilience/policy.py",
    "healthmon/__init__.py",
    "healthmon/watchdog.py",
    "kvstore/async_ps.py",
    "diagnostics/__init__.py",
)


class ThreadSharedMutationRule(Rule):
    id = "thread-shared-mutation"
    hint = ("take the module lock around the write (with _lock: ...), "
            "or suppress with a reason proving single-threadedness "
            "(e.g. 'written before the worker thread starts')")

    def applies(self, relpath: str) -> bool:
        return _path_matches(relpath, THREADED_MODULES)

    def check(self, ctx):
        out = []
        for fn in ast.walk(ctx.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            declared = set()
            for node in ast.walk(fn):
                if isinstance(node, ast.Global):
                    declared.update(node.names)
            if not declared:
                continue
            for node in ast.walk(fn):
                if isinstance(node, (ast.Assign, ast.AugAssign)):
                    targets = (node.targets
                               if isinstance(node, ast.Assign)
                               else [node.target])
                    flat = []
                    for t in targets:
                        flat.extend(t.elts if isinstance(
                            t, (ast.Tuple, ast.List)) else [t])
                    hit = [t.id for t in flat
                           if isinstance(t, ast.Name) and t.id in declared]
                    if hit and not self._under_lock(ctx, node):
                        out.append(self.finding(
                            ctx, node,
                            f"module-global {hit[0]!r} rebound outside a "
                            f"lock in a threaded module (function "
                            f"{fn.name!r})"))
        return out

    def _under_lock(self, ctx, node) -> bool:
        for parent in ctx.parents(node):
            if isinstance(parent, ast.With):
                for item in parent.items:
                    if "lock" in ctx.segment(
                            item.context_expr).lower():
                        return True
        return False


# ---------------------------------------------------------------------------
# duplicated-default-table
# ---------------------------------------------------------------------------

class DuplicatedDefaultTableRule(Rule):
    id = "duplicated-default-table"
    hint = ("keep ONE home for the table and import it (the PR 13 "
            "perf_sweep/bench DEFAULT_BATCH drift is the cautionary "
            "tale); if the copies are genuinely independent, suppress "
            "with a reason")

    MIN_ENTRIES = 4

    def __init__(self):
        self._seen: dict = {}     # shape key -> [(relpath, path, line, name)]

    def _literal_key(self, node):
        """A hashable structural key for a constant-enough dict literal,
        or None when the dict holds computed parts."""
        try:
            items = []
            for k, v in zip(node.keys, node.values):
                if not (isinstance(k, ast.Constant)
                        and isinstance(k.value, (str, int, float))):
                    return None
                items.append((repr(k.value), ast.dump(v)))
            # constant values only — a dict of lambdas/calls is wiring,
            # not a default table
            for v in node.values:
                for sub in ast.walk(v):
                    if isinstance(sub, (ast.Call, ast.Lambda, ast.Name)):
                        return None
            return tuple(sorted(items))
        except Exception:  # noqa: BLE001 — best-effort structural match
            return None

    def check(self, ctx):
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Assign) \
                    or not isinstance(node.value, ast.Dict) \
                    or len(node.value.keys) < self.MIN_ENTRIES:
                continue
            # module-level assignments only (a table built inside a
            # function is scratch state)
            parent = getattr(node, "_mxlint_parent", None)
            if not isinstance(parent, ast.Module):
                continue
            key = self._literal_key(node.value)
            if key is None:
                continue
            # this rule reports from finish(), after the engine's
            # per-file suppression filter already ran — honor the
            # directive at collection time instead
            if ctx.suppressed(self.id, node.lineno):
                continue
            name = (node.targets[0].id
                    if node.targets
                    and isinstance(node.targets[0], ast.Name) else "?")
            self._seen.setdefault(key, []).append(
                (ctx.relpath, ctx.path, node.lineno, name))
        return []

    def finish(self):
        from .engine import Finding
        out = []
        for key, sites in self._seen.items():
            files = {s[0] for s in sites}
            if len(files) < 2:
                continue
            # canonical home: prefer the package copy, then first path
            sites = sorted(sites, key=lambda s: (
                "incubator_mxnet_tpu/" not in f"/{s[0]}", s[0]))
            canon = sites[0]
            for rel, path, line, name in sites[1:]:
                out.append(Finding(
                    self.id, path, line, 0,
                    f"default table {name!r} is a structural duplicate "
                    f"of {canon[3]!r} in {canon[0]} — two homes WILL "
                    f"drift",
                    self.hint))
        self._seen.clear()
        return out


def default_rules() -> list:
    """Fresh rule instances (the duplicate-table rule is stateful)."""
    return [RawEnvReadRule(), UnregisteredCounterRule(),
            RaiseInNeverRaiseRule(), UnnormalizedDeviceKindRule(),
            ThreadSharedMutationRule(), DuplicatedDefaultTableRule()]


RULES = tuple(r.id for r in default_rules())


def rule_by_id(rule_id: str) -> Rule:
    for r in default_rules():
        if r.id == rule_id:
            return r
    raise KeyError(f"unknown mxlint rule {rule_id!r}; known: {RULES}")
