"""mxtpu.mxlint.runtime — the strict-mode jit-program auditor.

The static half of mxlint proves properties of the SOURCE; this module
audits what the process actually DOES. Armed (``MXTPU_STRICT=1``, or
``enable()`` — bench.py and the smokes arm it), three detectors watch
the steady loop:

* **host-sync detection** — :meth:`StrictAuditor.guarded` wraps each
  steady-loop dispatch in ``jax.transfer_guard_device_to_host
  ("disallow")`` AND a framework-level sync sentinel (the NDArray
  materialization entry points — ``asnumpy``/``asscalar``/
  ``__array__``/``wait_to_read`` — report into the auditor while a
  guarded dispatch is on this thread's stack). Two channels because the
  CPU backend's zero-copy arrays never trip jax's transfer guard, and
  tier-1 must be able to prove the detector fires; on a real
  accelerator both channels watch (on CPU the jax guard is additionally
  DISARMED outright — see ``_JAX_GUARD_OK``: this jaxlib's disallow
  guard destabilizes concurrent ``device_put``). A trip counts
  ``mxlint.transfer_guard_trips`` + flight breadcrumb + structured
  event. On CPU the sentinel counts WITHOUT perturbing the dispatch —
  the run completes; on an accelerator a jax-guard trip aborts the
  dispatch mid-flight (the XLA execution already ran and may have
  donated its inputs — no side-effect-safe re-run exists), so strict
  mode re-raises it as a counted, loud failure.
* **recompile-storm detection** — perfscope's ``record_program`` pushes
  every compile capture here (one predicate when off). After
  :meth:`mark_warmup_done`, a capture for an already-seen program name
  is a steady-state recompile: counted ``mxlint.recompiles`` and NAMED
  (the offender list lands in ``extra.mxlint.recompiled_programs``).
* **donation-violation detection** — a read of an already-donated
  (deleted) buffer inside a guarded dispatch raises jax's
  "Array has been deleted"; the auditor counts it
  (``mxlint.donation_violations``) before re-raising — unlike a host
  sync, a deleted-buffer read has no safe re-run.

Off-path cost: one ``_AUD is None`` predicate per hook (the healthmon/
devicescope module-global discipline), pinned by the overhead test.

``extra.mxlint`` (validated by trace_check's ``check_mxlint_extra``)::

    {"strict": true, "findings": 0, "transfer_guard_trips": 0,
     "allowed_syncs": 0, "recompiles": 0, "recompiled_programs": [],
     "donation_violations": 0, "guarded_dispatches": 200}

or the disabled shape ``{"strict": false}``.
"""
from __future__ import annotations

import contextlib
import threading

from ..diagnostics import flight as _flight
from ..profiler.counters import counter as _counter, set_gauge as _gauge
from .families import FAMILY_TABLES

__all__ = ["StrictAuditor", "enable", "disable", "enabled",
           "enable_from_env", "auditor", "guarded", "allowed_sync",
           "mark_warmup_done", "bench_extra", "settle", "MXLINT_FAMILIES"]

MXLINT_FAMILIES = FAMILY_TABLES["mxlint"]

# module global: None = strict mode off (THE fast-path predicate)
_AUD = None


def _classify_error(e: BaseException) -> str:
    msg = str(e).lower()
    if "deleted" in msg or "donated" in msg:
        return "donation"
    if "transfer" in msg and ("disallow" in msg or "guard" in msg):
        return "transfer"
    return "other"


# None = undetermined; the jax disallow-guard is armed only on real
# accelerators. On XLA:CPU it is BOTH useless (zero-copy arrays never
# trip it — measured) and dangerous: entering ONE empty, properly
# exited `transfer_guard_device_to_host("disallow")` scope destabilizes
# the CPU client's concurrent device_put (probed on this jaxlib: ~40%
# segfault rate in the prefetcher worker under the resilience suite
# afterwards, 0% without; the "allow" level is clean). The NDArray
# sentinel is the CPU detection channel.
_JAX_GUARD_OK = None


def _jax_guard_usable() -> bool:
    global _JAX_GUARD_OK
    if _JAX_GUARD_OK is None:
        try:
            import jax
            jax.transfer_guard_device_to_host  # noqa: B018 — probe
            _JAX_GUARD_OK = jax.default_backend() != "cpu"
        except Exception:  # noqa: BLE001 — no backend / old jax
            _JAX_GUARD_OK = False
    return _JAX_GUARD_OK


@contextlib.contextmanager
def _d2h_guard(level: str):
    """jax's device-to-host transfer guard on real accelerators; a
    no-op on CPU / without a backend (see _JAX_GUARD_OK above — the
    auditor's NDArray sentinel still watches everywhere)."""
    if not _jax_guard_usable():
        yield
        return
    import jax
    with jax.transfer_guard_device_to_host(level):
        yield


class StrictAuditor:
    """Per-process strict-mode state. Constructed via :func:`enable`."""

    def __init__(self):
        self._c_dispatches = _counter("mxlint.guarded_dispatches",
                                      "mxlint")
        self._c_trips = _counter("mxlint.transfer_guard_trips", "mxlint")
        self._c_allowed = _counter("mxlint.allowed_syncs", "mxlint")
        self._c_recompiles = _counter("mxlint.recompiles", "mxlint")
        self._c_donations = _counter("mxlint.donation_violations",
                                     "mxlint")
        self._lock = threading.Lock()
        self._seen_programs: set = set()
        self._recompiled: dict = {}       # name -> count after warmup
        self._warmed = False
        # guarded-dispatch depth per thread: the sync sentinel only
        # counts syncs that happen INSIDE a guarded dispatch on the
        # same thread (the end-of-loop loss fetch is outside, legit)
        self._local = threading.local()

    # -- per-dispatch guard ----------------------------------------------
    def guarded(self, thunk):
        """Run one steady-loop dispatch under the host-sync guard."""
        self._c_dispatches.increment()
        st = self._local
        st.depth = getattr(st, "depth", 0) + 1
        st.noted = False
        try:
            try:
                with _d2h_guard("disallow"):
                    return thunk()
            except Exception as e:  # noqa: BLE001 — classified below
                kind = _classify_error(e)
                if kind == "donation":
                    self._record("donation_violation", repr(e)[:200])
                    raise
                if kind == "transfer":
                    # the NDArray sentinel may have already counted this
                    # very sync before jax raised — one trip, not two
                    if not st.noted:
                        self._record("host_sync", repr(e)[:200])
                    # by the time the guard raised, the XLA dispatch
                    # already executed (and may have donated its
                    # inputs): re-running would double-apply the
                    # update or read deleted buffers. On an
                    # accelerator a guarded host sync is therefore a
                    # COUNTED, LOUD failure; the CPU path (jax guard
                    # disarmed, sentinel counts without raising) is
                    # the one that detects-and-continues.
                    raise
                raise
        finally:
            st.depth -= 1

    def note_sync(self, what: str):
        """NDArray materialization sentinel (pushed into the ndarray
        module by :func:`enable`). Counts only inside a guarded
        dispatch on this thread, and only when not explicitly
        allowed."""
        st = self._local
        if getattr(st, "depth", 0) <= 0 or getattr(st, "allowed", 0) > 0:
            return
        st.noted = True
        self._record("host_sync", what)

    @contextlib.contextmanager
    def allowed_sync(self, reason: str):
        """Declare a deliberate host sync inside a guarded region (a
        debugging fetch, a boundary barrier): counted separately, never
        a trip."""
        self._c_allowed.increment()
        st = self._local
        st.allowed = getattr(st, "allowed", 0) + 1
        try:
            with _d2h_guard("allow"):
                yield
        finally:
            st.allowed -= 1

    # -- recompile detector ----------------------------------------------
    def mark_warmup_done(self):
        """Everything compiled so far was warmup; from here on, a
        re-capture of a known program is a steady-state recompile."""
        with self._lock:
            self._warmed = True

    def note_program(self, name: str, kind: str = "program"):
        """perfscope ``record_program`` hook (one predicate when strict
        is off)."""
        with self._lock:
            if self._warmed and name in self._seen_programs:
                self._recompiled[name] = self._recompiled.get(name, 0) + 1
                recompile = True
            else:
                self._seen_programs.add(name)
                recompile = False
        if recompile:
            self._record("recompile", name)

    # -- reporting --------------------------------------------------------
    def _record(self, what: str, detail: str):
        """One finding on all three surfaces at once (the healthmon
        discipline): counter + flight breadcrumb + structured event."""
        cmap = {"host_sync": self._c_trips,
                "recompile": self._c_recompiles,
                "donation_violation": self._c_donations}
        cmap[what].increment()
        if _flight._REC is not None:
            _flight.record("alert", f"mxlint.{what}", {"detail": detail})
        try:
            from .. import healthmon as _hm
            if _hm._HM is not None:
                _hm._HM.events.emit("alert", f"mxlint.{what}",
                                    args={"detail": detail})
        except Exception:  # noqa: BLE001 — reporting must never raise
            pass

    def findings(self) -> int:
        return (int(self._c_trips.value) + int(self._c_recompiles.value)
                + int(self._c_donations.value))

    def bench_extra(self) -> dict:
        with self._lock:
            recompiled = sorted(self._recompiled)
        return {
            "strict": True,
            "findings": self.findings(),
            "transfer_guard_trips": int(self._c_trips.value),
            "allowed_syncs": int(self._c_allowed.value),
            "recompiles": int(self._c_recompiles.value),
            "recompiled_programs": recompiled,
            "donation_violations": int(self._c_donations.value),
            "guarded_dispatches": int(self._c_dispatches.value),
        }


# ---------------------------------------------------------------------------
# module surface (the _AUD predicate discipline)
# ---------------------------------------------------------------------------

def enable() -> StrictAuditor:
    """Arm strict mode: install the NDArray sync sentinel and the
    perfscope recompile hook, publish ``mxlint.strict=1``."""
    global _AUD
    if _AUD is not None:
        return _AUD
    _AUD = StrictAuditor()
    from .. import ndarray as _nd
    from ..perfscope import cost as _cost
    _nd._STRICT_SYNC = _AUD.note_sync
    _cost._STRICT_HOOK = _AUD.note_program
    _gauge("mxlint.strict", 1, "mxlint")
    return _AUD


def disable():
    global _AUD
    if _AUD is None:
        return
    from .. import ndarray as _nd
    from ..perfscope import cost as _cost
    _nd._STRICT_SYNC = None
    _cost._STRICT_HOOK = None
    _AUD = None
    _gauge("mxlint.strict", 0, "mxlint")


def enabled() -> bool:
    return _AUD is not None


def enable_from_env():
    """MXTPU_STRICT=1 arms the auditor at import (like MXTPU_HEALTHMON;
    raw read allowlisted — this runs during package init, before the
    knob home is guaranteed importable)."""
    import os
    if os.environ.get("MXTPU_STRICT", "") == "1":
        enable()


def auditor():
    return _AUD


def guarded(thunk):
    """Run a dispatch under the strict guard, or plainly when off (the
    one-predicate off path)."""
    if _AUD is None:
        return thunk()
    return _AUD.guarded(thunk)


@contextlib.contextmanager
def allowed_sync(reason: str):
    if _AUD is None:
        yield
        return
    with _AUD.allowed_sync(reason):
        yield


def mark_warmup_done():
    if _AUD is not None:
        _AUD.mark_warmup_done()


def settle():
    """Publish end-of-run gauges (bench calls this before emitting)."""
    if _AUD is not None:
        _gauge("mxlint.findings", _AUD.findings(), "mxlint")


def bench_extra() -> dict:
    """The ``extra.mxlint`` payload, or the disabled shape."""
    if _AUD is None:
        return {"strict": False}
    settle()
    return _AUD.bench_extra()
