"""mxtpu.mxlint.families — THE one home of the counter-family tables.

Before this module the schema-stability contract lived in NINE
hand-maintained ``*_FAMILIES`` dicts inside ``tools/trace_check.py``,
while the producers (healthmon, perfscope, commscope, ...) spelled the
same names a second time at their ``counter()``/``set_gauge()`` call
sites — nothing but review discipline kept the two from drifting, and
every PR's review-hardening list paid for it.  Now there is ONE table
per family here, and every consumer derives from it:

* ``tools/trace_check.py`` builds its ``*_FAMILIES`` module globals by
  loading this file (by path — this module is pure stdlib data, so the
  validator stays importable without jax);
* mxlint's ``unregistered-counter`` rule checks every statically
  resolvable ``counter``/``set_gauge``/``observe``/``histogram`` call
  against these tables;
* ``tests/test_mxlint.py`` carries the drift test: the tables
  trace_check exports must BE these tables.

Adding a metric to a governed family is therefore one edit, here —
the validator and the linter cannot disagree with it.

IMPORTANT: this module must import NOTHING beyond the stdlib (and no
sibling modules): trace_check loads it standalone, before any backend
exists.
"""
from __future__ import annotations

__all__ = ["FAMILY_TABLES", "family_table", "family_domains",
           "known_metric", "metric_kind"]

# Every table maps "domain/metric.name" -> kind
# ("counter" | "gauge" | "histogram"), the exact shape trace_check's
# validators consume. Docs per family: docs/observability.md points at
# each subsystem's page.
FAMILY_TABLES = {
    # docs/observability.md — cross-rank training health (PR 5)
    "healthmon": {
        "healthmon/healthmon.steps": "counter",
        "healthmon/healthmon.exchanges": "counter",
        "healthmon/healthmon.nan_alerts": "counter",
        "healthmon/healthmon.stall_alerts": "counter",
        "healthmon/healthmon.step_time_regressions": "counter",
        "healthmon/healthmon.straggler_flags": "counter",
        "healthmon/healthmon.exchange_errors": "counter",
        "healthmon/healthmon.recovery_hook_errors": "counter",
        "healthmon/healthmon.collective_skew_ms": "gauge",
        "healthmon/healthmon.slowest_rank": "gauge",
        "healthmon/healthmon.step_ms_ewma": "gauge",
        "healthmon/healthmon.grad_global_norm": "gauge",
    },
    # docs/io.md — staged ingest pipeline (PR 6 prefetcher, PR 17
    # reader/decode-pool/transfer stages + sharded record reader)
    "io": {
        "io/io.batches_prefetched": "counter",
        "io/io.batches_skipped": "counter",
        "io/io.wait_ms": "counter",
        "io/io.put_ms": "counter",
        "io/io.read_ms": "counter",
        "io/io.decode_ms": "counter",
        "io/io.stage_ms": "counter",
        "io/io.records_read": "counter",
        "io/io.depth": "gauge",
        "io/io.buffer_fill": "gauge",
        "io/io.workers": "gauge",
        "io/io.shard_rank": "gauge",
        "io/io.shard_ranks": "gauge",
        "io/io.shard_records": "gauge",
    },
    # docs/trainloop.md — whole-loop executor (PR 6)
    "trainloop": {
        "trainloop/trainloop.chunks": "counter",
        "trainloop/trainloop.steps": "counter",
        "trainloop/trainloop.dispatch_ms": "counter",
        "trainloop/trainloop.k": "gauge",
        "trainloop/trainloop.chunk_ms": "gauge",
        "trainloop/trainloop.in_program_lr": "gauge",
    },
    # docs/sharding.md — mesh-native GSPMD layout (PR 8)
    "sharding": {
        "sharding/sharding.resolves": "counter",
        "sharding/sharding.fallback_replicated": "counter",
        "sharding/sharding.mesh_devices": "gauge",
        "sharding/sharding.mesh_dp": "gauge",
        "sharding/sharding.mesh_mp": "gauge",
        "sharding/sharding.params_total": "gauge",
        "sharding/sharding.params_model_sharded": "gauge",
        "sharding/sharding.params_data_sharded": "gauge",
        "sharding/sharding.params_replicated": "gauge",
        "sharding/sharding.fsdp": "gauge",
        "sharding/sharding.param_bytes_per_device": "gauge",
        "sharding/sharding.state_bytes_per_device": "gauge",
    },
    # docs/perfscope.md — roofline attribution (PR 7)
    "perfscope": {
        "perfscope/perfscope.programs_analyzed": "counter",
        "perfscope/perfscope.compute_bound": "counter",
        "perfscope/perfscope.hbm_bound": "counter",
        "perfscope/perfscope.trivial": "counter",
        "perfscope/perfscope.unknown": "counter",
        "perfscope/perfscope.step_ms": "gauge",
        "perfscope/perfscope.device_compute_ms": "gauge",
        "perfscope/perfscope.collective_ms": "gauge",
        "perfscope/perfscope.input_wait_ms": "gauge",
        "perfscope/perfscope.host_gap_ms": "gauge",
        "perfscope/perfscope.other_ms": "gauge",
        "perfscope/perfscope.mfu": "gauge",
        "perfscope/perfscope.device_step_ms": "histogram",
    },
    # docs/commscope.md — collective & resharding observability (PR 9)
    "commscope": {
        "commscope/commscope.programs_analyzed": "counter",
        "commscope/commscope.collectives": "counter",
        "commscope/commscope.payload_bytes": "counter",
        "commscope/commscope.resharding_collectives": "counter",
        "commscope/commscope.all_reduce": "counter",
        "commscope/commscope.all_gather": "counter",
        "commscope/commscope.reduce_scatter": "counter",
        "commscope/commscope.all_to_all": "counter",
        "commscope/commscope.collective_permute": "counter",
        "commscope/commscope.other": "counter",
        "commscope/commscope.step_collective_est_ms": "gauge",
        "commscope/commscope.step_collective_bytes": "gauge",
    },
    # docs/devicescope.md — measured device timeline (PR 10)
    "devicescope": {
        "devicescope/devicescope.windows": "counter",
        "devicescope/devicescope.steps_captured": "counter",
        "devicescope/devicescope.declined": "counter",
        "devicescope/devicescope.ingest_errors": "counter",
        "devicescope/devicescope.drift_warnings": "counter",
        "devicescope/devicescope.busy_fraction": "gauge",
        "devicescope/devicescope.device_busy_ms": "gauge",
        "devicescope/devicescope.collective_ms": "gauge",
        "devicescope/devicescope.idle_ms": "gauge",
    },
    # docs/servescope.md — request-lifecycle tracing (PR 11)
    "servescope": {
        "servescope/servescope.requests_traced": "counter",
        "servescope/servescope.rejections_traced": "counter",
        "servescope/servescope.sampled_out": "counter",
        "servescope/servescope.device_drift_warnings": "counter",
        "servescope/servescope.sample_every": "gauge",
        "servescope/servescope.e2e_ms": "histogram",
        "servescope/servescope.queue_wait_ms": "histogram",
        "servescope/servescope.coalesce_delay_ms": "histogram",
        "servescope/servescope.pad_overhead_ms": "histogram",
        "servescope/servescope.device_exec_ms": "histogram",
        "servescope/servescope.respond_ms": "histogram",
    },
    # docs/resilience.md — elastic self-healing training (PR 12)
    "resilience": {
        "resilience/resilience.checkpoints_saved": "counter",
        "resilience/resilience.checkpoints_pruned": "counter",
        "resilience/resilience.saves_skipped": "counter",
        "resilience/resilience.save_errors": "counter",
        "resilience/resilience.corrupt_checkpoints": "counter",
        "resilience/resilience.recoveries_total": "counter",
        "resilience/resilience.rollbacks": "counter",
        "resilience/resilience.resumes": "counter",
        "resilience/resilience.steps_lost_total": "counter",
        "resilience/resilience.retries_exhausted": "counter",
        "resilience/resilience.restarts_requested": "counter",
        "resilience/resilience.rank_departures": "counter",
        "resilience/resilience.rank_joins": "counter",
        "resilience/resilience.last_checkpoint_step": "gauge",
        "resilience/resilience.rollback_in_progress": "gauge",
        "resilience/resilience.steps_lost_last": "gauge",
        "resilience/resilience.copy_ms": "histogram",
        "resilience/resilience.save_ms": "histogram",
    },
    # docs/autotune.md — measurement-driven knob tuner (PR 13)
    "autotune": {
        "autotune/autotune.searches": "counter",
        "autotune/autotune.trials": "counter",
        "autotune/autotune.trials_pruned": "counter",
        "autotune/autotune.trials_failed": "counter",
        "autotune/autotune.cache_hits": "counter",
        "autotune/autotune.cache_misses": "counter",
        "autotune/autotune.cache_rejects": "counter",
        "autotune/autotune.env_conflicts": "counter",
        "autotune/autotune.best_busy_fraction": "gauge",
        "autotune/autotune.trials_last_search": "gauge",
    },
    # docs/memscope.md — memory footprints, watermarks, OOM forensics
    "memscope": {
        "memscope/memscope.programs_captured": "counter",
        "memscope/memscope.capture_unknown": "counter",
        "memscope/memscope.capture_errors": "counter",
        "memscope/memscope.samples": "counter",
        "memscope/memscope.samples_unavailable": "counter",
        "memscope/memscope.stats_unavailable": "counter",
        "memscope/memscope.oom_events": "counter",
        "memscope/memscope.drift_warnings": "counter",
        "memscope/memscope.infeasible_candidates": "counter",
        "memscope/memscope.bytes_in_use": "gauge",
        "memscope/memscope.peak_bytes_in_use": "gauge",
        "memscope/memscope.host_rss_bytes": "gauge",
        "memscope/memscope.bytes_p50": "gauge",
        "memscope/memscope.bytes_p95": "gauge",
        "memscope/memscope.headroom_fraction": "gauge",
    },
    # docs/serving.md — continuous batching + replica fleet (PR 16)
    "fleet": {
        "fleet/fleet.routed": "counter",
        "fleet/fleet.routed_errors": "counter",
        "fleet/fleet.retries": "counter",
        "fleet/fleet.no_replica_available": "counter",
        "fleet/fleet.health_polls": "counter",
        "fleet/fleet.health_poll_errors": "counter",
        "fleet/fleet.drains": "counter",
        "fleet/fleet.readmits": "counter",
        "fleet/fleet.swaps": "counter",
        "fleet/fleet.compile_cache_hits": "counter",
        "fleet/fleet.compile_cache_misses": "counter",
        "fleet/fleet.compile_cache_stores": "counter",
        "fleet/fleet.compile_cache_errors": "counter",
        "fleet/fleet.replicas": "gauge",
        "fleet/fleet.replicas_healthy": "gauge",
        "fleet/fleet.forward_ms": "histogram",
    },
    # docs/embedding.md — sharded tables, dedup lookup, row-sparse
    # updates (PR 19)
    "embedding": {
        "embedding/embedding.lookups": "counter",
        "embedding/embedding.dedup_lookups": "counter",
        "embedding/embedding.oor_ids": "counter",
        "embedding/embedding.sparse_updates": "counter",
        "embedding/embedding.sparse_rows_updated": "counter",
        "embedding/embedding.tables": "gauge",
        "embedding/embedding.table_bytes_logical": "gauge",
        "embedding/embedding.table_bytes_per_device": "gauge",
        "embedding/embedding.ids_per_step": "gauge",
        "embedding/embedding.rows_touched_per_step": "gauge",
        "embedding/embedding.dedup_rate": "gauge",
    },
    # docs/fleetscope.md — cross-process trace context + clock-aligned
    # telemetry collection (PR 20)
    "fleetscope": {
        "fleetscope/fleetscope.ctx_minted": "counter",
        "fleetscope/fleetscope.ctx_accepted": "counter",
        "fleetscope/fleetscope.ctx_malformed": "counter",
        "fleetscope/fleetscope.ctx_propagated": "counter",
        "fleetscope/fleetscope.pulls": "counter",
        "fleetscope/fleetscope.pull_errors": "counter",
        "fleetscope/fleetscope.telem_reports": "counter",
        "fleetscope/fleetscope.telem_errors": "counter",
        "fleetscope/fleetscope.processes": "gauge",
        "fleetscope/fleetscope.pull_ms": "histogram",
    },
    # docs/mxlint.md — static analyzer + strict-mode jit auditor (PR 14)
    "mxlint": {
        "mxlint/mxlint.strict": "gauge",
        "mxlint/mxlint.findings": "gauge",
        "mxlint/mxlint.guarded_dispatches": "counter",
        "mxlint/mxlint.transfer_guard_trips": "counter",
        "mxlint/mxlint.allowed_syncs": "counter",
        "mxlint/mxlint.recompiles": "counter",
        "mxlint/mxlint.donation_violations": "counter",
    },
}


def family_table(*domains) -> dict:
    """The merged ``{"domain/name": kind}`` table for one or more
    families (trace_check's IO_TRAINLOOP_FAMILIES merges two)."""
    out = {}
    for d in domains:
        out.update(FAMILY_TABLES[d])
    return out


def family_domains() -> tuple:
    """Every governed counter domain (the mxlint unregistered-counter
    rule only judges metrics whose domain appears here)."""
    return tuple(FAMILY_TABLES)


def known_metric(full_name: str) -> bool:
    """Is ``domain/name`` registered in its family table? Metrics in
    ungoverned domains (``mxtpu``, ``bulk``, ...) return True — only a
    governed family constrains its namespace."""
    domain = full_name.split("/", 1)[0]
    table = FAMILY_TABLES.get(domain)
    return True if table is None else full_name in table


def metric_kind(full_name: str):
    """The declared kind for a governed metric, or None."""
    domain = full_name.split("/", 1)[0]
    return FAMILY_TABLES.get(domain, {}).get(full_name)
