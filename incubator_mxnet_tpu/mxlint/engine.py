"""mxtpu.mxlint.engine — the AST lint harness behind ``tools/mxlint.py``.

Plain stdlib ``ast``: parse each file once, hand the tree (with parent
links) to every rule whose scope covers the file, collect
:class:`Finding` records, then apply inline suppressions.

Suppression grammar (docs/mxlint.md):

* ``# mxlint: disable=<rule>[,<rule2>] -- <reason>`` suppresses those
  rules on the SAME line (or, when the directive is alone on its line,
  on the next code line — the long-statement form).
* ``# mxlint: disable-file=<rule>[,...] -- <reason>`` anywhere in the
  file suppresses the rules for the whole file.
* The reason string is REQUIRED: a directive without ``-- <reason>``
  suppresses nothing and is itself reported under the
  ``suppression-missing-reason`` rule — the point of a waiver is that
  the next reader learns why, not just that someone once said so.

Rules are small classes (:class:`Rule`); cross-file rules (the
duplicated-default-table detector) accumulate state in ``check`` and
report from ``finish``.
"""
from __future__ import annotations

import ast
import os
import re

__all__ = ["Finding", "Rule", "FileContext", "lint_paths",
           "lint_sources", "iter_files", "SUPPRESSION_RULE_ID",
           "parse_suppressions"]

SUPPRESSION_RULE_ID = "suppression-missing-reason"

_DIRECTIVE = re.compile(
    r"#\s*mxlint:\s*(disable|disable-file)\s*=\s*([\w\-, ]+?)"
    r"\s*(?:--\s*(.*\S))?\s*$")

# directories never walked (fixtures under tests/ carry deliberate
# violations; examples are user-facing snippets, not framework code)
SKIP_DIRS = {"__pycache__", ".git", ".jax_test_cache", "tests",
             "examples", "docs", "node_modules"}


class Finding:
    """One lint finding: rule id + location + message + fix-it hint."""

    __slots__ = ("rule", "path", "line", "col", "message", "hint")

    def __init__(self, rule, path, line, col, message, hint=""):
        self.rule = rule
        self.path = path
        self.line = int(line)
        self.col = int(col)
        self.message = message
        self.hint = hint

    def render(self, root=None) -> str:
        path = os.path.relpath(self.path, root) if root else self.path
        out = f"{path}:{self.line}:{self.col}: [{self.rule}] {self.message}"
        if self.hint:
            out += f"\n    fix: {self.hint}"
        return out

    def to_dict(self) -> dict:
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "col": self.col, "message": self.message,
                "hint": self.hint}

    def __repr__(self):
        return f"Finding({self.rule}@{self.path}:{self.line})"


class FileContext:
    """One parsed file: source, line list, AST with parent links, and
    the path both absolute and repo-relative (rules scope on the
    relative form)."""

    def __init__(self, path: str, relpath: str, src: str):
        self.path = path
        self.relpath = relpath.replace(os.sep, "/")
        self.src = src
        self.lines = src.splitlines()
        self.tree = ast.parse(src, filename=path)   # may raise SyntaxError
        for node in ast.walk(self.tree):
            for child in ast.iter_child_nodes(node):
                child._mxlint_parent = node
        # suppressions live on the context so CROSS-FILE rules (which
        # report from finish(), after per-file filtering already ran)
        # can honor them at collection time
        (self.suppress_per_line, self.suppress_file,
         self.bad_directives) = parse_suppressions(self.lines)

    def suppressed(self, rule_id: str, lineno: int) -> bool:
        return rule_id in self.suppress_file \
            or rule_id in self.suppress_per_line.get(lineno, ())

    def parents(self, node):
        """Ancestors of ``node``, innermost first."""
        cur = getattr(node, "_mxlint_parent", None)
        while cur is not None:
            yield cur
            cur = getattr(cur, "_mxlint_parent", None)

    def segment(self, node) -> str:
        """Source text of a node (empty string when unavailable)."""
        try:
            return ast.get_source_segment(self.src, node) or ""
        except Exception:  # noqa: BLE001 — cosmetic only
            return ""


class Rule:
    """Base rule. Subclasses set ``id``/``hint`` and override
    ``check`` (per file) and optionally ``finish`` (after all files —
    the cross-file reporting point) and ``applies`` (path scope)."""

    id = "abstract"
    hint = ""

    def applies(self, relpath: str) -> bool:
        return True

    def check(self, ctx: FileContext) -> list:
        return []

    def finish(self) -> list:
        return []

    def finding(self, ctx, node, message, hint=None) -> Finding:
        return Finding(self.id, ctx.path, getattr(node, "lineno", 1),
                       getattr(node, "col_offset", 0), message,
                       self.hint if hint is None else hint)


def parse_suppressions(lines):
    """Scan source lines for mxlint directives.

    Returns ``(per_line, file_level, bad)`` where ``per_line`` maps a
    1-based line number to the set of rule ids suppressed there,
    ``file_level`` is the set suppressed file-wide, and ``bad`` lists
    ``(lineno, directive_text)`` for directives missing the required
    reason (which therefore suppress nothing)."""
    per_line: dict = {}
    file_level: set = set()
    bad = []
    for i, line in enumerate(lines, 1):
        m = _DIRECTIVE.search(line)
        if not m:
            continue
        kind, rules_s, reason = m.groups()
        if not reason:
            bad.append((i, m.group(0)))
            continue
        rules = {r.strip() for r in rules_s.split(",") if r.strip()}
        if kind == "disable-file":
            file_level |= rules
        else:
            per_line.setdefault(i, set()).update(rules)
            # a directive alone on its line covers the NEXT CODE line
            # (the reason may continue over further comment lines)
            if line.strip().startswith("#"):
                j = i + 1
                while j <= len(lines) and (
                        not lines[j - 1].strip()
                        or lines[j - 1].strip().startswith("#")):
                    j += 1
                per_line.setdefault(j, set()).update(rules)
    return per_line, file_level, bad


def iter_files(paths, skip_dirs=SKIP_DIRS):
    """Expand files/directories into the sorted list of .py files."""
    out = []
    for p in paths:
        if os.path.isfile(p):
            out.append(p)
            continue
        for dirpath, dirnames, filenames in os.walk(p):
            dirnames[:] = sorted(d for d in dirnames
                                 if d not in skip_dirs)
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    out.append(os.path.join(dirpath, fn))
    return out


def _lint_context(ctx, rules) -> list:
    """Per-file core: run the in-scope rules, apply suppressions,
    report reasonless directives."""
    findings = []
    for rule in rules:
        if rule.applies(ctx.relpath):
            for f in rule.check(ctx):
                if ctx.suppressed(f.rule, f.line):
                    continue
                findings.append(f)
    for lineno, text in ctx.bad_directives:
        findings.append(Finding(
            SUPPRESSION_RULE_ID, ctx.path, lineno, 0,
            f"suppression without a reason: {text!r} (it suppresses "
            f"nothing)",
            "append ' -- <why this is safe here>' to the directive"))
    return findings


def lint_paths(paths, rules, root=None, skip_dirs=SKIP_DIRS) -> list:
    """Run ``rules`` over every .py file under ``paths``. Returns the
    surviving findings, sorted by (path, line).

    ``root`` anchors the repo-relative path rules scope on (default:
    the common prefix of ``paths``)."""
    files = iter_files(paths, skip_dirs=skip_dirs)
    root = root or (os.path.commonpath(files) if files else ".")
    findings = []
    for path in files:
        ap = os.path.abspath(path).replace(os.sep, "/")
        # rules scope on the package-relative spelling
        # ("incubator_mxnet_tpu/..."): anchor on the package component
        # when the path has one, so linting the package DIRECTLY
        # (lint_tree([pkg_dir]) — where commonpath strips the prefix)
        # still puts every file in the package rules' jurisdiction
        marker = "/incubator_mxnet_tpu/"
        if marker in ap:
            relpath = ap[ap.index(marker) + 1:]
        else:
            relpath = os.path.relpath(ap, os.path.abspath(root))
        try:
            with open(path, encoding="utf-8") as f:
                src = f.read()
            ctx = FileContext(path, relpath, src)
        except (OSError, SyntaxError, ValueError) as e:
            findings.append(Finding("parse-error", path,
                                    getattr(e, "lineno", 1) or 1, 0,
                                    f"cannot lint: {e}",
                                    "fix the syntax error (or drop the "
                                    "file from the lint set)"))
            continue
        findings.extend(_lint_context(ctx, rules))
    for rule in rules:
        findings.extend(rule.finish())
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings


def lint_sources(items, rules) -> list:
    """Lint in-memory sources as if they lived at the given
    repo-relative paths: ``items`` is ``(relpath, src)`` pairs. The
    fixture-test entry point — rules scope on the pretend path, so a
    fixture can stand in for any package module."""
    findings = []
    for relpath, src in items:
        ctx = FileContext(relpath, relpath, src)
        findings.extend(_lint_context(ctx, rules))
    for rule in rules:
        findings.extend(rule.finish())
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings
