"""mxtpu.mxlint — framework-invariant static analysis + strict-mode
jit-program auditing.

Two halves, one contract (docs/mxlint.md):

* **static** (:mod:`.engine` + :mod:`.rules`, driven by
  ``tools/mxlint.py``) — an stdlib-``ast`` lint suite whose rules encode
  the invariants PR 6–13's review-hardening passes kept re-finding by
  hand: knob reads that bypass ``autotune/knobs.py``'s documented
  resolution order, counter names drifting from the family tables,
  raises inside never-raise parsers, raw device-kind comparisons,
  unlocked writes to thread-shared module state, and duplicated default
  tables. ``tools/mxlint.py --check`` gates auto_guard/auto_sweep on a
  clean tree; ``mxdiag.py lint`` renders the findings report.
* **runtime** (:mod:`.runtime`, armed by ``MXTPU_STRICT=1``) — a
  strict-mode auditor over the steady train/serve loop:
  transfer-guard-based host-sync detection, a recompile-storm detector
  over perfscope's compile captures, and a donated-buffer-read check,
  all reporting through the ``mxlint.*`` counter family plus flight /
  ``mxtpu.events/1``, and landing in BENCH json as ``extra.mxlint``.

:mod:`.families` is the ONE home of the counter-family tables —
``tools/trace_check.py`` derives its ``*_FAMILIES`` globals from it, and
the ``unregistered-counter`` rule reads the same source, so the
validator and the linter cannot disagree.
"""
from __future__ import annotations

from . import engine, families, rules, runtime
from .engine import Finding, lint_paths
from .rules import RULES, default_rules

__all__ = ["engine", "families", "rules", "runtime", "Finding",
           "lint_paths", "RULES", "default_rules", "lint_tree"]


def lint_tree(paths, root=None):
    """Run the default rule set over ``paths`` (files or directories).
    Returns the list of :class:`Finding`."""
    return lint_paths(paths, default_rules(), root=root)
