"""mxtpu.autotune.knobs — ONE typed home for the performance knobs.

Before this module every tunable rode its own env spelling, resolved at
whatever call site happened to read it first: bench.py read
``BENCH_LOOP_CHUNK or MXTPU_LOOP_CHUNK``, TrainLoop read only
``MXTPU_LOOP_CHUNK``, the Trainer read it again with its own default,
``BENCH_MESH`` grammar lived inline in bench.py, and the pallas master
switch had three spellings (``MXTPU_PALLAS`` / ``MXTPU_NO_PALLAS`` /
``MXTPU_FORCE_PALLAS``) whose interaction was defined only by the order
of ``if`` statements in ``ops/pallas``. :class:`KnobConfig` replaces
that: one dataclass over the knob space the repo already exposes, with
ONE documented resolution order every consumer (bench.py, TrainLoop,
Trainer, the autotune trial runner) goes through:

    call-site argument  >  BENCH_*  >  MXTPU_*  >  cached winner  >  default

* **call-site argument** — an explicit Python argument always wins
  (``TrainLoop(chunk=8)``, ``Trainer(loop_chunk=4)``).
* **BENCH_*** — the bench driver's per-run override spelling.
* **MXTPU_*** — the ambient process-level spelling.
* **cached winner** — when ``mxtpu.autotune`` applied a tuning-cache
  winner (``MXTPU_AUTOTUNE=1``), its knob values fill in BELOW the env:
  an explicit env override always beats the tuner, so a human A/B run
  can never be silently reinterpreted.
* **default** — the knob's documented default.

When BOTH env spellings of one knob are set and DISAGREE, the higher-
precedence one wins and a conflict warning fires (once per knob per
process, counted as ``autotune.env_conflicts``) — the old behaviour was
whichever call site read first, i.e. silent.

The knob space (docs/autotune.md renders the full table):

=================  =====================================================
knob               meaning
=================  =====================================================
``loop_chunk``     micro-steps compiled into one XLA program (0/1 =
                   stepwise FusedTrainStep, >1 = whole-loop TrainLoop)
``remat``          rematerialize the forward during backward
``remat_policy``   what remat saves: ``dots`` / ``nothing`` /
                   ``everything`` (parallel/trainer_step.py)
``prefetch_depth`` io.DevicePrefetcher device-side buffer depth
``io_workers``     io.Pipeline decode-pool width (host threads decoding
                   ahead of the transfer stage; docs/io.md)
``pallas``         kernel-selection master switch: ``auto`` (TPU +
                   self-test gate) / ``on`` / ``force`` / ``off``
``mesh``           BENCH_MESH token grammar (``dp4``, ``dp2mp2``,
                   ``fsdp4``) — the sharding mode rides the tokens
``batch``          global batch size (bucket geometry on the serving
                   side)
=================  =====================================================
"""
from __future__ import annotations

import os
import warnings

__all__ = ["KnobConfig", "KNOB_FIELDS", "PALLAS_MODES", "REMAT_POLICIES",
           "resolve", "parse_mesh", "set_cached_defaults",
           "cached_defaults", "clear_cached_defaults", "reset_warned",
           "env_raw", "env_str", "env_int", "env_float", "env_flag",
           "TRUE_SPELLINGS", "FALSE_SPELLINGS"]

KNOB_FIELDS = ("loop_chunk", "remat", "remat_policy", "prefetch_depth",
               "io_workers", "pallas", "mesh", "batch")

# the pallas master-switch states the three historical spellings resolve
# into (ops/pallas.enabled() order: off beats force beats on beats auto)
PALLAS_MODES = ("auto", "on", "force", "off")

REMAT_POLICIES = (None, "dots", "nothing", "everything")

_DEFAULTS = {"loop_chunk": 0, "remat": False, "remat_policy": None,
             "prefetch_depth": 2, "io_workers": 2, "pallas": "auto",
             "mesh": None, "batch": None}

# (BENCH spelling, MXTPU spelling) per knob; pallas resolves through its
# own three-spelling table below
_ENV = {"loop_chunk": ("BENCH_LOOP_CHUNK", "MXTPU_LOOP_CHUNK"),
        "remat": ("BENCH_REMAT", "MXTPU_REMAT"),
        "remat_policy": ("BENCH_REMAT_POLICY", "MXTPU_REMAT_POLICY"),
        "prefetch_depth": ("BENCH_PREFETCH_DEPTH",
                           "MXTPU_PREFETCH_DEPTH"),
        "io_workers": ("BENCH_IO_WORKERS", "MXTPU_IO_WORKERS"),
        "mesh": ("BENCH_MESH", "MXTPU_MESH"),
        "batch": ("BENCH_BATCH", None)}

# cached tuning-cache winner applied by mxtpu.autotune (BELOW the env in
# precedence); module-level, set once per process by ensure_tuned()
_CACHED: dict = {}

# conflict warnings fire once per knob per process
_WARNED: set = set()


def set_cached_defaults(values: dict) -> None:
    """Install a tuning-cache winner as the below-env default layer
    (what ``MXTPU_AUTOTUNE=1`` + a cache hit or a finished search does).
    Unknown keys are ignored — a cache written by a future schema must
    not crash an older reader."""
    _CACHED.clear()
    for k, v in (values or {}).items():
        if k in KNOB_FIELDS:
            _CACHED[k] = v


def cached_defaults() -> dict:
    return dict(_CACHED)


def clear_cached_defaults() -> None:
    _CACHED.clear()


def reset_warned() -> None:
    """Test hook: re-arm the once-per-process conflict warnings."""
    _WARNED.clear()


def _parse(field: str, raw: str):
    """Parse one env string into the knob's type. Raises ValueError on
    garbage — a mistyped knob must fail loudly, not silently default."""
    raw = raw.strip()
    if field in ("loop_chunk", "prefetch_depth", "io_workers", "batch"):
        v = int(raw)
        # loop_chunk 0 = stepwise is legal; a zero buffer depth or
        # batch is not — reject HERE, naming the field, so every
        # consumer (KnobConfig and the single-field resolve() path
        # TrainLoop uses) sees the same verdict for the same env value
        floor = 0 if field == "loop_chunk" else 1
        if v < floor:
            raise ValueError(f"{field} must be >= {floor}, got {v}")
        return v
    if field == "remat":
        low = raw.lower()
        if low in ("1", "true", "on", "yes"):
            return True
        if low in ("0", "false", "off", "no", ""):
            return False
        raise ValueError(f"remat flag {raw!r} is not a boolean spelling")
    if field == "remat_policy":
        if raw in ("", "none", "None"):
            return None
        if raw not in REMAT_POLICIES:
            raise ValueError(f"unknown remat_policy {raw!r}; expected one "
                             f"of {[p for p in REMAT_POLICIES if p]}")
        return raw
    if field == "mesh":
        if not raw:
            return None
        parse_mesh(raw)          # grammar check; value stays the token str
        return raw
    raise ValueError(f"unknown knob field {field!r}")


def _warn_once(key: str, msg: str) -> None:
    """One warning per key per process (the conflict/loser channels)."""
    if key in _WARNED:
        return
    _WARNED.add(key)
    warnings.warn(msg + " (docs/autotune.md)", stacklevel=4)


def _conflict(field: str, win_name: str, win_val, lose_name: str,
              lose_val) -> None:
    """Both spellings set and disagreeing: warn once per knob, count."""
    if field in _WARNED:
        return
    _WARNED.add(field)
    try:
        from ..profiler import counter as _counter
        _counter("autotune.env_conflicts", "autotune").increment()
    except Exception:  # noqa: BLE001 — telemetry must never break resolve
        pass
    warnings.warn(
        f"knob {field!r}: {win_name}={win_val!r} and "
        f"{lose_name}={lose_val!r} disagree — {win_name} wins "
        f"(precedence: call-site > BENCH_* > MXTPU_* > cached winner > "
        f"default; docs/autotune.md)", stacklevel=3)


def _resolve_pallas():
    """The pallas master switch from its three spellings, mirroring
    ops/pallas.enabled()'s if-order exactly (off > force > on > auto) —
    this module must DESCRIBE the dispatch layer's behaviour, never
    contradict it."""
    master = os.environ.get("MXTPU_PALLAS", "").strip().lower()
    no = os.environ.get("MXTPU_NO_PALLAS", "").strip().lower() \
        not in ("", "0", "false")
    force = os.environ.get("MXTPU_FORCE_PALLAS", "").strip().lower() \
        not in ("", "0", "false")
    votes = {}
    if master in ("0", "false", "off"):
        votes["MXTPU_PALLAS"] = "off"
    elif master == "force":
        votes["MXTPU_PALLAS"] = "force"
    elif master in ("1", "true", "on"):
        votes["MXTPU_PALLAS"] = "on"
    if no:
        votes["MXTPU_NO_PALLAS"] = "off"
    if force:
        votes["MXTPU_FORCE_PALLAS"] = "force"
    if not votes:
        return None, None
    # enabled()'s order: any off-spelling beats force beats on
    for mode in ("off", "force", "on"):
        names = [n for n, m in votes.items() if m == mode]
        if names:
            losers = [(n, m) for n, m in votes.items() if m != mode]
            if losers:
                _conflict("pallas", names[0], mode, losers[0][0],
                          losers[0][1])
            return mode, names[0]
    return None, None


def resolve(field: str, call_site=None):
    """Resolve ONE knob through the documented precedence. Returns
    ``(value, source)`` where source names the layer that decided:
    ``"call_site"``, the winning env var name, ``"cached"`` or
    ``"default"``."""
    if field not in KNOB_FIELDS:
        raise ValueError(f"unknown knob field {field!r}; expected one of "
                         f"{KNOB_FIELDS}")
    if call_site is not None:
        return call_site, "call_site"
    if field == "pallas":
        mode, src = _resolve_pallas()
        if mode is not None:
            return mode, src
    else:
        bench_name, mxtpu_name = _ENV[field]
        bench_raw = os.environ.get(bench_name, "") if bench_name else ""
        mxtpu_raw = os.environ.get(mxtpu_name, "") if mxtpu_name else ""
        bench_raw, mxtpu_raw = bench_raw.strip(), mxtpu_raw.strip()
        if bench_raw:
            v = _parse(field, bench_raw)
            if mxtpu_raw:
                # conflict DETECTION only: the losing spelling must
                # never be able to crash a resolution its valid winner
                # already decided (a stale `export MXTPU_X=bogus` in a
                # shell profile would otherwise break every run) — an
                # unparseable loser warns and is ignored
                try:
                    mv = _parse(field, mxtpu_raw)
                except ValueError as e:
                    _warn_once(
                        field + "/loser",
                        f"knob {field!r}: ignoring unparseable "
                        f"{mxtpu_name}={mxtpu_raw!r} ({e}); "
                        f"{bench_name}={v!r} wins by precedence")
                else:
                    if mv != v:
                        _conflict(field, bench_name, v, mxtpu_name, mv)
            return v, bench_name
        if mxtpu_raw:
            return _parse(field, mxtpu_raw), mxtpu_name
    if field in _CACHED:
        return _CACHED[field], "cached"
    return _DEFAULTS[field], "default"


# ---------------------------------------------------------------------------
# secondary knobs (everything OUTSIDE the search space)
# ---------------------------------------------------------------------------
#
# The search space above has two env spellings and a cached-winner
# layer; the rest of the package's knobs (MXTPU_RESILIENCE_EVERY,
# MXTPU_SERVING_PORT, ...) have ONE spelling and no tuner — but they
# must still resolve through ONE home, or their parsing drifts exactly
# the way loop_chunk's did before PR 13 (three local _env_float helpers
# with three error behaviours existed when mxlint first ran). These
# accessors are that home: call-site argument > env > default, one
# truthy-spelling table, one error policy. mxlint's ``raw-env-read``
# rule holds every other module in the package to them.

# the one boolean spelling table (matches _parse's remat table)
TRUE_SPELLINGS = ("1", "true", "on", "yes")
FALSE_SPELLINGS = ("0", "false", "off", "no", "")


def env_raw(name: str, call_site=None):
    """The raw stripped env string, or None when unset/empty (an empty
    export is "unset", matching every historical call site)."""
    if call_site is not None:
        return call_site
    v = os.environ.get(name, "")
    v = v.strip()
    return v or None


def env_str(name: str, default=None, call_site=None):
    v = env_raw(name, call_site)
    return default if v is None else v


def _env_num(name, default, call_site, on_error, cast):
    if call_site is not None:
        return cast(call_site)
    raw = env_raw(name)
    if raw is None:
        return default
    try:
        return cast(raw)
    except (TypeError, ValueError) as e:
        if on_error == "default":
            # never-raise consumers (analysis paths, crash paths): a
            # typo'd knob degrades to the default, once, loudly
            _warn_once(name + "/parse",
                       f"knob {name}={raw!r} is not a valid "
                       f"{cast.__name__}; using default {default!r}")
            return default
        raise ValueError(f"knob {name}={raw!r}: {e}") from e


def env_int(name: str, default=None, call_site=None,
            on_error: str = "raise"):
    """Integer knob. ``on_error="default"`` for never-raise consumers;
    the default policy fails loudly — a mistyped knob must not
    silently become the default."""
    return _env_num(name, default, call_site, on_error, int)


def env_float(name: str, default=None, call_site=None,
              on_error: str = "raise"):
    return _env_num(name, default, call_site, on_error, float)


def env_flag(name: str, default: bool = False, call_site=None) -> bool:
    """Boolean knob over the ONE spelling table. Never raises: arming
    flags are read at import/enable time, where a typo must degrade
    (to the default, with a once-per-process warning), not crash the
    process."""
    if call_site is not None:
        return bool(call_site)
    raw = env_raw(name)
    if raw is None:
        return default
    low = raw.lower()
    if low in TRUE_SPELLINGS:
        return True
    if low in FALSE_SPELLINGS:
        return False
    _warn_once(name + "/flag",
               f"knob {name}={raw!r} is not a boolean spelling "
               f"({TRUE_SPELLINGS} / {FALSE_SPELLINGS[:-1]}); using "
               f"default {default!r}")
    return default


def parse_mesh(spec: str):
    """Validate/parse the BENCH_MESH token grammar — concatenated
    ``<axis><size>`` pairs (``dp4``, ``dp2mp2``, ``fsdp4``) — into
    ``(mode, axes)`` where mode is the sharding mode the tokens imply
    (``dp`` / ``fsdp`` / ``auto``) and axes maps mesh axis -> size.
    THE one home of the grammar: bench.py and the trial runner both
    resolve through it, so they can never drift apart on what a mesh
    token means. Raises ValueError on bad grammar, duplicate axes, and
    fsdp-with-model-axis layouts (silently-idle devices)."""
    import re
    spec = (spec or "").strip()
    if not spec:
        return None, {}
    toks = re.findall(r"([a-z]+)(\d+)", spec)
    if not toks or "".join(f"{n}{s}" for n, s in toks) != spec:
        raise ValueError(f"mesh spec {spec!r}: expected concatenated "
                         f"axis-size tokens (dp4, dp2mp2, fsdp4)")
    mode, axes = "dp", {}
    for name, size in toks:
        if name == "fsdp":
            mode, name = "fsdp", "dp"
        if name in axes:
            # dp2dp2 / fsdp2dp2 would silently keep only the last size —
            # half the requested devices idle with no error
            raise ValueError(f"mesh spec {spec!r}: axis {name!r} given "
                             f"more than once")
        axes[name] = int(size)
    try:
        from ..parallel.sharding import MODEL_AXES
    except Exception:  # noqa: BLE001 — grammar still checks standalone
        MODEL_AXES = ("mp", "tp", "model")
    if any(a in axes for a in MODEL_AXES):
        if mode == "fsdp":
            # fsdp leaves the net unannotated, so an mp axis would just
            # compute redundantly on every mp rank
            raise ValueError(
                f"mesh spec {spec!r}: fsdp with a model axis is not "
                f"supported (the fsdp path carries no model-axis "
                f"annotations); use dp2mp2-style layouts")
        mode = "auto"
    return mode, axes


class KnobConfig:
    """One resolved point in the knob space. Fields are plain attributes
    (see the module docstring's table); construct directly for an
    explicit config, or through :meth:`from_env` for the documented
    precedence chain. ``sources`` records which layer decided each
    field."""

    def __init__(self, loop_chunk=0, remat=False, remat_policy=None,
                 prefetch_depth=2, io_workers=2, pallas="auto", mesh=None,
                 batch=None):
        self.loop_chunk = int(loop_chunk)
        self.remat = bool(remat)
        self.remat_policy = remat_policy
        self.prefetch_depth = int(prefetch_depth)
        self.io_workers = int(io_workers)
        self.pallas = pallas
        self.mesh = mesh or None
        # None = unset; 0 is NOT coerced to unset — the env-parse path
        # rejects BENCH_BATCH=0 with a named error, and a dict/cache
        # path must reach the same verdict (_validate raises below)
        self.batch = None if batch is None else int(batch)
        self.sources = {}
        self._validate()

    def _validate(self):
        if self.loop_chunk < 0:
            raise ValueError(f"loop_chunk must be >= 0, "
                             f"got {self.loop_chunk}")
        if self.prefetch_depth < 1:
            raise ValueError(f"prefetch_depth must be >= 1, "
                             f"got {self.prefetch_depth}")
        if self.io_workers < 1:
            raise ValueError(f"io_workers must be >= 1, "
                             f"got {self.io_workers}")
        if self.remat_policy not in REMAT_POLICIES:
            raise ValueError(f"unknown remat_policy "
                             f"{self.remat_policy!r}; expected one of "
                             f"{[p for p in REMAT_POLICIES if p]}")
        if self.pallas not in PALLAS_MODES:
            raise ValueError(f"unknown pallas mode {self.pallas!r}; "
                             f"expected one of {PALLAS_MODES}")
        if self.mesh:
            parse_mesh(self.mesh)
        if self.batch is not None and self.batch < 1:
            raise ValueError(f"batch must be >= 1, got {self.batch}")

    # -- construction -----------------------------------------------------
    @classmethod
    def from_env(cls, **call_site):
        """Resolve every knob through call-site kwarg > BENCH_* >
        MXTPU_* > cached winner > default (the module contract)."""
        values, sources = {}, {}
        for field in KNOB_FIELDS:
            v, src = resolve(field, call_site.get(field))
            values[field] = v
            sources[field] = src
        cfg = cls(**values)
        cfg.sources = sources
        return cfg

    @classmethod
    def from_dict(cls, d: dict):
        if not isinstance(d, dict):
            raise ValueError(f"knob dict must be an object, "
                             f"got {type(d).__name__}")
        unknown = set(d) - set(KNOB_FIELDS)
        if unknown:
            raise ValueError(f"unknown knob fields {sorted(unknown)}")
        return cls(**d)

    def to_dict(self) -> dict:
        return {f: getattr(self, f) for f in KNOB_FIELDS}

    # -- trial plumbing ---------------------------------------------------
    def to_env(self) -> dict:
        """The canonical env spelling of this config — what the autotune
        trial runner exports into a bench.py subprocess so the trial is
        fully pinned (every knob explicit, nothing inherited). Pallas
        ``auto`` exports nothing (auto IS the unset state; the runner
        scrubs the parent's pallas spellings)."""
        env = {"BENCH_LOOP_CHUNK": str(self.loop_chunk),
               "BENCH_REMAT": "1" if self.remat else "0",
               "BENCH_PREFETCH_DEPTH": str(self.prefetch_depth),
               "BENCH_IO_WORKERS": str(self.io_workers)}
        if self.remat_policy:
            env["BENCH_REMAT_POLICY"] = self.remat_policy
        if self.pallas == "off":
            env["MXTPU_PALLAS"] = "0"
        elif self.pallas == "force":
            env["MXTPU_PALLAS"] = "force"
        elif self.pallas == "on":
            env["MXTPU_PALLAS"] = "1"
        if self.mesh:
            env["BENCH_MESH"] = self.mesh
        if self.batch:
            env["BENCH_BATCH"] = str(self.batch)
        return env

    # -- misc -------------------------------------------------------------
    def replace(self, **changes) -> "KnobConfig":
        d = self.to_dict()
        d.update(changes)
        return KnobConfig(**d)

    def describe(self) -> str:
        """Short human form, non-default fields only ("default" when
        everything is)."""
        parts = []
        for f in KNOB_FIELDS:
            v = getattr(self, f)
            if v != _DEFAULTS[f]:
                parts.append(f"{f}={v}")
        return " ".join(parts) or "default"

    def __eq__(self, other):
        return isinstance(other, KnobConfig) \
            and self.to_dict() == other.to_dict()

    def __hash__(self):
        return hash(tuple(sorted(
            (k, str(v)) for k, v in self.to_dict().items())))

    def __repr__(self):
        return f"KnobConfig({self.describe()})"
