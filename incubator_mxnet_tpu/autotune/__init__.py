"""mxtpu.autotune — the measurement-driven knob autotuner.

Six observability layers (docs/observability.md) measure where a run's
time goes; this subsystem is the layer that **spends** those
measurements: it searches the knob space the repo already exposes
(``loop_chunk`` x ``remat_policy`` x prefetch depth x pallas selection
x mesh layout x batch geometry, :mod:`.knobs`), scores each trial on
the MEASURED devicescope busy fraction + step wall + MFU
(:mod:`.trial` — every trial is a bench.py subprocess: jax's
one-trace-per-process profiler limit makes in-process back-to-back
windows impossible, and a fresh process quarantines compile-cache
state), prunes the space with the idle-gap taxonomy and the
``mfu_if_removed`` counterfactuals instead of grid-sweeping it
(:mod:`.space`), and persists winners per (model fingerprint, mesh,
device kind) with full provenance (:mod:`.cache`) so every later run
starts tuned.

Arming (``MXTPU_AUTOTUNE=1``; bench.py calls :func:`ensure_tuned`):
cache hit -> the winner's knobs install as the BELOW-ENV default layer
(:func:`.knobs.set_cached_defaults`) with ZERO trials; cache miss -> a
bounded search runs first (``MXTPU_AUTOTUNE_BUDGET`` trials of
``MXTPU_AUTOTUNE_STEPS`` steps each), then the winner installs and
persists. An explicit BENCH_*/MXTPU_* env override always beats the
tuner — the documented knob precedence is call-site > BENCH_* >
MXTPU_* > cached winner > default.

Telemetry: the ``autotune.*`` counter family (trace_check
AUTOTUNE_FAMILIES), ``extra.autotune`` in every training BENCH json
(``check_autotune_extra``), and the ``mxdiag.py tune`` renderer.
"""
from __future__ import annotations


from . import cache as cache_mod
from . import knobs
from . import space
from . import trial
from . import tuner
from .cache import TuningCache, current_device_kind, fingerprint
from .knobs import KnobConfig
from .trial import TrialResult, run_trial
from .tuner import SearchResult, search

__all__ = ["KnobConfig", "TuningCache", "SearchResult", "TrialResult",
           "search", "run_trial", "ensure_tuned", "bench_extra",
           "enabled", "fingerprint", "current_device_kind", "knobs",
           "space", "trial", "tuner", "cache_mod"]


def enabled() -> bool:
    """True when MXTPU_AUTOTUNE=1 (the bench/Trainer arming switch)."""
    return knobs.env_flag("MXTPU_AUTOTUNE", False)


def ensure_tuned(model="lenet", batch=None, dtype=None, mesh=None,
                 budget=None, steps=None, trial_timeout=None,
                 extra_env=None, cache_dir=None, log=None
                 ) -> SearchResult:
    """Resolve the tuning cache for this (model, mesh, device-kind) key
    — hit: zero trials; miss: bounded search — and install the winner
    as the below-env knob defaults for THIS process. Returns the
    SearchResult (``bench_extra`` turns it into the BENCH payload).

    Env knobs: ``MXTPU_AUTOTUNE_BUDGET`` (default 6 trials),
    ``MXTPU_AUTOTUNE_STEPS`` (default 12 steady steps per trial),
    ``MXTPU_AUTOTUNE_TRIAL_TIMEOUT`` (default 900 s),
    ``MXTPU_AUTOTUNE_CACHE`` (cache dir),
    ``MXTPU_AUTOTUNE_BATCH_CANDIDATES`` (comma-separated batch sizes to
    additionally explore — each candidate first passes memscope's
    memory-feasibility check, so an over-capacity batch is a counted
    pre-trial reject instead of a doomed subprocess)."""
    budget = knobs.env_int("MXTPU_AUTOTUNE_BUDGET", 6,
                           call_site=budget)
    steps = knobs.env_int("MXTPU_AUTOTUNE_STEPS", 12, call_site=steps)
    trial_timeout = knobs.env_int("MXTPU_AUTOTUNE_TRIAL_TIMEOUT", 900,
                                  call_site=trial_timeout)
    raw_bc = knobs.env_str("MXTPU_AUTOTUNE_BATCH_CANDIDATES", "") or ""
    batch_candidates = []
    for part in raw_bc.split(","):
        part = part.strip()
        if part:
            try:
                batch_candidates.append(int(part))
            except ValueError:
                pass
    result = tuner.search(model=model, batch=batch, dtype=dtype,
                          steps=steps, budget=budget, mesh=mesh,
                          cache_dir=cache_dir,
                          trial_timeout=trial_timeout,
                          extra_env=extra_env,
                          batch_candidates=tuple(batch_candidates),
                          log=log)
    if result.winner is not None:
        knobs.set_cached_defaults(result.winner.to_dict())
    return result


def bench_extra(result: SearchResult | None = None) -> dict:
    """The ``extra.autotune`` payload: the search/cache outcome, or the
    disabled shape ``{"enabled": false}`` — every training BENCH json
    carries one or the other, so the schema is uniform."""
    if result is None:
        return {"enabled": False}
    return result.to_extra()
