"""mxtpu.autotune.space — the knob space and the measurement-driven
pruning rules.

The search does NOT grid-sweep: a trial costs a subprocess compile, so
the space is pruned with the measurements the observability stack
already produces before anything is dispatched. The baseline trial's
devicescope idle-gap taxonomy names WHERE the idle time goes, and each
knob family only helps one class of idleness:

==================  ====================================================
diagnosis           knobs worth moving
==================  ====================================================
``input_starved``   ``prefetch_depth`` (feed the chip), ``io_workers``
                    (widen the decode pool — promoted to FIRST when the
                    starvation split says decode dominates),
                    ``loop_chunk`` (the executor is what the prefetcher
                    rides) — NOT ``remat_policy``: a recompute knob
                    cannot feed an input-starved chip
``dispatch_bound``  ``loop_chunk`` (amortize the per-step host
                    dispatch); deeper prefetch buys nothing — the
                    buffer is not empty, the host is
``device_bound``    ``pallas`` / ``remat_policy`` (make the device work
                    cheaper); dispatch/prefetch knobs buy nothing — the
                    chip is already busy
``unknown``         no window measured anything — nothing to prune
                    with, the core knobs all stay explorable
==================  ====================================================

``mesh`` is only explored when perfscope's ``mfu_if_removed``
counterfactual says collectives are worth at least
:data:`COLLECTIVE_GAIN_MIN` of MFU (and the caller supplied mesh
candidates); ``batch`` only when the caller supplied batch candidates —
geometry changes the semantics of a step, so the tuner never invents
one.

Candidates are one-knob-at-a-time variations of the incumbent
(coordinate moves), ordered so the diagnosis's own knob family is tried
first — budget exhaustion then cuts the least promising moves, not the
most.
"""
from __future__ import annotations

from .knobs import KnobConfig

__all__ = ["SPACE", "prune_plan", "candidates", "apply_knob",
           "DOMINANT_MIN_SHARE", "IDLE_MIN_FRACTION",
           "COLLECTIVE_GAIN_MIN", "DIAGNOSES"]

# candidate values per search knob. "remat_policy" folds the remat
# on/off flag and its policy into one axis: None = remat off, "dots" =
# save matmul outputs, "nothing" = recompute everything (max memory
# savings). "everything" is deliberately absent — it makes remat a
# no-op (docs/trainloop.md), i.e. a trial that re-measures the
# baseline.
SPACE = {
    "loop_chunk": (0, 4, 8),
    "prefetch_depth": (2, 4, 8),
    "io_workers": (1, 2, 4, 8),
    "remat_policy": (None, "dots", "nothing"),
    "pallas": ("auto", "off"),
}

DIAGNOSES = ("input_starved", "dispatch_bound", "device_bound", "unknown")

# a gap bucket must hold at least this share of the total measured idle
# time to name the diagnosis
DOMINANT_MIN_SHARE = 0.35
# measured idle below this fraction of the step = device-bound
IDLE_MIN_FRACTION = 0.15
# minimum MFU gain the collective counterfactual must promise before
# the mesh axis is worth a trial
COLLECTIVE_GAIN_MIN = 0.05


def _num(x):
    return float(x) if isinstance(x, (int, float)) \
        and not isinstance(x, bool) else None


def prune_plan(measurement, mesh_candidates=(), batch_candidates=()):
    """Decide which knobs the measured baseline makes worth exploring.

    ``measurement``: the baseline trial's measurement dict
    (:func:`..trial.measurement_from_artifact`) or None when the
    baseline trial died / carried no window.

    Returns ``{"diagnosis", "allowed", "pruned"}`` where allowed is an
    ordered knob list (most promising first) and pruned maps each
    skipped knob to its human-readable reason — the reasons land in
    ``extra.autotune.pruned`` and ``mxdiag.py tune``."""
    m = measurement or {}
    gaps = m.get("gaps") or {}
    tax = {k: _num(gaps.get(k)) or 0.0
           for k in ("input_starved_ms", "dispatch_serialized_ms",
                     "host_gap_ms")}
    idle = sum(tax.values())
    step_ms = _num(m.get("step_ms"))
    busy = _num(m.get("busy_fraction"))

    diagnosis = "unknown"
    if busy is not None:
        idle_frac = (idle / step_ms) if step_ms else (1.0 - busy)
        if idle_frac < IDLE_MIN_FRACTION or busy >= 1.0 - IDLE_MIN_FRACTION:
            diagnosis = "device_bound"
        elif idle > 0:
            dominant = max(tax, key=tax.get)
            if tax[dominant] / idle >= DOMINANT_MIN_SHARE:
                diagnosis = ("input_starved"
                             if dominant == "input_starved_ms"
                             else "dispatch_bound")

    allowed, pruned = [], {}
    if diagnosis == "input_starved":
        allowed = ["prefetch_depth", "io_workers", "loop_chunk"]
        # the pipeline's stage walls (extra.devicescope.gaps
        # .input_starved_split) say WHICH ingest stage starves the
        # chip: when host decode dominates, a deeper buffer just
        # drains slower — the decode pool is the move, so io_workers
        # leads the trial order
        split = m.get("starved_split") or {}
        if split.get("dominant") == "decode":
            allowed = ["io_workers", "prefetch_depth", "loop_chunk"]
        pruned["remat_policy"] = ("input-starved: a recompute knob "
                                  "cannot feed the chip")
        pruned["pallas"] = ("input-starved: kernel selection is not "
                            "the bottleneck")
    elif diagnosis == "dispatch_bound":
        allowed = ["loop_chunk", "prefetch_depth"]
        pruned["io_workers"] = ("dispatch-bound: the decode pool is "
                                "keeping up — the buffer is not empty, "
                                "the host dispatch is the gap")
        pruned["remat_policy"] = ("dispatch-bound: the chip idles "
                                  "between programs, not inside them")
        pruned["pallas"] = ("dispatch-bound: cheaper kernels widen the "
                            "dispatch gaps, they don't close them")
    elif diagnosis == "device_bound":
        allowed = ["pallas", "remat_policy"]
        pruned["loop_chunk"] = ("device-bound: dispatch amortization "
                                "buys nothing on a busy chip")
        pruned["prefetch_depth"] = ("device-bound: the buffer is never "
                                    "the wait")
        pruned["io_workers"] = ("device-bound: ingest already keeps "
                                "the buffer full")
    else:
        # no measured window: nothing to prune WITH — the core knobs
        # stay explorable and throughput decides
        allowed = ["loop_chunk", "prefetch_depth", "io_workers",
                   "remat_policy", "pallas"]

    # the mesh axis: only when the collective counterfactual promises a
    # real gain AND the caller supplied layouts to try
    mfu = _num(m.get("mfu"))
    cf = (m.get("mfu_if_removed") or {})
    coll_gain = None
    if mfu and _num(cf.get("collective")):
        coll_gain = (_num(cf.get("collective")) - mfu) / mfu
    if not mesh_candidates:
        pruned["mesh"] = "no mesh candidates supplied by the caller"
    elif coll_gain is None or coll_gain < COLLECTIVE_GAIN_MIN:
        pruned["mesh"] = (
            f"collective counterfactual promises "
            f"{coll_gain if coll_gain is not None else 0:.1%} MFU "
            f"< {COLLECTIVE_GAIN_MIN:.0%}: a resharding trial can't pay")
    else:
        allowed.append("mesh")
    if batch_candidates:
        allowed.append("batch")
    else:
        pruned["batch"] = ("batch geometry is pinned by the caller "
                           "(the tuner never changes step semantics "
                           "uninvited)")
    return {"diagnosis": diagnosis, "allowed": allowed, "pruned": pruned}


def apply_knob(config: KnobConfig, knob: str, value) -> KnobConfig:
    """One coordinate move. ``remat_policy`` folds the remat flag:
    None = remat off, a policy name = remat on with that policy."""
    if knob == "remat_policy":
        return config.replace(remat=value is not None, remat_policy=value)
    return config.replace(**{knob: value})


def candidates(incumbent: KnobConfig, plan: dict, mesh_candidates=(),
               batch_candidates=()):
    """One-knob-at-a-time variations of the incumbent over the plan's
    allowed knobs, most-promising knob family first. Yields
    ``(knob, value, KnobConfig)``; the incumbent's own value is
    skipped (it was already measured as the baseline)."""
    extra = {"mesh": tuple(mesh_candidates),
             "batch": tuple(batch_candidates)}
    out = []
    for knob in plan.get("allowed", ()):
        values = SPACE.get(knob) or extra.get(knob) or ()
        current = (incumbent.remat_policy if incumbent.remat else None) \
            if knob == "remat_policy" else getattr(incumbent, knob)
        for v in values:
            if v == current:
                continue
            out.append((knob, v, apply_knob(incumbent, knob, v)))
    return out
