"""mxtpu.autotune.trial — ONE way to measure a knob config.

Every trial — the tuner's, and tools/perf_sweep.py's manual rows, which
rebased onto this runner so the two can never disagree on how a config
is measured — executes a short steady-state bench.py window **in a
subprocess** and reads the measurement out of the emitted BENCH json.

Subprocess isolation is a design requirement, not a nicety:

* jax allows ONE profiler trace per process, so back-to-back devicescope
  windows (one per trial) are impossible in-process — the second window
  would DECLINE and every later trial would score on host_wall;
* a fresh process quarantines compile-cache state between configs (a
  corrupt deserialization in trial 3 cannot poison trial 4) and makes a
  trial death a counted skip instead of a tuner crash;
* the measured numbers come from the exact code path the driver runs.

The measurement a trial yields (:func:`measurement_from_artifact`):
measured devicescope busy fraction + idle-gap taxonomy (score
provenance ``measured(profile)``), perfscope step wall / MFU /
``mfu_if_removed`` counterfactuals, and the headline throughput. When
the run carried no completed window (declined profiler, stripped
build), provenance degrades to ``host_wall`` and throughput decides —
marked, never silent.
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import time

from .knobs import KnobConfig

__all__ = ["TrialResult", "run_trial", "trial_env",
           "measurement_from_artifact", "score", "last_json_line",
           "SCORE_SOURCES"]

# score provenance taxonomy (extra.autotune + trace_check)
SCORE_SOURCES = ("measured(profile)", "host_wall")

# env vars a trial must never inherit: every BENCH_* (the config IS the
# trial), the ambient knob spellings (the config pins them explicitly),
# and MXTPU_AUTOTUNE itself (a trial that re-entered the tuner would
# recurse)
_SCRUB_PREFIXES = ("BENCH_",)
_SCRUB_EXACT = ("MXTPU_AUTOTUNE", "MXTPU_LOOP_CHUNK", "MXTPU_REMAT",
                "MXTPU_REMAT_POLICY", "MXTPU_PREFETCH_DEPTH",
                "MXTPU_IO_WORKERS", "MXTPU_MESH", "MXTPU_PALLAS",
                "MXTPU_NO_PALLAS", "MXTPU_FORCE_PALLAS",
                "MXTPU_DEVICESCOPE", "MXTPU_MEMSCOPE",
                "MXTPU_MEMSCOPE_CAPACITY", "MXTPU_MEMSCOPE_HEADROOM",
                "MXTPU_MEMSCOPE_RING")


def _repo_root() -> str:
    return os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))


def last_json_line(stdout: str):
    """The last parseable JSON object line of a bench run's stdout (the
    bench contract: exactly one result line, possibly after logs)."""
    for ln in reversed((stdout or "").splitlines()):
        ln = ln.strip()
        if ln.startswith("{"):
            try:
                return json.loads(ln)
            except json.JSONDecodeError:
                continue
    return None


def _memscope_from_extra(extra: dict):
    """Pull the memory baseline the feasibility pruner scales from one
    BENCH artifact's ``extra.memscope``: the measured watermark peak
    when the ring saw the allocator (host RSS on backends whose devices
    report no memory_stats), else the largest static per-program
    footprint. None when the trial didn't arm memscope."""
    ms = extra.get("memscope")
    if not isinstance(ms, dict):
        return None
    peak, source = None, None
    wm = ms.get("watermarks") or {}
    for sect, tag in (("device", "watermark_device"),
                      ("host_rss", "watermark_host_rss")):
        s = wm.get(sect) if isinstance(wm, dict) else None
        p = s.get("peak") if isinstance(s, dict) else None
        if isinstance(p, (int, float)) and not isinstance(p, bool) \
                and p > 0:
            peak, source = int(p), tag
            break
    if peak is None:
        static = [r.get("peak_bytes") for r in (ms.get("programs") or [])
                  if isinstance(r, dict)
                  and isinstance(r.get("peak_bytes"), (int, float))
                  and not isinstance(r.get("peak_bytes"), bool)]
        if static:
            peak, source = int(max(static)), "static_footprint"
    cap = ms.get("capacity") if isinstance(ms.get("capacity"), dict) \
        else None
    batch = extra.get("batch")
    return {"peak_bytes": peak, "peak_source": source,
            "batch": (int(batch) if isinstance(batch, int)
                      and not isinstance(batch, bool) else None),
            "capacity": cap}


def measurement_from_artifact(doc: dict) -> dict:
    """Extract the scoring measurement from one BENCH artifact dict."""
    extra = (doc.get("extra") or {}) if isinstance(doc, dict) else {}
    ds = extra.get("devicescope") or {}
    bf = ds.get("busy_fraction")
    bf = float(bf) if isinstance(bf, (int, float)) \
        and not isinstance(bf, bool) else None
    gaps = None
    starved_split = None
    if isinstance(ds.get("gaps"), dict):
        if isinstance(ds["gaps"].get("taxonomy"), dict):
            gaps = dict(ds["gaps"]["taxonomy"])
        if isinstance(ds["gaps"].get("input_starved_split"), dict):
            # per-stage ingest attribution (read/decode/transfer) —
            # lets prune_plan pick io_workers over prefetch_depth when
            # the starvation is a decode problem
            starved_split = dict(ds["gaps"]["input_starved_split"])
    dec = (extra.get("perfscope") or {}).get("decomposition") or {}
    mfu = extra.get("mfu")
    value = doc.get("value") if isinstance(doc, dict) else None
    return {
        "memscope": _memscope_from_extra(extra),
        "busy_fraction": bf,
        "gaps": gaps,
        "starved_split": starved_split,
        "step_ms": dec.get("step_ms"),
        "mfu": mfu if isinstance(mfu, (int, float)) else None,
        "mfu_if_removed": dec.get("mfu_if_removed"),
        "value": float(value) if isinstance(value, (int, float))
        and not isinstance(value, bool) else None,
        "provenance": ("measured(profile)" if bf is not None
                       else "host_wall"),
    }


def score(measurement) -> tuple:
    """Orderable score: (busy_fraction rounded to 2 decimals, headline
    throughput). The primary key is the MEASURED busy fraction — the
    chip's idleness is what the tuner exists to close — rounded so
    near-ties defer to throughput, which also guards the remat
    pathology (a recompute knob can RAISE busy fraction while lowering
    samples/sec; throughput breaks that tie the right way). A trial
    with no measured window scores busy as -1: any measured trial
    outranks it, and among unmeasured trials throughput decides."""
    m = measurement or {}
    bf = m.get("busy_fraction")
    busy_key = round(float(bf), 2) if isinstance(bf, (int, float)) \
        and not isinstance(bf, bool) else -1.0
    v = m.get("value")
    val_key = float(v) if isinstance(v, (int, float)) \
        and not isinstance(v, bool) else 0.0
    return (busy_key, val_key)


class TrialResult:
    """One executed (or failed) trial. ``status``: "ok" | "failed".
    Failed trials carry ``error`` and no measurement — a counted skip,
    never a crash (the subprocess contract)."""

    def __init__(self, config, status, measurement=None, error=None,
                 wall_s=None, artifact=None, knob=None, value=None):
        self.config = config
        self.status = status
        self.measurement = measurement
        self.error = error
        self.wall_s = wall_s
        self.artifact = artifact
        self.knob = knob          # which coordinate move produced this
        self.value = value        # trial (None for the baseline)

    @property
    def ok(self) -> bool:
        return self.status == "ok"

    @property
    def score(self) -> tuple:
        return score(self.measurement)

    def row(self) -> dict:
        """The ``extra.autotune.trial_table`` row."""
        m = self.measurement or {}
        return {
            "knob": self.knob, "value": self.value,
            "config": self.config.to_dict() if self.config else None,
            "status": self.status,
            "busy_fraction": m.get("busy_fraction"),
            "step_ms": m.get("step_ms"),
            "mfu": m.get("mfu"),
            "throughput": m.get("value"),
            "provenance": m.get("provenance"),
            "wall_s": self.wall_s,
            "error": self.error,
        }


def trial_env(config=None, model=None, batch=None, dtype=None,
              steps=None, measure=True, extra_env=None,
              scrub_ambient=True) -> dict:
    """Build the subprocess environment for one trial: the parent's env
    with every BENCH_*/knob spelling scrubbed (driver parity — a stray
    BENCH_MODEL would silently mislabel every trial; the perf_sweep
    lesson), the config's canonical spellings exported, and — with
    ``measure=True`` — the measurement arming: one devicescope window
    (measured busy provenance), k=1 control off, Chrome trace off.
    ``extra_env`` applies LAST (the sweep's non-knob BENCH_K/BENCH_S2D
    rows ride there).

    ``scrub_ambient=False`` keeps the parent's MXTPU_* knob spellings
    (only BENCH_* is dropped, and MXTPU_AUTOTUNE still forced off) —
    the sweep's DRIVER-PARITY warm run: an operator's exported
    MXTPU_LOOP_CHUNK is part of the config the driver actually runs,
    and scrubbing it would silently mislabel the warm row. A search
    trial always scrubs: its config pins every knob explicitly."""
    scrub_exact = _SCRUB_EXACT if scrub_ambient else ("MXTPU_AUTOTUNE",)
    env = {k: v for k, v in os.environ.items()
           if not k.startswith(_SCRUB_PREFIXES) and k not in scrub_exact}
    env["MXTPU_AUTOTUNE"] = "0"
    if model:
        env["BENCH_MODEL"] = str(model)
    if batch:
        env["BENCH_BATCH"] = str(batch)
    if dtype:
        env["BENCH_DTYPE"] = str(dtype)
    if steps:
        env["BENCH_STEPS"] = str(steps)
    if measure:
        env["BENCH_DEVICESCOPE"] = "1"
        env["BENCH_DEVICESCOPE_STEPS"] = str(min(8, int(steps or 8)))
        # memscope rides the same measured trial: its watermark peak is
        # what the feasibility pruner scales for later batch candidates
        env["BENCH_MEMSCOPE"] = "1"
        env["BENCH_K1_CONTROL"] = "0"
        env["BENCH_TRACE"] = "0"
    if config is not None:
        env.update(config.to_env())
    for k, v in (extra_env or {}).items():
        env[k] = str(v)
    return env


def run_trial(config=None, *, model=None, batch=None, dtype=None,
              steps=12, timeout=900, measure=True, extra_env=None,
              bench_path=None, knob=None, value=None,
              scrub_ambient=True) -> TrialResult:
    """Execute one trial: bench.py in a subprocess under ``timeout``
    seconds, measurement read from its BENCH json line. NEVER raises —
    a timeout, a crash, an env_failure artifact, or garbage output all
    return ``status="failed"`` with the reason (the counted-skip
    contract; the search and the sweep both depend on a dead trial
    being data, not an exception).

    ``config=None`` exports NO knob env at all (bench resolves its own
    defaults) — the sweep's driver-parity warm run; a search trial
    always passes an explicit config so the trial is fully pinned."""
    env = trial_env(config, model=model, batch=batch, dtype=dtype,
                    steps=steps, measure=measure, extra_env=extra_env,
                    scrub_ambient=scrub_ambient)
    bench = bench_path or os.path.join(_repo_root(), "bench.py")
    t0 = time.time()
    try:
        r = subprocess.run([sys.executable, bench], timeout=timeout,
                           capture_output=True, text=True,
                           cwd=os.path.dirname(bench) or ".", env=env)
    except subprocess.TimeoutExpired:
        return TrialResult(config, "failed", knob=knob, value=value,
                           wall_s=round(time.time() - t0, 1),
                           error=f"trial timed out after {timeout}s")
    except OSError as e:
        return TrialResult(config, "failed", knob=knob, value=value,
                           error=f"could not spawn trial: {e}")
    wall = round(time.time() - t0, 1)
    doc = last_json_line(r.stdout)
    if doc is None:
        return TrialResult(
            config, "failed", knob=knob, value=value, wall_s=wall,
            error=f"no JSON line (rc={r.returncode}); stderr tail: "
                  f"{(r.stderr or '')[-200:]}")
    if doc.get("status") == "env_failure" or doc.get("error"):
        return TrialResult(
            config, "failed", knob=knob, value=value, wall_s=wall,
            artifact=doc,
            error=str(doc.get("error") or "env_failure")[:200])
    value_num = doc.get("value")
    if not isinstance(value_num, (int, float)) or value_num <= 0:
        return TrialResult(config, "failed", knob=knob, value=value,
                           wall_s=wall, artifact=doc,
                           error=f"non-positive value {value_num!r}")
    return TrialResult(config, "ok",
                       measurement=measurement_from_artifact(doc),
                       artifact=doc, wall_s=wall, knob=knob, value=value)
