"""mxtpu.autotune.tuner — the bounded, measurement-pruned trial loop.

(The module is named ``tuner`` rather than ``search`` so importing it
can never shadow the package-level ``autotune.search()`` function —
package globals ARE package attributes.)

``search()`` closes the loop ROADMAP item 4 names: it SPENDS the
observability stack instead of re-reporting it. The flow:

1. **cache first** — a stored winner for (fingerprint, mesh, device
   kind) returns immediately: ``cache_hit=True, trials=0`` (the
   every-later-run-starts-tuned contract).
2. **baseline trial** — the DEFAULT config (stepwise dispatch, depth-2
   prefetch, pallas auto) runs once so every later comparison has a
   measured anchor, and so the winner can never be worse than the
   default: the baseline is a candidate like any other.
3. **prune** — the baseline's devicescope idle-gap taxonomy and
   perfscope counterfactuals cut the knob families that cannot help
   (:mod:`.space`): input-starved prunes the remat axis, device-bound
   prunes the dispatch axes, a weak collective counterfactual prunes
   the mesh axis. Pruned candidates are COUNTED, with reasons.
4. **bounded coordinate trials** — one-knob-at-a-time moves off the
   baseline, most promising family first, until the trial budget is
   exhausted. Budget exhaustion returns best-so-far (pinned by test);
   a dead trial is a counted skip, never a crash.
5. **persist** — the winner lands in the tuning cache with its full
   measurement provenance and trial table.

Everything lands in the ``autotune.*`` counter family and the
``extra.autotune`` BENCH payload (``SearchResult.to_extra``).
"""
from __future__ import annotations

from . import space as _space
from .cache import TuningCache, current_device_kind, fingerprint
from .knobs import KnobConfig
from .trial import run_trial

__all__ = ["search", "SearchResult"]


def _counter(name):
    from ..profiler import counter as _c
    return _c(name, "autotune")


def _gauge(name, value):
    try:
        from ..profiler import set_gauge as _g
        _g(name, value, "autotune")
    except Exception:  # noqa: BLE001
        pass


def _feasibility_check(knob, value, baseline):
    """memscope's memory-feasibility verdict for one candidate move;
    fails open (feasible) if memscope is absent or errors."""
    try:
        from ..memscope.feasibility import feasibility_check
        return feasibility_check(knob, value, baseline)
    except Exception:  # noqa: BLE001 — the pruner never blocks a trial
        return {"feasible": True, "reason": None}


class SearchResult:
    """The outcome of one ``search()`` call (or one cache hit)."""

    def __init__(self, winner, score, cache_hit, trials, pruned,
                 diagnosis, default=None, budget=None, exhausted=False,
                 cache_info=None, error=None, cached_trials=None,
                 pruned_candidates=0):
        self.winner = winner              # KnobConfig | None
        self.score = score or {}          # winner measurement summary
        self.cache_hit = bool(cache_hit)
        self.trials = list(trials or [])  # trials run THIS search —
        #                                   empty on a cache hit (the
        #                                   hit=0-trials contract); the
        #                                   stored table rides below
        self.cached_trials = list(cached_trials or [])
        self.pruned = dict(pruned or {})
        # candidate VALUES the pruned knob families would have tried —
        # the same number the autotune.trials_pruned counter carries
        # (len(self.pruned) counts FAMILIES and always includes the
        # informational mesh/batch reasons; the two must not be
        # conflated in the published record)
        self.pruned_candidates = int(pruned_candidates)
        self.diagnosis = diagnosis
        self.default = default            # baseline measurement summary
        self.budget = budget
        self.exhausted = bool(exhausted)
        self.cache_info = dict(cache_info or {})
        self.error = error

    @property
    def trials_attempted(self) -> int:
        return len(self.trials)

    @property
    def trials_failed(self) -> int:
        n = 0
        for t in self.trials:
            status = t.get("status") if isinstance(t, dict) else t.status
            n += status == "failed"
        return n

    def trial_rows(self):
        """Rows for rendering: this search's trials, or — on a cache
        hit — the table the entry was stored with."""
        rows = self.trials or self.cached_trials
        return [t if isinstance(t, dict) else t.row() for t in rows]

    def to_extra(self) -> dict:
        """The ``extra.autotune`` BENCH payload (validated by
        tools/trace_check.py check_autotune_extra)."""
        return {
            "enabled": True,
            "cache_hit": self.cache_hit,
            "trials": self.trials_attempted,
            "trials_failed": self.trials_failed,
            "trials_pruned": self.pruned_candidates,
            "budget": self.budget,
            "budget_exhausted": self.exhausted,
            "diagnosis": self.diagnosis,
            "winner": self.winner.to_dict() if self.winner else None,
            "score": dict(self.score) or None,
            "default": dict(self.default) if self.default else None,
            "pruned": dict(self.pruned),
            "trial_table": self.trial_rows(),
            "cache": dict(self.cache_info),
            "error": self.error,
        }


def _measurement_summary(m) -> dict:
    m = m or {}
    return {"busy_fraction": m.get("busy_fraction"),
            "step_ms": m.get("step_ms"), "mfu": m.get("mfu"),
            "value": m.get("value"),
            "provenance": m.get("provenance", "host_wall")}


def search(model="lenet", batch=None, dtype=None, steps=12, budget=6,
           mesh=None, device_kind=None, runner=None, cache=None,
           cache_dir=None, use_cache=True, trial_timeout=900,
           extra_env=None, mesh_candidates=(), batch_candidates=(),
           log=None) -> SearchResult:
    """Tune the knob space for one (model, mesh, device-kind) key.

    ``budget``: max trials EXECUTED (baseline included). ``runner``:
    injectable ``f(config, knob, value) -> TrialResult`` — tests drive
    the search against deterministic fake measurements; the default is
    the subprocess bench runner (:func:`..trial.run_trial`). Never
    raises on trial failure; returns best-so-far whatever happens."""
    log = log or (lambda msg: None)
    cache = cache or TuningCache(cache_dir)
    fp = fingerprint(tag=model, batch=batch, dtype=dtype)
    dk = device_kind or current_device_kind()
    mesh = str(mesh).strip() if mesh else None
    cache_info = {"fingerprint": fp, "mesh": mesh, "device_kind": dk,
                  "path": cache.path_for(fp, mesh, dk),
                  "rejects": 0}
    _counter("autotune.searches").increment()

    if use_cache:
        rejects0 = cache.rejects
        entry = cache.lookup(fp, mesh, dk)
        cache_info["rejects"] = cache.rejects - rejects0
        if entry is not None:
            _counter("autotune.cache_hits").increment()
            log(f"autotune: cache HIT ({cache_info['path']}) -> "
                f"{entry['winner']} with 0 trials")
            winner = KnobConfig.from_dict(entry["winner"])
            sc = entry.get("score") or {}
            if isinstance(sc.get("busy_fraction"), (int, float)):
                _gauge("autotune.best_busy_fraction",
                       sc["busy_fraction"])
            return SearchResult(
                winner=winner, score=sc, cache_hit=True, trials=[],
                cached_trials=entry.get("trials") or [], pruned={},
                diagnosis=entry.get("diagnosis"),
                default=entry.get("default"), budget=budget,
                cache_info=dict(cache_info, hit=True))
        _counter("autotune.cache_misses").increment()
    cache_info["hit"] = False

    runner = runner or (
        lambda cfg, knob=None, value=None: run_trial(
            cfg, model=model, batch=batch, dtype=dtype, steps=steps,
            timeout=trial_timeout, extra_env=extra_env,
            knob=knob, value=value))

    budget = max(1, int(budget))
    trials, best = [], None

    def execute(cfg, knob=None, value=None):
        nonlocal best
        _counter("autotune.trials").increment()
        try:
            r = runner(cfg, knob=knob, value=value)
        except Exception as e:  # noqa: BLE001 — a dead trial is data
            from .trial import TrialResult
            r = TrialResult(cfg, "failed", knob=knob, value=value,
                            error=f"runner raised "
                                  f"{type(e).__name__}: {e}"[:200])
        trials.append(r)
        if r.ok:
            if best is None or r.score > best.score:
                best = r
            m = r.measurement or {}
            log(f"autotune trial [{r.config.describe()}]: "
                f"busy={m.get('busy_fraction')} "
                f"value={m.get('value')} ({m.get('provenance')})")
        else:
            _counter("autotune.trials_failed").increment()
            log(f"autotune trial [{cfg.describe()}] FAILED: {r.error}")
        return r

    # 1. baseline: the default config anchors every comparison and
    # guarantees winner >= default under the score order
    default_cfg = KnobConfig(mesh=mesh, batch=batch)
    base = execute(default_cfg)

    # 2. prune the space with the baseline's measurement (a dead
    # baseline prunes nothing: there is nothing to prune WITH)
    plan = _space.prune_plan(base.measurement if base.ok else None,
                             mesh_candidates=mesh_candidates,
                             batch_candidates=batch_candidates)
    cands = _space.candidates(default_cfg, plan,
                              mesh_candidates=mesh_candidates,
                              batch_candidates=batch_candidates)
    # pruned-candidate accounting: every value the cut knob families
    # would have tried is a trial NOT spent (the counter the smoke and
    # mxdiag report)
    n_pruned_cands = sum(
        max(0, len(_space.SPACE.get(k) or ()) - 1)
        for k in plan["pruned"] if k in _space.SPACE)
    if n_pruned_cands > 0:
        _counter("autotune.trials_pruned").increment(n_pruned_cands)
    log(f"autotune: diagnosis={plan['diagnosis']} "
        f"allowed={plan['allowed']} "
        f"pruned={sorted(plan['pruned'])} "
        f"({len(cands)} candidates, budget {budget})")

    # 2b. memory-feasibility baseline: the measured watermark peak from
    # the baseline trial (extra.memscope), joined with the config facts
    # the prediction scales over. Missing pieces disable the pruner —
    # it only ever rejects what it can defend.
    mem_base = None
    if base.ok and isinstance(base.measurement, dict):
        msm = base.measurement.get("memscope")
        if isinstance(msm, dict) and msm.get("peak_bytes"):
            mem_base = {"peak_bytes": msm["peak_bytes"],
                        "batch": msm.get("batch") or default_cfg.batch,
                        "remat": bool(default_cfg.remat)}

    # 2c. memory-feasibility gate, BEFORE the budget is spent: a
    # candidate whose predicted peak cannot fit under capacity x
    # headroom is a counted pre-trial reject (reason=memory) — a whole
    # subprocess trial saved, filed in plan["pruned"] beside the
    # knob-family prunes so the counter==payload contract holds. The
    # gate runs over EVERY candidate (a reject is free), so budget
    # exhaustion can never leave an infeasible candidate unjudged.
    if mem_base is not None:
        feasible = []
        for knob, value, cfg in cands:
            verdict = _feasibility_check(knob, value, mem_base)
            if verdict["feasible"]:
                feasible.append((knob, value, cfg))
                continue
            plan["pruned"][f"{knob}={value}"] = verdict["reason"]
            n_pruned_cands += 1
            _counter("autotune.trials_pruned").increment()
            log(f"autotune: candidate {knob}={value} pruned pre-trial "
                f"({verdict['reason']})")
        cands = feasible

    # 3. bounded coordinate moves, best-so-far under budget
    exhausted = False
    for knob, value, cfg in cands:
        if len(trials) >= budget:
            exhausted = True
            log(f"autotune: budget {budget} exhausted with "
                f"{len(cands) - (len(trials) - 1)} candidates untried "
                f"-> best-so-far")
            break
        execute(cfg, knob=knob, value=value)

    if best is None:
        log("autotune: every trial failed; nothing to cache")
        return SearchResult(
            winner=None, score=None, cache_hit=False, trials=trials,
            pruned=plan["pruned"], diagnosis=plan["diagnosis"],
            budget=budget, exhausted=exhausted, cache_info=cache_info,
            error="every trial failed",
            pruned_candidates=n_pruned_cands)

    bm = _measurement_summary(best.measurement)
    dm = _measurement_summary(base.measurement) if base.ok else None
    if isinstance(bm.get("busy_fraction"), (int, float)):
        _gauge("autotune.best_busy_fraction", bm["busy_fraction"])
    _gauge("autotune.trials_last_search", len(trials))

    # 4. persist the winner with provenance
    if use_cache:
        cache.store(fp, mesh, dk, best.config, score=bm, default=dm,
                    trials=[t.row() for t in trials],
                    diagnosis=plan["diagnosis"],
                    provenance=bm.get("provenance"))
    log(f"autotune: winner [{best.config.describe()}] "
        f"busy={bm.get('busy_fraction')} value={bm.get('value')} "
        f"({len(trials)} trials, {len(plan['pruned'])} knob(s) pruned)")
    return SearchResult(
        winner=best.config, score=bm, cache_hit=False, trials=trials,
        pruned=plan["pruned"], diagnosis=plan["diagnosis"], default=dm,
        budget=budget, exhausted=exhausted, cache_info=cache_info,
        pruned_candidates=n_pruned_cands)
