"""mxtpu.autotune.cache — persisted tuning winners with provenance.

One JSON file per key under ``MXTPU_AUTOTUNE_CACHE`` (default
``~/.cache/mxtpu/autotune``), keyed by **(model fingerprint, mesh
shape, device kind)** — the three things that change what the right
knobs are. Every entry carries the FULL measurement provenance (winner
score + the default config's measurement + the trial table), so a
cached decision is always auditable: ``mxdiag.py tune`` renders a
cache-hit run's winner-vs-default delta from the entry alone.

Trust rules (pinned by tests):

* a corrupt file (unreadable JSON, wrong shape) is REJECTED and counted
  (``autotune.cache_rejects``), never raised through;
* a schema bump rejects old entries — a future format change re-tunes
  rather than guessing at field meanings;
* the entry's OWN recorded key fields must match the lookup (device
  kind above all: a winner tuned on CPU must never configure a TPU run
  — same mesh, same fingerprint rules);
* writes are atomic (tmp + rename): a torn write is never a valid
  entry.
"""
from __future__ import annotations

import hashlib
import json
import os
import tempfile
import time

from .knobs import KnobConfig

__all__ = ["TuningCache", "SCHEMA", "fingerprint",
           "current_device_kind", "normalize_device_kind"]

SCHEMA = "mxtpu.autotune-cache/1"


def fingerprint(model=None, tag=None, batch=None, dtype=None) -> str:
    """Model fingerprint for the cache key. ``model`` (a Gluon Block):
    structural sha over sorted (param name, shape, dtype) — two nets
    with the same architecture tune identically; ``tag``: a caller
    label (the bench model tag) used as-is. Batch and dtype fold in —
    geometry changes the right knobs."""
    if model is not None and hasattr(model, "collect_params"):
        h = hashlib.sha256()
        params = model.collect_params()
        # creation-order (index, shape, dtype), NOT param names: gluon
        # auto-names count globally (dense0, dense1, ...), so two
        # identical nets built in one process would otherwise never
        # share a cache key
        for i, name in enumerate(params.keys()):
            p = params[name]
            h.update(f"{i}:{getattr(p, 'shape', None)}:"
                     f"{getattr(p, 'dtype', None)};".encode())
        tag = f"{tag or type(model).__name__}-{h.hexdigest()[:12]}"
    parts = [str(tag or "model")]
    if batch:
        parts.append(f"b{int(batch)}")
    if dtype:
        parts.append(str(dtype))
    return "|".join(parts)


def normalize_device_kind(kind) -> str:
    """Canonical device-kind spelling for cache keys: lowercased,
    stripped. jax reports 'TPU v4' raw while perfscope's peaks table
    records 'tpu v4' — every key producer (the tuner, bench, the
    sweep's artifact-derived ingestion) must land on ONE spelling or
    sweep-stored winners are never found by the driver's lookup."""
    return str(kind or "unknown").strip().lower() or "unknown"


def current_device_kind() -> str:
    """The attached device's kind string (the cache-key leg that keeps a
    CPU-tuned winner off a TPU run), normalized. "unknown" when no
    backend — an unknown kind still caches consistently within one
    environment."""
    try:
        import jax
        return normalize_device_kind(jax.devices()[0].device_kind)
    except Exception:  # noqa: BLE001
        return "unknown"


def _count_reject():
    try:
        from ..profiler import counter as _counter
        _counter("autotune.cache_rejects", "autotune").increment()
    except Exception:  # noqa: BLE001
        pass


class TuningCache:
    """File-backed winner store. All methods are best-effort: IO errors
    degrade to a miss (the tuner re-searches), never to a crash."""

    def __init__(self, root=None):
        from .knobs import env_str
        self.root = (root
                     or env_str("MXTPU_AUTOTUNE_CACHE")
                     or os.path.join(os.path.expanduser("~"), ".cache",
                                     "mxtpu", "autotune"))
        self.rejects = 0          # this instance's rejected-entry count

    # -- keying -----------------------------------------------------------
    @staticmethod
    def _norm_mesh(mesh):
        return str(mesh).strip() if mesh else None

    def path_for(self, fp: str, mesh, device_kind: str) -> str:
        key = (f"{fp}|{self._norm_mesh(mesh)}|"
               f"{normalize_device_kind(device_kind)}")
        h = hashlib.sha256(key.encode()).hexdigest()[:16]
        return os.path.join(self.root, f"at_{h}.json")

    # -- read -------------------------------------------------------------
    def lookup(self, fp: str, mesh, device_kind: str):
        """The stored entry for this key, or None (miss). Corrupt and
        stale entries are rejected + counted, and report as a miss."""
        path = self.path_for(fp, mesh, device_kind)
        if not os.path.exists(path):
            return None
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            self._reject(path, "unreadable/invalid JSON")
            return None
        if not isinstance(doc, dict):
            self._reject(path, "not a JSON object")
            return None
        if doc.get("schema") != SCHEMA:
            self._reject(path, f"schema {doc.get('schema')!r} != "
                               f"{SCHEMA!r} (schema bump: re-tune)")
            return None
        # the entry's own key fields must MATCH the lookup — the hash is
        # an address, not a proof; device kind is the safety-critical leg
        for field, want in (("fingerprint", fp),
                            ("mesh", self._norm_mesh(mesh)),
                            ("device_kind",
                             normalize_device_kind(device_kind))):
            if doc.get(field) != want:
                self._reject(path, f"{field} mismatch: entry "
                                   f"{doc.get(field)!r} vs lookup "
                                   f"{want!r}")
                return None
        try:
            KnobConfig.from_dict(doc.get("winner"))
        except ValueError as e:
            self._reject(path, f"unparseable winner config: {e}")
            return None
        return doc

    def _reject(self, path, why):
        self.rejects += 1
        _count_reject()
        import warnings
        warnings.warn(f"autotune cache entry {path} rejected ({why}); "
                      f"treating as a miss — the tuner will re-search",
                      stacklevel=3)

    # -- write ------------------------------------------------------------
    def store(self, fp: str, mesh, device_kind: str, winner: KnobConfig,
              score: dict, default=None, trials=None, diagnosis=None,
              provenance=None):
        """Persist a winner with full measurement provenance. Atomic;
        best-effort (an unwritable cache dir costs persistence, not the
        run). Returns the entry dict (written or not)."""
        entry = {
            "schema": SCHEMA,
            "fingerprint": fp,
            "mesh": self._norm_mesh(mesh),
            "device_kind": normalize_device_kind(device_kind),
            "winner": winner.to_dict(),
            "score": dict(score or {}),
            "default": dict(default) if default else None,
            "diagnosis": diagnosis,
            "provenance": provenance
            or (score or {}).get("provenance"),
            "trials": list(trials or []),
            "created": time.strftime("%Y-%m-%dT%H:%M:%S"),
        }
        path = self.path_for(fp, mesh, device_kind)
        try:
            os.makedirs(self.root, exist_ok=True)
            fd, tmp = tempfile.mkstemp(dir=self.root, suffix=".tmp")
            with os.fdopen(fd, "w") as f:
                json.dump(entry, f, indent=1)
            os.replace(tmp, path)          # atomic: torn write != entry
        except OSError as e:
            import warnings
            warnings.warn(f"autotune cache write failed ({e}); winner "
                          f"not persisted", stacklevel=2)
        return entry

    # -- sweep ingestion --------------------------------------------------
    def ingest(self, results, fp: str, mesh, device_kind: str):
        """Adopt the best OK trial of a manual sweep
        (tools/perf_sweep.py) as this key's winner — sweep rows and
        tuner trials are the same record shape by construction, so the
        manual protocol feeds the same cache the tuner reads. Returns
        the stored entry, or None when no usable trial."""
        from .trial import score as _score
        ok = [r for r in results if getattr(r, "ok", False)
              and r.config is not None]
        if not ok:
            return None
        best = max(ok, key=lambda r: _score(r.measurement))
        m = best.measurement or {}
        return self.store(
            fp, mesh, device_kind, best.config,
            score={"busy_fraction": m.get("busy_fraction"),
                   "step_ms": m.get("step_ms"), "mfu": m.get("mfu"),
                   "value": m.get("value"),
                   "provenance": m.get("provenance")},
            trials=[r.row() for r in results],
            provenance=m.get("provenance"))
