"""Runtime watermark timeline: a bounded ring of allocator samples.

Each sample is taken at an existing step mark (TrainLoop.run_chunk,
bench.py's steady loops, the serving batcher) and records what the XLA
allocator says each device holds RIGHT NOW — ``bytes_in_use`` and
``peak_bytes_in_use`` from ``device.memory_stats()`` via the
normalized :func:`profiler.device_memory_stats` helper — plus the host
RSS. Backends whose devices report nothing (XLA:CPU returns None) are
recorded ``{"available": false}`` per device and counted
``memscope.samples_unavailable``; the host RSS is still real there,
which is exactly the number that bounds a CPU tier-1 run.

The ring is bounded (``MXTPU_MEMSCOPE_RING``, default 256, oldest
evicted) so an armed long run cannot grow it; the summary feeds the
p50/p95/peak gauges and the headroom fraction, and the last few
samples — the *tail* — are what an OOM post-mortem attaches as "what
memory did in the steps before death".
"""
from __future__ import annotations

import os
import threading
import time
from collections import deque

from ..profiler.counters import counter as _counter, \
    set_gauge as _set_gauge

__all__ = ["WatermarkRing", "host_rss_bytes"]


def host_rss_bytes():
    """Current resident set size of this process in bytes, or None.
    /proc/self/statm is current truth; ru_maxrss (the fallback) is a
    peak, still useful as an upper bound on exotic platforms."""
    try:
        with open("/proc/self/statm") as f:
            pages = int(f.read().split()[1])
        return pages * os.sysconf("SC_PAGE_SIZE")
    except Exception:  # noqa: BLE001
        pass
    try:
        import resource
        return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss * 1024
    except Exception:  # noqa: BLE001
        return None


def _pct(vals, q):
    """Nearest-rank percentile over a small sample list, None on
    empty."""
    if not vals:
        return None
    s = sorted(vals)
    return s[min(len(s) - 1, int(round(q * (len(s) - 1))))]


class WatermarkRing:
    """The bounded per-step allocator-sample timeline."""

    def __init__(self, limit=256):
        try:
            self.limit = max(1, int(limit))
        except (TypeError, ValueError):
            self.limit = 256
        self._ring = deque(maxlen=self.limit)
        self._lock = threading.Lock()
        self.samples_total = 0

    def reset(self):
        with self._lock:
            self._ring.clear()
            self.samples_total = 0

    # -- ingestion ---------------------------------------------------------
    def sample(self, step=None, workload=None):
        """Take one sample. Never raises — this sits on the hot step
        path of armed runs."""
        try:
            return self._sample(step, workload)
        except Exception:  # noqa: BLE001 — sampling never breaks a step
            return None

    def _sample(self, step, workload):
        from ..profiler import device_memory_stats
        devices = {}
        available = False
        try:
            import jax
            local = jax.local_devices()
        except Exception:  # noqa: BLE001
            local = []
        for d in local:
            st = device_memory_stats(d)
            if not st or st.get("available") is False:
                devices[str(d)] = {"available": False}
                continue
            available = True
            devices[str(d)] = {
                "available": True,
                "bytes_in_use": st.get("bytes_in_use"),
                "peak_bytes_in_use": st.get("peak_bytes_in_use"),
                "bytes_limit": st.get("bytes_limit")}
        rec = {"step": None if step is None else int(step),
               "t": time.monotonic(),
               "workload": workload,
               "host_rss_bytes": host_rss_bytes(),
               "devices": devices,
               "available": available}
        with self._lock:
            self._ring.append(rec)
            self.samples_total += 1
        _counter("memscope.samples", "memscope").increment()
        if not available:
            _counter("memscope.samples_unavailable",
                     "memscope").increment()
        else:
            in_use = sum(d.get("bytes_in_use") or 0
                         for d in devices.values() if d.get("available"))
            peak = max((d.get("peak_bytes_in_use") or 0
                        for d in devices.values() if d.get("available")),
                       default=0)
            _set_gauge("memscope.bytes_in_use", in_use, "memscope")
            _set_gauge("memscope.peak_bytes_in_use", peak, "memscope")
        if rec["host_rss_bytes"]:
            _set_gauge("memscope.host_rss_bytes", rec["host_rss_bytes"],
                       "memscope")
        return rec

    # -- reporting ---------------------------------------------------------
    def snapshot(self) -> list:
        with self._lock:
            return [dict(r) for r in self._ring]

    def latest(self):
        with self._lock:
            return dict(self._ring[-1]) if self._ring else None

    def tail(self, n=8) -> list:
        with self._lock:
            return [dict(r) for r in list(self._ring)[-int(n):]]

    def summary(self) -> dict:
        """p50/p95/peak over the ring for device bytes and host RSS,
        plus the bound bookkeeping trace_check pins (``ring`` <=
        ``ring_limit`` even when ``samples`` exceeds it)."""
        snap = self.snapshot()
        dev_in_use, dev_peak, rss = [], [], []
        for r in snap:
            if r.get("available"):
                devs = [d for d in r.get("devices", {}).values()
                        if isinstance(d, dict) and d.get("available")]
                dev_in_use.append(sum(d.get("bytes_in_use") or 0
                                      for d in devs))
                dev_peak.append(max((d.get("peak_bytes_in_use") or 0
                                     for d in devs), default=0))
            if r.get("host_rss_bytes"):
                rss.append(r["host_rss_bytes"])
        out = {"samples": self.samples_total, "ring": len(snap),
               "ring_limit": self.limit,
               "available": bool(dev_in_use),
               "device": None, "host_rss": None,
               "tail": self.tail(8)}
        if dev_in_use:
            out["device"] = {"p50": _pct(dev_in_use, 0.50),
                             "p95": _pct(dev_in_use, 0.95),
                             "peak": max(dev_peak) if dev_peak else None,
                             "latest": dev_in_use[-1]}
            _set_gauge("memscope.bytes_p50", out["device"]["p50"],
                       "memscope")
            _set_gauge("memscope.bytes_p95", out["device"]["p95"],
                       "memscope")
        if rss:
            out["host_rss"] = {"p50": _pct(rss, 0.50),
                               "p95": _pct(rss, 0.95),
                               "peak": max(rss), "latest": rss[-1]}
        return out
