"""mxtpu.memscope — per-program memory footprints, device watermark
timelines, and OOM forensics.

The eighth observability layer (docs/observability.md). The earlier
layers explain *time* — perfscope's rooflines, devicescope's measured
timelines, commscope's collectives, servescope's request tails — but
*memory*, the resource that bounds every knob the autotuner searches
(batch × remat × mesh) and the classic way a TPU run dies
(``RESOURCE_EXHAUSTED`` with no attribution), had no layer. Memscope is
that layer:

* **static per-program footprints** (:mod:`.footprint`) — every
  perfscope compile site (FusedTrainStep, TrainLoop chunks, the
  hybridize jit cache, serving buckets) additionally captures XLA's
  ``compiled.memory_analysis()`` — argument / output / temp /
  generated-code bytes and the peak — into a program table joined to
  the roofline verdicts by name. Backends without the analysis are
  counted ``unavailable``, never raised.
* **runtime watermark timeline** (:mod:`.watermark`) — a bounded ring
  (``MXTPU_MEMSCOPE_RING``, default 256) of per-step-boundary
  ``device.memory_stats()`` samples (bytes_in_use, peak_bytes_in_use)
  plus host RSS, sampled at the existing step marks so the off path
  pays one predicate, feeding p50/p95/peak gauges and a headroom
  fraction.
* **OOM forensics** (:mod:`.forensics`) — a ``RESOURCE_EXHAUSTED`` /
  allocator-failure hook on the dispatch sites that assembles a
  post-mortem (the offending program's static footprint, the watermark
  tail, top-K live buffers from the diagnostics ledger, the resolved
  knob config) and lands it on the healthmon alert surfaces, so an OOM
  names its program instead of dying mute.
* **feasibility** (:mod:`.feasibility`) — the memory-feasibility math
  the autotuner's pre-trial pruner spends: a batch/remat candidate
  whose predicted peak exceeds device capacity ×
  ``MXTPU_MEMSCOPE_HEADROOM`` is a counted reject (``reason=memory``)
  before a subprocess trial is ever paid for; fleet/serving admission
  embeds the live headroom in deep ``/healthz`` so the router can
  weigh it.

Everything lands in the ``memscope.*`` counter family,
``extra.memscope`` in BENCH json, and ``tools/mxdiag.py mem``.

Fast-path contract: the single module global ``_MS`` (the perfscope /
commscope / devicescope discipline) — every passive hook costs one
predicate when memscope is off, and ingestion never raises.
"""
from __future__ import annotations

import os
import warnings

from ..diagnostics import flight as _flight
from ..profiler.counters import counter as _counter
from . import feasibility as _feasibility
from . import footprint as _footprint
from . import forensics as _forensics
from . import watermark as _watermark
from .feasibility import predict_candidate_peak, feasibility_check
from .footprint import capture, footprints, footprint_of
from .forensics import is_oom_error, post_mortem, record_oom, \
    last_post_mortem
from .watermark import WatermarkRing, host_rss_bytes

__all__ = ["enable", "disable", "enabled", "enable_from_env", "reset",
           "capture", "footprints", "footprint_of", "sample",
           "watermark_summary", "device_capacity", "headroom_target",
           "headroom_state", "register_analytic", "reconciliation",
           "bench_extra", "is_oom_error", "post_mortem", "record_oom",
           "last_post_mortem", "predict_candidate_peak",
           "feasibility_check", "WatermarkRing", "host_rss_bytes",
           "DRIFT_THRESHOLD", "DEFAULT_HEADROOM", "DEFAULT_RING"]

# analytic-vs-measured relative disagreement that fires the loud drift
# warning — deliberately the same 25% devicescope established, so one
# number means "an estimate went stale" across the whole layer map
DRIFT_THRESHOLD = 0.25

# usable fraction of device capacity: a candidate whose predicted peak
# exceeds capacity * headroom is infeasible (MXTPU_MEMSCOPE_HEADROOM)
DEFAULT_HEADROOM = 0.9

# watermark ring bound (MXTPU_MEMSCOPE_RING)
DEFAULT_RING = 256

# module global: None = memscope off (THE fast-path predicate)
_MS = None

# analytic per-device expectation registered by an FSDP-aware call site
# (bench.py hands fsdp.memory_report here) — the reconciliation's
# analytic side
_ANALYTIC = None


class _MemScope:
    """Marker object holding enable-time state (the perfscope
    module-global discipline). Owns the watermark ring."""

    def __init__(self, ring_limit=None):
        if ring_limit is None:
            from ..autotune.knobs import env_int
            ring_limit = env_int("MXTPU_MEMSCOPE_RING", DEFAULT_RING,
                                 on_error="default")
        self.ring = WatermarkRing(ring_limit)


def enable(ring_limit=None):
    """Arm memscope: perfscope's compile sites start capturing static
    footprints, the step marks start feeding the watermark ring, and
    the OOM guards start assembling post-mortems.

    Arms perfscope too when it is off — the footprint capture hook
    lives inside perfscope's analyze funnel (the commscope
    discipline), so memscope without perfscope would see no compiles.
    """
    global _MS
    try:
        from .. import perfscope as _ps
        if _ps._PS is None:
            _ps.enable()
    except Exception:  # noqa: BLE001 — arming must never raise
        pass
    _MS = _MemScope(ring_limit)
    return _MS


def disable():
    global _MS
    _MS = None


def enabled() -> bool:
    return _MS is not None


def enable_from_env():
    """MXTPU_MEMSCOPE=1 arms memscope at import (like MXTPU_PERFSCOPE /
    MXTPU_DEVICESCOPE)."""
    if os.environ.get("MXTPU_MEMSCOPE", "") == "1":
        enable()


def reset():
    """Test hook: drop the footprint table, the ring, the last
    post-mortem and any registered analytic expectation."""
    global _ANALYTIC
    _ANALYTIC = None
    _footprint.reset()
    _forensics.reset()
    if _MS is not None:
        _MS.ring.reset()


# ---------------------------------------------------------------------------
# watermark surface (delegates to the armed ring)
# ---------------------------------------------------------------------------

def sample(step=None, workload=None):
    """Take one watermark sample into the armed ring (the step-mark
    hook). No-op returning None when memscope is off. Never raises."""
    ms = _MS
    if ms is None:
        return None
    return ms.ring.sample(step=step, workload=workload)


def watermark_summary():
    """The armed ring's p50/p95/peak summary, or the armed-but-empty
    shape; None when memscope is off."""
    ms = _MS
    if ms is None:
        return None
    return ms.ring.summary()


# ---------------------------------------------------------------------------
# capacity + headroom
# ---------------------------------------------------------------------------

def headroom_target() -> float:
    """Usable fraction of capacity (MXTPU_MEMSCOPE_HEADROOM, default
    0.9): predicted peaks above capacity * target are infeasible."""
    from ..autotune.knobs import env_float
    v = env_float("MXTPU_MEMSCOPE_HEADROOM", DEFAULT_HEADROOM,
                  on_error="default")
    try:
        v = float(v)
    except (TypeError, ValueError):
        return DEFAULT_HEADROOM
    return v if 0.0 < v <= 1.0 else DEFAULT_HEADROOM


def device_capacity() -> dict:
    """Per-accelerator memory capacity, ``{"bytes", "source"}`` (+
    ``per_device`` when the allocator reports limits).

    Resolution: ``MXTPU_MEMSCOPE_CAPACITY`` override >
    ``memory_stats()["bytes_limit"]`` (the tightest device bounds) >
    host RAM (the honest bound on XLA:CPU, where device stats are
    absent) > unknown. Never raises."""
    from ..autotune.knobs import env_int
    override = env_int("MXTPU_MEMSCOPE_CAPACITY", None,
                       on_error="default")
    if override:
        return {"bytes": int(override), "source": "env"}
    try:
        import jax
        per = {}
        for d in jax.local_devices():
            try:
                st = d.memory_stats()
            except Exception:  # noqa: BLE001
                st = None
            if st and st.get("bytes_limit"):
                per[str(d)] = int(st["bytes_limit"])
        if per:
            return {"bytes": min(per.values()),
                    "source": "memory_stats", "per_device": per}
    except Exception:  # noqa: BLE001
        pass
    try:
        cap = os.sysconf("SC_PHYS_PAGES") * os.sysconf("SC_PAGE_SIZE")
        if cap > 0:
            return {"bytes": int(cap), "source": "host_ram"}
    except (ValueError, OSError, AttributeError):
        pass
    return {"bytes": None, "source": "unknown"}


def headroom_state() -> dict:
    """Live headroom verdict: how much of capacity is in use right now,
    and whether the configured target still holds.

    ``in_use`` pairs with its matching capacity source — device
    bytes_in_use against the allocator limit when the backend reports
    both, host RSS against host RAM on backends (XLA:CPU) that report
    neither — so the fraction always compares like with like."""
    cap = device_capacity()
    target = headroom_target()
    out = {"capacity_bytes": cap.get("bytes"),
           "capacity_source": cap.get("source"),
           "in_use_bytes": None, "in_use_source": None,
           "headroom_fraction": None, "target": target,
           "verdict": "unknown"}
    ms = _MS
    latest = ms.ring.latest() if ms is not None else None
    in_use = None
    if latest is not None and latest.get("available"):
        vals = [d.get("bytes_in_use") or 0
                for d in latest.get("devices", {}).values()
                if isinstance(d, dict) and d.get("bytes_in_use")]
        if vals:
            in_use = max(vals)
            out["in_use_source"] = "memory_stats"
    if in_use is None:
        rss = latest.get("host_rss_bytes") if latest is not None \
            else host_rss_bytes()
        if rss and cap.get("source") in ("host_ram", "env", "unknown"):
            in_use = rss
            out["in_use_source"] = "host_rss"
    if in_use is not None and cap.get("bytes"):
        out["in_use_bytes"] = int(in_use)
        frac = 1.0 - float(in_use) / float(cap["bytes"])
        out["headroom_fraction"] = round(max(0.0, frac), 6)
        out["verdict"] = "ok" if float(in_use) <= cap["bytes"] * target \
            else "tight"
        try:
            from ..profiler.counters import set_gauge as _set_gauge
            _set_gauge("memscope.headroom_fraction",
                       out["headroom_fraction"], "memscope")
        except Exception:  # noqa: BLE001
            pass
    return out


# ---------------------------------------------------------------------------
# analytic-vs-measured reconciliation
# ---------------------------------------------------------------------------

def register_analytic(report, source="fsdp.memory_report"):
    """Hand memscope an analytic per-device expectation (bench.py calls
    this with ``parallel/fsdp.memory_report`` under fsdp meshes) — the
    reconciliation's analytic side. Never raises; a malformed report is
    dropped."""
    global _ANALYTIC
    try:
        if not isinstance(report, dict):
            return
        per = report.get("param_bytes_per_device")
        state = report.get("state_bytes_per_device")
        if per is None:
            return
        _ANALYTIC = {"param_bytes_per_device": int(per),
                     "state_bytes_per_device": int(state or 0),
                     "total_per_device": int(per) + int(state or 0),
                     "reduction": report.get("reduction"),
                     "source": source}
    except Exception:  # noqa: BLE001 — registration never breaks callers
        _ANALYTIC = None


def reconciliation() -> dict:
    """Analytic per-device bytes (fsdp.memory_report, when registered)
    BESIDE the measured truth — watermark device peaks when the
    allocator reports them, the diagnostics ledger's sharding-aware
    live census otherwise — with the devicescope drift discipline:
    >25% disagreement fires the loud warning, and the analytic number
    stays in the block either way."""
    measured = {"peak_bytes_in_use": None, "per_device_live_bytes": None,
                "source": None}
    ms = _MS
    if ms is not None:
        s = ms.ring.summary()
        dev = (s or {}).get("device") or {}
        if dev.get("peak"):
            measured["peak_bytes_in_use"] = dev["peak"]
            measured["source"] = "memory_stats"
    if measured["source"] is None:
        try:
            from ..diagnostics.memory import reconcile as _ledger_rec
            rec = _ledger_rec()
            per = rec.get("per_device_live_bytes")
            if per:
                measured["per_device_live_bytes"] = dict(per)
                measured["peak_bytes_in_use"] = max(per.values())
                measured["source"] = "ledger_census"
        except Exception:  # noqa: BLE001
            pass
    out = {"analytic": dict(_ANALYTIC) if _ANALYTIC else None,
           "measured": measured,
           "drift": None, "threshold": DRIFT_THRESHOLD,
           "drift_warning": False}
    if _ANALYTIC and measured["peak_bytes_in_use"]:
        analytic = float(_ANALYTIC["total_per_device"])
        meas = float(measured["peak_bytes_in_use"])
        if analytic > 1e-9:
            drift = abs(meas - analytic) / analytic
            out["drift"] = {"per_device_bytes": round(drift, 6)}
            if drift > DRIFT_THRESHOLD:
                out["drift_warning"] = True
                _warn_drift(analytic, meas, drift)
    return out


def _warn_drift(analytic, measured, drift):
    """The loud estimate-went-stale signal: counter + flight breadcrumb
    + structured event + Python warning. Never raises."""
    try:
        _counter("memscope.drift_warnings", "memscope").increment()
        if _flight._REC is not None:
            _flight.record("alert", "memscope.drift", {
                "analytic_bytes": analytic, "measured_bytes": measured,
                "drift": round(drift, 4),
                "threshold": DRIFT_THRESHOLD})
        try:
            from .. import healthmon as _hm
            if _hm._HM is not None:
                _hm._HM.events.emit(
                    "alert", "memscope.drift",
                    args={"analytic_bytes": analytic,
                          "measured_bytes": measured,
                          "threshold": DRIFT_THRESHOLD})
        except Exception:  # noqa: BLE001
            pass
        warnings.warn(
            f"memscope: analytic per-device bytes "
            f"({analytic / 2**20:.1f} MiB) and measured peak "
            f"({measured / 2**20:.1f} MiB) disagree by {drift:.0%} "
            f"(threshold {DRIFT_THRESHOLD:.0%}) — the FSDP memory "
            f"claim has gone stale against the allocator; trust the "
            f"measurement (docs/memscope.md)", stacklevel=3)
    except Exception:  # noqa: BLE001 — warning plumbing must never raise
        pass


# ---------------------------------------------------------------------------
# bench payload
# ---------------------------------------------------------------------------

def _programs_joined() -> list:
    """The footprint table with each record joined to its perfscope
    roofline verdict by name (the memscope-perfscope join key)."""
    progs = footprints()
    roof = {}
    try:
        from ..perfscope import cost as _cost
        roof = {r.get("name"): r for r in _cost.programs()}
    except Exception:  # noqa: BLE001
        roof = {}
    for rec in progs:
        r = roof.get(rec.get("name"))
        rec["roofline"] = r.get("verdict") if r else None
    return progs


def bench_extra() -> dict:
    """The ``extra.memscope`` payload for BENCH json: the footprint
    table joined to the roofline verdicts, the watermark summary, the
    capacity/headroom verdict, the analytic-vs-measured
    reconciliation, and the last OOM post-mortem (usually None)."""
    return {"programs": _programs_joined(),
            "watermarks": watermark_summary(),
            "capacity": device_capacity(),
            "headroom": headroom_state(),
            "reconciliation": reconciliation(),
            "oom": last_post_mortem()}
