"""Static per-program memory footprints from ``compiled.memory_analysis()``.

Every compiled hot program already passes through perfscope's analyze
funnel (the HybridBlock jit cache, FusedTrainStep's programs, TrainLoop
chunks, FrozenModel serving buckets). When memscope is armed, that
funnel's ``_memscope_capture`` hook hands each program here, and XLA's
compiled-executable memory analysis — argument / output / temp /
alias / generated-code bytes, plus the peak — lands in a process-wide
table keyed by program name, the same key perfscope's roofline table
uses, so ``extra.memscope.programs`` joins the two for free.

Acquisition follows commscope's discipline: a site that already holds
the compiled executable (serving buckets) passes it and the analysis is
free; a site that only lowered pays one extra host-side XLA compile —
which is why memscope is off by default and armed per bench run.

Peak provenance is a CLOSED taxonomy (trace_check pins it):

* ``reported`` — the backend's analysis carried an explicit peak field;
* ``derived`` — no peak field (CPU jaxlib): peak approximated as
  argument + output + temp + generated-code bytes;
* ``unavailable`` — no executable or no analysis on this backend:
  counted ``memscope.capture_unknown``, never raised.
"""
from __future__ import annotations

import threading

from ..diagnostics import flight as _flight
from ..profiler.counters import counter as _counter

__all__ = ["capture", "footprints", "footprint_of", "reset",
           "FOOTPRINT_PROVENANCE", "BYTE_FIELDS"]

FOOTPRINT_PROVENANCE = ("reported", "derived", "unavailable")

# normalized field -> attribute spellings across jaxlib versions (the
# device_memory_stats key-normalization discipline, compile-side)
_FIELD_CANDIDATES = {
    "argument_bytes": ("argument_size_in_bytes", "arg_size_in_bytes"),
    "output_bytes": ("output_size_in_bytes",),
    "temp_bytes": ("temp_size_in_bytes",),
    "alias_bytes": ("alias_size_in_bytes",),
    "generated_code_bytes": ("generated_code_size_in_bytes",
                             "code_size_in_bytes"),
}

BYTE_FIELDS = tuple(_FIELD_CANDIDATES)

# explicit peak spellings (absent on CPU jaxlib: peak is then derived)
_PEAK_CANDIDATES = ("peak_memory_in_bytes", "peak_memory_bytes")

# process-wide table: name -> record (last analysis wins per name — the
# perfscope _PROGRAMS discipline, recompiles overwrite)
_FOOTPRINTS: "dict[str, dict]" = {}
_flock = threading.Lock()


def footprints() -> list:
    """Snapshot of every captured footprint, insertion-ordered."""
    with _flock:
        return [dict(v) for v in _FOOTPRINTS.values()]


def footprint_of(name):
    """The captured footprint record for one program name, or None."""
    with _flock:
        rec = _FOOTPRINTS.get(name)
        return dict(rec) if rec is not None else None


def reset() -> None:
    with _flock:
        _FOOTPRINTS.clear()


def _read_bytes(ma, spellings):
    for attr in spellings:
        v = getattr(ma, attr, None)
        if isinstance(v, (int, float)) and v >= 0:
            return int(v)
    return None


def _unavailable(name, kind) -> dict:
    return {"name": name, "kind": kind, "available": False,
            "provenance": "unavailable", "peak_bytes": None,
            **{f: None for f in BYTE_FIELDS}}


def capture(name, lowered=None, compiled=None, kind="program"):
    """Capture one program's static memory footprint. Never raises —
    called from inside compile sites via perfscope's hook, where an
    analysis failure must not break the compile. Returns the stored
    record (an ``unavailable`` record when the backend has no
    analysis), or None on an internal error."""
    try:
        return _capture(str(name), lowered, compiled, str(kind))
    except Exception:  # noqa: BLE001 — ingestion never raises
        try:
            _counter("memscope.capture_errors", "memscope").increment()
        except Exception:  # noqa: BLE001
            pass
        return None


def _capture(name, lowered, compiled, kind):
    if compiled is None and lowered is not None:
        # the commscope acquisition pattern: pay one host-side compile
        # to read the optimized executable (why memscope is opt-in)
        try:
            compiled = lowered.compile()
        except Exception:  # noqa: BLE001 — backend-dependent surface
            compiled = None
    ma = None
    if compiled is not None:
        try:
            ma = compiled.memory_analysis()
        except Exception:  # noqa: BLE001 — absent on some backends
            ma = None
    if ma is None:
        rec = _unavailable(name, kind)
        _counter("memscope.capture_unknown", "memscope").increment()
    else:
        rec = {"name": name, "kind": kind, "available": True}
        for field, spellings in _FIELD_CANDIDATES.items():
            rec[field] = _read_bytes(ma, spellings)
        peak = _read_bytes(ma, _PEAK_CANDIDATES)
        if peak is not None:
            rec["peak_bytes"] = peak
            rec["provenance"] = "reported"
        else:
            rec["peak_bytes"] = sum(
                rec[f] or 0 for f in ("argument_bytes", "output_bytes",
                                      "temp_bytes",
                                      "generated_code_bytes"))
            rec["provenance"] = "derived"
        _counter("memscope.programs_captured", "memscope").increment()
        if _flight._REC is not None:
            # the compile span gains the footprint — a crash dump now
            # says how much memory each program wanted
            _flight.record("compile", f"memscope.footprint:{name}", {
                "peak_bytes": rec["peak_bytes"],
                "temp_bytes": rec["temp_bytes"],
                "argument_bytes": rec["argument_bytes"],
                "output_bytes": rec["output_bytes"],
                "provenance": rec["provenance"]})
    with _flock:
        _FOOTPRINTS[name] = rec
    return dict(rec)
