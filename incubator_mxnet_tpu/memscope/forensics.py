"""OOM forensics: when a run dies of RESOURCE_EXHAUSTED, name the killer.

The classic TPU death is an allocator failure with a raw XLA error
string and no attribution — which program, how big, what was already
live, which knobs produced the shape. The dispatch sites (TrainLoop,
FusedTrainStep) wrap their device calls with :func:`record_oom`: when
the escaping exception matches the allocator-failure taxonomy, a
post-mortem is assembled from evidence memscope already holds —

* the offending program's **static footprint** (what the compile said
  it would need),
* the **watermark tail** (what memory did in the steps before death),
* the **top-K live buffers** from the diagnostics ledger (who held the
  bytes),
* the **resolved knob config** (which batch/remat/mesh produced it),
* the **capacity** verdict,

— counted, breadcrumbed, and emitted on the healthmon alert surface,
then the exception re-raises unchanged. The last post-mortem rides
``extra.memscope.oom`` in BENCH json and renders via
``tools/mxdiag.py mem``. Assembly never raises: forensics on a dying
process must not replace the real error with its own.
"""
from __future__ import annotations

from ..diagnostics import flight as _flight
from ..profiler.counters import counter as _counter
from . import footprint as _footprint

__all__ = ["is_oom_error", "post_mortem", "record_oom",
           "last_post_mortem", "reset", "OOM_SCHEMA"]

OOM_SCHEMA = "mxtpu.memscope.oom/1"

# substrings (lowercased) that mark an allocator failure across
# backends: XLA's status code, the C++ allocator, plain host OOM
_OOM_MARKERS = ("resource_exhausted", "resource exhausted",
                "out of memory", "failed to allocate",
                "allocation failure", "bad_alloc")

_LAST_PM = None


def reset():
    global _LAST_PM
    _LAST_PM = None


def last_post_mortem():
    """The most recent OOM post-mortem dict, or None."""
    return _LAST_PM


def is_oom_error(exc) -> bool:
    """Is this exception an allocator failure? Matches the
    RESOURCE_EXHAUSTED taxonomy on the message (XlaRuntimeError carries
    the status code in its text) and plain MemoryError. Never raises."""
    try:
        if isinstance(exc, MemoryError):
            return True
        text = f"{type(exc).__name__}: {exc}".lower()
        return any(m in text for m in _OOM_MARKERS)
    except Exception:  # noqa: BLE001
        return False


def _top_buffers(k=8) -> list:
    """Top-K live buffers by Gluon-Block attribution from the
    diagnostics ledger (empty when the ledger is off)."""
    try:
        from ..diagnostics.memory import memory_summary
        s = memory_summary(include_reconcile=False)
        top = sorted(s.get("by_block", {}).items(),
                     key=lambda kv: -kv[1])[:int(k)]
        return [{"block": b, "bytes": int(n)} for b, n in top]
    except Exception:  # noqa: BLE001
        return []


def post_mortem(error=None, program=None, step=None) -> dict:
    """Assemble (but do not publish) an OOM post-mortem. Every section
    degrades independently — a dead allocator must still yield
    whatever evidence survives. See the module docstring for the
    sections."""
    pm = {"schema": OOM_SCHEMA,
          "error": None, "error_type": None,
          "program": program, "step": step,
          "footprint": None, "watermark_tail": [],
          "top_buffers": [], "ledger": None,
          "knobs": None, "capacity": None}
    try:
        if error is not None:
            pm["error"] = str(error)[:2000]
            pm["error_type"] = type(error).__name__
    except Exception:  # noqa: BLE001
        pass
    try:
        if program is not None:
            pm["footprint"] = _footprint.footprint_of(program)
    except Exception:  # noqa: BLE001
        pass
    try:
        from . import _MS
        if _MS is not None:
            pm["watermark_tail"] = _MS.ring.tail(8)
    except Exception:  # noqa: BLE001
        pass
    pm["top_buffers"] = _top_buffers()
    try:
        from ..diagnostics.memory import memory_summary
        s = memory_summary(include_reconcile=False)
        pm["ledger"] = {"current_bytes": s.get("current_bytes"),
                        "peak_bytes": s.get("peak_bytes"),
                        "live_arrays": s.get("live_arrays")}
    except Exception:  # noqa: BLE001
        pass
    try:
        from ..autotune.knobs import KnobConfig
        pm["knobs"] = KnobConfig.from_env().to_dict()
    except Exception:  # noqa: BLE001
        pass
    try:
        from . import device_capacity
        pm["capacity"] = device_capacity()
    except Exception:  # noqa: BLE001
        pass
    return pm


def record_oom(error, program=None, step=None):
    """The dispatch-site hook: if ``error`` is an allocator failure,
    assemble the post-mortem and land it on every finding surface
    (counter + flight breadcrumb + healthmon structured event), then
    return it so the caller re-raises the original error. Returns None
    for non-OOM errors. Never raises."""
    global _LAST_PM
    try:
        if not is_oom_error(error):
            return None
        pm = post_mortem(error=error, program=program, step=step)
        _LAST_PM = pm
        _counter("memscope.oom_events", "memscope").increment()
        if _flight._REC is not None:
            _flight.record("alert", "memscope.oom", {
                "program": program, "step": step,
                "error_type": pm.get("error_type"),
                "footprint_peak_bytes":
                    (pm.get("footprint") or {}).get("peak_bytes"),
                "ledger_current_bytes":
                    (pm.get("ledger") or {}).get("current_bytes")})
        try:
            from .. import healthmon as _hm
            if _hm._HM is not None:
                _hm._HM.events.emit(
                    "alert", "memscope.oom",
                    args={"program": program, "step": step,
                          "error_type": pm.get("error_type")})
        except Exception:  # noqa: BLE001
            pass
        return pm
    except Exception:  # noqa: BLE001 — forensics never masks the OOM
        return None
