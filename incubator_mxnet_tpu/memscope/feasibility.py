"""Memory-feasibility math for the autotuner's pre-trial pruner.

A knob candidate that cannot fit is the cheapest possible trial to
win: reject it BEFORE the subprocess is spawned. The prediction is
deliberately conservative and only covers moves whose memory effect is
honestly predictable from the baseline measurement:

* **batch** — activation-dominated peaks scale ~linearly with global
  batch, so ``predicted = baseline_peak * candidate / baseline_batch``
  (a lower bound for super-linear programs, which is the safe
  direction for a *pruner*: it only ever under-predicts, so a pruned
  candidate was truly hopeless);
* **remat** off (``remat_policy`` -> None / ``remat`` -> False from a
  rematerializing baseline) — disabling remat cannot shrink the peak,
  so the baseline peak is a floor; the candidate is rejected only when
  even that floor exceeds the limit.

Everything else returns "no prediction" and runs normally — the
pruner must never invent memory physics it cannot defend. The limit is
``capacity * MXTPU_MEMSCOPE_HEADROOM`` (capacity from
:func:`memscope.device_capacity`, override ``MXTPU_MEMSCOPE_CAPACITY``
— what the smoke uses to inject an over-capacity candidate on CPU).
An infeasible verdict is counted (``memscope.infeasible_candidates``)
and breadcrumbed; the tuner files it under ``plan["pruned"]`` with a
``memory:`` reason so ``extra.autotune.trials_pruned`` keeps its
counter==payload contract.
"""
from __future__ import annotations

from ..diagnostics import flight as _flight
from ..profiler.counters import counter as _counter

__all__ = ["predict_candidate_peak", "feasibility_check"]


def predict_candidate_peak(knob, value, baseline):
    """Predicted peak bytes for one knob move, or ``(None, basis)``
    when no honest prediction exists.

    ``baseline`` is the measurement dict the tuner extracted from the
    baseline artifact's ``extra.memscope``: ``{"peak_bytes", "batch",
    "remat"}`` (missing fields disable the matching predictions).
    Returns ``(predicted_bytes_or_None, basis_str)``. Never raises."""
    try:
        peak = baseline.get("peak_bytes") if isinstance(baseline, dict) \
            else None
        if not peak or peak <= 0:
            return None, "no_baseline_peak"
        peak = float(peak)
        if knob == "batch":
            b0 = baseline.get("batch")
            if not b0 or int(b0) <= 0 or value is None:
                return None, "no_baseline_batch"
            return peak * float(value) / float(b0), "linear_batch"
        if knob == "remat_policy" and value is None \
                and baseline.get("remat"):
            return peak, "remat_floor"
        if knob == "remat" and value is False and baseline.get("remat"):
            return peak, "remat_floor"
        return None, "not_memory_knob"
    except Exception:  # noqa: BLE001 — prediction never breaks the tuner
        return None, "error"


def feasibility_check(knob, value, baseline, capacity_bytes=None,
                      target=None) -> dict:
    """Full pre-trial verdict for one candidate.

    Returns ``{"feasible", "predicted_peak_bytes", "limit_bytes",
    "basis", "reason"}`` — ``feasible`` is True (run the trial)
    whenever prediction or capacity is unavailable; ``reason`` is the
    ``memory: ...`` string the tuner files under ``plan["pruned"]``
    when False. An infeasible verdict is counted and breadcrumbed
    here, the single home of the judgement. Never raises."""
    out = {"feasible": True, "predicted_peak_bytes": None,
           "limit_bytes": None, "basis": None, "reason": None}
    try:
        from . import device_capacity, headroom_target
        predicted, basis = predict_candidate_peak(knob, value, baseline)
        out["basis"] = basis
        if predicted is None:
            return out
        out["predicted_peak_bytes"] = int(predicted)
        if capacity_bytes is None:
            capacity_bytes = device_capacity().get("bytes")
        if not capacity_bytes:
            return out
        if target is None:
            target = headroom_target()
        limit = float(capacity_bytes) * float(target)
        out["limit_bytes"] = int(limit)
        if predicted <= limit:
            return out
        out["feasible"] = False
        out["reason"] = (
            f"memory: predicted peak {int(predicted)} B "
            f"({basis}) exceeds capacity {int(capacity_bytes)} B x "
            f"headroom {float(target):g} = {int(limit)} B")
        _counter("memscope.infeasible_candidates",
                 "memscope").increment()
        if _flight._REC is not None:
            _flight.record("alert", "memscope.infeasible", {
                "knob": str(knob), "value": str(value),
                "predicted_peak_bytes": int(predicted),
                "limit_bytes": int(limit), "basis": basis})
        return out
    except Exception:  # noqa: BLE001 — the pruner fails open
        out["feasible"] = True
        out["reason"] = None
        return out
