"""TPU/XLA bridge for the profiler.

Two jobs:

* :func:`annotation` — when the default backend is a TPU, wrap host-side
  :class:`~incubator_mxnet_tpu.profiler.Scope` regions in
  ``jax.profiler.TraceAnnotation`` so they line up with the XLA device
  trace (TensorBoard/Perfetto shows the host scope spanning the device
  ops it dispatched). On CPU/GPU backends this returns None — the host
  Chrome trace is the single source and the annotation would be dead
  weight in the hot path.
* :func:`start_device_trace` / :func:`stop_device_trace` — drive
  ``jax.profiler`` for a full XLA capture when
  ``set_config(profile_xla=True)`` — and for mxtpu.devicescope's
  bounded capture windows, which need to know whether the capture
  actually armed (jax allows ONE active trace per process, so a window
  opened while ``profile_xla`` is tracing must DECLINE, not silently
  share the artifact): ``start_device_trace`` returns True only when
  this call started a fresh trace.

Backend detection is done once and cached; everything degrades to a no-op
if jax's profiler is unavailable (e.g. stripped builds)."""
from __future__ import annotations

_is_tpu = None          # tri-state: None = not yet probed
_tracing = False


def on_tpu() -> bool:
    global _is_tpu
    if _is_tpu is None:
        try:
            import jax
            _is_tpu = jax.default_backend() == "tpu"
        except Exception:
            _is_tpu = False
    return _is_tpu


def annotation(name: str):
    """A TraceAnnotation context manager for `name` on TPU, else None."""
    if not on_tpu():
        return None
    try:
        import jax
        return jax.profiler.TraceAnnotation(name)
    except Exception:
        return None


def start_device_trace(logdir: str) -> bool:
    """Start a jax profiler trace into ``logdir``. Returns True when
    THIS call armed a fresh trace; False when one is already running
    (ours or anyone's — jax allows one per process) or the profiler is
    unavailable. Callers that need exclusivity (devicescope windows)
    key off the return value."""
    global _tracing
    if _tracing:
        return False
    try:
        import jax
        jax.profiler.start_trace(logdir)
        _tracing = True
        return True
    except Exception:
        return False              # already tracing / profiler unavailable


def stop_device_trace():
    global _tracing
    try:
        import jax
        jax.profiler.stop_trace()
    except Exception:
        pass                      # never started / profiler unavailable
    _tracing = False


def tracing() -> bool:
    """True while a device trace started here is running."""
    return _tracing
