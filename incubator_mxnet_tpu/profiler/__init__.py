"""mxtpu.profiler — TPU-native profiling & metrics subsystem.

Parity surface: python/mxnet/profiler.py (`set_config` / `set_state` /
`start` / `stop` / `pause` / `resume` / `dump` / `dumps`), emitting
Chrome-trace-event JSON loadable in chrome://tracing / Perfetto, plus an
aggregate-stats backend (per-op count/total/min/max — the reference
`profiler.dumps()` table) and a counters/gauges registry (see
``profiler.counters``) that bench.py uses for per-phase step-time
breakdowns.

Three event sources feed one recorder:

* **imperative ops** — a hook on the ndarray ``_apply`` funnel times each
  eager op, synchronizing on the outputs so durations are device-compute
  times, not dispatch times (``profile_imperative``);
* **layer scopes** — the hot layers (autograd tape, host engine,
  gluon.Trainer phases, KVStore collectives, HybridBlock jit cache,
  symbolize) open :class:`Scope` regions around their work. Each hook is
  a single module-flag predicate (``profiler._ACTIVE``) when profiling is
  off — no dict lookups, no string formatting, no allocation;
* **user scopes** — ``with profiler.Scope("region"):`` (alias
  ``record_function``) times arbitrary regions; user scopes synchronize
  the device on exit by default so the number is wall-true.

TPU bridge: when the default backend is TPU (see
:mod:`incubator_mxnet_tpu.profiler.tpu`), every scope additionally enters
``jax.profiler.TraceAnnotation`` so host-side regions line up with the XLA
device trace, and ``set_config(profile_xla=True)`` drives
``jax.profiler.start_trace`` for a full TensorBoard/Perfetto capture.

Off-path contract: when profiling is disabled the ndarray funnel checks
one module-global (``_op_hook is None``) and every layer hook checks one
module-global bool — verified by the <5% microloop-overhead assertion in
``tests/test_profiler.py``.
"""
from __future__ import annotations

import json
import threading
import time

from .counters import (Counter, Histogram, counter, histogram, observe,
                       counters, reset_counters, set_gauge,
                       registry_snapshot, counter_kinds, _counter_events)
from . import tpu as _tpu

__all__ = ["set_config", "set_state", "start", "stop", "pause", "resume",
           "dump", "dumps", "reset", "aggregate_stats", "Scope", "scope",
           "record_function", "Counter", "Histogram", "counter",
           "histogram", "observe", "counters", "set_gauge",
           "reset_counters", "registry_snapshot", "counter_kinds",
           "device_memory_stats"]

# --------------------------------------------------------------------------
# State. `_ACTIVE` is THE fast-path predicate: hot layers guard their
# instrumentation with `if _prof._ACTIVE:` and nothing else. It is True
# exactly while profiling is running and not paused.
# --------------------------------------------------------------------------
_ACTIVE = False
_RUNNING = False
# True only while a profile_xla device trace WE started is running —
# set_state must never stop a trace owned by someone else (a
# devicescope capture window holds the one-per-process jax trace)
_xla_trace_owned = False

_config = {
    "filename": "profile.json",
    "aggregate_stats": True,
    # reference set_config knobs — profile_all turns everything on
    "profile_all": False,
    "profile_imperative": True,   # eager op timing via the _apply hook
    "profile_api": True,          # engine / kvstore / trainer scopes
    "profile_symbolic": True,     # symbolize / jit cache events
    "profile_memory": False,      # attach device memory stats to dump()
    "continuous_dump": False,     # accepted for parity; dump() is explicit
    "dump_period": 1.0,           # accepted for parity
    # XLA device trace (TensorBoard/Perfetto), beyond the reference surface
    "profile_xla": False,
    "xla_logdir": "/tmp/mxtpu_xla_trace",
}

_records: list[dict] = []            # chrome trace events (X phase)
_agg: dict[str, list] = {}           # name -> [count, total_us, min_us, max_us]
_lock = threading.Lock()             # guards _agg merges from engine threads
_t0 = time.perf_counter()
_tls = threading.local()             # per-thread scope nesting depth


def _now_us() -> float:
    return (time.perf_counter() - _t0) * 1e6


def _emit(name: str, cat: str, ts_us: float, dur_us: float, args=None):
    """Record one complete ('X') event and fold it into the aggregate."""
    ev = {"name": name, "cat": cat, "ph": "X", "pid": 0,
          "tid": threading.get_ident() & 0xFFFF, "ts": ts_us, "dur": dur_us}
    if args:
        ev["args"] = args
    _records.append(ev)
    if _config["aggregate_stats"]:
        with _lock:
            ent = _agg.get(name)
            if ent is None:
                _agg[name] = [1, dur_us, dur_us, dur_us]
            else:
                ent[0] += 1
                ent[1] += dur_us
                if dur_us < ent[2]:
                    ent[2] = dur_us
                if dur_us > ent[3]:
                    ent[3] = dur_us


def _instant(name: str, cat: str, args=None):
    """Record an instant ('i') event — used for cache hit/miss marks."""
    ev = {"name": name, "cat": cat, "ph": "i", "pid": 0,
          "tid": threading.get_ident() & 0xFFFF, "ts": _now_us(), "s": "t"}
    if args:
        ev["args"] = args
    _records.append(ev)


# --------------------------------------------------------------------------
# Configuration / lifecycle
# --------------------------------------------------------------------------

def set_config(**kwargs):
    """set_config(profile_all=..., filename=..., aggregate_stats=..., ...).

    Accepts the reference kwargs; unknown ones are ignored (everything here
    runs through the same eager/jit funnel, so e.g. ``profile_process`` has
    no distinct meaning). ``profile_all=True`` enables every source."""
    for k, v in kwargs.items():
        if k in _config:
            _config[k] = v


def _imperative_on() -> bool:
    return _config["profile_all"] or _config["profile_imperative"]


def _install_hooks(on: bool):
    from .. import ndarray as _nd
    _nd._op_hook = _op_hook if (on and _imperative_on()) else None


def set_state(state: str = "stop"):
    """'run' starts collection, 'stop' ends it. Idempotent."""
    assert state in ("run", "stop")
    global _RUNNING, _ACTIVE
    was_running = _RUNNING
    _RUNNING = state == "run"
    _ACTIVE = _RUNNING
    _install_hooks(_RUNNING)
    if _config["profile_xla"] and was_running != _RUNNING:
        global _xla_trace_owned
        if _RUNNING:
            # jax allows ONE trace per process: if a devicescope
            # capture window (or anyone else) is already tracing,
            # start returns False and this session must NOT stop the
            # trace it failed to start — stopping would kill the
            # window's capture mid-flight while it still counts steps
            _xla_trace_owned = _tpu.start_device_trace(
                _config["xla_logdir"])
        elif _xla_trace_owned:
            _tpu.stop_device_trace()
            _xla_trace_owned = False


def start():
    """Parity: profiler.start() — begin collecting."""
    set_state("run")


def stop():
    """Parity: profiler.stop() — end collecting (does not clear records)."""
    set_state("stop")


def pause():
    """Suspend collection without tearing down the run (parity: pause)."""
    global _ACTIVE
    if _RUNNING:
        _ACTIVE = False
        _install_hooks(False)


def resume():
    global _ACTIVE
    if _RUNNING:
        _ACTIVE = True
        _install_hooks(True)


def reset():
    """Clear recorded events and aggregate stats (not the counters)."""
    _records.clear()
    with _lock:
        _agg.clear()


# --------------------------------------------------------------------------
# Imperative op hook (installed on ndarray._op_hook while active)
# --------------------------------------------------------------------------

def _op_hook(fn, raws, name):
    import jax
    if any(isinstance(r, jax.core.Tracer) for r in raws):
        # inside a jit/eval_shape trace of a hybridized block: not a device
        # execution, don't record (times would be Python tracing time)
        return fn(*raws)
    start_t = time.perf_counter()
    outs = fn(*raws)
    jax.block_until_ready(outs)
    dur = time.perf_counter() - start_t
    _emit(name or getattr(fn, "__name__", "op"), "operator",
          (start_t - _t0) * 1e6, dur * 1e6)
    return outs


# --------------------------------------------------------------------------
# Scopes
# --------------------------------------------------------------------------

class Scope:
    """Context manager timing a named region (reference: profiler scopes /
    frame markers; torch alias: ``record_function``).

    ``sync=True`` (the default for user code) drains device work on exit so
    the duration is wall-true; internal layer hooks pass ``sync=False`` to
    avoid perturbing the async pipeline. Inert (near-zero cost) when
    profiling is off or paused, so scopes can stay in production loops."""

    __slots__ = ("name", "cat", "sync", "_start", "_active", "_depth", "_ann")

    def __init__(self, name: str = "<unk>", cat: str = "scope",
                 sync: bool = True):
        self.name = name
        self.cat = cat
        self.sync = sync
        self._active = False
        self._ann = None

    def __enter__(self):
        self._active = _ACTIVE
        if self._active:
            self._depth = getattr(_tls, "depth", 0)
            _tls.depth = self._depth + 1
            self._ann = _tpu.annotation(self.name)
            if self._ann is not None:
                self._ann.__enter__()
            self._start = time.perf_counter()
        return self

    def __exit__(self, *exc):
        if self._active:
            if self.sync:
                from .. import ndarray as _nd
                _nd.waitall()
            dur = time.perf_counter() - self._start
            if self._ann is not None:
                self._ann.__exit__(*exc)
                self._ann = None
            _tls.depth = self._depth
            _emit(self.name, self.cat, (self._start - _t0) * 1e6, dur * 1e6,
                  args={"depth": self._depth})
            self._active = False
        return False


# aliases: `with profiler.scope("x"):` (old mxtpu surface) and
# `with profiler.record_function("x"):` (torch-style, per the issue)
scope = Scope
record_function = Scope


# --------------------------------------------------------------------------
# Dump / aggregate backends
# --------------------------------------------------------------------------

def dump(finished: bool = True, filename: str | None = None) -> str:
    """Write the Chrome trace-event JSON to `filename` (default: the
    configured one). Returns the path written."""
    path = filename or _config["filename"]
    events = [{"name": "process_name", "ph": "M", "pid": 0,
               "args": {"name": "mxtpu"}}]
    events.extend(_records)
    events.extend(_counter_events())
    payload = {"traceEvents": events, "displayTimeUnit": "ms"}
    if _config["profile_memory"] or _config["profile_all"]:
        try:
            payload["deviceMemory"] = device_memory_stats()
        except Exception:
            pass
    with open(path, "w") as f:
        json.dump(payload, f)
    return path


def aggregate_stats() -> dict:
    """Per-name aggregate: {name: {count, total_us, min_us, max_us,
    avg_us}} — the machine-readable form of `dumps()`."""
    with _lock:
        return {name: {"count": c, "total_us": tot, "min_us": mn,
                       "max_us": mx, "avg_us": tot / c}
                for name, (c, tot, mn, mx) in _agg.items()}


def dumps(reset: bool = False) -> str:
    """Aggregate-stats table (reference `profiler.dumps()` format)."""
    with _lock:
        items = sorted(_agg.items(), key=lambda kv: -kv[1][1])
        lines = [f"{'Name':<40}{'Calls':>8}{'Total(ms)':>12}{'Min(us)':>12}"
                 f"{'Avg(us)':>12}{'Max(us)':>12}"]
        for name, (c, tot, mn, mx) in items:
            lines.append(f"{name[:39]:<40}{c:>8}{tot / 1e3:>12.3f}"
                         f"{mn:>12.1f}{tot / c:>12.1f}{mx:>12.1f}")
    out = "\n".join(lines)
    if reset:
        globals()["reset"]()
    return out


# normalized key -> spellings observed across jaxlib versions/backends
# (the memscope watermark ring and mxdiag consume the normalized names)
_MEMSTATS_KEYS = {
    "bytes_in_use": ("bytes_in_use",),
    "peak_bytes_in_use": ("peak_bytes_in_use", "max_bytes_in_use"),
    "bytes_limit": ("bytes_limit", "bytes_reservable_limit"),
    "largest_alloc_size": ("largest_alloc_size", "largest_allocation"),
}


def device_memory_stats(device=None):
    """XLA allocator counters for a device (bytes_in_use, peak_bytes_in_use,
    ...), key spellings normalized across jaxlib versions, plus
    ``"available": True``. Reference analogue: gpu memory profile /
    storage stats.

    Backends whose devices lack ``memory_stats()`` or return None for
    it (XLA:CPU) degrade to a counted ``{"available": False}`` instead
    of raising — every consumer (memscope's watermark ring, the dump
    payload) branches on the one flag rather than on exceptions."""
    try:
        if device is None:
            import jax
            device = jax.local_devices()[0]
        fn = getattr(device, "memory_stats", None)
        stats = fn() if callable(fn) else None
    except Exception:  # noqa: BLE001 — backend-dependent surface
        stats = None
    if not stats:
        try:
            from .counters import counter as _ctr
            _ctr("memscope.stats_unavailable", "memscope").increment()
        except Exception:  # noqa: BLE001
            pass
        return {"available": False}
    out = dict(stats)
    for norm, spellings in _MEMSTATS_KEYS.items():
        if norm in out:
            continue
        for s in spellings:
            if s in stats:
                out[norm] = stats[s]
                break
    out["available"] = True
    return out
