"""Counters/gauges registry (parity: mx.profiler.Counter).

A :class:`Counter` is a named monotonically-adjustable value grouped under
a domain. The registry is always live (reads/writes are independent of
whether tracing is running) so subsystems can share one stats path —
`Monitor` publishes per-tensor stats here, `bench.py` publishes per-phase
step-time breakdowns, the jit cache publishes hit/miss counts. `dump()`
folds the registry into the Chrome trace as counter ('C') events so
values show up in chrome://tracing.

Thread-safety contract: the diagnostics sampler thread reads the registry
while engine worker threads and the training loop write it, so every
mutation (`increment`/`decrement`/`set_value`) and every snapshot takes
the ONE module lock — a single uncontended lock acquire per op, which is
cheap enough for the always-on path (verified by the concurrency test in
tests/test_diagnostics.py: N threads x M increments land exactly N*M).

Each counter carries a `kind`: "counter" (monotonic, incremented),
"gauge" (latest-value, written via `set_value`/`set_gauge`), or
"histogram" (:class:`Histogram` — Prometheus-style cumulative buckets
with `observe()`, used for serving latency distributions). Exporters
(diagnostics/export.py) use the kind for Prometheus TYPE lines and
validators use it to check monotonicity of time series (for histograms,
monotonicity of the observation count).
"""
from __future__ import annotations

import bisect
import threading

__all__ = ["Counter", "Histogram", "counter", "histogram", "observe",
           "counters", "set_gauge", "reset_counters",
           "registry_snapshot", "counter_kinds"]

_registry: "dict[str, Counter]" = {}
_lock = threading.Lock()


class Counter:
    """A named value in the registry. `increment`/`decrement` for counts,
    `set_value` for gauges (latest-value semantics). All mutations are
    atomic under the registry lock."""

    __slots__ = ("name", "domain", "value", "kind")

    def __init__(self, name: str, domain: str = "mxtpu", value=0):
        self.name = name
        self.domain = domain
        self.value = value
        self.kind = "counter"

    @property
    def full_name(self) -> str:
        return f"{self.domain}/{self.name}"

    def increment(self, delta=1):
        with _lock:
            self.value += delta
            return self.value

    def decrement(self, delta=1):
        with _lock:
            self.value -= delta
            return self.value

    def set_value(self, value):
        with _lock:
            self.value = value
            self.kind = "gauge"

    def __repr__(self):
        return f"Counter({self.full_name}={self.value})"


# Default bounds target request latencies in MILLISECONDS: sub-ms eager
# dispatch up through multi-second compiles, ~4 buckets per decade.
DEFAULT_HISTOGRAM_BOUNDS = (
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0,
    250.0, 500.0, 1000.0, 2500.0, 5000.0, 10000.0, 30000.0, 60000.0)


class Histogram:
    """A named latency/size distribution in the registry (kind
    "histogram"): fixed upper bounds, cumulative bucket counts on
    snapshot (the Prometheus `le` convention), plus sum/count/min/max and
    interpolated percentile estimates. `observe()` is one lock acquire,
    same always-on cost contract as `Counter.increment`."""

    __slots__ = ("name", "domain", "kind", "bounds", "_counts",
                 "_sum", "_count", "_min", "_max")

    def __init__(self, name: str, domain: str = "mxtpu", bounds=None):
        self.name = name
        self.domain = domain
        self.kind = "histogram"
        self.bounds = tuple(sorted(bounds or DEFAULT_HISTOGRAM_BOUNDS))
        self._counts = [0] * (len(self.bounds) + 1)   # last = +Inf overflow
        self._sum = 0.0
        self._count = 0
        self._min = None
        self._max = None

    @property
    def full_name(self) -> str:
        return f"{self.domain}/{self.name}"

    def observe(self, value):
        v = float(value)
        with _lock:
            self._counts[bisect.bisect_left(self.bounds, v)] += 1
            self._sum += v
            self._count += 1
            if self._min is None or v < self._min:
                self._min = v
            if self._max is None or v > self._max:
                self._max = v

    @staticmethod
    def _percentile(counts, bounds, n, mn, mx, q):
        """Linear interpolation inside the bucket holding quantile q
        (0..1), clamped to the observed min/max so estimates never exceed
        the true extremes. Pure function of a copied counts list."""
        if n == 0:
            return None
        target = q * n
        cum = 0
        for i, c in enumerate(counts):
            prev, cum = cum, cum + c
            if cum >= target and c:
                lo = bounds[i - 1] if i > 0 else \
                    (mn if mn is not None else 0.0)
                hi = bounds[i] if i < len(bounds) else \
                    (mx if mx is not None else lo)
                est = lo + (hi - lo) * (target - prev) / c
                if mn is not None:
                    est = max(est, mn)
                if mx is not None:
                    est = min(est, mx)
                return est
        return mx

    @property
    def value(self) -> dict:
        """Exporter-facing snapshot: cumulative buckets keyed by their
        upper bound (Prometheus `le`), totals, and percentile estimates.
        JSON-serializable; `counters()`/flight dumps embed it whole.

        LOCK-FREE by design: registry snapshot functions hold the module
        lock while reading `.value`, and the flight recorder's
        signal-handler path reads it with NO lock — so this must never
        acquire `_lock`. The counts list is copied in one C-level slice
        (GIL-atomic), and count/+Inf derive from that same copy, so the
        snapshot is internally consistent and monotone across reads."""
        counts = list(self._counts)
        mn, mx, total = self._min, self._max, self._sum
        n = 0
        cum = 0
        buckets = {}
        for bound, c in zip(self.bounds, counts):
            cum += c
            buckets[repr(float(bound))] = cum
        n = cum + counts[-1]
        buckets["+Inf"] = n
        return {
            "count": n,
            "sum": total,
            "min": mn,
            "max": mx,
            "buckets": buckets,
            "p50": self._percentile(counts, self.bounds, n, mn, mx, 0.50),
            "p95": self._percentile(counts, self.bounds, n, mn, mx, 0.95),
            "p99": self._percentile(counts, self.bounds, n, mn, mx, 0.99),
        }

    def __repr__(self):
        return f"Histogram({self.full_name}, n={self._count})"


def counter(name: str, domain: str = "mxtpu") -> Counter:
    """Get-or-create the counter `domain/name`."""
    key = f"{domain}/{name}"
    c = _registry.get(key)
    if c is None:
        with _lock:
            c = _registry.setdefault(key, Counter(name, domain))
    if isinstance(c, Histogram):
        # symmetric with histogram()'s guard: fail HERE with the real
        # cause, not later with AttributeError on .increment/.set_value
        raise TypeError(f"{key} is already registered as a histogram")
    return c


def histogram(name: str, domain: str = "mxtpu", bounds=None) -> Histogram:
    """Get-or-create the histogram `domain/name`."""
    key = f"{domain}/{name}"
    h = _registry.get(key)
    if h is None:
        with _lock:
            h = _registry.setdefault(key, Histogram(name, domain, bounds))
    if not isinstance(h, Histogram):
        raise TypeError(f"{key} is already registered as a {h.kind}")
    return h


def observe(name: str, value, domain: str = "mxtpu") -> None:
    """One-shot histogram observation: get-or-create and record."""
    histogram(name, domain).observe(value)


def set_gauge(name: str, value, domain: str = "mxtpu") -> None:
    """One-shot gauge write: get-or-create and set latest value."""
    counter(name, domain).set_value(value)


def counters() -> dict:
    """Snapshot of the registry: {domain/name: value}."""
    with _lock:
        return {k: c.value for k, c in _registry.items()}


def registry_snapshot() -> dict:
    """Consistent snapshot with kinds: {domain/name: (value, kind)} —
    the exporter-facing view (one lock acquire for the whole registry)."""
    with _lock:
        return {k: (c.value, c.kind) for k, c in _registry.items()}


def counter_kinds() -> dict:
    """{domain/name: 'counter'|'gauge'} for every registered metric."""
    with _lock:
        return {k: c.kind for k, c in _registry.items()}


def reset_counters():
    with _lock:
        _registry.clear()


def _counter_events() -> list:
    """Chrome 'C' events for every registered counter (called by dump).
    Histograms surface as numeric series (count + percentiles) since
    chrome://tracing counter tracks only plot numbers."""
    from . import _now_us
    ts = _now_us()
    events = []
    with _lock:
        for c in _registry.values():
            if c.kind == "histogram":
                v = c.value
                args = {"count": v["count"]}
                if v["p50"] is not None:
                    args["p50"] = v["p50"]
                    args["p99"] = v["p99"]
            else:
                args = {"value": c.value}
            events.append({"name": c.full_name, "cat": c.domain, "ph": "C",
                           "pid": 0, "ts": ts, "args": args})
    return events
