"""Counters/gauges registry (parity: mx.profiler.Counter).

A :class:`Counter` is a named monotonically-adjustable value grouped under
a domain. The registry is always live (reads/writes are independent of
whether tracing is running) so subsystems can share one stats path —
`Monitor` publishes per-tensor stats here, `bench.py` publishes per-phase
step-time breakdowns, the jit cache publishes hit/miss counts. `dump()`
folds the registry into the Chrome trace as counter ('C') events so
values show up in chrome://tracing.

Thread-safety contract: the diagnostics sampler thread reads the registry
while engine worker threads and the training loop write it, so every
mutation (`increment`/`decrement`/`set_value`) and every snapshot takes
the ONE module lock — a single uncontended lock acquire per op, which is
cheap enough for the always-on path (verified by the concurrency test in
tests/test_diagnostics.py: N threads x M increments land exactly N*M).

Each counter carries a `kind`: "counter" (monotonic, incremented) or
"gauge" (latest-value, written via `set_value`/`set_gauge`). Exporters
(diagnostics/export.py) use the kind for Prometheus TYPE lines and
validators use it to check monotonicity of time series.
"""
from __future__ import annotations

import threading

__all__ = ["Counter", "counter", "counters", "set_gauge", "reset_counters",
           "registry_snapshot", "counter_kinds"]

_registry: "dict[str, Counter]" = {}
_lock = threading.Lock()


class Counter:
    """A named value in the registry. `increment`/`decrement` for counts,
    `set_value` for gauges (latest-value semantics). All mutations are
    atomic under the registry lock."""

    __slots__ = ("name", "domain", "value", "kind")

    def __init__(self, name: str, domain: str = "mxtpu", value=0):
        self.name = name
        self.domain = domain
        self.value = value
        self.kind = "counter"

    @property
    def full_name(self) -> str:
        return f"{self.domain}/{self.name}"

    def increment(self, delta=1):
        with _lock:
            self.value += delta
            return self.value

    def decrement(self, delta=1):
        with _lock:
            self.value -= delta
            return self.value

    def set_value(self, value):
        with _lock:
            self.value = value
            self.kind = "gauge"

    def __repr__(self):
        return f"Counter({self.full_name}={self.value})"


def counter(name: str, domain: str = "mxtpu") -> Counter:
    """Get-or-create the counter `domain/name`."""
    key = f"{domain}/{name}"
    c = _registry.get(key)
    if c is None:
        with _lock:
            c = _registry.setdefault(key, Counter(name, domain))
    return c


def set_gauge(name: str, value, domain: str = "mxtpu") -> None:
    """One-shot gauge write: get-or-create and set latest value."""
    counter(name, domain).set_value(value)


def counters() -> dict:
    """Snapshot of the registry: {domain/name: value}."""
    with _lock:
        return {k: c.value for k, c in _registry.items()}


def registry_snapshot() -> dict:
    """Consistent snapshot with kinds: {domain/name: (value, kind)} —
    the exporter-facing view (one lock acquire for the whole registry)."""
    with _lock:
        return {k: (c.value, c.kind) for k, c in _registry.items()}


def counter_kinds() -> dict:
    """{domain/name: 'counter'|'gauge'} for every registered metric."""
    with _lock:
        return {k: c.kind for k, c in _registry.items()}


def reset_counters():
    with _lock:
        _registry.clear()


def _counter_events() -> list:
    """Chrome 'C' events for every registered counter (called by dump)."""
    from . import _now_us
    ts = _now_us()
    with _lock:
        return [{"name": c.full_name, "cat": c.domain, "ph": "C", "pid": 0,
                 "ts": ts, "args": {"value": c.value}}
                for c in _registry.values()]
