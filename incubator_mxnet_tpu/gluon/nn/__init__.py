"""gluon.nn layers (parity: python/mxnet/gluon/nn/{basic_layers,conv_layers}.py).

Every layer is a HybridBlock whose forward runs through the recordable op
funnel, so the same code serves eager, taped, and jit-compiled execution.
Conv/pool accept `layout=` with NCHW default (API parity) — pass NHWC for the
TPU-preferred channels-last path (model zoo does this on TPU).
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ... import autograd  # noqa: F401 (re-export convenience)
from ...ndarray import NDArray, _apply
from ... import ndarray as nd
from ... import ops
from ...ops import _raw
from ..block import Block, HybridBlock

__all__ = ["Sequential", "HybridSequential", "Dense", "Dropout", "Flatten",
           "Activation", "LeakyReLU", "PReLU", "ELU", "SELU", "GELU", "Swish",
           "SiLU", "Embedding", "BatchNorm", "BatchNormReLU", "LayerNorm", "InstanceNorm",
           "GroupNorm", "Conv1D", "Conv2D", "Conv3D", "Conv1DTranspose",
           "Conv2DTranspose", "Conv3DTranspose", "MaxPool1D", "MaxPool2D",
           "MaxPool3D", "AvgPool1D", "AvgPool2D", "AvgPool3D",
           "GlobalMaxPool1D", "GlobalMaxPool2D", "GlobalMaxPool3D",
           "GlobalAvgPool1D", "GlobalAvgPool2D", "GlobalAvgPool3D",
           "Lambda", "HybridLambda", "Identity", "Concatenate",
           "ReflectionPad2D"]


def _pair(x, n):
    if isinstance(x, (tuple, list)):
        assert len(x) == n
        return tuple(int(v) for v in x)
    return (int(x),) * n


# ---------------------------------------------------------------------------
# containers
# ---------------------------------------------------------------------------

class Sequential(Block):
    def __init__(self, *blocks, prefix=None, params=None):
        super().__init__(prefix, params)
        for b in blocks:
            self.add(b)

    def add(self, *blocks):
        for b in blocks:
            self.register_child(b)

    def forward(self, x, *args):
        for child in self._children.values():
            x = child(x, *args)
            args = ()
        return x

    def __len__(self):
        return len(self._children)

    def __getitem__(self, idx):
        vals = list(self._children.values())
        if isinstance(idx, slice):
            out = type(self)()
            out.add(*vals[idx])
            return out
        return vals[idx]

    def __iter__(self):
        return iter(self._children.values())


class HybridSequential(Sequential, HybridBlock):
    def __init__(self, *blocks, prefix=None, params=None):
        HybridBlock.__init__(self, prefix, params)
        for b in blocks:
            self.add(b)


# ---------------------------------------------------------------------------
# basic layers
# ---------------------------------------------------------------------------

class Dense(HybridBlock):
    """FullyConnected layer; weight (units, in_units) like the reference."""

    def __init__(self, units, activation=None, use_bias=True, flatten=True,
                 dtype="float32", weight_initializer=None, bias_initializer="zeros",
                 in_units=0, prefix=None, params=None):
        super().__init__(prefix, params)
        self._units = units
        self._flatten = flatten
        self.act = activation
        self.weight = self.params.get("weight", shape=(units, in_units),
                                      dtype=dtype, init=weight_initializer)
        self.bias = (self.params.get("bias", shape=(units,), dtype=dtype,
                                     init=bias_initializer) if use_bias else None)

    def infer_shape(self, x):
        in_units = int(np.prod(x.shape[1:])) if self._flatten else x.shape[-1]
        self.weight.shape = (self._units, in_units)

    def forward(self, x):
        out = ops.FullyConnected(x, self.weight.data(),
                                 None if self.bias is None else self.bias.data(),
                                 flatten=self._flatten)
        if self.act:
            out = ops.Activation(out, self.act)
        return out


class Activation(HybridBlock):
    def __init__(self, activation, prefix=None, params=None):
        super().__init__(prefix, params)
        self._act = activation

    def forward(self, x):
        return ops.Activation(x, self._act)


class Dropout(HybridBlock):
    def __init__(self, rate, axes=(), prefix=None, params=None):
        super().__init__(prefix, params)
        self._rate = rate
        self._axes = axes

    def forward(self, x):
        return ops.Dropout(x, p=self._rate, axes=self._axes)


class Flatten(HybridBlock):
    def forward(self, x):
        return x.flatten()


class Identity(HybridBlock):
    def forward(self, x):
        return x


class Lambda(Block):
    def __init__(self, function, prefix=None):
        super().__init__(prefix)
        self._fn = function if callable(function) else getattr(nd, function)

    def forward(self, *args):
        return self._fn(*args)


class HybridLambda(HybridBlock, Lambda):
    def __init__(self, function, prefix=None):
        HybridBlock.__init__(self, prefix)
        self._fn = function if callable(function) else getattr(nd, function)

    def forward(self, *args):
        return self._fn(*args)


class Concatenate(HybridSequential):
    """Run children on the same input, concat outputs along `axis`."""

    def __init__(self, axis=-1, prefix=None):
        super().__init__(prefix=prefix)
        self._axis = axis

    def forward(self, x):
        return nd.concat(*[child(x) for child in self._children.values()],
                         dim=self._axis)


class LeakyReLU(HybridBlock):
    def __init__(self, alpha=0.01, prefix=None, params=None):
        super().__init__(prefix, params)
        self._alpha = alpha

    def forward(self, x):
        return nd.leaky_relu(x, self._alpha)


class PReLU(HybridBlock):
    def __init__(self, alpha_initializer=None, in_channels=1, prefix=None, params=None):
        super().__init__(prefix, params)
        from ... import initializer as init_mod
        self.alpha = self.params.get(
            "alpha", shape=(in_channels,),
            init=alpha_initializer or init_mod.Constant(0.25))

    def forward(self, x):
        a = self.alpha.data()
        return _apply(lambda xr, ar: jnp.where(xr >= 0, xr, ar * xr),
                      [x, a], name="prelu")


class ELU(HybridBlock):
    def __init__(self, alpha=1.0, prefix=None, params=None):
        super().__init__(prefix, params)
        self._alpha = alpha

    def forward(self, x):
        return nd.elu(x, self._alpha)


class SELU(HybridBlock):
    def forward(self, x):
        return nd.selu(x)


class GELU(HybridBlock):
    def __init__(self, approximation="erf", prefix=None, params=None):
        super().__init__(prefix, params)
        self._approx = approximation != "erf"

    def forward(self, x):
        return nd.gelu(x, approximate=self._approx)


class Swish(HybridBlock):
    def forward(self, x):
        return nd.silu(x)


SiLU = Swish


class Embedding(HybridBlock):
    """Index handling follows the embedding subsystem's shared policy
    (embedding/lookup.normalize_ids): ids are rounded to int32 and
    `oor_policy` ('clip' or 'error') pins the out-of-range behavior that
    used to be backend-dependent (docs/embedding.md)."""

    def __init__(self, input_dim, output_dim, dtype="float32",
                 weight_initializer=None, sparse_grad=False,
                 oor_policy="clip", prefix=None, params=None):
        super().__init__(prefix, params)
        self._input_dim = input_dim
        self._output_dim = output_dim
        self._sparse_grad = sparse_grad
        self._oor_policy = oor_policy
        self.weight = self.params.get("weight", shape=(input_dim, output_dim),
                                      dtype=dtype, init=weight_initializer)

    def forward(self, x):
        return nd.embedding(x, self.weight.data(), input_dim=self._input_dim,
                            sparse_grad=self._sparse_grad,
                            oor_policy=self._oor_policy)


# ---------------------------------------------------------------------------
# normalization layers
# ---------------------------------------------------------------------------

class BatchNorm(HybridBlock):
    def __init__(self, axis=1, momentum=0.9, epsilon=1e-5, center=True,
                 scale=True, use_global_stats=False, beta_initializer="zeros",
                 gamma_initializer="ones", running_mean_initializer="zeros",
                 running_variance_initializer="ones", in_channels=0,
                 prefix=None, params=None):
        super().__init__(prefix, params)
        self._axis = axis
        self._momentum = momentum
        self._eps = epsilon
        self._center = center
        self._scale = scale
        self._use_global_stats = use_global_stats
        self.gamma = self.params.get("gamma", shape=(in_channels,),
                                     init=gamma_initializer,
                                     grad_req="write" if scale else "null")
        self.beta = self.params.get("beta", shape=(in_channels,),
                                    init=beta_initializer,
                                    grad_req="write" if center else "null")
        self.running_mean = self.params.get("running_mean", shape=(in_channels,),
                                            init=running_mean_initializer,
                                            grad_req="null")
        self.running_var = self.params.get("running_var", shape=(in_channels,),
                                           init=running_variance_initializer,
                                           grad_req="null")

    def infer_shape(self, x):
        c = x.shape[self._axis]
        for p in (self.gamma, self.beta, self.running_mean, self.running_var):
            p.shape = (c,)

    def forward(self, x):
        return self._forward_impl(x, act=None)

    def _forward_impl(self, x, act=None):
        from ..symbolize import is_symbol
        if is_symbol(x):  # symbol trace (gluon/symbolize.py)
            from ..symbolize import sym_call
            out = sym_call(
                "BatchNorm", out_index=0, data=x, gamma=self.gamma.data(),
                beta=self.beta.data(), moving_mean=self.running_mean.data(),
                moving_var=self.running_var.data(), axis=self._axis,
                eps=self._eps, momentum=self._momentum,
                fix_gamma=not self._scale,
                use_global_stats=self._use_global_stats)
            return out.relu() if act == "relu" else out
        training = autograd.is_training() and not self._use_global_stats
        axis, eps, mom = self._axis, self._eps, self._momentum
        fix_gamma = not self._scale

        def f(xr, gr, br, mmr, mvr):
            return _raw.batch_norm(xr, gr, br, mmr, mvr, axis=axis, eps=eps,
                                   momentum=mom, training=training,
                                   use_global_stats=self._use_global_stats,
                                   fix_gamma=fix_gamma, act=act)

        y, nm, nv = _apply(f, [x, self.gamma.data(), self.beta.data(),
                               self.running_mean.data(), self.running_var.data()],
                           n_out=3, name="BatchNorm" if act is None
                           else "BatchNorm" + act.upper())
        if training:
            self.running_mean.update_aux(nm._data)
            self.running_var.update_aux(nv._data)
        return y


class BatchNormReLU(BatchNorm):
    """BatchNorm with a fused trailing ReLU (parity:
    gluon.nn.BatchNormReLU / the reference's fused CUDNN_BATCHNORM_OPS
    path). The normalize+affine+relu tail routes through the kernel-
    selection layer (ops/select.py): on qualifying channels-last shapes
    it runs as ONE pallas HBM pass (scale_shift_act — the stats
    reduction stays XLA in training mode); elsewhere XLA fuses the relu
    into the normalization chain, numerics unchanged."""

    def forward(self, x):
        return self._forward_impl(x, act="relu")


class LayerNorm(HybridBlock):
    def __init__(self, axis=-1, epsilon=1e-5, center=True, scale=True,
                 beta_initializer="zeros", gamma_initializer="ones",
                 in_channels=0, prefix=None, params=None):
        super().__init__(prefix, params)
        self._axis = axis
        self._eps = epsilon
        self.gamma = self.params.get("gamma", shape=(in_channels,),
                                     init=gamma_initializer,
                                     grad_req="write" if scale else "null")
        self.beta = self.params.get("beta", shape=(in_channels,),
                                    init=beta_initializer,
                                    grad_req="write" if center else "null")

    def infer_shape(self, x):
        c = x.shape[self._axis]
        self.gamma.shape = (c,)
        self.beta.shape = (c,)

    def forward(self, x):
        return ops.LayerNorm(x, self.gamma.data(), self.beta.data(),
                             axis=self._axis, eps=self._eps)


class InstanceNorm(HybridBlock):
    def __init__(self, axis=1, epsilon=1e-5, center=True, scale=True,
                 beta_initializer="zeros", gamma_initializer="ones",
                 in_channels=0, prefix=None, params=None):
        super().__init__(prefix, params)
        self._eps = epsilon
        self.gamma = self.params.get("gamma", shape=(in_channels,),
                                     init=gamma_initializer,
                                     grad_req="write" if scale else "null")
        self.beta = self.params.get("beta", shape=(in_channels,),
                                    init=beta_initializer,
                                    grad_req="write" if center else "null")

    def infer_shape(self, x):
        self.gamma.shape = (x.shape[1],)
        self.beta.shape = (x.shape[1],)

    def forward(self, x):
        return ops.InstanceNorm(x, self.gamma.data(), self.beta.data(), eps=self._eps)


class GroupNorm(HybridBlock):
    def __init__(self, num_groups=1, epsilon=1e-5, center=True, scale=True,
                 beta_initializer="zeros", gamma_initializer="ones",
                 in_channels=0, prefix=None, params=None):
        super().__init__(prefix, params)
        self._ng = num_groups
        self._eps = epsilon
        self.gamma = self.params.get("gamma", shape=(in_channels,),
                                     init=gamma_initializer,
                                     grad_req="write" if scale else "null")
        self.beta = self.params.get("beta", shape=(in_channels,),
                                    init=beta_initializer,
                                    grad_req="write" if center else "null")

    def infer_shape(self, x):
        self.gamma.shape = (x.shape[1],)
        self.beta.shape = (x.shape[1],)

    def forward(self, x):
        return ops.GroupNorm(x, self.gamma.data(), self.beta.data(),
                             num_groups=self._ng, eps=self._eps)


# ---------------------------------------------------------------------------
# convolution layers
# ---------------------------------------------------------------------------

class _Conv(HybridBlock):
    def __init__(self, channels, kernel_size, strides, padding, dilation,
                 groups, layout, in_channels=0, activation=None, use_bias=True,
                 weight_initializer=None, bias_initializer="zeros",
                 op=ops.Convolution, adj=None, prefix=None, params=None):
        super().__init__(prefix, params)
        nsp = len(kernel_size)
        self._channels = channels
        self._in_channels = in_channels
        self._kernel = kernel_size
        self._stride = _pair(strides, nsp)
        self._pad = _pair(padding, nsp)
        self._dilate = _pair(dilation, nsp)
        self._groups = groups
        self._layout = layout
        self._op = op
        self._adj = adj
        self.act = activation
        self.weight = self.params.get("weight",
                                      shape=self._weight_shape(in_channels),
                                      init=weight_initializer)
        self.bias = (self.params.get("bias", shape=(channels,),
                                     init=bias_initializer) if use_bias else None)

    def _weight_shape(self, in_channels):
        k = tuple(self._kernel)
        if self._op is ops.Deconvolution:
            if self._layout.startswith("NC"):
                return (in_channels, self._channels // self._groups) + k
            return k + (self._channels // self._groups, in_channels)
        if self._layout.startswith("NC"):
            return (self._channels, in_channels // self._groups if in_channels else 0) + k
        return k + (in_channels // self._groups if in_channels else 0, self._channels)

    def infer_shape(self, x):
        c_axis = 1 if self._layout.startswith("NC") else x.ndim - 1
        self._in_channels = x.shape[c_axis]
        self.weight.shape = self._weight_shape(self._in_channels)

    def forward(self, x):
        kw = dict(kernel=self._kernel, stride=self._stride, pad=self._pad,
                  dilate=self._dilate, num_group=self._groups,
                  layout=self._layout)
        if self._op is ops.Deconvolution:
            kw.pop("kernel")
            kw["adj"] = self._adj
        out = self._op(x, self.weight.data(),
                       None if self.bias is None else self.bias.data(), **kw)
        if self.act:
            out = ops.Activation(out, self.act)
        return out


class Conv1D(_Conv):
    def __init__(self, channels, kernel_size, strides=1, padding=0, dilation=1,
                 groups=1, layout="NCW", **kwargs):
        if isinstance(kernel_size, int):
            kernel_size = (kernel_size,)
        super().__init__(channels, kernel_size, strides, padding, dilation,
                         groups, layout, **kwargs)


class Conv2D(_Conv):
    def __init__(self, channels, kernel_size, strides=1, padding=0, dilation=1,
                 groups=1, layout="NCHW", **kwargs):
        if isinstance(kernel_size, int):
            kernel_size = (kernel_size,) * 2
        super().__init__(channels, kernel_size, strides, padding, dilation,
                         groups, layout, **kwargs)


class Conv3D(_Conv):
    def __init__(self, channels, kernel_size, strides=1, padding=0, dilation=1,
                 groups=1, layout="NCDHW", **kwargs):
        if isinstance(kernel_size, int):
            kernel_size = (kernel_size,) * 3
        super().__init__(channels, kernel_size, strides, padding, dilation,
                         groups, layout, **kwargs)


class Conv1DTranspose(_Conv):
    def __init__(self, channels, kernel_size, strides=1, padding=0,
                 output_padding=0, dilation=1, groups=1, layout="NCW", **kwargs):
        if isinstance(kernel_size, int):
            kernel_size = (kernel_size,)
        super().__init__(channels, kernel_size, strides, padding, dilation,
                         groups, layout, op=ops.Deconvolution,
                         adj=_pair(output_padding, 1), **kwargs)


class Conv2DTranspose(_Conv):
    def __init__(self, channels, kernel_size, strides=1, padding=0,
                 output_padding=0, dilation=1, groups=1, layout="NCHW", **kwargs):
        if isinstance(kernel_size, int):
            kernel_size = (kernel_size,) * 2
        super().__init__(channels, kernel_size, strides, padding, dilation,
                         groups, layout, op=ops.Deconvolution,
                         adj=_pair(output_padding, 2), **kwargs)


class Conv3DTranspose(_Conv):
    def __init__(self, channels, kernel_size, strides=1, padding=0,
                 output_padding=0, dilation=1, groups=1, layout="NCDHW", **kwargs):
        if isinstance(kernel_size, int):
            kernel_size = (kernel_size,) * 3
        super().__init__(channels, kernel_size, strides, padding, dilation,
                         groups, layout, op=ops.Deconvolution,
                         adj=_pair(output_padding, 3), **kwargs)


# ---------------------------------------------------------------------------
# pooling layers
# ---------------------------------------------------------------------------

class _Pool(HybridBlock):
    def __init__(self, pool_type, pool_size, strides, padding, global_pool,
                 layout, count_include_pad=True, ceil_mode=False,
                 prefix=None, params=None):
        super().__init__(prefix, params)
        self._type = pool_type
        self._kernel = pool_size
        self._stride = strides
        self._pad = padding
        self._global = global_pool
        self._layout = layout
        self._cip = count_include_pad
        self._ceil = ceil_mode

    def forward(self, x):
        return ops.Pooling(x, pool_type=self._type, kernel=self._kernel,
                           stride=self._stride, pad=self._pad,
                           global_pool=self._global,
                           count_include_pad=self._cip, layout=self._layout,
                           ceil_mode=self._ceil)


def _mkpool(name, ptype, ndim, global_pool):
    default_layout = {1: "NCW", 2: "NCHW", 3: "NCDHW"}[ndim]

    class P(_Pool):
        def __init__(self, pool_size=2, strides=None, padding=0,
                     layout=default_layout, count_include_pad=True,
                     ceil_mode=False, prefix=None, params=None):
            ks = _pair(pool_size, ndim)
            st = None if strides is None else _pair(strides, ndim)
            pd = _pair(padding, ndim)
            super().__init__(ptype, ks, st, pd, global_pool, layout,
                             count_include_pad, ceil_mode, prefix, params)

    P.__name__ = P.__qualname__ = name
    return P


MaxPool1D = _mkpool("MaxPool1D", "max", 1, False)
MaxPool2D = _mkpool("MaxPool2D", "max", 2, False)
MaxPool3D = _mkpool("MaxPool3D", "max", 3, False)
AvgPool1D = _mkpool("AvgPool1D", "avg", 1, False)
AvgPool2D = _mkpool("AvgPool2D", "avg", 2, False)
AvgPool3D = _mkpool("AvgPool3D", "avg", 3, False)
GlobalMaxPool1D = _mkpool("GlobalMaxPool1D", "max", 1, True)
GlobalMaxPool2D = _mkpool("GlobalMaxPool2D", "max", 2, True)
GlobalMaxPool3D = _mkpool("GlobalMaxPool3D", "max", 3, True)
GlobalAvgPool1D = _mkpool("GlobalAvgPool1D", "avg", 1, True)
GlobalAvgPool2D = _mkpool("GlobalAvgPool2D", "avg", 2, True)
GlobalAvgPool3D = _mkpool("GlobalAvgPool3D", "avg", 3, True)


class ReflectionPad2D(HybridBlock):
    """Reflection padding on H/W of NCHW input (parity:
    gluon.nn.ReflectionPad2D / src/operator/pad.cc mode='reflect').
    padding: int, the reference's 8-tuple NCHW pad_width
    (0, 0, 0, 0, top, bottom, left, right), or — as an extension — a
    4-tuple (left, right, top, bottom)."""

    def __init__(self, padding=0, prefix=None, params=None):
        super().__init__(prefix, params)
        if isinstance(padding, int):
            padding = (padding,) * 4
        elif len(padding) == 8:
            if any(int(p) != 0 for p in padding[:4]):
                raise ValueError(
                    "8-tuple pad_width must not pad N/C axes: leading four "
                    "entries must be 0, got " + repr(padding))
            t, b, l, r = (int(p) for p in padding[4:])
            padding = (l, r, t, b)
        if len(padding) != 4:
            raise ValueError("padding must be an int, an NCHW 8-tuple "
                             "pad_width, or a 4-tuple "
                             "(left, right, top, bottom)")
        self._padding = tuple(int(p) for p in padding)

    def forward(self, x):
        l, r, t, b = self._padding
        return _apply(
            lambda a: jnp.pad(a, ((0, 0), (0, 0), (t, b), (l, r)),
                              mode="reflect"),
            [x], name="reflection_pad2d")
