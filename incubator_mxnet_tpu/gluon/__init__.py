"""gluon: the imperative/hybrid high-level API (parity: python/mxnet/gluon)."""
from . import data, loss, nn, rnn
from . import contrib
from . import model_zoo
from . import utils
from .block import Block, HybridBlock, SymbolBlock
from .parameter import Constant, Parameter, ParameterDict
from .trainer import Trainer

__all__ = ["Block", "HybridBlock", "Parameter", "ParameterDict", "Constant",
           "Trainer", "nn", "loss", "rnn", "data", "contrib", "model_zoo",
           "utils"]
