"""Gluon block -> Symbol graph tracing (the F-dispatch of the reference).

Reference parity: in upstream MXNet a HybridBlock's ``hybrid_forward(F, x)``
runs once with ``F = mx.sym`` to produce the serializable symbol graph that
``HybridBlock.export`` writes (python/mxnet/gluon/block.py:_build_cache /
export). This framework's Gluon layers are written eager-first (they call
``ops.*`` / ``nd.*`` directly — the TPU CachedOp jits that same code), so
the symbol graph is recovered differently: call the block with a *Symbol*
input under a :class:`SymbolizeScope`, and every mirrored operator
dispatches to its symbol builder instead of executing. Parameter NDArrays
encountered as operator arguments become ``Variable`` nodes named after the
parameter, so the traced graph binds directly against
``block.collect_params()`` values.

Used by :func:`trace_symbol` (public), ``HybridBlock.export`` (writes
``-symbol.json`` + params loadable by ``SymbolBlock.imports``), and the
ONNX exporter (``contrib/onnx``) for Gluon models.
"""
from .parameter import DeferredInitializationError
from .. import profiler as _prof

__all__ = ["SymbolizeScope", "trace_symbol", "active_scope", "sym_call",
           "to_input"]

_SCOPE = [None]  # innermost active scope (plain stack: tracing is sync)


def active_scope():
    return _SCOPE[-1]


class SymbolizeScope:
    """Maps parameter NDArrays (by object identity) to named Variables for
    the duration of a symbol trace."""

    def __init__(self, id2name, values=None):
        self.id2name = dict(id2name)   # id(NDArray) -> parameter name
        self.values = values or {}     # parameter name -> NDArray
        self.vars = {}                 # parameter name -> Variable (cached)
        self.used = []                 # parameter names in first-use order

    def variable(self, name):
        from ..symbol import Variable
        if name not in self.vars:
            self.vars[name] = Variable(name)
            self.used.append(name)
        return self.vars[name]

    def __enter__(self):
        _SCOPE.append(self)
        return self

    def __exit__(self, *exc):
        _SCOPE.pop()


def is_symbol(x):
    from ..symbol import Symbol
    return isinstance(x, Symbol)


def to_input(x):
    """Convert one operator argument for symbol building: Symbols pass
    through, parameter NDArrays become named Variables, None stays None."""
    from ..ndarray import NDArray
    from ..symbol import Symbol
    if x is None or isinstance(x, Symbol):
        return x
    if isinstance(x, NDArray):
        scope = active_scope()
        name = scope.id2name.get(id(x)) if scope is not None else None
        if name is None:
            raise NotImplementedError(
                "symbol tracing hit an NDArray that is not a registered "
                "parameter (a constant created inside forward). Precompute "
                "it as a Parameter or use symbol ops directly.")
        return scope.variable(name)
    return x


def sym_call(builder_name, out_index=None, **kwargs):
    """Invoke symbol builder `builder_name` with operator arguments given as
    kwargs; tensor-valued kwargs are converted via to_input. `out_index`
    selects one output of a multi-output node (e.g. BatchNorm's y)."""
    from .. import symbol as S
    builder = getattr(S, builder_name, None)
    if builder is None:
        raise NotImplementedError(
            "no symbol builder for %r; this operator cannot be traced to a "
            "symbol graph" % builder_name)
    conv = {k: (tuple(to_input(x) for x in v)
                if isinstance(v, (list, tuple)) and any(is_symbol(x)
                                                        for x in v)
                else to_input(v))
            for k, v in kwargs.items()}
    out = builder(**conv)
    return out[out_index] if out_index is not None else out


def trace_symbol(net, *input_names):
    """Trace an initialized Gluon block into (symbol, arg_params, aux_params).

    ``input_names`` default to ``("data",)``. The block's forward runs once
    with Variable inputs; the returned params are the block's parameter
    NDArrays keyed by the names the graph references (aux = names the
    symbol reports as auxiliary states, i.e. BatchNorm running stats).

    Reference parity: the _cached_graph / export path of
    python/mxnet/gluon/block.py — there via hybrid_forward(F=symbol), here
    via operator-level symbol dispatch.
    """
    from ..symbol import Variable, Group, Symbol

    if not input_names:
        input_names = ("data",)
    id2name, values = {}, {}
    for name, p in net.collect_params().items():
        try:
            nd_val = p.data()
        except DeferredInitializationError:
            raise DeferredInitializationError(
                "trace_symbol needs initialized parameters with known "
                "shapes; run the block on a real batch once (deferred "
                "init), then trace")
        id2name[id(nd_val)] = name
        values[name] = nd_val

    if _prof._ACTIVE:
        with _prof.Scope("symbolize.trace:" + net.name, "symbolic",
                         sync=False), SymbolizeScope(id2name, values):
            out = net(*[Variable(n) for n in input_names])
    else:
        with SymbolizeScope(id2name, values):
            out = net(*[Variable(n) for n in input_names])

    if isinstance(out, Symbol):
        sym = out
    elif isinstance(out, (list, tuple)):
        sym = Group(list(out))
    else:
        raise TypeError("block returned %r under symbol tracing" % type(out))

    aux_names = set(sym.list_auxiliary_states())
    arg_params, aux_params = {}, {}
    for name in set(sym.list_arguments()) | aux_names:
        if name in values:
            (aux_params if name in aux_names else arg_params)[name] = \
                values[name]
    return sym, arg_params, aux_params
