"""gluon.model_zoo (parity: python/mxnet/gluon/model_zoo/__init__.py) —
namespace bridge so reference call sites
(`from mxnet.gluon.model_zoo import vision; vision.get_model(...)`)
work unchanged. The actual registry lives in incubator_mxnet_tpu.models."""
from . import vision  # noqa: F401

get_model = vision.get_model
