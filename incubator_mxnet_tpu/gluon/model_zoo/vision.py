"""gluon.model_zoo.vision (parity: python/mxnet/gluon/model_zoo/vision/):
re-exports the vision model registry under the reference's namespace.

No pretrained-weight download here (zero-egress TPU pods); `pretrained=True`
raises with a pointer to `load_parameters` on a local checkpoint, which is
how reference users on air-gapped clusters work anyway.
"""
from ...models import (  # noqa: F401
    get_model as _get_model,
    LeNet, lenet,
    AlexNet, alexnet,
    VGG, get_vgg, vgg11, vgg13, vgg16, vgg19,
    vgg11_bn, vgg13_bn, vgg16_bn, vgg19_bn,
    get_resnet, resnet18_v1, resnet34_v1, resnet50_v1, resnet101_v1,
    resnet152_v1, resnet18_v2, resnet34_v2, resnet50_v2, resnet101_v2,
    resnet152_v2,
    MobileNet, MobileNetV2, mobilenet1_0, mobilenet0_75, mobilenet0_5,
    mobilenet0_25, mobilenet_v2_1_0, mobilenet_v2_0_75, mobilenet_v2_0_5,
    mobilenet_v2_0_25,
    SqueezeNet, squeezenet1_0, squeezenet1_1,
    DenseNet, densenet121, densenet161, densenet169, densenet201,
    Inception3, inception_v3,
)


def get_model(name, pretrained=False, classes=1000, **kwargs):
    if pretrained:
        raise ValueError(
            "pretrained weights are not bundled (no model download in this "
            "environment); build the model and load_parameters() from a "
            "local checkpoint instead")
    return _get_model(name, classes=classes, **kwargs)
