"""gluon.utils (parity: python/mxnet/gluon/utils.py): split_data,
split_and_load, clip_global_norm, check_sha1.

TPU-native note on the multi-device idiom: the reference's per-GPU loop
(`split_and_load` -> per-ctx forward/backward -> kvstore sum) exists here
for API compatibility and host-side sharding, but the throughput path on a
mesh is `parallel.FusedTrainStep`, where the batch split, collective, and
update all live inside one compiled computation.
"""
from __future__ import annotations

import hashlib

import jax
import jax.numpy as jnp
import numpy as np

from ..context import Context
from ..ndarray import NDArray

__all__ = ["split_data", "split_and_load", "clip_global_norm", "check_sha1",
           "download"]


def split_data(data, num_slice, batch_axis=0, even_split=True):
    """Split along batch_axis into num_slice parts (parity:
    gluon.utils.split_data). With even_split the batch must divide; without
    it the last slice takes the remainder."""
    if not isinstance(data, NDArray):
        data = NDArray(jnp.asarray(data))
    size = data.shape[batch_axis]
    if num_slice > size:
        raise ValueError(
            f"cannot split {size} samples into {num_slice} slices")
    if even_split and size % num_slice:
        raise ValueError(
            f"batch {size} not divisible by {num_slice}; pass "
            "even_split=False to allow a ragged final slice")
    step = size // num_slice
    slices = []
    for i in range(num_slice):
        start = i * step
        stop = (i + 1) * step if i < num_slice - 1 else size
        idx = [slice(None)] * data.ndim
        idx[batch_axis] = slice(start, stop)
        slices.append(NDArray(data._data[tuple(idx)]))
    return slices


def split_and_load(data, ctx_list, batch_axis=0, even_split=True):
    """split_data + placement of each slice on its context (parity:
    gluon.utils.split_and_load)."""
    if not isinstance(ctx_list, (list, tuple)):
        ctx_list = [ctx_list]
    if len(ctx_list) == 1:
        arr = data if isinstance(data, NDArray) else NDArray(jnp.asarray(data))
        return [arr.as_in_context(ctx_list[0])]
    slices = split_data(data, len(ctx_list), batch_axis, even_split)
    return [s.as_in_context(ctx) if isinstance(ctx, Context) else s
            for s, ctx in zip(slices, ctx_list)]


@jax.jit
def _clip_impl(rs, max_norm):
    total = sum(jnp.sum(jnp.square(r.astype(jnp.float32))) for r in rs)
    norm = jnp.sqrt(total)
    scale = jnp.minimum(1.0, max_norm / jnp.maximum(norm, 1e-12))
    return norm, [(r * scale.astype(r.dtype)) for r in rs]


def clip_global_norm(arrays, max_norm, check_isfinite=True):
    """Rescale `arrays` in place so their joint L2 norm is at most max_norm;
    returns the pre-clip global norm (parity: gluon.utils.clip_global_norm —
    the BERT/RNN training staple). One fused jitted computation, cached
    across steps (module-level jit; max_norm is a traced argument)."""
    if not arrays:
        raise ValueError("clip_global_norm needs at least one array")
    raws = [a._data for a in arrays]
    norm, new = _clip_impl(raws, jnp.float32(max_norm))
    norm_val = float(norm)
    if check_isfinite and not np.isfinite(norm_val):
        import warnings
        warnings.warn("nan or inf is detected. Clipping results will be "
                      "undefined.", stacklevel=2)
    for a, r in zip(arrays, new):
        a._data = r
    return norm_val


def check_sha1(filename, sha1_hash):
    """Parity: gluon.utils.check_sha1."""
    sha1 = hashlib.sha1()
    with open(filename, "rb") as f:
        while True:
            chunk = f.read(1 << 20)
            if not chunk:
                break
            sha1.update(chunk)
    return sha1.hexdigest() == sha1_hash


def download(url, path=None, overwrite=False, sha1_hash=None, retries=5,
             verify_ssl=True):
    """Parity surface for gluon.utils.download. TPU pods here are
    zero-egress: `file://` and existing local paths work; network URLs
    raise with instructions instead of hanging."""
    import os
    if url.startswith("file://"):
        src = url[len("file://"):]
        if not os.path.exists(src):
            raise RuntimeError(f"download({url!r}): local file not found")
    elif os.path.exists(url):
        src = url
    else:
        raise RuntimeError(
            f"download({url!r}): no network egress in this environment; "
            "stage the file locally and pass its path (or file:// URL)")
    if path is None:
        if sha1_hash and not check_sha1(src, sha1_hash):
            raise RuntimeError(f"sha1 mismatch for {src}")
        return src
    import shutil
    dest = os.path.join(path, os.path.basename(src)) \
        if os.path.isdir(path) else path
    if os.path.abspath(src) != os.path.abspath(dest) and (
            overwrite or not os.path.exists(dest)):
        shutil.copy(src, dest)
    if sha1_hash and not check_sha1(dest, sha1_hash):
        raise RuntimeError(f"sha1 mismatch for {dest}")
    return dest
