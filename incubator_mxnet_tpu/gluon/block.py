"""Block / HybridBlock (parity: python/mxnet/gluon/block.py).

Block = imperative module tree. HybridBlock adds `hybridize()`: the forward
is traced ONCE per (input-signature, training-mode) into a single `jax.jit`
executable — the TPU-native CachedOp. Parameters enter the compiled function
as arguments (no retrace on update); BatchNorm-style aux state comes back as
extra outputs and is written back after the call; dropout keys are threaded
in so compiled randomness differs per step. Under the eager tape, one cached
call records as ONE node whose vjp re-enters XLA — so loss.backward() on a
hybridized net runs forward+backward as compiled XLA computations, matching
the reference's CachedOp forward/backward graph pair.
"""
from __future__ import annotations

from collections import OrderedDict

import jax

from .. import autograd
from .. import profiler as _prof
from ..diagnostics import memory as _dmem
from ..diagnostics import flight as _flight
from .. import perfscope as _perfscope
from ..base import NameManager, camel_to_snake
from ..ndarray import NDArray, _apply
from ..ndarray import random as ndrandom
from .parameter import (DeferredInitializationError, Parameter, ParameterDict,
                        _ParamTraceScope, _trace)

__all__ = ["Block", "HybridBlock", "SymbolBlock"]


class _NameScope:
    """Parity shim for `with self.name_scope():` — naming is automatic here."""

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


class Block:
    def __init__(self, prefix=None, params=None):
        hint = camel_to_snake(type(self).__name__) + "_"
        self._prefix = NameManager.current().get(prefix, hint)
        self._params = ParameterDict(self._prefix)
        if params is not None:
            self._params.update(params.items() if isinstance(params, ParameterDict)
                                else params)
        self._children: "OrderedDict[str, Block]" = OrderedDict()
        self._reg_params: "OrderedDict[str, Parameter]" = OrderedDict()
        self._forward_hooks = []
        self._forward_pre_hooks = []

    # -- registration -----------------------------------------------------
    def __setattr__(self, name, value):
        if isinstance(value, Block):
            existing = self.__dict__.get("_children")
            if existing is not None:
                existing[name] = value
        elif isinstance(value, Parameter):
            reg = self.__dict__.get("_reg_params")
            if reg is not None:
                reg[name] = value
        super().__setattr__(name, value)

    def register_child(self, block, name=None):
        self._children[name or str(len(self._children))] = block
        return block

    def register_forward_hook(self, hook):
        self._forward_hooks.append(hook)

    def register_forward_pre_hook(self, hook):
        self._forward_pre_hooks.append(hook)

    # -- properties -------------------------------------------------------
    @property
    def prefix(self):
        return self._prefix

    @property
    def name(self):
        return self._prefix.rstrip("_")

    @property
    def params(self) -> ParameterDict:
        return self._params

    def name_scope(self):
        return _NameScope()

    # -- parameter collection --------------------------------------------
    def collect_params(self, select=None) -> ParameterDict:
        out = ParameterDict(self._prefix)
        out.update({p.name: p for p in self._params.values()})
        out.update({p.name: p for p in self._reg_params.values()})
        for child in self._children.values():
            out.update(child.collect_params().items())
        if select is not None:
            import re
            pat = re.compile(select)
            filtered = ParameterDict(self._prefix)
            filtered.update({k: v for k, v in out.items() if pat.search(k)})
            return filtered
        return out

    def initialize(self, init=None, ctx=None, verbose=False, force_reinit=False):
        self.collect_params().initialize(init=init, ctx=ctx, verbose=verbose,
                                         force_reinit=force_reinit)

    def cast(self, dtype):
        # own params only; the child recursion covers descendants exactly once
        for p in self._params.values():
            p.cast(dtype)
        for p in self._reg_params.values():
            p.cast(dtype)
        for child in self._children.values():
            child.cast(dtype)
        self._dtype = dtype

    def apply(self, fn):
        for child in self._children.values():
            child.apply(fn)
        fn(self)
        return self

    # -- sharding annotations (mxtpu.sharding, docs/sharding.md) ----------
    def shard(self, spec="__unset__", recursive=True, **by_name):
        """Attach GSPMD sharding annotations to this block's parameters.

        `spec` is a `jax.sharding.PartitionSpec` whose entries may be
        mesh axis names (``'dp'``, ``'mp'``) or LOGICAL names
        (``'model'``, ``'batch'``, …) resolved through the active
        `sharding.axis_rules` at build time. It applies to every
        parameter in the subtree whose rank matches ``len(spec)`` —
        `net.shard(P('model', None))` puts all 2-D kernels on the model
        axis and leaves 1-D biases/norms alone. Keyword form targets
        parameters by registered attribute name on each block:
        `dense.shard(weight=P('model', None), bias=P())`.
        `block.shard(None)` CLEARS the subtree's annotations.

        Annotations are layout hints consumed by the sharded executor
        (Trainer/TrainLoop/FusedTrainStep with a mesh); a dim that does
        not divide its mesh axis falls back to replicated. Returns
        ``self`` for chaining."""
        from jax.sharding import PartitionSpec

        matched = set()

        def visit(blk):
            for name, p in blk._reg_params.items():
                if name in by_name:
                    matched.add(name)
                    p._sharding = by_name[name]
                elif spec is None:
                    p._sharding = None
                elif spec != "__unset__" and p._shape is not None \
                        and len(p._shape) == len(tuple(spec)):
                    p._sharding = spec
            if recursive:
                for child in blk._children.values():
                    visit(child)

        if spec != "__unset__" and spec is not None \
                and not isinstance(spec, PartitionSpec):
            raise TypeError(f"spec must be a PartitionSpec or None, "
                            f"got {type(spec).__name__}")
        for v in by_name.values():
            if v is not None and not isinstance(v, PartitionSpec):
                raise TypeError("by-name sharding values must be "
                                "PartitionSpec or None")
        visit(self)
        unmatched = set(by_name) - matched
        if unmatched:
            # a typo'd keyword must not leave the model silently
            # replicated while the user believes it is sharded
            raise ValueError(
                f"shard() keywords {sorted(unmatched)} match no "
                f"registered parameter in this subtree (this block "
                f"registers: {sorted(self._reg_params)})")
        return self

    # -- persistence ------------------------------------------------------
    def _collect_params_with_prefix(self, prefix=""):
        """Structural names ('features.0.weight'), independent of the
        global auto-name counters (parity: reference block.py
        _collect_params_with_prefix — what makes save/load work across
        processes and across separately-constructed identical nets)."""
        if prefix:
            prefix += "."
        ret = {prefix + k: v for k, v in self._reg_params.items()}
        for name, child in self._children.items():
            ret.update(child._collect_params_with_prefix(prefix + name))
        return ret

    def save_parameters(self, filename, deduplicate=False):
        from ..ndarray import save as nd_save
        params = self._collect_params_with_prefix()
        arrays = {}
        seen = {}
        for name, p in params.items():
            if p._data is None:
                continue
            if deduplicate and id(p) in seen:
                continue
            seen[id(p)] = name
            arrays[name] = p.data()
        nd_save(filename, arrays)

    def load_parameters(self, filename, ctx=None, allow_missing=False,
                        ignore_extra=False, cast_dtype=False):
        from ..ndarray import load as nd_load
        arrays = nd_load(filename)
        params = self._collect_params_with_prefix()
        if arrays and not any(k in params for k in arrays):
            # legacy/name-based file (or symbol checkpoint): fall back to
            # the full-name ParameterDict path
            self.collect_params().load(filename, ctx=ctx,
                                       allow_missing=allow_missing,
                                       ignore_extra=ignore_extra)
            return
        for name, p in params.items():
            if name in arrays:
                v = arrays[name]
                p.set_data(v if ctx is None else v.as_in_context(ctx))
            elif not allow_missing:
                raise KeyError(f"Parameter {name} missing from {filename}")
        if not ignore_extra:
            extra = set(arrays) - set(params)
            if extra:
                raise KeyError(
                    f"File {filename} has extra parameters {sorted(extra)}")

    # -- execution --------------------------------------------------------
    def __call__(self, *args, **kwargs):
        for hook in self._forward_pre_hooks:
            hook(self, args)
        if _dmem._ACTIVE:
            # attribute arrays created during this forward to this block
            # (innermost scope wins) for memory_summary()'s by-block view
            _dmem.push_block(self.name)
            try:
                out = self._invoke(*args, **kwargs)
            finally:
                _dmem.pop_block()
        else:
            out = self._invoke(*args, **kwargs)
        for hook in self._forward_hooks:
            hook(self, args, out)
        return out

    def _invoke(self, *args, **kwargs):
        try:
            return self.forward(*args, **kwargs)
        except DeferredInitializationError:
            self._deferred_infer(*args, **kwargs)
            return self.forward(*args, **kwargs)

    def _deferred_infer(self, *args, **kwargs):
        """Complete deferred shapes: per-layer infer_shape if provided."""
        self.infer_shape(*args, **kwargs)
        for p in self.collect_params().values():
            p.finish_deferred_init()

    def infer_shape(self, *args, **kwargs):
        """Layers with deferred params override this; containers recurse by
        just re-running forward (children infer on their own calls)."""
        raise DeferredInitializationError(
            f"{type(self).__name__} has uninitialized parameters and no "
            f"infer_shape; initialize with explicit shapes")

    def forward(self, *args, **kwargs):
        raise NotImplementedError

    def summary(self, *inputs):
        lines = [f"{type(self).__name__}("]
        for name, child in self._children.items():
            lines.append(f"  ({name}): {type(child).__name__}")
        lines.append(")")
        return "\n".join(lines)

    def __repr__(self):
        mods = "\n".join(f"  ({k}): {type(v).__name__}" for k, v in self._children.items())
        return f"{type(self).__name__}(\n{mods}\n)" if mods else f"{type(self).__name__}()"


class _CacheEntry:
    """One compiled signature: jitted forward + (lazily) jitted pullback —
    the forward/backward executable pair of the reference's CachedOp."""

    __slots__ = ("raw_fn", "jitted", "_vjp_jit", "n_real", "n_aux",
                 "aux_params", "out_treedef")

    def __init__(self, raw_fn, jitted, n_real, n_aux, aux_params, out_treedef):
        self.raw_fn = raw_fn      # (key, *raws) -> flat outputs, UNJITTED
        self.jitted = jitted      # jax.jit(raw_fn)
        self._vjp_jit = None
        self.n_real = n_real
        self.n_aux = n_aux
        self.aux_params = aux_params
        self.out_treedef = out_treedef

    def vjp_jit(self):
        # jax 0.9 cannot linearize some primitives (reduce_window) through an
        # inner pjit, so the pullback is built from the UNJITTED fn and jitted
        # as a whole: one compiled backward executable per signature.
        if self._vjp_jit is None:
            raw_fn = self.raw_fn

            def vjp_core(key, n_in_args):
                primals, cots = n_in_args
                _, pull = jax.vjp(lambda *p: raw_fn(key, *p), *primals)
                return pull(tuple(cots))

            self._vjp_jit = jax.jit(vjp_core)
        return self._vjp_jit


def _flatten_out(out):
    """Forward outputs → (list of NDArray, treedef). Supports NDArray or
    (possibly nested) tuple/list of NDArrays."""
    leaves = []

    def walk(o):
        if isinstance(o, NDArray):
            leaves.append(o)
            return ("leaf", len(leaves) - 1)
        if isinstance(o, (tuple, list)):
            return ("seq", type(o).__name__, [walk(i) for i in o])
        raise TypeError(f"hybridized forward must return NDArrays, got {type(o)}")

    tree = walk(out)
    return leaves, tree


def _unflatten_out(tree, leaves):
    kind = tree[0]
    if kind == "leaf":
        return leaves[tree[1]]
    _, tname, children = tree
    seq = [_unflatten_out(c, leaves) for c in children]
    return tuple(seq) if tname == "tuple" else seq


class HybridBlock(Block):
    def __init__(self, prefix=None, params=None):
        super().__init__(prefix, params)
        self._active = False
        self._cache = {}

    def hybridize(self, active=True, **kwargs):
        self._active = active
        self._cache = {}
        for child in self._children.values():
            if isinstance(child, HybridBlock):
                child.hybridize(active, **kwargs)

    def _invoke(self, *args, **kwargs):
        if self._active and not _trace.active and not kwargs:
            if all(isinstance(a, NDArray) for a in args):
                return self._call_cached(*args)
        return super()._invoke(*args, **kwargs)

    # -- the TPU CachedOp -------------------------------------------------
    def _call_cached(self, *args):
        params = list(self.collect_params().values())
        try:
            param_nds = [p.data() for p in params]
        except DeferredInitializationError:
            with autograd.pause(False):  # one shape-inference pass, no aux drift
                super()._invoke(*args)
            params = list(self.collect_params().values())
            param_nds = [p.data() for p in params]

        training = autograd.is_training()
        sig = (tuple((tuple(a.shape), str(a._data.dtype)) for a in args), training)
        entry = self._cache.get(sig)
        if entry is None:
            if _flight._REC is not None:
                _flight.record("compile", "jit.compile:" + self.name,
                               {"signature": repr(sig)})
            if _prof._ACTIVE:
                # jit compile-cache miss: the recorded span covers the
                # trace/lower work in _build_cache; the device compile
                # itself happens lazily inside the first dispatch, which
                # the op hook times as the first `<name>_cachedop` event
                _prof.counter("jit.cache_miss", "gluon").increment()
                with _prof.Scope("jit.compile:" + self.name, "jit",
                                 sync=False):
                    entry = self._build_cache(params, args, training)
            else:
                entry = self._build_cache(params, args, training)
            self._cache[sig] = entry
        elif _prof._ACTIVE:
            _prof.counter("jit.cache_hit", "gluon").increment()

        key_raw = ndrandom._key()
        n_total = entry.n_real + entry.n_aux
        n_in = len(params) + len(args)

        def node_fn(*raws):  # unjitted: stays on the tape for any re-derivation
            flat = entry.raw_fn(key_raw, *raws)
            return flat[0] if n_total == 1 else tuple(flat)

        def fwd_fn(*raws):  # compiled forward executable
            flat = entry.jitted(key_raw, *raws)
            return flat[0] if n_total == 1 else tuple(flat)

        def vjp_fn(*raws_and_cots):  # compiled backward executable
            primals = tuple(raws_and_cots[:n_in])
            cots = tuple(raws_and_cots[n_in:])
            in_cots = entry.vjp_jit()(key_raw, (primals, cots))
            return in_cots[0] if n_in == 1 else tuple(in_cots)

        outs = _apply(node_fn, param_nds + list(args), n_out=n_total,
                      name=self.name + "_cachedop", fn_fwd=fwd_fn, fn_vjp=vjp_fn)
        if n_total == 1:
            outs = (outs,)
        real, aux = outs[:entry.n_real], outs[entry.n_real:]
        for p, new in zip(entry.aux_params, aux):
            p._data._data = new._data  # write back outside the tape
        return _unflatten_out(entry.out_treedef, list(real))

    def _build_cache(self, params, args, training):
        from ..ops import select as _sel
        sub_ids = [id(p) for p in params]
        n_p = len(params)
        out_info = {}

        def raw_fn(key_raw, *raws):
            p_raws, a_raws = raws[:n_p], raws[n_p:]
            sub = dict(zip(sub_ids, p_raws))
            with _ParamTraceScope(sub), autograd._Scope(False, training), \
                    ndrandom._TraceKeyScope(key_raw):
                nd_args = [NDArray(r) for r in a_raws]
                out = self.forward(*nd_args)
                leaves, tree = _flatten_out(out)
                aux_items = [(_trace.params_seen[i], raw)
                             for i, raw in _trace.aux_updates.items()]
            out_info["tree"] = tree
            out_info["aux_params"] = [p for p, _ in aux_items]
            return tuple(x._data for x in leaves) + tuple(raw for _, raw in aux_items)

        jitted = jax.jit(raw_fn)
        # Abstract trace once to learn output structure (no device work).
        # The kernel-selection layer (ops/select) logs which pallas
        # kernels this signature's trace picked; the decisions go to the
        # flight recorder so "which kernels did my model get" is
        # answerable from a crash dump or a bench artifact.
        p_raws = [p.data()._data for p in params]
        dummy_key = jax.random.PRNGKey(0)
        with _sel.capture() as kernel_log:
            shapes = jax.eval_shape(raw_fn, dummy_key, *p_raws,
                                    *[a._data for a in args])
        if kernel_log and _flight._REC is not None:
            _flight.record("compile", "pallas.selection:" + self.name,
                           {"decisions": kernel_log[:32]})
        ps = _perfscope._PS
        if ps is not None and ps.capture_jit_cache:
            # roofline verdict for this signature's forward executable
            # (host-side lowering only; one extra trace per compile —
            # the reason jit-cache capture is gated on perfscope being
            # armed rather than always-on). Under a registered mesh the
            # same hook feeds commscope's collective extraction (mode
            # unknown for a bare forward, so its resharding detector
            # stays conservative here — docs/commscope.md)
            shape0 = tuple(args[0].shape) if args else ()
            _perfscope.analyze_jit(
                jitted, (dummy_key, *p_raws, *[a._data for a in args]),
                name=f"jit:{self.name}:{'x'.join(map(str, shape0))}",
                dtype=(args[0]._data.dtype if args else "float32"),
                kind="jit_cache",
                extra={"training": training,
                       "pallas_selections": len(kernel_log or ())})
        n_aux = len(out_info["aux_params"])
        n_real = len(shapes) - n_aux
        return _CacheEntry(raw_fn, jitted, n_real, n_aux,
                           out_info["aux_params"], out_info["tree"])

    def export(self, path, epoch=0):
        """Parity: HybridBlock.export (python/mxnet/gluon/block.py:export) —
        writes `path-symbol.json` + `path-{epoch:04d}.params` (checkpoint
        format, `arg:`/`aux:` prefixes) loadable by SymbolBlock.imports or
        Module. The graph comes from symbol tracing the eager forward
        (gluon/symbolize.py); blocks whose forward uses raw jax closures
        (custom `_apply` fns) cannot be traced and raise
        NotImplementedError — for those, save_parameters still works."""
        from .symbolize import trace_symbol
        from .. import ndarray as nd_mod
        sym, arg_params, aux_params = trace_symbol(self)
        sym.save(f"{path}-symbol.json")
        save_dict = {f"arg:{k}": v for k, v in arg_params.items()}
        save_dict.update({f"aux:{k}": v for k, v in aux_params.items()})
        nd_mod.save(f"{path}-{epoch:04d}.params", save_dict)
        return sym, arg_params, aux_params

    def freeze(self, input_shape, dtype="float32", **kwargs):
        """Export→serve handoff without the disk round trip: snapshot
        this block's parameters and AOT-compile per-bucket inference
        executables (see serving.FrozenModel). `input_shape` is the
        PER-SAMPLE shape (no batch dim). The returned FrozenModel is
        immutable — further training of this block does not affect it.
        For the on-disk flow, pair `export()` with
        `serving.FrozenModel.from_exported(prefix, input_shape)`."""
        from ..serving import FrozenModel
        return FrozenModel(self, input_shape, dtype=dtype, **kwargs)

    def forward(self, *args, **kwargs):
        raise NotImplementedError


class SymbolBlock(HybridBlock):
    """Wrap a (bound-able) Symbol graph as a Gluon block (parity:
    python/mxnet/gluon/block.py SymbolBlock) — the serving/fine-tuning
    bridge between the Symbol and Gluon APIs: import a saved symbol +
    checkpoint, then treat it as an ordinary HybridBlock (compose, train,
    hybridize).

    TPU-native: forward evaluates the graph through the same jnp-level
    graph runner the Executor compiles, recorded on the autograd tape as
    one node (`_apply`), so eager backward and the hybridized CachedOp both
    run the graph as fused XLA computations.
    """

    def __init__(self, outputs, inputs, params=None):
        from .. import symbol as sym_mod
        from ..symbol import _topo
        from ..symbol.executor import _graph_runner

        super().__init__(prefix="", params=None)
        if isinstance(outputs, (list, tuple)):
            outputs = sym_mod.Group(list(outputs))
        if isinstance(inputs, (str, sym_mod.Symbol)):
            inputs = [inputs]
        input_names = [i.name if isinstance(i, sym_mod.Symbol) else str(i)
                       for i in inputs]
        self._symbol = outputs
        self._input_names = input_names
        arg_names = outputs.list_arguments()
        aux_names = outputs.list_auxiliary_states()
        missing = [n for n in input_names if n not in arg_names]
        if missing:
            raise ValueError(f"inputs {missing} are not arguments of the "
                             f"symbol (arguments: {arg_names})")
        self._arg_names = arg_names
        self._aux_names = aux_names
        param_names = [n for n in arg_names if n not in input_names]

        shared = dict(params.items()) if params is not None else {}
        self._arg_params_list = []
        for n in param_names:
            if n in shared:
                self._params.update([(n, shared[n])])
                self._arg_params_list.append(shared[n])
            else:
                self._arg_params_list.append(
                    self._params.get(n, shape=None, allow_deferred_init=True))
        self._aux_params_list = []
        for n in aux_names:
            if n in shared:
                self._params.update([(n, shared[n])])
                self._aux_params_list.append(shared[n])
            else:
                self._aux_params_list.append(
                    self._params.get(n, shape=None, grad_req="null",
                                     init="zeros", allow_deferred_init=True))

        order = _topo(outputs._entries)
        var_by_name = {n.name: n for n in order if n.is_var}
        self._runner = _graph_runner(outputs._entries,
                                     [var_by_name[n] for n in arg_names],
                                     [var_by_name[n] for n in aux_names])
        self._n_out = len(outputs._entries)
        # positions of inputs vs params within the symbol's argument order
        self._input_pos = [arg_names.index(n) for n in input_names]
        self._param_pos = [arg_names.index(n) for n in param_names]

    @staticmethod
    def imports(symbol_file, input_names, param_file=None, ctx=None):
        """Load `prefix-symbol.json` (+ optional `prefix-NNNN.params` in the
        checkpoint format, `arg:`/`aux:` prefixes) into a SymbolBlock."""
        from .. import ndarray as nd_mod
        from .. import symbol as sym_mod

        symbol = sym_mod.load(symbol_file)
        if isinstance(input_names, str):
            input_names = [input_names]
        block = SymbolBlock(symbol, input_names)
        if param_file is not None:
            loaded = nd_mod.load(param_file)
            by_name = {}
            for k, v in loaded.items():
                by_name[k.split(":", 1)[1] if ":" in k else k] = v
            for name, p in block._params.items():
                if name in by_name:
                    p.set_data(by_name[name])
                else:
                    raise KeyError(f"Parameter {name} missing from "
                                   f"{param_file}")
        return block

    def _complete_deferred(self, args):
        """Finish deferred param init by running symbol shape inference with
        the observed input shapes."""
        pending = [p for p in self._arg_params_list + self._aux_params_list
                   if p._data is None]
        if not pending:
            return
        shapes = {n: tuple(a.shape)
                  for n, a in zip(self._input_names, args)}
        arg_shapes, _, aux_shapes = self._symbol.infer_shape(**shapes)
        for pos, p in zip(self._param_pos, self._arg_params_list):
            if p._data is None and arg_shapes[pos] is not None:
                p.shape = arg_shapes[pos]
        for s, p in zip(aux_shapes, self._aux_params_list):
            if p._data is None and s is not None:
                p.shape = s
        for p in pending:
            if p._deferred is not None:
                p.finish_deferred_init()
            if p._data is None:
                raise DeferredInitializationError(
                    f"Parameter {p.name}: call initialize() before forward")

    def forward(self, *args):
        from ..symbol import _Runtime

        if len(args) != len(self._input_names):
            raise ValueError(f"SymbolBlock expects {len(self._input_names)} "
                             f"inputs {self._input_names}, got {len(args)}")
        self._complete_deferred(args)
        param_nds = [p.data() for p in self._arg_params_list]
        aux_nds = [p.data() for p in self._aux_params_list]
        is_train = autograd.is_training()
        key = ndrandom._key()
        runner = self._runner
        n_in, n_p = len(args), len(param_nds)
        n_out, n_aux = self._n_out, len(aux_nds)
        n_args_total = len(self._arg_names)
        input_pos, param_pos = self._input_pos, self._param_pos

        def f(*raws):
            in_raws = raws[:n_in]
            p_raws = raws[n_in:n_in + n_p]
            aux_raws = raws[n_in + n_p:]
            arg_raws = [None] * n_args_total
            for pos, r in zip(input_pos, in_raws):
                arg_raws[pos] = r
            for pos, r in zip(param_pos, p_raws):
                arg_raws[pos] = r
            rt = _Runtime(is_train, key)
            outs, new_aux = runner(rt, arg_raws, aux_raws)
            flat = tuple(outs) + tuple(new_aux)
            # a 1-tuple under _apply(n_out=1) would stack into a bogus
            # leading axis (bit every no-aux graph, e.g. the causal LM)
            return flat[0] if len(flat) == 1 else flat

        res = _apply(f, list(args) + param_nds + aux_nds,
                     n_out=n_out + n_aux, name="symbolblock")
        if n_out + n_aux == 1:
            res = (res,)
        outs, new_aux = res[:n_out], res[n_out:]
        if is_train:
            for p, new in zip(self._aux_params_list, new_aux):
                p.update_aux(new._data)
        return outs[0] if n_out == 1 else list(outs)
