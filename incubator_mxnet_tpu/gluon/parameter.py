"""Parameter & ParameterDict (parity: python/mxnet/gluon/parameter.py).

A Parameter owns an NDArray plus grad bookkeeping. Two extras make the
TPU-native design work:

* trace substitution — while a HybridBlock is being traced under jax.jit,
  `param.data()` returns the traced value injected as a jit argument (so one
  compiled executable serves every step without retracing as weights change);
* aux-state sink — non-learnable state (BatchNorm running stats) updated
  during a traced forward is captured as extra jit outputs and written back
  after the call, keeping the jitted function pure.
"""
from __future__ import annotations

import threading
from collections import OrderedDict

import numpy as np

from .. import autograd, initializer as _initializer
from ..context import Context, current_context
from ..ndarray import NDArray
from .. import ndarray as nd


class DeferredInitializationError(RuntimeError):
    pass


class _TraceState(threading.local):
    def __init__(self):
        self.active = False
        self.sub = {}          # id(Parameter) -> raw traced array
        self.aux_updates = {}  # id(Parameter) -> raw traced array
        self.params_seen = {}  # id(Parameter) -> Parameter (ordering)


_trace = _TraceState()


class _ParamTraceScope:
    """Context manager installing the substitution map during tracing."""

    def __init__(self, sub):
        self._sub = sub

    def __enter__(self):
        _trace.active = True
        _trace.sub = self._sub
        _trace.aux_updates = {}
        return _trace

    def __exit__(self, *exc):
        _trace.active = False
        _trace.sub = {}
        _trace.params_seen = {}  # drop refs: avoid pinning dead models' aux
        return False


class Parameter:
    """A weight/bias/aux tensor of a Block.

    grad_req: 'write' | 'add' | 'null' ('null' → aux state, no gradient).
    Shapes may contain 0 (unknown) for deferred initialization; they are
    completed from the first forward's input shapes.
    """

    def __init__(self, name, shape=None, dtype="float32", init=None,
                 grad_req="write", lr_mult=1.0, wd_mult=1.0,
                 allow_deferred_init=True, differentiable=True):
        self.name = name
        self._shape = tuple(shape) if shape is not None else None
        self.dtype = dtype
        self.init = init
        self.grad_req = grad_req if differentiable else "null"
        self.lr_mult = lr_mult
        self.wd_mult = wd_mult
        self.allow_deferred_init = allow_deferred_init
        self._data: NDArray | None = None
        self._deferred = None  # (init, ctx) awaiting shape completion
        self._sharding = None  # parallel/: optional PartitionSpec annotation

    # -- shape ------------------------------------------------------------
    @property
    def shape(self):
        return self._shape

    @shape.setter
    def shape(self, new):
        if self._shape is not None:
            assert len(self._shape) == len(new) and all(
                s in (0, n) for s, n in zip(self._shape, new)), (
                f"Inferred shape {new} incompatible with declared {self._shape} "
                f"for parameter {self.name}")
        self._shape = tuple(int(s) for s in new)

    @property
    def shape_is_known(self):
        return self._shape is not None and all(s > 0 for s in self._shape)

    # -- init -------------------------------------------------------------
    def initialize(self, init=None, ctx=None, default_init=None, force_reinit=False):
        if self._data is not None and not force_reinit:
            return
        if isinstance(ctx, (list, tuple)):
            ctx = ctx[0] if ctx else None  # single-device storage; mesh sharding
        ctx = ctx or current_context()     # is handled by parallel/, not replicas
        eff = init or self.init or default_init or _initializer.create("uniform")
        if isinstance(eff, str):
            eff = _initializer.create(eff)
        if isinstance(eff, _initializer.Mixed):
            eff = eff.init_for(self.name)
        if not self.shape_is_known:
            if not self.allow_deferred_init:
                raise DeferredInitializationError(
                    f"Parameter {self.name} has unknown shape {self._shape}")
            self._deferred = (eff, ctx)
            return
        self._finish_init(eff, ctx)

    def _finish_init(self, init_obj, ctx):
        from ..ndarray import random as ndrandom
        key = ndrandom._key()
        raw = init_obj(key, self._shape, self.dtype)
        self._data = NDArray(raw, ctx=ctx)
        if self.grad_req != "null":
            self._data.attach_grad(self.grad_req)
        self._deferred = None

    def finish_deferred_init(self):
        if self._deferred is not None:
            if not self.shape_is_known:
                raise DeferredInitializationError(
                    f"Parameter {self.name}: shape still unknown {self._shape}")
            init_obj, ctx = self._deferred
            self._finish_init(init_obj, ctx)

    # -- access -----------------------------------------------------------
    def data(self, ctx=None) -> NDArray:
        if _trace.active:
            raw = _trace.sub.get(id(self))
            if raw is not None:
                return NDArray(raw)
        if self._data is None:
            if self._deferred is not None:
                raise DeferredInitializationError(
                    f"Parameter {self.name} deferred; call net once or set shape")
            raise RuntimeError(
                f"Parameter {self.name} is not initialized; call .initialize()")
        return self._data

    def set_data(self, data):
        if not isinstance(data, NDArray):
            data = nd.array(data)
        if self._data is None:
            self.shape = data.shape
            self._data = data.astype(self.dtype)
            if self.grad_req != "null":
                self._data.attach_grad(self.grad_req)
        else:
            self._data._data = data._data.astype(self._data._data.dtype)

    def grad(self, ctx=None) -> NDArray:
        d = self.data()
        if d._grad is None:
            raise RuntimeError(f"Parameter {self.name} has no gradient "
                               f"(grad_req={self.grad_req})")
        return d._grad

    def zero_grad(self):
        if self._data is not None and self._data._grad is not None:
            self._data._grad._data = nd.zeros(self._data.shape,
                                              dtype=self._data._data.dtype)._data

    def register_grad_hook(self, fn):
        """``fn(self)`` fires the moment this parameter's gradient is
        finalized inside ``autograd.backward`` — i.e. mid-backward, as
        soon as no remaining node can contribute to it. The readiness
        signal for overlapped gradient communication (reference: BytePS /
        ByteScheduler per-tensor ready callbacks in ps-lite's push/pull
        pipeline). ``fn=None`` clears. Requires an initialized parameter
        (call after ``initialize()``/first forward for deferred shapes)."""
        if self._data is None:
            raise RuntimeError(
                f"Parameter {self.name} is not initialized; grad hooks "
                "attach to the parameter's storage")
        self._data._grad_hook = None if fn is None else (lambda _leaf: fn(self))

    def list_ctx(self):
        return [self._data.context] if self._data is not None else []

    def update_aux(self, raw):
        """Write new aux-state value; inside a trace this is captured as an
        extra output instead of mutating (keeps the jitted fn pure)."""
        if _trace.active:
            _trace.aux_updates[id(self)] = raw
            _trace.params_seen[id(self)] = self
        else:
            self._data._data = raw

    def cast(self, dtype):
        self.dtype = dtype
        if self._data is not None:
            had_grad = self._data._grad is not None
            self._data = self._data.astype(dtype)
            if had_grad:
                self._data.attach_grad(self.grad_req)

    def __repr__(self):
        return f"Parameter {self.name} (shape={self._shape}, dtype={self.dtype})"


class Constant(Parameter):
    """Non-learnable constant parameter (parity: gluon.Constant)."""

    def __init__(self, name, value):
        if not isinstance(value, NDArray):
            value = nd.array(value)
        super().__init__(name, shape=value.shape, dtype=str(np.dtype(value._data.dtype))
                         if value._data.dtype != np.dtype("V2") else "bfloat16",
                         init="zeros", grad_req="null")
        self._value = value

    def initialize(self, init=None, ctx=None, default_init=None, force_reinit=False):
        self._data = NDArray(self._value._data, ctx=ctx if not isinstance(ctx, list) else ctx[0])


class ParameterDict:
    """Ordered name→Parameter mapping (parity: gluon.ParameterDict)."""

    def __init__(self, prefix=""):
        self.prefix = prefix
        self._params = OrderedDict()

    def get(self, name, **kwargs) -> Parameter:
        full = self.prefix + name
        if full in self._params:
            return self._params[full]
        p = Parameter(full, **kwargs)
        self._params[full] = p
        return p

    def get_constant(self, name, value=None):
        full = self.prefix + name
        if full not in self._params:
            self._params[full] = Constant(full, value)
        return self._params[full]

    def update(self, other):
        items = other.items() if hasattr(other, "items") else other
        for k, v in items:
            self._params[k] = v

    def items(self):
        return self._params.items()

    def keys(self):
        return self._params.keys()

    def values(self):
        return self._params.values()

    def __iter__(self):
        return iter(self._params)

    def __getitem__(self, k):
        return self._params[k]

    def __contains__(self, k):
        return k in self._params

    def __len__(self):
        return len(self._params)

    def initialize(self, init=None, ctx=None, verbose=False, force_reinit=False):
        for p in self.values():
            p.initialize(init=None, ctx=ctx, default_init=init, force_reinit=force_reinit)

    def zero_grad(self):
        for p in self.values():
            p.zero_grad()

    def reset_ctx(self, ctx):
        for p in self.values():
            if p._data is not None:
                p._data = p._data.as_in_context(ctx)
                if p.grad_req != "null":
                    p._data.attach_grad(p.grad_req)

    def setattr(self, name, value):
        for p in self.values():
            setattr(p, name, value)

    def save(self, fname, strip_prefix=""):
        arrays = {}
        for name, p in self.items():
            if p._data is None:
                continue
            key = name[len(strip_prefix):] if name.startswith(strip_prefix) else name
            arrays[key] = p._data
        nd.save(fname, arrays)

    def load(self, fname, ctx=None, allow_missing=False,
             ignore_extra=False, restore_prefix=""):
        arrays = nd.load(fname)
        arrays = {restore_prefix + k: v for k, v in arrays.items()}
        for name, p in self.items():
            if name in arrays:
                p.set_data(arrays[name] if ctx is None else arrays[name].as_in_context(ctx))
            elif not allow_missing:
                raise KeyError(f"Parameter {name} missing from {fname}")
        if not ignore_extra:
            extra = set(arrays) - set(self._params)
            if extra:
                raise KeyError(f"File {fname} has extra parameters {sorted(extra)}")

    def __repr__(self):
        inner = "\n".join(f"  {p}" for p in self.values())
        return f"ParameterDict(\n{inner}\n)"
