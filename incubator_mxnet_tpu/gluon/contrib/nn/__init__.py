"""gluon.contrib.nn (parity: python/mxnet/gluon/contrib/nn/basic_layers.py):
SyncBatchNorm, HybridConcurrent, Concurrent, Identity, SparseEmbedding.
"""
from __future__ import annotations

from .... import ndarray as nd
from ...nn import BatchNorm, Embedding, HybridSequential
from ...block import HybridBlock

__all__ = ["SyncBatchNorm", "HybridConcurrent", "Concurrent", "Identity",
           "SparseEmbedding"]


class SyncBatchNorm(BatchNorm):
    """Cross-device synchronized BatchNorm (parity:
    gluon.contrib.nn.SyncBatchNorm, reference key=/num_devices API).

    TPU-native semantics: the reference needs an explicit NCCL allreduce of
    the batch statistics because each GPU sees only its slice. Under this
    framework's compiled mesh path (pjit over a `Mesh` — FusedTrainStep,
    dryrun_multichip) arrays are GLOBAL-view: `mean(x, axis=0)` inside the
    jitted step is already the global-batch mean, and XLA inserts the
    all-reduce over the data-parallel axis itself. So synchronized stats
    are the DEFAULT here, not an extra kernel — this class exists for API
    parity and asserts nothing extra is needed. (Per-device-view code
    paths — shard_map kernels — must psum stats explicitly; none of the
    shipped layers compute BN inside shard_map.)

    `num_devices`/`key` are accepted and ignored, matching call sites
    written for the reference.
    """

    def __init__(self, in_channels=0, num_devices=None, momentum=0.9,
                 epsilon=1e-5, center=True, scale=True,
                 use_global_stats=False, beta_initializer="zeros",
                 gamma_initializer="ones",
                 running_mean_initializer="zeros",
                 running_variance_initializer="ones", key=None, **kwargs):
        super().__init__(axis=kwargs.pop("axis", 1), momentum=momentum,
                         epsilon=epsilon, center=center, scale=scale,
                         use_global_stats=use_global_stats,
                         beta_initializer=beta_initializer,
                         gamma_initializer=gamma_initializer,
                         running_mean_initializer=running_mean_initializer,
                         running_variance_initializer=running_variance_initializer,
                         in_channels=in_channels, **kwargs)
        self._num_devices = num_devices


class HybridConcurrent(HybridSequential):
    """Runs each child on the SAME input and concatenates the outputs along
    `axis` (parity: contrib.nn.HybridConcurrent — Inception-style blocks)."""

    def __init__(self, axis=-1, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._concat_axis = axis

    def forward(self, x):
        outs = [child(x) for child in self._children.values()]
        return nd.concat(*outs, dim=self._concat_axis)


class Concurrent(HybridConcurrent):
    """Imperative alias (the reference distinguishes Block vs HybridBlock;
    both compile here)."""


class Identity(HybridBlock):
    """Passthrough (parity: contrib.nn.Identity — residual plumbing)."""

    def forward(self, x):
        return x


class SparseEmbedding(Embedding):
    """Embedding with row_sparse gradient (parity: contrib.nn.SparseEmbedding
    — the reference stores the weight itself row_sparse for ps-lite; here
    the weight is dense-on-HBM and the GRADIENT is RowSparse, which is the
    part that matters for the optimizer's lazy row update)."""

    def __init__(self, input_dim, output_dim, dtype="float32",
                 weight_initializer=None, **kwargs):
        super().__init__(input_dim, output_dim, dtype=dtype,
                         weight_initializer=weight_initializer,
                         sparse_grad=True, **kwargs)
