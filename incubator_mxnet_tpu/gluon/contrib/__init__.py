"""gluon.contrib (parity: python/mxnet/gluon/contrib/) — the extras the
reference ships outside the core layer set."""
from . import nn  # noqa: F401
from . import rnn  # noqa: F401
from . import estimator  # noqa: F401
