"""Event handlers for the Gluon Estimator (reference:
python/mxnet/gluon/contrib/estimator/event_handler.py).

Handlers mix in the stage marker classes (TrainBegin/…/BatchEnd); the
Estimator sorts same-stage handlers by ``priority``, LOWER FIRST: at batch
end the gradient update runs first (GradientUpdateHandler, -2000), then
metric updates (MetricHandler, -1000), then observers like logging
(+1000). A handler that must act on gradients BEFORE the optimizer step
(e.g. clipping) needs priority < -2000.
"""
import logging
import os
import time

import numpy as np

from .... import metric as metric_mod

__all__ = ["TrainBegin", "TrainEnd", "EpochBegin", "EpochEnd", "BatchBegin",
           "BatchEnd", "StoppingHandler", "MetricHandler",
           "ValidationHandler", "LoggingHandler", "CheckpointHandler",
           "EarlyStoppingHandler", "GradientUpdateHandler"]


class TrainBegin:
    def train_begin(self, estimator, *args, **kwargs):
        pass


class TrainEnd:
    def train_end(self, estimator, *args, **kwargs):
        pass


class EpochBegin:
    def epoch_begin(self, estimator, *args, **kwargs):
        pass


class EpochEnd:
    def epoch_end(self, estimator, *args, **kwargs):
        pass


class BatchBegin:
    def batch_begin(self, estimator, *args, **kwargs):
        pass


class BatchEnd:
    def batch_end(self, estimator, *args, **kwargs):
        pass


class StoppingHandler(TrainBegin, BatchEnd, EpochEnd):
    """Stop after ``max_epoch`` epochs or ``max_batch`` total batches."""

    def __init__(self, max_epoch=None, max_batch=None):
        self.max_epoch = max_epoch
        self.max_batch = max_batch
        self.current_batch = 0
        self.current_epoch = 0
        self.stop_training = False

    def train_begin(self, estimator, *args, **kwargs):
        self.current_batch = 0
        self.current_epoch = 0

    def batch_end(self, estimator, *args, **kwargs):
        self.current_batch += 1
        if self.max_batch is not None and self.current_batch >= self.max_batch:
            estimator.stop_training = True

    def epoch_end(self, estimator, *args, **kwargs):
        self.current_epoch += 1
        if self.max_epoch is not None and self.current_epoch >= self.max_epoch:
            estimator.stop_training = True


class MetricHandler(EpochBegin, BatchEnd):
    """Reset train metrics at epoch start; update them from each batch's
    (label, pred) — and the loss metrics from the batch loss. Runs before
    other batch-end handlers (priority -1000) so logging sees fresh
    values."""

    priority = -1000

    def __init__(self, metrics):
        self.metrics = list(metrics)

    def epoch_begin(self, estimator, *args, **kwargs):
        for m in self.metrics:
            m.reset()

    def batch_end(self, estimator, *args, **kwargs):
        pred = kwargs.get("pred")
        label = kwargs.get("label")
        loss = kwargs.get("loss")
        for m in self.metrics:
            if isinstance(m, metric_mod.Loss):
                m.update(0, loss)
            else:
                m.update(label, pred)


class GradientUpdateHandler(BatchEnd):
    """Apply the optimizer step (trainer.step) at batch end. Split out as a
    handler (reference design) so users can reorder/replace it — e.g. for
    gradient accumulation. Priority -2000: runs first."""

    priority = -2000

    def batch_end(self, estimator, *args, **kwargs):
        loss = kwargs.get("loss")
        batch_size = 0
        if loss is not None:
            losses = loss if isinstance(loss, (list, tuple)) else [loss]
            batch_size = sum(l.shape[0] if getattr(l, "ndim", 0) else 1
                             for l in losses)
        estimator.trainer.step(batch_size or 1)


class ValidationHandler(TrainBegin, BatchEnd, EpochEnd):
    """Run ``eval_fn`` (usually ``estimator.evaluate``) every
    ``epoch_period`` epochs and/or every ``batch_period`` batches."""

    priority = -500

    def __init__(self, val_data, eval_fn, epoch_period=1, batch_period=None,
                 event_handlers=None):
        self.val_data = val_data
        self.eval_fn = eval_fn
        self.epoch_period = epoch_period
        self.batch_period = batch_period
        self.event_handlers = event_handlers
        self.current_batch = 0
        self.current_epoch = 0

    def train_begin(self, estimator, *args, **kwargs):
        self.current_batch = 0
        self.current_epoch = 0

    def batch_end(self, estimator, *args, **kwargs):
        self.current_batch += 1
        if (self.batch_period is not None
                and self.current_batch % self.batch_period == 0):
            self.eval_fn(self.val_data, event_handlers=self.event_handlers)

    def epoch_end(self, estimator, *args, **kwargs):
        self.current_epoch += 1
        if (self.epoch_period is not None
                and self.current_epoch % self.epoch_period == 0):
            self.eval_fn(self.val_data, event_handlers=self.event_handlers)


class LoggingHandler(TrainBegin, TrainEnd, EpochBegin, EpochEnd, BatchBegin,
                     BatchEnd):
    """Log training progress: per-epoch always; per-batch every
    ``log_interval`` batches when set."""

    priority = 1000  # after metric updates

    def __init__(self, log_interval=None, metrics=None):
        self.log_interval = log_interval
        self.metrics = metrics
        self.batch_index = 0
        self.current_epoch = 0
        self.processed_samples = 0
        self.logger = logging.getLogger("incubator_mxnet_tpu.estimator")

    def _fmt(self, estimator):
        ms = self.metrics if self.metrics is not None else (
            estimator.train_metrics)
        return ", ".join(f"{n}: {v:.4f}" if isinstance(v, float)
                         else f"{n}: {v}"
                         for n, v in (m.get_name_value()[0] for m in ms))

    def train_begin(self, estimator, *args, **kwargs):
        self.train_start = time.time()
        self.batch_index = 0
        self.current_epoch = 0
        self.logger.info("Training begin")

    def train_end(self, estimator, *args, **kwargs):
        self.logger.info("Training finished in %.2fs (%d epochs)",
                         time.time() - self.train_start, self.current_epoch)

    def epoch_begin(self, estimator, *args, **kwargs):
        self.epoch_start = time.time()
        self.batch_index = 0
        self.processed_samples = 0

    def epoch_end(self, estimator, *args, **kwargs):
        self.logger.info("Epoch %d finished in %.2fs: %s",
                         self.current_epoch,
                         time.time() - self.epoch_start, self._fmt(estimator))
        self.current_epoch += 1

    def batch_end(self, estimator, *args, **kwargs):
        batch = kwargs.get("batch")
        if batch is not None:
            try:
                self.processed_samples += batch[0].shape[0]
            except Exception:  # noqa: BLE001 — non-array batch payloads
                pass
        self.batch_index += 1
        if self.log_interval and self.batch_index % self.log_interval == 0:
            self.logger.info("Epoch %d batch %d: %s", self.current_epoch,
                             self.batch_index, self._fmt(estimator))


class CheckpointHandler(TrainBegin, BatchEnd, EpochEnd):
    """Save model (and trainer) state every ``epoch_period`` epochs /
    ``batch_period`` batches to ``model_dir/model_prefix-epochN.params``;
    optionally track the best value of ``monitor`` and keep
    ``model_prefix-best.params`` (reference CheckpointHandler)."""

    priority = 500

    def __init__(self, model_dir, model_prefix="model", monitor=None,
                 mode="auto", epoch_period=1, batch_period=None,
                 save_best=False, max_checkpoints=5):
        self.model_dir = model_dir
        self.model_prefix = model_prefix
        self.monitor = monitor
        self.epoch_period = epoch_period
        self.batch_period = batch_period
        self.save_best = save_best
        self.max_checkpoints = max_checkpoints
        self.saved = []
        self.current_epoch = 0
        self.current_batch = 0
        if mode == "auto":
            name = monitor.get()[0] if monitor is not None else ""
            mode = "max" if ("acc" in str(name).lower()
                             or "f1" in str(name).lower()) else "min"
        self.mode = mode
        self.best = -np.inf if mode == "max" else np.inf

    def train_begin(self, estimator, *args, **kwargs):
        os.makedirs(self.model_dir, exist_ok=True)
        self.current_epoch = 0
        self.current_batch = 0

    def _save(self, estimator, tag):
        path = os.path.join(self.model_dir, f"{self.model_prefix}-{tag}")
        estimator.net.save_parameters(path + ".params")
        if estimator.trainer is not None:
            estimator.trainer.save_states(path + ".states")
        self.saved.append(path)
        while len(self.saved) > self.max_checkpoints:
            old = self.saved.pop(0)
            for suffix in (".params", ".states"):
                try:
                    os.remove(old + suffix)
                except OSError:
                    pass
        return path

    def _maybe_save_best(self, estimator):
        if not (self.save_best and self.monitor is not None):
            return
        _, value = self.monitor.get_name_value()[0]
        better = (value > self.best if self.mode == "max"
                  else value < self.best)
        if better:
            self.best = value
            path = os.path.join(self.model_dir, f"{self.model_prefix}-best")
            estimator.net.save_parameters(path + ".params")

    def batch_end(self, estimator, *args, **kwargs):
        self.current_batch += 1
        if (self.batch_period is not None
                and self.current_batch % self.batch_period == 0):
            self._save(estimator, f"batch{self.current_batch}")

    def epoch_end(self, estimator, *args, **kwargs):
        self.current_epoch += 1
        if (self.epoch_period is not None
                and self.current_epoch % self.epoch_period == 0):
            self._save(estimator, f"epoch{self.current_epoch - 1}")
            self._maybe_save_best(estimator)


class EarlyStoppingHandler(TrainBegin, EpochEnd, TrainEnd):
    """Stop training when ``monitor`` stops improving by ``min_delta`` for
    ``patience`` consecutive epochs (reference EarlyStoppingHandler)."""

    priority = 800

    def __init__(self, monitor, min_delta=0.0, patience=0, mode="auto",
                 baseline=None):
        self.monitor = monitor
        self.min_delta = min_delta
        self.patience = patience
        self.baseline = baseline
        name = monitor.get()[0] if monitor is not None else ""
        if mode == "auto":
            mode = "max" if ("acc" in str(name).lower()
                             or "f1" in str(name).lower()) else "min"
        self.mode = mode
        self.stopped_epoch = None
        self.logger = logging.getLogger("incubator_mxnet_tpu.estimator")

    def train_begin(self, estimator, *args, **kwargs):
        self.wait = 0
        self.current_epoch = 0
        self.stopped_epoch = None
        self.best = (self.baseline if self.baseline is not None
                     else (-np.inf if self.mode == "max" else np.inf))

    def epoch_end(self, estimator, *args, **kwargs):
        _, value = self.monitor.get_name_value()[0]
        if isinstance(value, str) or value != value:  # non-numeric / nan
            self.current_epoch += 1
            return
        improved = (value - self.min_delta > self.best
                    if self.mode == "max"
                    else value + self.min_delta < self.best)
        if improved:
            self.best = value
            self.wait = 0
        else:
            self.wait += 1
            if self.wait > self.patience:
                self.stopped_epoch = self.current_epoch
                estimator.stop_training = True
        self.current_epoch += 1

    def train_end(self, estimator, *args, **kwargs):
        if self.stopped_epoch is not None:
            self.logger.info("Early stopping at epoch %d (%s best %.4f)",
                             self.stopped_epoch, self.monitor.get()[0],
                             self.best)
