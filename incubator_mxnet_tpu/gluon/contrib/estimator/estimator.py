"""Gluon Estimator: high-level fit/evaluate loop over Block + Loss +
Trainer (reference: python/mxnet/gluon/contrib/estimator/estimator.py).

TPU-first notes: the inner batch step is the standard gluon tape step
(record → backward → trainer.step), so a hybridized net runs as one XLA
computation per forward/backward; data is split across the context list
with ``split_and_load`` (single-chip by default). The event-handler
protocol (and handler set) mirrors the reference so training scripts
port unchanged.
"""
import logging

from ... import utils as gluon_utils
from .... import autograd
from .... import context as context_mod
from .... import metric as metric_mod
from ....gluon import loss as gluon_loss
from ....gluon.trainer import Trainer
from .event_handler import (BatchBegin, BatchEnd, EpochBegin, EpochEnd,
                            GradientUpdateHandler, LoggingHandler,
                            MetricHandler, StoppingHandler, TrainBegin,
                            TrainEnd, ValidationHandler)

__all__ = ["Estimator"]


def _as_list(x):
    if x is None:
        return []
    return list(x) if isinstance(x, (list, tuple)) else [x]


class Estimator:
    """Drives the train loop: ``fit`` iterates (data, label) batches from a
    DataLoader, runs forward/loss under ``autograd.record``, backward, and
    dispatches the event-handler protocol.

    Parameters mirror the reference: net (Block), loss (gluon loss),
    train_metrics/val_metrics (EvalMetric or list), trainer (created with
    sgd lr=1e-3 if omitted), context (Context or list)."""

    logger = logging.getLogger("incubator_mxnet_tpu.estimator")

    def __init__(self, net, loss, train_metrics=None, val_metrics=None,
                 trainer=None, context=None, batch_axis=0):
        self.net = net
        self.loss = loss
        if not isinstance(loss, gluon_loss.Loss):
            raise ValueError(f"loss must be a gluon Loss, got {type(loss)}")
        self.train_metrics = _as_list(train_metrics)
        self.val_metrics = _as_list(val_metrics)
        for m in self.train_metrics + self.val_metrics:
            if not isinstance(m, metric_mod.EvalMetric):
                raise ValueError(f"metrics must be EvalMetric, got {type(m)}")
        # loss metrics ride along with their own Loss-typed entries
        self.train_loss_metric = metric_mod.Loss(
            f"train {type(loss).__name__.lower()}")
        self.val_loss_metric = metric_mod.Loss(
            f"validation {type(loss).__name__.lower()}")
        self.train_metrics.append(self.train_loss_metric)
        self.val_metrics.append(self.val_loss_metric)
        self.context = _as_list(context) or [context_mod.current_context()]
        self.trainer = trainer if trainer is not None else Trainer(
            net.collect_params(), "sgd", {"learning_rate": 1e-3})
        self.batch_axis = batch_axis
        self.stop_training = False
        self.max_epoch = None
        self.max_batch = None

    # -- data plumbing ----------------------------------------------------
    def _get_data_and_label(self, batch):
        data, label = batch[0], batch[1]
        data = gluon_utils.split_and_load(data, self.context,
                                          batch_axis=self.batch_axis)
        label = gluon_utils.split_and_load(label, self.context,
                                           batch_axis=self.batch_axis)
        return data, label

    # -- evaluation -------------------------------------------------------
    def evaluate_batch(self, batch):
        data, label = self._get_data_and_label(batch)
        pred = [self.net(x) for x in data]
        loss = [self.loss(p, y) for p, y in zip(pred, label)]
        return data, label, pred, loss

    def evaluate(self, val_data, event_handlers=None):
        """Run the val loop, updating ``self.val_metrics``. Optional
        ``event_handlers`` observe the val pass: epoch_begin before it,
        batch_end per batch (with batch/pred/label/loss), epoch_end
        after."""
        _, epoch_begin, batch_begin, batch_end, epoch_end, _ = \
            self._categorize(_as_list(event_handlers))
        for m in self.val_metrics:
            m.reset()
        for h in epoch_begin:
            h.epoch_begin(self)
        for batch in val_data:
            for h in batch_begin:
                h.batch_begin(self, batch=batch)
            _, label, pred, loss = self.evaluate_batch(batch)
            for m in self.val_metrics:
                if isinstance(m, metric_mod.Loss):
                    m.update(0, loss)
                else:
                    m.update(label, pred)
            for h in batch_end:
                h.batch_end(self, batch=batch, pred=pred, label=label,
                            loss=loss)
        for h in epoch_end:
            h.epoch_end(self)
        return {n: v for n, v in
                (m.get_name_value()[0] for m in self.val_metrics)}

    # -- training ---------------------------------------------------------
    def fit_batch(self, batch):
        data, label = self._get_data_and_label(batch)
        with autograd.record():
            pred = [self.net(x) for x in data]
            loss = [self.loss(p, y) for p, y in zip(pred, label)]
        for l in loss:
            l.backward()
        return data, label, pred, loss

    def fit(self, train_data, val_data=None, epochs=None, event_handlers=None,
            batches=None):
        """Train for ``epochs`` epochs (or ``batches`` total batches —
        exactly one of the two)."""
        if (epochs is None) == (batches is None):
            raise ValueError("pass exactly one of epochs / batches")
        limit = epochs if epochs is not None else batches
        if limit < 0:
            raise ValueError(f"epochs/batches must be >= 0, got {limit}")
        if limit == 0:
            return  # zero training requested: touch nothing
        self.max_epoch = epochs
        self.max_batch = batches
        self.stop_training = False

        handlers = self._prepare_handlers(val_data, event_handlers)
        train_begin, epoch_begin, batch_begin, batch_end, epoch_end, \
            train_end = self._categorize(handlers)

        for h in train_begin:
            h.train_begin(self)
        while not self.stop_training:
            for h in epoch_begin:
                h.epoch_begin(self)
            n_batches = 0
            for batch in train_data:
                n_batches += 1
                for h in batch_begin:
                    h.batch_begin(self, batch=batch)
                _, label, pred, loss = self.fit_batch(batch)
                for h in batch_end:
                    h.batch_end(self, batch=batch, pred=pred, label=label,
                                loss=loss)
                if self.stop_training:
                    break
            else:
                if n_batches == 0:
                    raise ValueError(
                        "train_data yielded no batches — with batches=N "
                        "this would loop forever")
                for h in epoch_end:
                    h.epoch_end(self)
                continue
            # batch-level stop: still fire epoch_end so epoch-scoped
            # handlers (checkpoint, logging) observe the partial epoch
            for h in epoch_end:
                h.epoch_end(self)
        for h in train_end:
            h.train_end(self)

    # -- handler plumbing -------------------------------------------------
    def _prepare_handlers(self, val_data, event_handlers):
        handlers = _as_list(event_handlers)
        added = []
        if not any(isinstance(h, StoppingHandler) for h in handlers):
            h = StoppingHandler(self.max_epoch, self.max_batch)
            handlers.append(h)
            added.append(h)
        if not any(isinstance(h, MetricHandler) for h in handlers):
            h = MetricHandler(self.train_metrics)
            handlers.append(h)
            added.append(h)
        if not any(isinstance(h, GradientUpdateHandler) for h in handlers):
            h = GradientUpdateHandler()
            handlers.append(h)
            added.append(h)
        if val_data is not None and not any(
                isinstance(h, ValidationHandler) for h in handlers):
            h = ValidationHandler(val_data, eval_fn=self.evaluate)
            handlers.append(h)
            added.append(h)
        if not any(isinstance(h, LoggingHandler) for h in handlers):
            h = LoggingHandler()
            handlers.append(h)
            added.append(h)
        if added:
            self.logger.debug("added default handlers: %s",
                              [type(h).__name__ for h in added])
        return handlers

    @staticmethod
    def _categorize(handlers):
        def of(kind):
            hs = [h for h in handlers if isinstance(h, kind)]
            return sorted(hs, key=lambda h: getattr(h, "priority", 0))

        return (of(TrainBegin), of(EpochBegin), of(BatchBegin), of(BatchEnd),
                of(EpochEnd), of(TrainEnd))
