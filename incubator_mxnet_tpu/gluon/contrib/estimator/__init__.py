"""Gluon Estimator API (reference:
python/mxnet/gluon/contrib/estimator/): train-loop-as-a-library with a
composable event-handler protocol."""
from .estimator import Estimator
from .event_handler import (BatchBegin, BatchEnd, CheckpointHandler,
                            EarlyStoppingHandler, EpochBegin, EpochEnd,
                            GradientUpdateHandler, LoggingHandler,
                            MetricHandler, StoppingHandler, TrainBegin,
                            TrainEnd, ValidationHandler)

__all__ = ["Estimator", "TrainBegin", "TrainEnd", "EpochBegin", "EpochEnd",
           "BatchBegin", "BatchEnd", "StoppingHandler", "MetricHandler",
           "ValidationHandler", "LoggingHandler", "CheckpointHandler",
           "EarlyStoppingHandler", "GradientUpdateHandler"]
