"""gluon.contrib.rnn (parity: python/mxnet/gluon/contrib/rnn/{rnn_cell,
conv_rnn_cell}.py): VariationalDropoutCell, LSTMPCell, and the
convolutional RNN/LSTM/GRU cell family.

TPU-first notes: conv cells run their i2h/h2h convolutions through the
same XLA conv path as gluon.nn.Conv* (MXU-tiled); under hybridize the
whole unrolled recurrence fuses into one XLA computation. Variational
dropout samples its masks once per sequence (per `reset`), so the mask is
a loop constant XLA hoists out of the unrolled graph.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .... import autograd
from ....ndarray import _apply
from ....ndarray import random as ndrandom
from ....ops import _raw
from ...rnn import RecurrentCell

__all__ = ["VariationalDropoutCell", "LSTMPCell",
           "Conv1DRNNCell", "Conv2DRNNCell", "Conv3DRNNCell",
           "Conv1DLSTMCell", "Conv2DLSTMCell", "Conv3DLSTMCell",
           "Conv1DGRUCell", "Conv2DGRUCell", "Conv3DGRUCell"]


class VariationalDropoutCell(RecurrentCell):
    """Variational (locked) dropout around a cell (parity:
    gluon.contrib.rnn.VariationalDropoutCell): ONE mask per sequence for
    inputs/states/outputs, reused at every timestep (Gal & Ghahramani),
    unlike DropoutCell's fresh mask per step."""

    def __init__(self, base_cell, drop_inputs=0.0, drop_states=0.0,
                 drop_outputs=0.0, **kwargs):
        super().__init__(**kwargs)
        self.base_cell = base_cell
        self.drop_inputs = drop_inputs
        self.drop_states = drop_states
        self.drop_outputs = drop_outputs
        self._input_mask = None
        self._state_mask = None
        self._output_mask = None

    def state_info(self, batch_size=0):
        return self.base_cell.state_info(batch_size)

    def reset(self):
        super().reset()
        self._input_mask = None
        self._state_mask = None
        self._output_mask = None

    def begin_state(self, batch_size=0, func=None, **kwargs):
        self.reset()
        return self.base_cell.begin_state(batch_size, func, **kwargs)

    @staticmethod
    def _mask(rate, like):
        key = ndrandom._key()
        return _apply(
            lambda a: jax.random.bernoulli(key, 1.0 - rate, a.shape)
            .astype(a.dtype) / (1.0 - rate),
            [like], name="vdrop_mask")

    def forward(self, inputs, states):
        if autograd.is_training():
            if self.drop_inputs:
                if self._input_mask is None:
                    self._input_mask = self._mask(self.drop_inputs, inputs)
                inputs = inputs * self._input_mask
            if self.drop_states:
                if self._state_mask is None:
                    self._state_mask = self._mask(self.drop_states, states[0])
                states = [states[0] * self._state_mask] + list(states[1:])
        out, new_states = self.base_cell(inputs, states)
        if autograd.is_training() and self.drop_outputs:
            if self._output_mask is None:
                self._output_mask = self._mask(self.drop_outputs, out)
            out = out * self._output_mask
        return out, new_states

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None, valid_length=None):
        self.reset()
        return super().unroll(length, inputs, begin_state, layout,
                              merge_outputs, valid_length=valid_length)


class LSTMPCell(RecurrentCell):
    """LSTM with a projected hidden state (parity:
    gluon.contrib.rnn.LSTMPCell / LSTMP of Sak et al.): the recurrent
    state is r = h @ W_proj, shrinking the h2h matmul from HxH to HxP."""

    def __init__(self, hidden_size, projection_size, input_size=0,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 h2r_weight_initializer=None, i2h_bias_initializer="zeros",
                 h2h_bias_initializer="zeros", prefix=None, params=None):
        super().__init__(prefix, params)
        self._hidden_size = hidden_size
        self._projection_size = projection_size
        self._input_size = input_size
        self.i2h_weight = self.params.get(
            "i2h_weight", shape=(4 * hidden_size, input_size),
            init=i2h_weight_initializer)
        self.h2h_weight = self.params.get(
            "h2h_weight", shape=(4 * hidden_size, projection_size),
            init=h2h_weight_initializer)
        self.h2r_weight = self.params.get(
            "h2r_weight", shape=(projection_size, hidden_size),
            init=h2r_weight_initializer)
        self.i2h_bias = self.params.get(
            "i2h_bias", shape=(4 * hidden_size,),
            init=i2h_bias_initializer)
        self.h2h_bias = self.params.get(
            "h2h_bias", shape=(4 * hidden_size,),
            init=h2h_bias_initializer)

    def infer_shape(self, x, *args):
        self.i2h_weight.shape = (4 * self._hidden_size, x.shape[-1])

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._projection_size),
                 "__layout__": "NC"},
                {"shape": (batch_size, self._hidden_size),
                 "__layout__": "NC"}]

    def forward(self, inputs, states):
        raws = [inputs] + list(states)

        def f(x, r, c, wi, wh, wr, bi, bh):
            pre = x @ wi.T + bi + r @ wh.T + bh
            i, fg, g, o = jnp.split(pre, 4, axis=-1)
            c2 = jax.nn.sigmoid(fg) * c + jax.nn.sigmoid(i) * jnp.tanh(g)
            h2 = jax.nn.sigmoid(o) * jnp.tanh(c2)
            r2 = h2 @ wr.T
            return r2, r2, c2

        outs = _apply(f, raws + [self.i2h_weight.data(),
                                 self.h2h_weight.data(),
                                 self.h2r_weight.data(),
                                 self.i2h_bias.data(),
                                 self.h2h_bias.data()],
                      n_out=3, name="lstmp_cell")
        return outs[0], [outs[1], outs[2]]


def _same_pad(kernel, dilate):
    for k in kernel:
        if k % 2 == 0:
            raise ValueError("h2h_kernel must be odd to preserve the "
                             f"state's spatial shape, got {kernel}")
    return tuple(d * (k - 1) // 2 for k, d in zip(kernel, dilate))


class _ConvRNNCellBase(RecurrentCell):
    """Shared machinery for the conv cell family (parity:
    gluon.contrib.rnn._BaseConvRNNCell). Channel-first layouts
    (NCW / NCHW / NCDHW); input_shape = (C, *spatial) is required, like
    the reference, so weights and state shapes are static."""

    def __init__(self, input_shape, hidden_channels, i2h_kernel, h2h_kernel,
                 gates, conv_layout, activation="tanh",
                 i2h_pad=None, i2h_dilate=None, h2h_dilate=None,
                 prefix=None, params=None):
        super().__init__(prefix, params)
        dims = len(conv_layout) - 2
        def _tup(v, default):
            if v is None:
                v = default
            return (v,) * dims if isinstance(v, int) else tuple(v)
        self._layout = conv_layout
        self._input_shape = tuple(input_shape)
        self._channels = hidden_channels
        self._gates = gates
        self._activation = activation
        self._i2h_kernel = _tup(i2h_kernel, None)
        self._h2h_kernel = _tup(h2h_kernel, None)
        self._i2h_pad = _tup(i2h_pad, 0)
        self._i2h_dilate = _tup(i2h_dilate, 1)
        self._h2h_dilate = _tup(h2h_dilate, 1)
        self._h2h_pad = _same_pad(self._h2h_kernel, self._h2h_dilate)
        c_in = self._input_shape[0]
        spatial_in = self._input_shape[1:]
        self._state_spatial = tuple(
            (s + 2 * p - d * (k - 1) - 1) + 1
            for s, p, k, d in zip(spatial_in, self._i2h_pad,
                                  self._i2h_kernel, self._i2h_dilate))
        self.i2h_weight = self.params.get(
            "i2h_weight", shape=(gates * hidden_channels, c_in)
            + self._i2h_kernel)
        self.h2h_weight = self.params.get(
            "h2h_weight", shape=(gates * hidden_channels, hidden_channels)
            + self._h2h_kernel)
        self.i2h_bias = self.params.get(
            "i2h_bias", shape=(gates * hidden_channels,), init="zeros")
        self.h2h_bias = self.params.get(
            "h2h_bias", shape=(gates * hidden_channels,), init="zeros")

    def state_info(self, batch_size=0):
        shape = (batch_size, self._channels) + self._state_spatial
        n = 2 if self._gates == 4 else 1   # lstm carries (h, c)
        return [{"shape": shape, "__layout__": self._layout}] * n

    def _pre(self, x, h, wi, wh, bi, bh):
        pi = _raw.conv(x, wi, bi, kernel=self._i2h_kernel,
                       pad=self._i2h_pad, dilate=self._i2h_dilate,
                       layout=self._layout)
        ph = _raw.conv(h, wh, bh, kernel=self._h2h_kernel,
                       pad=self._h2h_pad, dilate=self._h2h_dilate,
                       layout=self._layout)
        return pi, ph

    def _act(self, x):
        return jax.nn.relu(x) if self._activation == "relu" else jnp.tanh(x)

    def _weights(self):
        return [self.i2h_weight.data(), self.h2h_weight.data(),
                self.i2h_bias.data(), self.h2h_bias.data()]


class _ConvRNNCell(_ConvRNNCellBase):
    def __init__(self, input_shape, hidden_channels, i2h_kernel, h2h_kernel,
                 conv_layout, activation="tanh", **kwargs):
        super().__init__(input_shape, hidden_channels, i2h_kernel,
                         h2h_kernel, 1, conv_layout, activation, **kwargs)

    def forward(self, inputs, states):
        def f(x, h, wi, wh, bi, bh):
            pi, ph = self._pre(x, h, wi, wh, bi, bh)
            out = self._act(pi + ph)
            return out, out
        outs = _apply(f, [inputs, states[0]] + self._weights(), n_out=2,
                      name="conv_rnn_cell")
        return outs[0], [outs[1]]


class _ConvLSTMCell(_ConvRNNCellBase):
    def __init__(self, input_shape, hidden_channels, i2h_kernel, h2h_kernel,
                 conv_layout, activation="tanh", **kwargs):
        super().__init__(input_shape, hidden_channels, i2h_kernel,
                         h2h_kernel, 4, conv_layout, activation, **kwargs)

    def forward(self, inputs, states):
        def f(x, h, c, wi, wh, bi, bh):
            pi, ph = self._pre(x, h, wi, wh, bi, bh)
            pre = pi + ph
            i, fg, g, o = jnp.split(pre, 4, axis=1)
            c2 = jax.nn.sigmoid(fg) * c + jax.nn.sigmoid(i) * self._act(g)
            h2 = jax.nn.sigmoid(o) * self._act(c2)
            return h2, h2, c2
        outs = _apply(f, [inputs] + list(states) + self._weights(), n_out=3,
                      name="conv_lstm_cell")
        return outs[0], [outs[1], outs[2]]


class _ConvGRUCell(_ConvRNNCellBase):
    def __init__(self, input_shape, hidden_channels, i2h_kernel, h2h_kernel,
                 conv_layout, activation="tanh", **kwargs):
        super().__init__(input_shape, hidden_channels, i2h_kernel,
                         h2h_kernel, 3, conv_layout, activation, **kwargs)

    def forward(self, inputs, states):
        def f(x, h, wi, wh, bi, bh):
            pi, ph = self._pre(x, h, wi, wh, bi, bh)
            ir, iz, inn = jnp.split(pi, 3, axis=1)
            hr, hz, hn = jnp.split(ph, 3, axis=1)
            r = jax.nn.sigmoid(ir + hr)
            z = jax.nn.sigmoid(iz + hz)
            n = self._act(inn + r * hn)
            h2 = (1 - z) * n + z * h
            return h2, h2
        outs = _apply(f, [inputs, states[0]] + self._weights(), n_out=2,
                      name="conv_gru_cell")
        return outs[0], [outs[1]]


def _conv_cell_class(kind, dims, layout):
    base = {"RNN": _ConvRNNCell, "LSTM": _ConvLSTMCell,
            "GRU": _ConvGRUCell}[kind]

    class Cell(base):
        def __init__(self, input_shape, hidden_channels, i2h_kernel=3,
                     h2h_kernel=3, conv_layout=layout, **kwargs):
            super().__init__(input_shape, hidden_channels, i2h_kernel,
                             h2h_kernel, conv_layout, **kwargs)

    Cell.__name__ = f"Conv{dims}D{kind}Cell"
    Cell.__qualname__ = Cell.__name__
    Cell.__doc__ = (f"{dims}D convolutional {kind} cell (parity: "
                    f"gluon.contrib.rnn.Conv{dims}D{kind}Cell); "
                    f"layout {layout}.")
    return Cell


Conv1DRNNCell = _conv_cell_class("RNN", 1, "NCW")
Conv2DRNNCell = _conv_cell_class("RNN", 2, "NCHW")
Conv3DRNNCell = _conv_cell_class("RNN", 3, "NCDHW")
Conv1DLSTMCell = _conv_cell_class("LSTM", 1, "NCW")
Conv2DLSTMCell = _conv_cell_class("LSTM", 2, "NCHW")
Conv3DLSTMCell = _conv_cell_class("LSTM", 3, "NCDHW")
Conv1DGRUCell = _conv_cell_class("GRU", 1, "NCW")
Conv2DGRUCell = _conv_cell_class("GRU", 2, "NCHW")
Conv3DGRUCell = _conv_cell_class("GRU", 3, "NCDHW")
