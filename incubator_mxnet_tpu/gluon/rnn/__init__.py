"""gluon.rnn (parity: python/mxnet/gluon/rnn/{rnn_cell,rnn_layer}.py).

Two surfaces, same math:
- Cells (RNNCell/LSTMCell/GRUCell + wrappers): explicit per-step API;
  `unroll` loops in Python eagerly and fuses into one XLA loop under
  hybridize — flexible, for custom recurrences.
- Layers (RNN/LSTM/GRU): the fused path. The WHOLE sequence × layers ×
  directions runs as one recorded op on `lax.scan` (ops/_rnn.py), the
  TPU-native equivalent of the reference's cuDNN fused RNN kernel.

Parameter naming matches the reference ("l0_i2h_weight", "r0_h2h_bias", ...)
so checkpoints and tests line up; gate orders match rnn-inl.h.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ... import autograd
from ... import ndarray as nd
from ...ndarray import NDArray, _apply
from ...ndarray import random as ndrandom
from ...ops import _rnn
from ..block import HybridBlock

__all__ = ["RecurrentCell", "RNNCell", "LSTMCell", "GRUCell",
           "SequentialRNNCell", "DropoutCell", "ZoneoutCell", "ResidualCell",
           "BidirectionalCell", "RNN", "LSTM", "GRU"]


# ---------------------------------------------------------------------------
# cells
# ---------------------------------------------------------------------------

class RecurrentCell(HybridBlock):
    """Base class (parity: gluon.rnn.RecurrentCell)."""

    def reset(self):
        for child in self._children.values():
            if isinstance(child, RecurrentCell):
                child.reset()

    def state_info(self, batch_size=0):
        raise NotImplementedError

    def begin_state(self, batch_size=0, func=None, **kwargs):
        func = func or nd.zeros
        states = []
        for info in self.state_info(batch_size):
            shape = info["shape"]
            states.append(func(shape, **kwargs))
        return states

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None, valid_length=None):
        """Unroll `length` steps. inputs: NDArray (layout) or list of (N, C).
        Python loop — under hybridize it traces into one XLA computation."""
        axis = layout.find("T")
        if isinstance(inputs, NDArray):
            steps = [inputs.take(i, axis=axis) for i in range(length)]
        else:
            steps = list(inputs)
            assert len(steps) == length
        batch = steps[0].shape[0]
        states = begin_state if begin_state is not None \
            else self.begin_state(batch)
        outputs = []
        all_states = []
        for t in range(length):
            out, states = self(steps[t], states)
            outputs.append(out)
            if valid_length is not None:
                all_states.append(states)
        if valid_length is not None:
            # hold final state at each sequence's true end
            vl = valid_length if isinstance(valid_length, NDArray) \
                else nd.array(valid_length)
            picked = []
            for k in range(len(states)):
                stk = nd.stack(*[s[k] for s in all_states], axis=0)  # (T,N,H)
                picked.append(_apply(
                    lambda s, v: jnp.take_along_axis(
                        s, (v.astype(jnp.int32) - 1).clip(0)[None, :, None],
                        axis=0)[0],
                    [stk, vl], name="select_last_state"))
            states = picked
            mask = _apply(lambda v: (jnp.arange(length)[:, None]
                                     < v[None, :]).astype(jnp.float32),
                          [vl], name="len_mask")
            outputs = [o * mask[t].reshape((-1,) + (1,) * (o.ndim - 1))
                       for t, o in enumerate(outputs)]
        if merge_outputs is False:
            return outputs, states
        merged = nd.stack(*outputs, axis=axis)
        return merged, states

    def forward(self, inputs, states):
        raise NotImplementedError


class _BaseRNNCell(RecurrentCell):
    def __init__(self, hidden_size, gates, input_size=0,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 prefix=None, params=None):
        super().__init__(prefix, params)
        self._hidden_size = hidden_size
        self._gates = gates
        self._input_size = input_size
        self.i2h_weight = self.params.get(
            "i2h_weight", shape=(gates * hidden_size, input_size),
            init=i2h_weight_initializer)
        self.h2h_weight = self.params.get(
            "h2h_weight", shape=(gates * hidden_size, hidden_size),
            init=h2h_weight_initializer)
        self.i2h_bias = self.params.get(
            "i2h_bias", shape=(gates * hidden_size,),
            init=i2h_bias_initializer)
        self.h2h_bias = self.params.get(
            "h2h_bias", shape=(gates * hidden_size,),
            init=h2h_bias_initializer)

    def infer_shape(self, x, *args):
        self.i2h_weight.shape = (self._gates * self._hidden_size, x.shape[-1])

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size), "__layout__": "NC"}]

    def _step(self, mode, x, states):
        raws = [x] + list(states)
        n_states = len(states)

        def f(xr, *rest):
            sts = rest[:n_states]
            wi, wh, bi, bh = rest[n_states:]
            out, new = _rnn.rnn_cell_step(mode, xr, sts, wi, wh, bi, bh)
            return (out,) + tuple(new)

        outs = _apply(f, raws + [self.i2h_weight.data(), self.h2h_weight.data(),
                                 self.i2h_bias.data(), self.h2h_bias.data()],
                      n_out=1 + n_states, name=mode + "_cell")
        return outs[0], list(outs[1:])


class RNNCell(_BaseRNNCell):
    def __init__(self, hidden_size, activation="tanh", input_size=0, **kwargs):
        super().__init__(hidden_size, 1, input_size, **kwargs)
        self._mode = "rnn_relu" if activation == "relu" else "rnn_tanh"

    def forward(self, inputs, states):
        return self._step(self._mode, inputs, states)


class LSTMCell(_BaseRNNCell):
    def __init__(self, hidden_size, input_size=0, **kwargs):
        super().__init__(hidden_size, 4, input_size, **kwargs)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size), "__layout__": "NC"},
                {"shape": (batch_size, self._hidden_size), "__layout__": "NC"}]

    def forward(self, inputs, states):
        return self._step("lstm", inputs, states)


class GRUCell(_BaseRNNCell):
    def __init__(self, hidden_size, input_size=0, **kwargs):
        super().__init__(hidden_size, 3, input_size, **kwargs)

    def forward(self, inputs, states):
        return self._step("gru", inputs, states)


class SequentialRNNCell(RecurrentCell):
    """Stack cells; state list is the concatenation of children's states."""

    def add(self, cell):
        self.register_child(cell)

    def __len__(self):
        return len(self._children)

    def state_info(self, batch_size=0):
        infos = []
        for cell in self._children.values():
            infos.extend(cell.state_info(batch_size))
        return infos

    def begin_state(self, batch_size=0, func=None, **kwargs):
        states = []
        for cell in self._children.values():
            states.extend(cell.begin_state(batch_size, func, **kwargs))
        return states

    def forward(self, inputs, states):
        next_states = []
        p = 0
        for cell in self._children.values():
            n = len(cell.state_info())
            inputs, sts = cell(inputs, states[p:p + n])
            next_states.extend(sts)
            p += n
        return inputs, next_states


class DropoutCell(RecurrentCell):
    def __init__(self, rate, **kwargs):
        super().__init__(**kwargs)
        self.rate = rate

    def state_info(self, batch_size=0):
        return []

    def forward(self, inputs, states):
        from ... import ops
        return ops.Dropout(inputs, self.rate), states


class ZoneoutCell(RecurrentCell):
    """Zoneout (parity: gluon.rnn.ZoneoutCell): randomly hold previous
    states instead of updating — the RNN analogue of dropout."""

    def __init__(self, base_cell, zoneout_outputs=0.0, zoneout_states=0.0,
                 **kwargs):
        super().__init__(**kwargs)
        self.base_cell = base_cell
        self.zoneout_outputs = zoneout_outputs
        self.zoneout_states = zoneout_states
        self._prev_output = None

    def state_info(self, batch_size=0):
        return self.base_cell.state_info(batch_size)

    def begin_state(self, batch_size=0, func=None, **kwargs):
        self._prev_output = None
        return self.base_cell.begin_state(batch_size, func, **kwargs)

    def forward(self, inputs, states):
        out, new_states = self.base_cell(inputs, states)
        if not autograd.is_training():
            return out, new_states

        def zone(new, old, rate):
            if rate == 0.0:
                return new
            key = ndrandom._key()
            return _apply(
                lambda n_, o_: jnp.where(jax.random.bernoulli(key, rate, n_.shape),
                                         o_, n_),
                [new, old], name="zoneout")

        prev_out = self._prev_output
        if prev_out is None:
            prev_out = nd.zeros(out.shape)
        out_z = zone(out, prev_out, self.zoneout_outputs)
        states_z = [zone(n, o, self.zoneout_states)
                    for n, o in zip(new_states, states)]
        self._prev_output = out_z  # held positions chain the emitted value
        return out_z, states_z


class ResidualCell(RecurrentCell):
    def __init__(self, base_cell, **kwargs):
        super().__init__(**kwargs)
        self.base_cell = base_cell

    def state_info(self, batch_size=0):
        return self.base_cell.state_info(batch_size)

    def begin_state(self, *args, **kwargs):
        return self.base_cell.begin_state(*args, **kwargs)

    def forward(self, inputs, states):
        out, states = self.base_cell(inputs, states)
        return out + inputs, states


def _seq_reverse_steps(steps, valid_length):
    """Reverse a list of (N, C) steps within each sample's valid prefix,
    keeping padding steps in place (SequenceReverse semantics)."""
    vl = valid_length if isinstance(valid_length, NDArray) \
        else nd.array(valid_length)
    T = len(steps)
    stk = nd.stack(*steps, axis=0)  # (T, N, C)
    rev = _apply(
        lambda s, v: jnp.take_along_axis(
            s,
            jnp.where(jnp.arange(T)[:, None] < v.astype(jnp.int32)[None, :],
                      v.astype(jnp.int32)[None, :] - 1
                      - jnp.arange(T)[:, None],
                      jnp.arange(T)[:, None])[:, :, None],
            axis=0),
        [stk, vl], name="sequence_reverse")
    return [rev.take(t, axis=0) for t in range(T)]


class BidirectionalCell(RecurrentCell):
    def __init__(self, l_cell, r_cell, **kwargs):
        super().__init__(**kwargs)
        self.l_cell = l_cell
        self.r_cell = r_cell

    def state_info(self, batch_size=0):
        return (self.l_cell.state_info(batch_size) +
                self.r_cell.state_info(batch_size))

    def begin_state(self, batch_size=0, func=None, **kwargs):
        return (self.l_cell.begin_state(batch_size, func, **kwargs) +
                self.r_cell.begin_state(batch_size, func, **kwargs))

    def forward(self, inputs, states):
        raise NotImplementedError("BidirectionalCell must be used via unroll")

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None, valid_length=None):
        axis = layout.find("T")
        if isinstance(inputs, NDArray):
            steps = [inputs.take(i, axis=axis) for i in range(length)]
        else:
            steps = list(inputs)
        batch = steps[0].shape[0]
        states = begin_state if begin_state is not None \
            else self.begin_state(batch)
        nl = len(self.l_cell.state_info())
        l_states, r_states = states[:nl], states[nl:]
        l_out, l_states = self.l_cell.unroll(
            length, steps, l_states, layout="NTC" if axis else "TNC",
            merge_outputs=False, valid_length=valid_length)
        if valid_length is None:
            rev_steps = list(reversed(steps))
        else:
            # SequenceReverse semantics (reference src/operator/
            # sequence_reverse.cc): reverse each sample WITHIN its valid
            # prefix, leaving padding positions in place, so the reverse
            # cell consumes real data first.
            rev_steps = _seq_reverse_steps(steps, valid_length)
        r_out, r_states = self.r_cell.unroll(
            length, rev_steps, r_states,
            layout="NTC" if axis else "TNC", merge_outputs=False,
            valid_length=valid_length)
        if valid_length is None:
            r_out = list(reversed(r_out))
        else:
            r_out = _seq_reverse_steps(r_out, valid_length)
        outs = [nd.concat(lo, ro, dim=-1) for lo, ro in zip(l_out, r_out)]
        states = l_states + r_states
        if merge_outputs is False:
            return outs, states
        return nd.stack(*outs, axis=axis), states


# ---------------------------------------------------------------------------
# fused layers
# ---------------------------------------------------------------------------

class _RNNLayer(HybridBlock):
    """Fused multi-layer (bi)RNN on lax.scan (the cuDNN-RNN replacement)."""

    def __init__(self, mode, hidden_size, num_layers=1, layout="TNC",
                 dropout=0.0, bidirectional=False, input_size=0,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 prefix=None, params=None):
        super().__init__(prefix, params)
        assert layout in ("TNC", "NTC")
        self._mode = mode
        self._hidden_size = hidden_size
        self._num_layers = num_layers
        self._layout = layout
        self._dropout = dropout
        self._dir = 2 if bidirectional else 1
        self._input_size = input_size
        gates = _rnn.GATES[mode]
        self._gates = gates
        ni = input_size
        for layer in range(num_layers):
            for d in range(self._dir):
                pre = f"{'r' if d else 'l'}{layer}_"
                setattr(self, f"_{pre}i2h_weight", self.params.get(
                    pre + "i2h_weight", shape=(gates * hidden_size, ni),
                    init=i2h_weight_initializer))
                setattr(self, f"_{pre}h2h_weight", self.params.get(
                    pre + "h2h_weight", shape=(gates * hidden_size, hidden_size),
                    init=h2h_weight_initializer))
                setattr(self, f"_{pre}i2h_bias", self.params.get(
                    pre + "i2h_bias", shape=(gates * hidden_size,),
                    init=i2h_bias_initializer))
                setattr(self, f"_{pre}h2h_bias", self.params.get(
                    pre + "h2h_bias", shape=(gates * hidden_size,),
                    init=h2h_bias_initializer))
            ni = hidden_size * self._dir

    def _layer_param(self, layer, d, name):
        return getattr(self, f"_{'r' if d else 'l'}{layer}_{name}")

    def infer_shape(self, x, *args, **kwargs):
        in_size = x.shape[-1]
        ni = in_size
        for layer in range(self._num_layers):
            for d in range(self._dir):
                self._layer_param(layer, d, "i2h_weight").shape = \
                    (self._gates * self._hidden_size, ni)
            ni = self._hidden_size * self._dir

    def state_info(self, batch_size=0):
        n = self._num_layers * self._dir
        shapes = [{"shape": (n, batch_size, self._hidden_size),
                   "__layout__": "LNC"}]
        if self._mode == "lstm":
            shapes.append({"shape": (n, batch_size, self._hidden_size),
                           "__layout__": "LNC"})
        return shapes

    def begin_state(self, batch_size=0, func=None, **kwargs):
        func = func or nd.zeros
        return [func(info["shape"], **kwargs)
                for info in self.state_info(batch_size)]

    def forward(self, inputs, states=None, sequence_length=None):
        ntc = self._layout == "NTC"
        return_states = states is not None
        if states is None:
            batch = inputs.shape[0] if ntc else inputs.shape[1]
            states = self.begin_state(batch)
        if not isinstance(states, (list, tuple)):
            states = [states]
        states = list(states)
        n_states = len(states)
        params = []
        for layer in range(self._num_layers):
            for d in range(self._dir):
                params.extend([
                    self._layer_param(layer, d, "i2h_weight").data(),
                    self._layer_param(layer, d, "h2h_weight").data(),
                    self._layer_param(layer, d, "i2h_bias").data(),
                    self._layer_param(layer, d, "h2h_bias").data()])
        mode = self._mode
        bidir = self._dir == 2
        dropout = self._dropout
        training = autograd.is_training()
        key = ndrandom._key() if (dropout > 0.0 and training) else None
        has_vl = sequence_length is not None
        extra = [sequence_length] if has_vl else []

        def f(x_raw, *rest):
            sts = rest[:n_states]
            vl = rest[n_states] if has_vl else None
            praws = rest[n_states + (1 if has_vl else 0):]
            lp = [tuple(praws[i:i + 4]) for i in range(0, len(praws), 4)]
            x_tnc = jnp.transpose(x_raw, (1, 0, 2)) if ntc else x_raw
            out, new_states = _rnn.rnn_forward(
                x_tnc, list(sts), lp, mode, bidirectional=bidir,
                dropout=dropout, dropout_key=key, training=training,
                valid_len=vl)
            if ntc:
                out = jnp.transpose(out, (1, 0, 2))
            return (out,) + tuple(new_states)

        outs = _apply(f, [inputs] + states + extra + params,
                      n_out=1 + n_states, name=mode)
        out, new_states = outs[0], list(outs[1:])
        return (out, new_states) if return_states else out

    def __call__(self, inputs, states=None, **kwargs):
        return super().__call__(inputs, states, **kwargs) if states is not None \
            else super().__call__(inputs, **kwargs)


class RNN(_RNNLayer):
    def __init__(self, hidden_size, num_layers=1, activation="relu", **kwargs):
        mode = "rnn_relu" if activation == "relu" else "rnn_tanh"
        super().__init__(mode, hidden_size, num_layers, **kwargs)


class LSTM(_RNNLayer):
    def __init__(self, hidden_size, num_layers=1, **kwargs):
        super().__init__("lstm", hidden_size, num_layers, **kwargs)


class GRU(_RNNLayer):
    def __init__(self, hidden_size, num_layers=1, **kwargs):
        super().__init__("gru", hidden_size, num_layers, **kwargs)
