"""gluon.loss (parity: python/mxnet/gluon/loss.py).

Same semantics as the reference: per-sample losses averaged over all axes
except batch_axis, with optional `sample_weight` rescaling.
"""
from __future__ import annotations

import jax.numpy as jnp

from .. import ndarray as nd
from ..ndarray import NDArray, _apply, _as_nd
from ..ops import _raw
from .block import HybridBlock

__all__ = ["Loss", "L2Loss", "L1Loss", "SigmoidBinaryCrossEntropyLoss",
           "SigmoidBCELoss", "SoftmaxCrossEntropyLoss", "SoftmaxCELoss",
           "KLDivLoss", "HuberLoss", "HingeLoss", "SquaredHingeLoss",
           "LogisticLoss", "TripletLoss", "CosineEmbeddingLoss", "CTCLoss"]


def _reduce(loss: NDArray, batch_axis: int) -> NDArray:
    if loss.ndim <= 1:
        return loss
    axes = tuple(i for i in range(loss.ndim) if i != batch_axis)
    return loss.mean(axis=axes)


def _weighted(loss, weight, sample_weight):
    if weight is not None and weight != 1.0:
        loss = loss * weight
    if sample_weight is not None:
        loss = loss * sample_weight
    return loss


class Loss(HybridBlock):
    def __init__(self, weight=1.0, batch_axis=0, prefix=None, params=None):
        super().__init__(prefix, params)
        self._weight = weight
        self._batch_axis = batch_axis


class L2Loss(Loss):
    def __init__(self, weight=1.0, batch_axis=0, **kw):
        super().__init__(weight, batch_axis, **kw)

    def forward(self, pred, label, sample_weight=None):
        label = _as_nd(label, pred)
        loss = nd.square(label.reshape(pred.shape) - pred) / 2
        loss = _weighted(loss, self._weight, sample_weight)
        return _reduce(loss, self._batch_axis)


class L1Loss(Loss):
    def forward(self, pred, label, sample_weight=None):
        label = _as_nd(label, pred)
        loss = nd.abs(label.reshape(pred.shape) - pred)
        loss = _weighted(loss, self._weight, sample_weight)
        return _reduce(loss, self._batch_axis)


class SigmoidBinaryCrossEntropyLoss(Loss):
    def __init__(self, from_sigmoid=False, weight=1.0, batch_axis=0, **kw):
        super().__init__(weight, batch_axis, **kw)
        self._from_sigmoid = from_sigmoid

    def forward(self, pred, label, sample_weight=None, pos_weight=None):
        label = _as_nd(label, pred)
        lab = label.reshape(pred.shape)
        if not self._from_sigmoid:
            # numerically stable: max(x,0) - x*z + log(1+exp(-|x|))
            def f(x, z):
                return (jnp.maximum(x, 0) - x * z +
                        jnp.log1p(jnp.exp(-jnp.abs(x))))
            loss = _apply(f, [pred, lab], name="sigmoid_bce")
        else:
            eps = 1e-12
            loss = -(lab * nd.log(pred + eps) + (1 - lab) * nd.log(1 - pred + eps))
        if pos_weight is not None:
            loss = loss * (lab * (pos_weight - 1) + 1)
        loss = _weighted(loss, self._weight, sample_weight)
        return _reduce(loss, self._batch_axis)


SigmoidBCELoss = SigmoidBinaryCrossEntropyLoss


class SoftmaxCrossEntropyLoss(Loss):
    def __init__(self, axis=-1, sparse_label=True, from_logits=False,
                 weight=1.0, batch_axis=0, **kw):
        super().__init__(weight, batch_axis, **kw)
        self._axis = axis
        self._sparse = sparse_label
        self._from_logits = from_logits

    def forward(self, pred, label, sample_weight=None):
        label = _as_nd(label)
        axis, sparse = self._axis, self._sparse
        if self._from_logits:
            if sparse:
                loss = -nd.pick(pred, label, axis=axis)
            else:
                loss = -(pred * label).sum(axis=axis)
        else:
            loss = _apply(
                lambda x, l: _raw.softmax_cross_entropy(x, l, axis, sparse),
                [pred, label], name="softmax_ce")
        loss = _weighted(loss, self._weight, sample_weight)
        return _reduce(loss, self._batch_axis)


SoftmaxCELoss = SoftmaxCrossEntropyLoss


class KLDivLoss(Loss):
    def __init__(self, from_logits=True, axis=-1, weight=1.0, batch_axis=0, **kw):
        super().__init__(weight, batch_axis, **kw)
        self._from_logits = from_logits
        self._axis = axis

    def forward(self, pred, label, sample_weight=None):
        label = _as_nd(label, pred)
        if not self._from_logits:
            pred = nd.log_softmax(pred, axis=self._axis)
        loss = label * (nd.log(label + 1e-12) - pred)
        loss = _weighted(loss, self._weight, sample_weight)
        return _reduce(loss, self._batch_axis)


class HuberLoss(Loss):
    def __init__(self, rho=1.0, weight=1.0, batch_axis=0, **kw):
        super().__init__(weight, batch_axis, **kw)
        self._rho = rho

    def forward(self, pred, label, sample_weight=None):
        label = _as_nd(label, pred)
        rho = self._rho

        def f(p, l):
            err = jnp.abs(l.reshape(p.shape) - p)
            return jnp.where(err > rho, err - 0.5 * rho,
                             (0.5 / rho) * jnp.square(err))
        loss = _apply(f, [pred, label], name="huber")
        loss = _weighted(loss, self._weight, sample_weight)
        return _reduce(loss, self._batch_axis)


class HingeLoss(Loss):
    def __init__(self, margin=1.0, weight=1.0, batch_axis=0, **kw):
        super().__init__(weight, batch_axis, **kw)
        self._margin = margin

    def forward(self, pred, label, sample_weight=None):
        label = _as_nd(label, pred)
        loss = nd.relu(self._margin - pred * label.reshape(pred.shape))
        loss = _weighted(loss, self._weight, sample_weight)
        return _reduce(loss, self._batch_axis)


class SquaredHingeLoss(HingeLoss):
    def forward(self, pred, label, sample_weight=None):
        label = _as_nd(label, pred)
        loss = nd.square(nd.relu(self._margin - pred * label.reshape(pred.shape)))
        loss = _weighted(loss, self._weight, sample_weight)
        return _reduce(loss, self._batch_axis)


class LogisticLoss(Loss):
    def __init__(self, label_format="signed", weight=1.0, batch_axis=0, **kw):
        super().__init__(weight, batch_axis, **kw)
        self._fmt = label_format

    def forward(self, pred, label, sample_weight=None):
        label = _as_nd(label, pred)
        lab = label.reshape(pred.shape)
        if self._fmt == "binary":
            lab = lab * 2 - 1

        def f(x, z):
            return jnp.log1p(jnp.exp(-jnp.abs(x * z))) + jnp.maximum(-x * z, 0)
        loss = _apply(f, [pred, lab], name="logistic")
        loss = _weighted(loss, self._weight, sample_weight)
        return _reduce(loss, self._batch_axis)


class TripletLoss(Loss):
    def __init__(self, margin=1.0, weight=1.0, batch_axis=0, **kw):
        super().__init__(weight, batch_axis, **kw)
        self._margin = margin

    def forward(self, anchor, positive, negative, sample_weight=None):
        loss = nd.relu(
            nd.sum(nd.square(anchor - positive) - nd.square(anchor - negative),
                   axis=tuple(range(1, anchor.ndim))) + self._margin)
        return _weighted(loss, self._weight, sample_weight)


class CosineEmbeddingLoss(Loss):
    def __init__(self, weight=1.0, batch_axis=0, margin=0.0, **kw):
        super().__init__(weight, batch_axis, **kw)
        self._margin = margin

    def forward(self, input1, input2, label, sample_weight=None):
        label = _as_nd(label, input1)

        def f(a, b, l):
            cos = (jnp.sum(a * b, -1) /
                   (jnp.linalg.norm(a, axis=-1) * jnp.linalg.norm(b, axis=-1) + 1e-12))
            lf = l.reshape(cos.shape)
            return jnp.where(lf == 1, 1 - cos, jnp.maximum(0.0, cos - self._margin))
        loss = _apply(f, [input1, input2, label], name="cosine_embedding")
        return _weighted(loss, self._weight, sample_weight)


class CTCLoss(Loss):
    """CTC (parity: mx.gluon.loss.CTCLoss, layout NTC, labels padded with -1).

    Forward-algorithm alpha recursion in log space via lax.scan — XLA-friendly
    (static shapes, no host loop). Reference: src/operator/contrib/ctc_loss.cc.
    """

    def __init__(self, layout="NTC", label_layout="NT", weight=None, **kw):
        super().__init__(weight, 0, **kw)
        self._layout = layout
        self._label_layout = label_layout

    def forward(self, pred, label, pred_lengths=None, label_lengths=None,
                sample_weight=None):
        import jax
        from jax import lax
        label = _as_nd(label)
        if self._layout == "TNC":
            pred = pred.swapaxes(0, 1)
        if self._label_layout == "TN":
            label = label.swapaxes(0, 1)
        blank = 0  # mxnet CTC uses alphabet_size-1 by default in warp-ctc mode,
        # but gluon CTCLoss reserves index 0? Reference uses blank=alphabet-1
        # for 'last' mode; gluon default is blank at 0 via 'first'.
        inputs = [pred, label]
        if pred_lengths is not None:
            inputs.append(_as_nd(pred_lengths))
        if label_lengths is not None:
            inputs.append(_as_nd(label_lengths))

        def f(p, l, *rest):
            pl = rest[0] if pred_lengths is not None else None
            ll = rest[-1] if label_lengths is not None else None
            B, T, C = p.shape
            L = l.shape[1]
            logp = jax.nn.log_softmax(p.astype(jnp.float32), -1)
            lab = l.astype(jnp.int32)
            if ll is None:
                lab_len = jnp.sum((lab >= 0).astype(jnp.int32), -1)
            else:
                lab_len = ll.astype(jnp.int32)
            if pl is None:
                t_len = jnp.full((B,), T, jnp.int32)
            else:
                t_len = pl.astype(jnp.int32)
            lab = jnp.where(lab < 0, 0, lab)
            # extended labels: blank, l1, blank, l2, ... blank  (len 2L+1)
            S = 2 * L + 1
            ext = jnp.full((B, S), blank, jnp.int32)
            ext = ext.at[:, 1::2].set(lab)
            NEG = jnp.float32(-1e30)
            alpha0 = jnp.full((B, S), NEG)
            alpha0 = alpha0.at[:, 0].set(logp[:, 0, blank])
            alpha0 = alpha0.at[:, 1].set(
                jnp.take_along_axis(logp[:, 0, :], ext[:, 1:2], axis=1)[:, 0])
            same = jnp.concatenate(
                [jnp.ones((B, 2), bool), ext[:, 2:] == ext[:, :-2]], axis=1)

            def step(alpha, t):
                a_shift1 = jnp.concatenate([jnp.full((B, 1), NEG), alpha[:, :-1]], 1)
                a_shift2 = jnp.concatenate([jnp.full((B, 2), NEG), alpha[:, :-2]], 1)
                a_shift2 = jnp.where(same, NEG, a_shift2)
                merged = jnp.logaddexp(jnp.logaddexp(alpha, a_shift1), a_shift2)
                emit = jnp.take_along_axis(logp[:, t, :], ext, axis=1)
                new = merged + emit
                new = jnp.where((t < t_len)[:, None], new, alpha)
                return new, None

            alpha, _ = lax.scan(step, alpha0, jnp.arange(1, T))
            end1 = 2 * lab_len  # final blank
            end2 = 2 * lab_len - 1
            ll1 = jnp.take_along_axis(alpha, end1[:, None], 1)[:, 0]
            ll2 = jnp.take_along_axis(alpha, jnp.maximum(end2, 0)[:, None], 1)[:, 0]
            return -jnp.logaddexp(ll1, ll2)

        loss = _apply(f, inputs, name="ctc")
        return _weighted(loss, self._weight, sample_weight)
