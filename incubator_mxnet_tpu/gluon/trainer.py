"""gluon.Trainer (parity: python/mxnet/gluon/trainer.py).

step() = allreduce_grads() (kvstore) + update() (optimizer), as in the
reference. Each parameter's update is one jitted XLA kernel; the fully-fused
single-computation train step (forward+backward+psum+update in one jit) lives
in parallel/ and is what bench/dryrun use.
"""
from __future__ import annotations

import os
import pickle

import jax
import numpy as np

from .. import healthmon as _hm
from .. import kvstore as kvs_mod
from .. import optimizer as opt_mod
from .. import profiler as _prof
from ..diagnostics import flight as _flight
from ..ndarray import NDArray
from .parameter import Parameter, ParameterDict

__all__ = ["Trainer"]


class _GradCommScheduler:
    """ByteScheduler-style priority scheduler for gradient aggregation
    (reference: ps-lite push/pull pipelining in src/kvstore/kvstore_dist.h
    and the BytePS/ByteScheduler papers the ymjiang fork exists for).

    Semantics rebuilt TPU-native:

    * **readiness** — parameters' grad hooks fire mid-backward the moment
      each gradient is finalized (reverse layer order), not at step();
    * **priority** — forward-order parameter index, ascending: the next
      iteration's forward is unblocked by the FRONT layers, so when
      several buckets are ready the front-most is issued first;
    * **overlap** — each issued aggregation is an XLA computation that
      dispatches asynchronously, so device collective work runs while the
      host continues the remaining backward walk (the reference overlaps
      NCCL/ps-lite transfers the same way);
    * **credit** — at most ``credit_bytes`` of aggregation may be in
      flight (completion polled via ``jax.Array.is_ready``); when credit
      is exhausted, ready buckets wait in a priority heap — so a
      front-layer gradient arriving later OVERTAKES queued lower-priority
      buckets, which is the ByteScheduler reordering;
    * **bucketing** — consecutive parameters are grouped into ~``
      bucket_bytes`` buckets (0 = one bucket per parameter); a bucket
      issues once every member's grad is ready.

    ``step()`` calls ``flush()`` which force-issues stragglers (params
    that never fired — e.g. unused this pass) and drains the heap, so the
    result is always bit-identical to the unscheduled batched path.
    """

    def __init__(self, kvstore, params, bucket_bytes=0,
                 credit_bytes=4 << 20):
        self._kv = kvstore
        self._params = params
        self._bucket_bytes = int(bucket_bytes)
        self._credit = int(credit_bytes)
        # SPMD safety: when aggregation is a cross-process collective
        # (process_allgather in _batch_aggregate), EVERY process must
        # issue buckets in the SAME order — credit-based overtaking
        # depends on local is_ready() timing and would mispair the
        # collectives. Multi-process clusters therefore issue strictly in
        # (deterministic) availability order; overlap is kept, only the
        # reordering is dropped. Single-process keeps full ByteScheduler
        # semantics.
        self._deterministic = jax.process_count() > 1
        self._buckets = []           # list[list[int]] consecutive indices
        self._bucket_of = {}         # param idx -> bucket idx
        self._rebucket()
        self._ready = set()          # param indices with finalized grads
        self._issued = set()         # bucket indices already issued
        self._heap = []              # [(priority, bucket_idx)]
        self._inflight = []          # [(nbytes, [jax.Array])]
        self.issued_log = []         # bucket priority order (tests/debug)

    def _rebucket(self):
        self._buckets, self._bucket_of = [], {}
        cur, cur_bytes = [], 0
        for i, p in enumerate(self._params):
            itemsize = np.dtype(p.dtype).itemsize if p.dtype else 4
            nbytes = (itemsize * int(np.prod(p.shape))
                      if p.shape_is_known else 0)
            cur.append(i)
            cur_bytes += nbytes
            if self._bucket_bytes <= 0 or cur_bytes >= self._bucket_bytes:
                self._buckets.append(cur)
                cur, cur_bytes = [], 0
        if cur:
            self._buckets.append(cur)
        for b, members in enumerate(self._buckets):
            for i in members:
                self._bucket_of[i] = b

    # -- readiness --------------------------------------------------------
    def notify(self, i):
        """Param i's grad finalized mid-backward: queue its bucket when
        complete, then drain as much as credit allows."""
        import heapq
        if self._kv.num_workers <= 1:
            return                    # nothing to aggregate: keep backward hot
        b = self._bucket_of[i]
        if i in self._ready or b in self._issued:
            # a SECOND finalization before step(): the bucket's aggregated
            # value is already (or about to be) replaced by the collective,
            # so re-aggregating would double-count the earlier contribution
            # across workers. Real overlapped schedulers (BytePS) share
            # this one-push-per-iteration contract.
            raise RuntimeError(
                "overlap_comm saw a second backward pass before the "
                "scheduler was flushed; gradient accumulation across "
                "multiple backwards is not compatible with mid-backward "
                "aggregation — after each backward call step(), or "
                "allreduce_grads() followed by update(), or construct "
                "the Trainer with overlap_comm=False")
        self._ready.add(i)
        if all(j in self._ready for j in self._buckets[b]):
            heapq.heappush(self._heap, (self._buckets[b][0], b))
            self._issued.add(b)
        self._drain(force=self._deterministic)

    # -- issue ------------------------------------------------------------
    def _inflight_bytes(self):
        self._inflight = [(n, arrs) for n, arrs in self._inflight
                          if not all(a.is_ready() for a in arrs)]
        return sum(n for n, _ in self._inflight)

    def _issue(self, b):
        members = self._buckets[b]
        grads = [self._params[i].grad() for i in members]
        keys = [f"grad{i}" for i in members]
        if _prof._ACTIVE:
            with _prof.Scope("overlap_comm.issue_bucket%d" % b, "trainer",
                             sync=False):
                self._kv.pushpull(keys, grads, out=grads)
        else:
            self._kv.pushpull(keys, grads, out=grads)
        self.issued_log.append(b)
        nbytes = sum(int(np.prod(g.shape)) * g._data.dtype.itemsize
                     for g in grads)
        self._inflight.append((nbytes, [g._data for g in grads]))

    def _drain(self, force):
        import heapq
        while self._heap:
            if not force and self._inflight_bytes() >= self._credit:
                return
            _, b = heapq.heappop(self._heap)
            self._issue(b)

    def flush(self):
        """step(): issue stragglers (whole-bucket, priority order) and
        drain the heap unconditionally; afterwards every param's .grad()
        holds the aggregated value, as the batched path would.

        issued_log is reset here (start of flush) so it never grows across
        steps: after step() it holds exactly this flush's issuance order;
        mid-backward issuance is readable between backward() and step()."""
        import heapq
        self.issued_log.clear()
        if self._kv.num_workers <= 1:
            return
        # EVERY bucket not yet issued goes now — including ones whose
        # hooks never fired (deferred-init params, partial buckets): the
        # batched path aggregates all params, and parity is the contract
        for b, members in enumerate(self._buckets):
            if b not in self._issued:
                heapq.heappush(self._heap, (members[0], b))
                self._issued.add(b)
        self._drain(force=True)
        self._ready.clear()
        self._issued.clear()
        self._inflight.clear()

    def reset(self):
        """Drop all per-pass state WITHOUT issuing anything. update()
        calls this when the user skipped allreduce_grads(): whatever was
        already issued mid-backward stays aggregated (that money is
        spent), but nothing further is launched — crucially the next
        backward starts from a clean slate instead of tripping notify()'s
        second-backward guard with a misleading error."""
        self._ready.clear()
        self._issued.clear()
        self._heap.clear()
        self._inflight.clear()
        self.issued_log.clear()


class Trainer:
    def __init__(self, params, optimizer, optimizer_params=None, kvstore="device",
                 compression_params=None, update_on_kvstore=None,
                 overlap_comm=False, comm_bucket_bytes=0,
                 comm_credit_bytes=4 << 20, fused_update=None,
                 loop_chunk=None, sharding=None, resilience=None):
        if isinstance(params, (dict, ParameterDict)):
            params = [params[k] for k in sorted(params.keys())] \
                if isinstance(params, dict) else list(params.values())
        self._params = [p for p in params if p.grad_req != "null"]
        self._all_params = list(params)
        param_dict = {i: p for i, p in enumerate(self._params)}
        optimizer_params = optimizer_params or {}
        if isinstance(optimizer, opt_mod.Optimizer):
            self._optimizer = optimizer
            self._optimizer.param_dict = param_dict
        else:
            self._optimizer = opt_mod.create(optimizer, param_dict=param_dict,
                                             **optimizer_params)
        self._states = [None] * len(self._params)
        self._states_created = [False] * len(self._params)
        self._kvstore = None
        if kvstore is not None:
            self._kvstore = (kvstore if isinstance(kvstore, kvs_mod.KVStore)
                             else kvs_mod.create(kvstore))
        self._scale = 1.0
        # update_on_kvstore (parity: reference trainer's
        # _update_on_kvstore): the optimizer runs SERVER-side — step()
        # pushes gradients and pulls back updated weights; update() is
        # then unsupported. Auto (None) resolves True only for
        # `dist_async`, whose per-worker-update semantics only exist
        # server-side; everywhere else the local fused update is the
        # faster TPU-native path.
        if update_on_kvstore is None:
            update_on_kvstore = (self._kvstore is not None
                                 and self._kvstore.type == "dist_async")
        if update_on_kvstore and self._kvstore is None:
            raise ValueError("update_on_kvstore=True requires a kvstore")
        if update_on_kvstore and overlap_comm:
            raise ValueError(
                "overlap_comm schedules client-side aggregation; it is "
                "incompatible with server-side updates "
                "(update_on_kvstore)")
        self._update_on_kvstore = bool(update_on_kvstore)
        # fused multi-tensor apply: group params by (rule, dtype) and run
        # each group's updates as ONE jitted call (vs one call per param).
        # Default on; env MXTPU_FUSED_UPDATE=0 disables globally.
        if fused_update is None:
            from ..autotune.knobs import env_flag
            fused_update = env_flag("MXTPU_FUSED_UPDATE", True)
        self._fused_update = bool(fused_update)
        # loop_chunk=N marks this trainer for WHOLE-LOOP execution: the
        # trainloop executor (mxtpu.trainloop.TrainLoop) compiles N
        # micro-steps (fwd+bwd+collective+update+lr schedule) into one
        # donated XLA program and reads this chunk size when constructed
        # from the Trainer. The env layers resolve through the ONE knob
        # table (autotune.knobs: BENCH_LOOP_CHUNK > MXTPU_LOOP_CHUNK >
        # cached tuning winner); an explicit loop_chunk= argument wins.
        # The eager step()/update() path ignores it (per-step by
        # construction).
        if loop_chunk is None:
            from ..autotune import knobs as _knobs
            loop_chunk = _knobs.resolve("loop_chunk")[0]
        self.loop_chunk = int(loop_chunk) if loop_chunk else None
        # sharding='dp'|'fsdp'|'auto' marks this trainer for MESH-NATIVE
        # execution (mxtpu.sharding, docs/sharding.md): TrainLoop /
        # FusedTrainStep constructed from this Trainer lower fwd+bwd+
        # optimizer into ONE jit whose in/out shardings carry the
        # resolved per-param NamedShardings — XLA inserts the
        # collectives, replacing kvstore pushpull on that path. The
        # eager step()/update() path ignores it (kvstore aggregation
        # stays). Env default: MXTPU_SHARDING. Needs a process-global
        # mesh (sharding.set_mesh) or an explicit mesh= at the executor.
        if sharding is None:
            from ..autotune.knobs import env_str
            sharding = env_str("MXTPU_SHARDING", None)
        from ..parallel import sharding as _sharding_mod
        if sharding is not None and sharding not in _sharding_mod.MODES:
            raise ValueError(f"unknown sharding mode {sharding!r}; "
                             f"expected one of {_sharding_mod.MODES}")
        self.sharding = sharding
        # resilience=<checkpoint dir> marks this trainer for SUPERVISED
        # recovery (mxtpu.resilience, docs/resilience.md): TrainLoop.fit
        # constructed from this Trainer checkpoints asynchronously into
        # the directory, resumes from its manifest on restart, and rolls
        # back on NaN instead of dying. Env default: MXTPU_RESILIENCE_DIR.
        # The eager step()/update() path ignores it.
        if resilience is None:
            from ..autotune.knobs import env_str
            resilience = env_str("MXTPU_RESILIENCE_DIR", None)
        self.resilience = resilience
        self._kv_params_init = False
        self._sched = None
        if overlap_comm:
            if self._kvstore is None:
                raise ValueError("overlap_comm=True requires a kvstore")
            self._sched = _GradCommScheduler(
                self._kvstore, self._params,
                bucket_bytes=comm_bucket_bytes,
                credit_bytes=comm_credit_bytes)
            self._ensure_grad_hooks()

    def _ensure_grad_hooks(self):
        """Attach readiness hooks to every initialized param; deferred-init
        params get theirs on a later call (their first backward simply
        falls back to flush-time aggregation — numerics are unchanged).
        Keyed on the parameter's CURRENT storage, not a one-shot latch:
        initialize(force_reinit=True) and cast() replace `p._data` (and
        with it the hook slot), so hooks are re-attached whenever the live
        storage has none — overlap survives re-init instead of silently
        degrading to flush-time aggregation."""
        sched = self._sched
        for i, p in enumerate(self._params):
            if p._data is not None and p._data._grad_hook is None:
                p.register_grad_hook(
                    lambda _p, _i=i: sched.notify(_i))

    # -- properties -------------------------------------------------------
    @property
    def learning_rate(self):
        return self._optimizer.learning_rate

    @property
    def optimizer(self):
        return self._optimizer

    def set_learning_rate(self, lr):
        self._optimizer.set_learning_rate(lr)

    # -- core -------------------------------------------------------------
    def _init_state(self, i, p):
        if not self._states_created[i]:
            self._states[i] = self._optimizer.create_state_multi_precision(
                i, p.data()._data)
            self._states_created[i] = True

    def allreduce_grads(self):
        """Aggregate gradients across devices/workers. Single-chip: no-op.
        The mesh path does this inside the compiled step via psum."""
        if self._sched is not None:
            # overlapped path: most buckets were issued mid-backward by
            # grad hooks; flush issues stragglers and resets the pass
            self._ensure_grad_hooks()
            self._sched.flush()
            return
        if self._kvstore is not None and self._kvstore.num_workers > 1:
            grads = [p.grad() for p in self._params]
            keys = [f"grad{i}" for i in range(len(grads))]
            # one batched call → one compiled bucketed collective
            self._kvstore.pushpull(keys, grads, out=grads)

    def step(self, batch_size, ignore_stale_grad=False):
        hm = _hm._HM
        if hm is not None:
            hm.step_begin()
        if _flight._REC is not None:
            _flight.record("trainer", "trainer.step",
                           {"batch_size": int(batch_size)})
        self._optimizer.rescale_grad = self._scale / batch_size
        if self._update_on_kvstore:
            if _prof._ACTIVE:
                with _prof.Scope("trainer.kvstore_step", "trainer",
                                 sync=False):
                    self._kvstore_step()
            else:
                self._kvstore_step()
            if hm is not None:
                # grad-norm sentinel BEFORE step_end: the kvstore step
                # left this worker's grads untouched, and step_end's
                # periodic exchange should see the freshest NaN verdict
                hm.maybe_check_grad_norm(self._params)
                hm.step_end(kv=self._kvstore, batch_size=batch_size)
            return
        phases = None
        if hm is not None:
            # healthmon step phases (cheap wall timing, on whether or not
            # a trace session is running — the event log is the consumer)
            import time as _time
            t0 = _time.perf_counter()
        if _prof._ACTIVE:
            # step phases as separate trace buckets: grad aggregation
            # (incl. overlap-comm stragglers) vs the optimizer update
            _prof.counter("trainer.steps").increment()
            with _prof.Scope("trainer.allreduce_grads", "trainer",
                             sync=False):
                self.allreduce_grads()
            if hm is not None:
                t1 = _time.perf_counter()
            with _prof.Scope("trainer.optimizer_update", "trainer",
                             sync=False):
                self._update()
        else:
            self.allreduce_grads()
            if hm is not None:
                t1 = _time.perf_counter()
            self._update()
        if hm is not None:
            t2 = _time.perf_counter()
            phases = {"allreduce_ms": (t1 - t0) * 1e3,
                      "update_ms": (t2 - t1) * 1e3}
            # grads survive _update (it only reads them), so the opt-in
            # global-norm sentinel runs on exactly what was applied
            hm.maybe_check_grad_norm(self._params)
            hm.step_end(kv=self._kvstore, batch_size=batch_size,
                        phases=phases)

    def _kvstore_step(self):
        """Server-side update round: push grads, pull updated weights
        (reference kvstore_dist flow). For dist_async the push applies as
        this worker's own arrival-order update on the rank-0 server; for
        sync stores it is aggregate-then-update."""
        kv = self._kvstore
        keys = [f"param{i}" for i in range(len(self._params))]
        if not self._kv_params_init:
            kv.set_optimizer(self._optimizer)
            kv.init(keys, [p.data() for p in self._params])
            self._kv_params_init = True
        kv.push(keys, [p.grad() for p in self._params])
        kv.pull(keys, out=[p.data() for p in self._params])

    def update(self, batch_size, ignore_stale_grad=False):
        if self._update_on_kvstore:
            raise ValueError(
                "update() is not supported when parameters are updated "
                "on the kvstore (update_on_kvstore=True); call step()")
        if self._sched is not None:
            # update() without allreduce_grads() must not leave the
            # overlap scheduler's _ready/_issued sets stale — the next
            # backward's first grad hook would raise the (misleading)
            # second-backward error. A correct allreduce_grads()+update()
            # sequence already flushed, so this reset is a no-op there;
            # re-flushing here instead would double-aggregate.
            self._sched.reset()
        self._optimizer.rescale_grad = self._scale / batch_size
        self._update()

    def _update(self):
        from .. import bulk as _bulk
        # grads/weights must be concrete before the optimizer reads them
        # (unconditional: cheap thread-local check, and a pending segment
        # can outlive its scope on this thread)
        _bulk.flush("step")
        skip = getattr(self, "_amp_skip", None)  # on-device found-inf bool
        opt = self._optimizer
        dispatches = 0
        if not (self._fused_update and opt.supports_fused()):
            for i, p in enumerate(self._params):
                self._init_state(i, p)
                self._states[i] = opt.update(i, p.data(), p.grad(),
                                             self._states[i], skip=skip)
                dispatches += 1
            _prof.set_gauge("optimizer.fused_groups", 0)
            _prof.set_gauge("trainer.dispatches_per_step", dispatches)
            _prof.counter("optimizer.dispatches").increment(dispatches)
            return
        from ..ndarray import sparse as _sparse
        groups = {}   # dtype str -> param indices (one rule per Trainer)
        for i, p in enumerate(self._params):
            self._init_state(i, p)
            g = p.grad()
            if isinstance(g, _sparse.RowSparseNDArray):
                # sparse rules keep the per-param (lazy-row) path
                self._states[i] = opt.update(i, p.data(), g,
                                             self._states[i], skip=skip)
                dispatches += 1
            else:
                groups.setdefault(str(p.data()._data.dtype), []).append(i)
        for idxs in groups.values():
            new_states = opt.fused_update(
                idxs,
                [self._params[i].data() for i in idxs],
                [self._params[i].grad() for i in idxs],
                [self._states[i] for i in idxs], skip=skip)
            for i, s in zip(idxs, new_states):
                self._states[i] = s
            dispatches += 1
        _prof.set_gauge("optimizer.fused_groups", len(groups))
        _prof.set_gauge("trainer.dispatches_per_step", dispatches)
        _prof.counter("optimizer.dispatches").increment(dispatches)

    # -- persistence ------------------------------------------------------
    def save_states(self, fname):
        blob = {
            "num_update": self._optimizer.num_update,
            "index_update_count": dict(self._optimizer._index_update_count),
            "states": [jax.tree_util.tree_map(lambda a: np.asarray(a), s)
                       for s in self._states],
        }
        with open(fname, "wb") as f:
            pickle.dump(blob, f, protocol=4)

    def load_states(self, fname):
        import jax.numpy as jnp
        with open(fname, "rb") as f:
            blob = pickle.load(f)
        self._optimizer.num_update = blob["num_update"]
        self._optimizer._index_update_count = dict(blob.get("index_update_count", {}))
        self._states = [jax.tree_util.tree_map(jnp.asarray, s)
                        for s in blob["states"]]
        self._states_created = [s is not None for s in self._states]
