"""gluon.Trainer (parity: python/mxnet/gluon/trainer.py).

step() = allreduce_grads() (kvstore) + update() (optimizer), as in the
reference. Each parameter's update is one jitted XLA kernel; the fully-fused
single-computation train step (forward+backward+psum+update in one jit) lives
in parallel/ and is what bench/dryrun use.
"""
from __future__ import annotations

import pickle

import jax
import numpy as np

from .. import kvstore as kvs_mod
from .. import optimizer as opt_mod
from ..ndarray import NDArray
from .parameter import Parameter, ParameterDict

__all__ = ["Trainer"]


class Trainer:
    def __init__(self, params, optimizer, optimizer_params=None, kvstore="device",
                 compression_params=None, update_on_kvstore=None):
        if isinstance(params, (dict, ParameterDict)):
            params = [params[k] for k in sorted(params.keys())] \
                if isinstance(params, dict) else list(params.values())
        self._params = [p for p in params if p.grad_req != "null"]
        self._all_params = list(params)
        param_dict = {i: p for i, p in enumerate(self._params)}
        optimizer_params = optimizer_params or {}
        if isinstance(optimizer, opt_mod.Optimizer):
            self._optimizer = optimizer
            self._optimizer.param_dict = param_dict
        else:
            self._optimizer = opt_mod.create(optimizer, param_dict=param_dict,
                                             **optimizer_params)
        self._states = [None] * len(self._params)
        self._states_created = [False] * len(self._params)
        self._kvstore = None
        if kvstore is not None:
            self._kvstore = (kvstore if isinstance(kvstore, kvs_mod.KVStore)
                             else kvs_mod.create(kvstore))
        self._scale = 1.0

    # -- properties -------------------------------------------------------
    @property
    def learning_rate(self):
        return self._optimizer.learning_rate

    @property
    def optimizer(self):
        return self._optimizer

    def set_learning_rate(self, lr):
        self._optimizer.set_learning_rate(lr)

    # -- core -------------------------------------------------------------
    def _init_state(self, i, p):
        if not self._states_created[i]:
            self._states[i] = self._optimizer.create_state_multi_precision(
                i, p.data()._data)
            self._states_created[i] = True

    def allreduce_grads(self):
        """Aggregate gradients across devices/workers. Single-chip: no-op.
        The mesh path does this inside the compiled step via psum."""
        if self._kvstore is not None and self._kvstore.num_workers > 1:
            grads = [p.grad() for p in self._params]
            keys = [f"grad{i}" for i in range(len(grads))]
            # one batched call → one compiled bucketed collective
            self._kvstore.pushpull(keys, grads, out=grads)

    def step(self, batch_size, ignore_stale_grad=False):
        self._optimizer.rescale_grad = self._scale / batch_size
        self.allreduce_grads()
        self._update()

    def update(self, batch_size, ignore_stale_grad=False):
        self._optimizer.rescale_grad = self._scale / batch_size
        self._update()

    def _update(self):
        skip = getattr(self, "_amp_skip", None)  # on-device found-inf bool
        for i, p in enumerate(self._params):
            self._init_state(i, p)
            w = p.data()
            g = p.grad()
            self._states[i] = self._optimizer.update(i, w, g, self._states[i],
                                                     skip=skip)

    # -- persistence ------------------------------------------------------
    def save_states(self, fname):
        blob = {
            "num_update": self._optimizer.num_update,
            "index_update_count": dict(self._optimizer._index_update_count),
            "states": [jax.tree_util.tree_map(lambda a: np.asarray(a), s)
                       for s in self._states],
        }
        with open(fname, "wb") as f:
            pickle.dump(blob, f, protocol=4)

    def load_states(self, fname):
        import jax.numpy as jnp
        with open(fname, "rb") as f:
            blob = pickle.load(f)
        self._optimizer.num_update = blob["num_update"]
        self._optimizer._index_update_count = dict(blob.get("index_update_count", {}))
        self._states = [jax.tree_util.tree_map(jnp.asarray, s)
                        for s in blob["states"]]
        self._states_created = [s is not None for s in self._states]
