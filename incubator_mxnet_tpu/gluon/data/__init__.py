"""gluon.data: Dataset / Sampler / DataLoader (parity: python/mxnet/gluon/data).

DataLoader's multi-worker path uses a host-side prefetch pipeline (threads
now, the C++ runtime engine underneath once built) — on TPU the goal is to
keep the input pipeline off the critical path so the chip never starves.
"""
from __future__ import annotations

import numpy as np

from ... import ndarray as nd
from ...ndarray import NDArray
from . import sampler as _sampler
from .sampler import BatchSampler, RandomSampler, SequentialSampler

__all__ = ["Dataset", "ArrayDataset", "SimpleDataset", "RecordFileDataset",
           "DataLoader", "BatchSampler", "RandomSampler", "SequentialSampler"]


class Dataset:
    def __getitem__(self, idx):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError

    def transform(self, fn, lazy=True):
        return _LazyTransformDataset(self, fn)

    def transform_first(self, fn, lazy=True):
        def first(*items):
            if len(items) == 1:
                return fn(items[0])
            return (fn(items[0]),) + items[1:]
        return self.transform(first, lazy)

    def filter(self, fn):
        idx = [i for i in range(len(self)) if fn(self[i])]
        return _SubsetDataset(self, idx)

    def shard(self, num_shards, index):
        idx = list(range(index, len(self), num_shards))
        return _SubsetDataset(self, idx)

    def take(self, count):
        return _SubsetDataset(self, list(range(min(count, len(self)))))


class _SubsetDataset(Dataset):
    def __init__(self, base, indices):
        self._base = base
        self._indices = indices

    def __getitem__(self, idx):
        return self._base[self._indices[idx]]

    def __len__(self):
        return len(self._indices)


class _LazyTransformDataset(Dataset):
    def __init__(self, base, fn):
        self._base = base
        self._fn = fn

    def __getitem__(self, idx):
        item = self._base[idx]
        if isinstance(item, tuple):
            return self._fn(*item)
        return self._fn(item)

    def __len__(self):
        return len(self._base)


class ArrayDataset(Dataset):
    def __init__(self, *args):
        assert len(args) > 0
        self._length = len(args[0])
        self._data = []
        for a in args:
            assert len(a) == self._length
            self._data.append(a)

    def __getitem__(self, idx):
        if len(self._data) == 1:
            return self._data[0][idx]
        return tuple(d[idx] for d in self._data)

    def __len__(self):
        return self._length


class SimpleDataset(Dataset):
    def __init__(self, data):
        self._data = data

    def __getitem__(self, idx):
        return self._data[idx]

    def __len__(self):
        return len(self._data)


class RecordFileDataset(Dataset):
    """Reads a MXNet .rec record file (reference: src/io/ recordio). Format:
    [magic(4) | lrecord(4) | data...] per record, magic=0xced7230a."""

    MAGIC = 0xCED7230A

    def __init__(self, filename):
        self._filename = filename
        self._offsets = []
        idx_file = filename[:-4] + ".idx" if filename.endswith(".rec") else None
        import os
        if idx_file and os.path.exists(idx_file):
            with open(idx_file) as f:
                for line in f:
                    parts = line.split()
                    if len(parts) >= 2:
                        self._offsets.append(int(parts[1]))
        else:
            self._scan()

    def _scan(self):
        import struct
        with open(self._filename, "rb") as f:
            pos = 0
            while True:
                header = f.read(8)
                if len(header) < 8:
                    break
                magic, lrec = struct.unpack("<II", header)
                if magic != self.MAGIC:
                    raise IOError(f"bad record magic at {pos}")
                length = lrec & ((1 << 29) - 1)
                self._offsets.append(pos)
                pad = (4 - length % 4) % 4
                f.seek(length + pad, 1)
                pos = f.tell()

    def _handle(self):
        # One handle per (pid, thread): DataLoader workers are threads, so a
        # shared handle would race on seek+read; forked processes must also
        # not inherit a shared seek position.
        import os
        import threading
        local = getattr(self, "_fh_local", None)
        if local is None or getattr(self, "_fh_pid", None) != os.getpid():
            local = self._fh_local = threading.local()
            self._fh_pid = os.getpid()
        if not hasattr(local, "fh"):
            local.fh = open(self._filename, "rb")
        return local.fh

    def __getitem__(self, idx):
        import struct
        f = self._handle()
        f.seek(self._offsets[idx])
        magic, lrec = struct.unpack("<II", f.read(8))
        length = lrec & ((1 << 29) - 1)
        return f.read(length)

    def __len__(self):
        return len(self._offsets)


def default_batchify_fn(data):
    """Stack samples into a batch (parity: gluon.data.DataLoader default)."""
    if isinstance(data[0], NDArray):
        return nd.stack(*data, axis=0)
    if isinstance(data[0], (tuple, list)):
        return tuple(default_batchify_fn(list(items)) for items in zip(*data))
    arr = np.asarray(data)
    if arr.dtype == np.float64:
        arr = arr.astype(np.float32)
    return nd.array(arr)


class DataLoader:
    def __init__(self, dataset, batch_size=None, shuffle=False, sampler=None,
                 last_batch=None, batch_sampler=None, batchify_fn=None,
                 num_workers=0, pin_memory=False, prefetch=None):
        self._dataset = dataset
        if batch_sampler is None:
            if batch_size is None:
                raise ValueError("batch_size required when batch_sampler is None")
            if sampler is None:
                sampler = RandomSampler(len(dataset)) if shuffle \
                    else SequentialSampler(len(dataset))
            batch_sampler = BatchSampler(sampler, batch_size,
                                         last_batch or "keep")
        self._batch_sampler = batch_sampler
        self._batchify_fn = batchify_fn or default_batchify_fn
        self._num_workers = num_workers
        self._prefetch = max(1, prefetch if prefetch is not None
                             else 2 * max(num_workers, 1))

    def __len__(self):
        return len(self._batch_sampler)

    def _load_batch(self, indices):
        return self._batchify_fn([self._dataset[i] for i in indices])

    def __iter__(self):
        if self._num_workers == 0:
            for indices in self._batch_sampler:
                yield self._load_batch(indices)
            return
        yield from self._prefetch_iter()

    def _prefetch_iter(self):
        """Prefetch pipeline on the native runtime (reference: src/io
        PrefetcherIter): worker threads of the C++ engine load batches; a
        bounded native TokenQueue provides backpressure (a worker holding a
        loaded batch blocks GIL-free in C until the consumer catches up)."""
        import threading
        from ... import runtime as _rt

        batches = list(self._batch_sampler)
        if not batches:
            return
        eng = _rt.Engine(self._num_workers)
        q = _rt.TokenQueue(self._prefetch)
        results = {}
        lock = threading.Lock()

        def make_task(i, indices):
            def task():
                try:
                    b = self._load_batch(indices)
                except Exception as e:          # surfaced at consume time
                    b = e
                with lock:
                    results[i] = b
                q.push(i)
            return task

        # sliding submission window: at most `prefetch` batches in flight, so
        # a straggler can't make completed batches pile up unboundedly and an
        # early break only drains the window, not the epoch
        submitted = 0

        def submit_next():
            nonlocal submitted
            if submitted < len(batches):
                eng.push(make_task(submitted, batches[submitted]))
                submitted += 1

        for _ in range(min(self._prefetch, len(batches))):
            submit_next()
        try:
            next_i, ready = 0, set()
            while next_i < len(batches):
                while next_i not in ready:
                    tok = q.pop()
                    if tok is None:
                        return
                    ready.add(tok)
                ready.discard(next_i)
                with lock:
                    b = results.pop(next_i)
                if isinstance(b, Exception):
                    raise b
                submit_next()   # refill before yielding: overlap with consumer
                yield b
                next_i += 1
        finally:
            q.close()       # unblocks any producer stuck in push
            eng.wait_all()  # only the in-flight window remains


# vision importable as an attribute (mx.gluon.data.vision.MNIST etc.);
# at the end of the module so vision's `from .. import Dataset` resolves
from . import vision  # noqa: E402,F401
