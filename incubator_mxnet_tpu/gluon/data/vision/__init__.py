"""gluon.data.vision: datasets + transforms (parity: python/mxnet/gluon/data/vision).

Zero-egress note: datasets read standard local files (idx/npz/binary); when
files are absent, MNIST/FashionMNIST/CIFAR fall back to deterministic
synthetic data with the real shapes/classes so examples, tests, and benches
run anywhere."""
from __future__ import annotations

import gzip
import os
import struct

import numpy as np

from .... import ndarray as nd
from ....recordio import unpack_img
from ....ndarray import NDArray
from .. import ArrayDataset, Dataset, RecordFileDataset
from . import transforms

__all__ = ["MNIST", "FashionMNIST", "CIFAR10", "CIFAR100",
           "ImageFolderDataset", "ImageRecordDataset", "transforms"]


def _read_idx(path):
    op = gzip.open if path.endswith(".gz") else open
    with op(path, "rb") as f:
        magic = int.from_bytes(f.read(4), "big")
        ndim = magic & 0xFF
        shape = [int.from_bytes(f.read(4), "big") for _ in range(ndim)]
        return np.frombuffer(f.read(), np.uint8).reshape(shape)


def _synthetic_images(n, shape, num_classes, seed):
    rng = np.random.RandomState(seed)
    labels = rng.randint(0, num_classes, n).astype(np.int32)
    h, w = shape[0], shape[1]
    c = shape[2] if len(shape) > 2 else 1
    X = np.zeros((n, h, w, c), np.uint8)
    for i, l in enumerate(labels):
        r0 = (2 + l * 2) % max(h - 6, 1)
        X[i, r0:r0 + 4, 2:w - 2] = 200
    X = np.clip(X + rng.randint(0, 40, X.shape), 0, 255).astype(np.uint8)
    return X.squeeze(-1) if c == 1 and len(shape) == 2 else X, labels


class _DownloadedDataset(Dataset):
    def __init__(self, root, train, transform):
        self._root = os.path.expanduser(root)
        self._train = train
        self._transform = transform
        self._data = None
        self._label = None
        self._get_data()

    def __getitem__(self, idx):
        img = nd.array(self._data[idx])
        label = self._label[idx]
        if self._transform is not None:
            return self._transform(img, label)
        return img, label

    def __len__(self):
        return len(self._label)


class MNIST(_DownloadedDataset):
    _files = {True: ("train-images-idx3-ubyte", "train-labels-idx1-ubyte"),
              False: ("t10k-images-idx3-ubyte", "t10k-labels-idx1-ubyte")}
    _synth_seed = 42

    def __init__(self, root="~/.mxtpu/datasets/mnist", train=True, transform=None):
        super().__init__(root, train, transform)

    def _get_data(self):
        img_f, lab_f = self._files[self._train]
        img_path = os.path.join(self._root, img_f)
        if os.path.exists(img_path) or os.path.exists(img_path + ".gz"):
            p = img_path if os.path.exists(img_path) else img_path + ".gz"
            lp = os.path.join(self._root, lab_f)
            lp = lp if os.path.exists(lp) else lp + ".gz"
            self._data = _read_idx(p).astype(np.float32)[..., None] / 1.0
            self._label = _read_idx(lp).astype(np.int32)
        else:
            n = 10000 if self._train else 2000
            X, y = _synthetic_images(n, (28, 28), 10, self._synth_seed)
            self._data = X[..., None].astype(np.float32)
            self._label = y


class FashionMNIST(MNIST):
    _synth_seed = 43

    def __init__(self, root="~/.mxtpu/datasets/fashion-mnist", train=True,
                 transform=None):
        _DownloadedDataset.__init__(self, root, train, transform)


class CIFAR10(_DownloadedDataset):
    _nclass = 10
    _synth_seed = 44

    def __init__(self, root="~/.mxtpu/datasets/cifar10", train=True, transform=None):
        super().__init__(root, train, transform)

    def _get_data(self):
        batches = ([f"data_batch_{i}" for i in range(1, 6)] if self._train
                   else ["test_batch"])
        paths = [os.path.join(self._root, "cifar-10-batches-py", b) for b in batches]
        if all(os.path.exists(p) for p in paths):
            import pickle
            xs, ys = [], []
            for p in paths:
                with open(p, "rb") as f:
                    d = pickle.load(f, encoding="bytes")
                xs.append(d[b"data"].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1))
                ys.append(d[b"labels" if b"labels" in d else b"fine_labels"])
            self._data = np.concatenate(xs).astype(np.float32)
            self._label = np.concatenate(ys).astype(np.int32)
        else:
            n = 10000 if self._train else 2000
            X, y = _synthetic_images(n, (32, 32, 3), self._nclass, self._synth_seed)
            self._data = X.astype(np.float32)
            self._label = y


class CIFAR100(CIFAR10):
    _nclass = 100
    _synth_seed = 45

    def __init__(self, root="~/.mxtpu/datasets/cifar100", train=True, transform=None):
        _DownloadedDataset.__init__(self, root, train, transform)


class ImageFolderDataset(Dataset):
    """folder/class_name/*.png layout; decodes via PIL if available, else
    npy files."""

    def __init__(self, root, flag=1, transform=None):
        self._root = os.path.expanduser(root)
        self._transform = transform
        self.synsets = []
        self.items = []
        for folder in sorted(os.listdir(self._root)):
            path = os.path.join(self._root, folder)
            if not os.path.isdir(path):
                continue
            label = len(self.synsets)
            self.synsets.append(folder)
            for fname in sorted(os.listdir(path)):
                self.items.append((os.path.join(path, fname), label))

    def __getitem__(self, idx):
        path, label = self.items[idx]
        if path.endswith(".npy"):
            img = np.load(path)
        else:
            from PIL import Image
            img = np.asarray(Image.open(path).convert("RGB"))
        img = nd.array(img.astype(np.float32))
        if self._transform is not None:
            return self._transform(img, label)
        return img, label

    def __len__(self):
        return len(self.items)


class ImageRecordDataset(Dataset):
    """`.rec` image records -> (image NDArray HWC, label) samples
    (reference: python/mxnet/gluon/data/vision/datasets.py
    ImageRecordDataset). Each record is an IRHeader + encoded image; the
    header's label (scalar or vector) rides along. Decode is host-side
    (PIL), feeding numpy/NDArray batches to the chip via DataLoader."""

    def __init__(self, filename, flag=1, transform=None):
        self._record = RecordFileDataset(filename)
        self._flag = flag
        self._transform = transform

    def __getitem__(self, idx):
        header, img = unpack_img(self._record[idx], iscolor=self._flag)
        label = header.label
        if isinstance(label, np.ndarray) and label.size == 1:
            label = float(label[0])
        img = nd.array(img.astype(np.float32))
        if self._transform is not None:
            return self._transform(img, label)
        return img, label

    def __len__(self):
        return len(self._record)
