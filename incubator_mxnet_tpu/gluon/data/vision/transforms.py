"""Vision transforms (parity: python/mxnet/gluon/data/vision/transforms.py).

Transforms are HybridBlocks operating on HWC images (float or uint8-valued
NDArrays), mirroring the reference semantics: ToTensor converts HWC [0,255]
→ CHW [0,1]."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .... import ndarray as nd
from ....image import _GRAY

_gray_cache = {}


def _gray_nd():
    """_GRAY as a cached device NDArray (lazy: no backend init at import)."""
    if "v" not in _gray_cache:
        _gray_cache["v"] = nd.array(_GRAY)
    return _gray_cache["v"]

from ....ndarray import NDArray, _apply
from ....ndarray import random as ndrandom
from ...block import Block, HybridBlock
from ...nn import HybridSequential

__all__ = ["Compose", "Cast", "ToTensor", "Normalize", "Resize", "CenterCrop",
           "RandomResizedCrop", "RandomFlipLeftRight", "RandomFlipTopBottom",
           "RandomBrightness", "RandomContrast", "CropResize",
           "RandomSaturation", "RandomHue", "RandomColorJitter",
           "RandomLighting", "RandomGray"]


class Compose(HybridSequential):
    def __init__(self, transforms):
        super().__init__()
        for t in transforms:
            self.add(t)


class Cast(HybridBlock):
    def __init__(self, dtype="float32"):
        super().__init__()
        self._dtype = dtype

    def forward(self, x):
        return x.astype(self._dtype)


class ToTensor(HybridBlock):
    """HWC [0,255] → CHW [0,1] float32 (batch: NHWC → NCHW)."""

    def forward(self, x):
        def f(a):
            a = a.astype(jnp.float32) / 255.0
            if a.ndim == 3:
                return jnp.transpose(a, (2, 0, 1))
            return jnp.transpose(a, (0, 3, 1, 2))
        return _apply(f, [x], name="to_tensor")


class Normalize(HybridBlock):
    """Channel-wise (x - mean) / std on CHW tensors."""

    def __init__(self, mean=0.0, std=1.0):
        super().__init__()
        self._mean = np.asarray(mean, np.float32)
        self._std = np.asarray(std, np.float32)

    def forward(self, x):
        mean, std = self._mean, self._std

        def f(a):
            shape = (-1, 1, 1) if a.ndim == 3 else (1, -1, 1, 1)
            return (a - mean.reshape(shape)) / std.reshape(shape)
        return _apply(f, [x], name="normalize")


def _resize_hwc(a, size, interp="bilinear"):
    h, w = (size, size) if isinstance(size, int) else (size[1], size[0])
    if a.ndim == 3:
        return jax.image.resize(a, (h, w, a.shape[2]), method=interp)
    return jax.image.resize(a, (a.shape[0], h, w, a.shape[3]), method=interp)


class Resize(HybridBlock):
    def __init__(self, size, keep_ratio=False, interpolation="bilinear"):
        super().__init__()
        self._size = size
        self._keep = keep_ratio
        self._interp = interpolation

    def forward(self, x):
        size = self._size
        if self._keep and isinstance(size, int):
            # shorter edge → size, aspect preserved (reference semantics)
            h, w = (x.shape[0], x.shape[1]) if x.ndim == 3 else (x.shape[1], x.shape[2])
            if h < w:
                size = (int(round(w * size / h)), size)  # (W, H)
            else:
                size = (size, int(round(h * size / w)))
        return _apply(lambda a: _resize_hwc(a, size, self._interp), [x],
                      name="resize")


class CenterCrop(HybridBlock):
    def __init__(self, size):
        super().__init__()
        self._size = (size, size) if isinstance(size, int) else (size[1], size[0])

    def forward(self, x):
        ch, cw = self._size

        def f(a):
            h, w = (a.shape[0], a.shape[1]) if a.ndim == 3 else (a.shape[1], a.shape[2])
            y0, x0 = max((h - ch) // 2, 0), max((w - cw) // 2, 0)
            if a.ndim == 3:
                return a[y0:y0 + ch, x0:x0 + cw]
            return a[:, y0:y0 + ch, x0:x0 + cw]
        return _apply(f, [x], name="center_crop")


class CropResize(HybridBlock):
    def __init__(self, x0, y0, width, height, size=None, interpolation="bilinear"):
        super().__init__()
        self._box = (x0, y0, width, height)
        self._size = size
        self._interp = interpolation

    def forward(self, x):
        x0, y0, w, h = self._box

        def f(a):
            crop = a[y0:y0 + h, x0:x0 + w] if a.ndim == 3 else a[:, y0:y0 + h, x0:x0 + w]
            if self._size is not None:
                crop = _resize_hwc(crop, self._size, self._interp)
            return crop
        return _apply(f, [x], name="crop_resize")


class RandomFlipLeftRight(Block):
    def forward(self, x):
        if float(ndrandom.uniform(shape=(1,)).asnumpy()[0]) < 0.5:
            return x.flip(axis=-2)  # W axis in both HWC and NHWC
        return x


class RandomFlipTopBottom(Block):
    def forward(self, x):
        if float(ndrandom.uniform(shape=(1,)).asnumpy()[0]) < 0.5:
            return x.flip(axis=0 if x.ndim == 3 else 1)
        return x


class RandomBrightness(Block):
    def __init__(self, brightness):
        super().__init__()
        self._b = brightness

    def forward(self, x):
        f = 1.0 + float(ndrandom.uniform(-self._b, self._b, shape=(1,)).asnumpy()[0])
        return x * f


class RandomContrast(Block):
    def __init__(self, contrast):
        super().__init__()
        self._c = contrast

    def forward(self, x):
        f = 1.0 + float(ndrandom.uniform(-self._c, self._c, shape=(1,)).asnumpy()[0])
        # luminance-weighted gray mean over pixels (reference semantics;
        # shape-agnostic: channels are the last axis)
        n_px = x.size // x.shape[-1]
        gray_mean = (x * _gray_nd()).sum() / n_px
        return x * f + gray_mean * (1 - f)


class RandomResizedCrop(Block):
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3),
                 interpolation="bilinear"):
        super().__init__()
        self._size = size
        self._scale = scale
        self._ratio = ratio
        self._interp = interpolation

    def forward(self, x):
        h, w = x.shape[0], x.shape[1]
        area = h * w
        rng = np.random
        for _ in range(10):
            target_area = rng.uniform(*self._scale) * area
            ar = np.exp(rng.uniform(np.log(self._ratio[0]), np.log(self._ratio[1])))
            cw = int(round(np.sqrt(target_area * ar)))
            ch = int(round(np.sqrt(target_area / ar)))
            if cw <= w and ch <= h:
                x0 = rng.randint(0, w - cw + 1)
                y0 = rng.randint(0, h - ch + 1)
                crop = x[y0:y0 + ch, x0:x0 + cw]
                return _apply(lambda a: _resize_hwc(a, self._size, self._interp),
                              [crop], name="rrc_resize")
        return _apply(lambda a: _resize_hwc(a, self._size, self._interp), [x],
                      name="rrc_resize")


class RandomSaturation(Block):
    """Parity: transforms.RandomSaturation."""

    def __init__(self, saturation):
        super().__init__()
        self._s = saturation

    def forward(self, x):
        f = 1.0 + float(ndrandom.uniform(-self._s, self._s,
                                         shape=(1,)).asnumpy()[0])
        gray = (x * _gray_nd()).sum(axis=-1, keepdims=True)
        return x * f + gray * (1.0 - f)


class RandomHue(Block):
    """Parity: transforms.RandomHue (YIQ rotation, reference math)."""

    _T_YIQ = np.array([[0.299, 0.587, 0.114],
                       [0.596, -0.274, -0.321],
                       [0.211, -0.523, 0.311]], np.float32)
    _T_RGB = np.array([[1.0, 0.956, 0.621],
                       [1.0, -0.272, -0.647],
                       [1.0, -1.107, 1.705]], np.float32)

    def __init__(self, hue):
        super().__init__()
        self._h = hue

    def forward(self, x):
        alpha = float(ndrandom.uniform(-self._h, self._h,
                                       shape=(1,)).asnumpy()[0])
        u, w = np.cos(alpha * np.pi), np.sin(alpha * np.pi)
        rot = np.array([[1, 0, 0], [0, u, -w], [0, w, u]], np.float32)
        m = self._T_RGB @ rot @ self._T_YIQ
        return nd.dot(x, nd.array(m.T.astype(np.float32)))


class RandomColorJitter(Block):
    """Parity: transforms.RandomColorJitter — brightness/contrast/
    saturation/hue applied in random order."""

    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0):
        super().__init__()
        self._ts = []
        if brightness:
            self._ts.append(RandomBrightness(brightness))
        if contrast:
            self._ts.append(RandomContrast(contrast))
        if saturation:
            self._ts.append(RandomSaturation(saturation))
        if hue:
            self._ts.append(RandomHue(hue))

    def forward(self, x):
        # order drawn from the framework RNG chain -> reproducible under
        # mx.random.seed
        keys = ndrandom.uniform(0, 1, shape=(len(self._ts),)).asnumpy()
        for i in np.argsort(keys):
            x = self._ts[i](x)
        return x


class RandomLighting(Block):
    """Parity: transforms.RandomLighting (AlexNet-style PCA noise)."""

    _eigval = np.array([55.46, 4.794, 1.148], np.float32)
    _eigvec = np.array([[-0.5675, 0.7192, 0.4009],
                        [-0.5808, -0.0045, -0.8140],
                        [-0.5836, -0.6948, 0.4203]], np.float32)

    def __init__(self, alpha):
        super().__init__()
        self._alpha = alpha

    def forward(self, x):
        a = np.asarray(ndrandom.normal(0, self._alpha,
                                       shape=(3,)).asnumpy())
        rgb = (self._eigvec * a) @ self._eigval
        return x + nd.array(rgb.astype(np.float32))


class RandomGray(Block):
    """Parity: transforms.RandomGray — grayscale with probability p."""

    def __init__(self, p=0.5):
        super().__init__()
        self._p = p

    def forward(self, x):
        coin = float(ndrandom.uniform(0, 1, shape=(1,)).asnumpy()[0])
        if coin < self._p:
            gray = (x * _gray_nd()).sum(axis=-1, keepdims=True)
            return nd.concat(gray, gray, gray, dim=-1)
        return x
