"""mxtpu.io.pipeline — the staged host ingest engine behind
:class:`~.prefetch.DevicePrefetcher`.

The PR 6 prefetcher was ONE worker thread that read, decoded, stacked
and ``jax.device_put`` each chunk *serially*, so decode wall and
transfer wall added instead of overlapping. This module splits that
body into the classic input-pipeline stages, each on its own thread(s),
with the batch ORDER pinned by sequence numbers so the resume cursor
and the training trajectory are bit-identical to the serial reader no
matter how decode completions interleave:

    reader ──► decode pool (io_workers) ──► ordered staging ring ──► transfer
    (source next, skip/cycle      (host decode/transform/stack,      (device_put
     cursor — the order            completes out of order)            in seq order,
     authority)                                                       depth slots)

* **reader** — the single thread that iterates the source. It owns the
  ``skip=`` data cursor and the cycle/epoch-fold logic (resilience
  resume semantics live HERE, before any parallelism), assigns each
  chunk a sequence number, and feeds a bounded work queue.
* **decode pool** — ``workers`` threads perform the host-side work:
  the optional ``transform`` hook, NDArray→raw conversion, the
  mixed-label check, and numpy stacking for chunk mode. Results land
  in the staging ring keyed by sequence number — completion order is
  irrelevant.
* **transfer** — one thread pops the ring strictly in sequence order
  (the wait is ``io.stage_ms``) and parks the batch in the
  ``depth``-bounded buffer the consumer pops. On thread-safe backends
  (TPU) it also resolves the late-bound sharding and issues
  ``jax.device_put`` itself under the process-wide
  :data:`TRANSFER_GATE` (the wall is ``io.put_ms``); on XLA:CPU the
  put is deferred to the consumer thread — see the safety model below.

Per-stage wall counters split devicescope's ``input_starved`` bucket
into disk-vs-decode-vs-transfer attribution (docs/io.md):

* ``io.read_ms``   counter — reader wall inside the SOURCE's next();
* ``io.decode_ms`` counter — decode-pool wall (sums across workers, so
  it can exceed wall-clock — it is host-work attribution, not a span);
* ``io.stage_ms``  counter — transfer wall waiting for the next
  in-order chunk (reordering/decode-lag wait);
* ``io.put_ms``    counter — convert + ``device_put`` wall;
* ``io.workers``   gauge   — resolved decode-pool width.

Backend-safety model (the PR 14 1-in-3 ``test_resilience`` flake):
this jaxlib's XLA:CPU client is not safe against host↔device copies
concurrent with a DONATING execution running on its internal threads —
the donated-buffer handoff happens *during* the async execution, and a
concurrent ``BufferFromHostBuffer`` corrupts the heap (the crash then
detonates anywhere: the copy itself, the next dispatch, orbax's
asyncio loop). Empirically it does not matter which *Python* thread
issues the copy: gating the dispatch enqueue, fencing on the last
dispatch handle, and even moving every put onto the dispatching thread
each still crashed 2-3 in 5-6 suite runs — because (PR 17's flake hunt)
the DOMINANT planter was not a transfer race at all: this jaxlib also
mis-deserializes persistent-compile-cache entries for donated
executables, probabilistically per READ (warm cache: 6/10 process
crashes on the resume tests; cache wiped per run: 1/12; reads
quarantined: 0/12 — see runtime/cache_guard.py). The fix therefore has
four parts — the cache-read quarantine removes the dominant planter,
and the transfer serialization below closes the concurrency windows
the PR 14 diagnosis named:

1. **deferred put** (this module): on the CPU backend the transfer
   stage parks host-staged batches in the buffer and the CONSUMER
   thread issues ``device_put`` inside ``next()`` — every XLA call the
   pipeline makes comes from the one thread that also dispatches.
   Decode-pool ∥ compute overlap (the CPU win) is preserved; only the
   put moves on-thread, and on CPU a put is a host-memory copy with
   negligible wall.
2. **synchronous donating dispatch**
   (:class:`~..parallel.trainer_step.FusedTrainStep`): on the CPU
   backend the dispatch blocks until the donating execution retires,
   so no client call can ever overlap the donation window. Only async
   dispatch depth is forfeited, on the backend where it buys nothing —
   compute still overlaps the decode pool (host threads). The block
   happens INSIDE the gate, so on CPU the donation window and the gate
   window coincide.
3. **gated checkpoint serialization**
   (:class:`~..resilience.checkpoint.CheckpointManager`): the async
   checkpoint worker holds the same gate for the whole orbax save on
   CPU. With part 2 the gate covers every XLA window, so a save can
   never overlap one.
4. **donated cache-read quarantine**
   (:mod:`~..runtime.cache_guard`): donating fused-step dispatches
   run under a forced persistent-cache MISS, so their executables
   always come from a fresh backend compile, never from the unsound
   deserialization path.

On TPU the client supports concurrent transfers and donation is
handled by the runtime, so the transfer thread issues the put itself
(put ∥ compute overlap kept) and dispatches stay async. Both backends
still serialize the put against the dispatch enqueue via the
process-wide :data:`TRANSFER_GATE` that FusedTrainStep shares.
"""
from __future__ import annotations

import queue as _queue
import threading
import time

import numpy as np

from .. import profiler as _prof

__all__ = ["Pipeline", "ShardedRecordReader", "TRANSFER_GATE",
           "transfer_gate"]

_SENTINEL = object()
_DONE = object()          # decode-pool poison pill

# default close() deadline for a reader parked inside the source's
# next(); DevicePrefetcher passes its own (monkeypatchable) constant
_CLOSE_DEADLINE_S = 5.0

# Process-wide host→device transfer gate. Held around every pipeline
# device_put and by FusedTrainStep around the donating dispatch
# enqueue, so a put enqueue never interleaves a dispatch enqueue on
# the client. One lock for the process: the ordering it protects is a
# client-level property, not a per-pipeline one.
TRANSFER_GATE = threading.Lock()

# lazily-probed "must the put run on the consumer thread?" cache.
# XLA:CPU yes — its client races off-thread host→device copies against
# the donated-buffer handoff of a RUNNING execution (see the module
# docstring); TPU no — concurrent transfers are supported there, and
# deferring would forfeit the put∥compute overlap.
_DEFER_BACKEND = []


def transfer_gate():
    """The process-wide transfer/dispatch serialization lock (use as
    ``with transfer_gate(): ...``)."""
    return TRANSFER_GATE


def _defer_put_needed():
    if not _DEFER_BACKEND:
        import jax
        _DEFER_BACKEND.append(jax.default_backend() == "cpu")
    return _DEFER_BACKEND[0]


class _HostStaged:
    """Buffer wrapper for a batch whose device_put is deferred to the
    consumer thread (CPU backend — see the module docstring). Holds
    only host/already-landed arrays, so close()-time draining frees
    nothing the client could still be writing."""
    __slots__ = ("payload",)

    def __init__(self, payload):
        self.payload = payload


def _split_batch(b):
    """Normalize one source item to (x, y): DataBatch, (x, y) pair, or a
    bare array (y=None)."""
    data = getattr(b, "data", None)
    if data is not None and not isinstance(b, (tuple, list, np.ndarray)):
        label = getattr(b, "label", None)
        return data[0], (label[0] if label else None)
    if isinstance(b, (tuple, list)) and len(b) == 2:
        return b[0], b[1]
    return b, None


def _raw(a):
    from ..ndarray import NDArray
    if isinstance(a, NDArray):
        return a._data
    return np.asarray(a)


def _stack_dev(arrs):
    import jax.numpy as jnp
    return jnp.stack([jnp.asarray(a) for a in arrs])


def _resolve_workers(workers):
    """Decode-pool width through the ONE knob table (call-site >
    BENCH_IO_WORKERS > MXTPU_IO_WORKERS > cached winner > 2)."""
    from ..autotune import knobs as _knobs
    v = int(_knobs.resolve("io_workers", workers)[0])
    if v < 1:
        raise ValueError(f"io workers must be >= 1, got {v}")
    return v


class Pipeline:
    """Staged host ingest: reader → decode pool → ordered ring →
    transfer → ``depth`` device slots. See the module docstring for the
    stage model; :class:`~.prefetch.DevicePrefetcher` is the public
    face and documents the source/depth/chunk/sharding/cycle/skip
    contract (unchanged from PR 6).

    workers   : decode-pool width (the ``io_workers`` knob; None
                resolves through autotune.knobs).
    transform : optional host-side hook ``(x, y) -> (x, y)`` applied to
                each batch INSIDE the decode pool — the place for
                per-batch decode/augment work (and for the smoke's
                injected decode latency), because the pool parallelizes
                it while order stays pinned by the ring.
    """

    def __init__(self, source, depth=2, chunk=None, sharding=None,
                 cycle=False, skip=0, workers=None, transform=None):
        if depth < 1:
            raise ValueError(f"depth must be >= 1, got {depth}")
        if chunk is not None and chunk < 1:
            raise ValueError(f"chunk must be >= 1, got {chunk}")
        if skip < 0:
            raise ValueError(f"skip must be >= 0, got {skip}")
        self._source = source
        self._depth = int(depth)
        self._chunk = int(chunk) if chunk else None
        self._sharding = sharding
        self._cycle = bool(cycle)
        self._skip = int(skip)
        self._workers = _resolve_workers(workers)
        self._transform = transform
        self._epoch_len = None   # learned at the first source wrap
        self._buf = _queue.Queue(maxsize=self._depth)
        self._stop = threading.Event()
        self._exhausted = False
        # counters exist from construction so smoke checks can assert on
        # them even for an all-hits run (wait_ms == 0 is a signal too)
        self._c_batches = _prof.counter("io.batches_prefetched", "io")
        self._c_wait = _prof.counter("io.wait_ms", "io")
        self._c_put = _prof.counter("io.put_ms", "io")
        self._c_read = _prof.counter("io.read_ms", "io")
        self._c_decode = _prof.counter("io.decode_ms", "io")
        self._c_stage = _prof.counter("io.stage_ms", "io")
        _prof.set_gauge("io.depth", self._depth, "io")
        _prof.set_gauge("io.buffer_fill", 0, "io")
        _prof.set_gauge("io.workers", self._workers, "io")
        # work queue bound: enough for every decoder plus readahead
        self._work = _queue.Queue(maxsize=self._workers + 2)
        # in-flight window: the reader may run at most this many chunks
        # ahead of the transfer stage (acquired per chunk read, released
        # per chunk popped from the ring). Without it the decode pool
        # churns arbitrarily far ahead of a slow consumer on a cycling
        # source — unbounded ring memory AND host CPU stolen from
        # compute (the io_smoke caught the pipelined run running SLOWER
        # than serial through exactly this)
        self._window = threading.Semaphore(
            self._workers + self._depth + 2)
        self._ring = {}          # seq -> ("ok", payload) | ("err", exc)
        self._ring_cv = threading.Condition()
        self._eof_seq = None     # chunk count, set once by the reader
        self._threads = [
            threading.Thread(target=self._read_loop, daemon=True,
                             name="mxtpu-io-read")]
        self._threads += [
            threading.Thread(target=self._decode_loop, daemon=True,
                             name=f"mxtpu-io-decode-{i}")
            for i in range(self._workers)]
        # the transfer thread keeps the historical name: it is the one
        # that lands batches on device, i.e. the old worker's role
        self._thread = threading.Thread(target=self._transfer_loop,
                                        daemon=True,
                                        name="mxtpu-device-prefetch")
        self._threads.append(self._thread)
        for t in self._threads:
            t.start()

    # -- reader stage -----------------------------------------------------
    def _iter_source(self):
        src = self._source
        while True:
            it = iter(src) if not hasattr(src, "next") else src
            n = 0
            while True:
                t0 = time.perf_counter()
                try:
                    b = next(it)
                except StopIteration:
                    self._c_read.increment(
                        (time.perf_counter() - t0) * 1e3)
                    break
                self._c_read.increment((time.perf_counter() - t0) * 1e3)
                n += 1
                yield b
            if n and self._epoch_len is None:
                self._epoch_len = n
            if not self._cycle:
                return
            if hasattr(src, "reset"):
                src.reset()
            elif iter(src) is src:
                return          # a bare iterator can't be rewound

    def _read_loop(self):
        """The order authority: iterates the source, applies the resume
        cursor, numbers chunks. Runs the EXACT skip/cycle semantics of
        the PR 6 serial worker — parallelism starts downstream of the
        cursor, so a resumed run sees the same batches in the same
        order at any worker count."""
        seq = 0
        try:
            pending = []
            n = self._chunk or 1
            to_skip = self._skip
            if to_skip:
                c_skip = _prof.counter("io.batches_skipped", "io")
            for b in self._iter_source():
                if self._stop.is_set():
                    break
                if to_skip > 0:
                    # cursor resume: already-consumed batches are
                    # dropped host-side, before any conversion/transfer.
                    # An ABSOLUTE cursor through a cycling source only
                    # matters modulo the epoch: once the first wrap
                    # teaches us the epoch length, whole epochs of the
                    # remaining skip fold away instead of being read and
                    # discarded — resume cost stays bounded by ~one
                    # epoch of host reads however long the run was
                    if self._cycle and self._epoch_len:
                        to_skip %= self._epoch_len
                        if to_skip == 0:
                            pass   # fell exactly on a boundary: train b
                        else:
                            to_skip -= 1
                            c_skip.increment()
                            continue
                    else:
                        to_skip -= 1
                        c_skip.increment()
                        continue
                pending.append(_split_batch(b))
                if len(pending) < n:
                    continue
                if not self._put_work((seq, pending)):
                    break
                seq += 1
                pending = []
            # a trailing partial chunk is dropped (static-shape programs
            # can't take a short chunk); per-batch mode has no remainder
            with self._ring_cv:
                if self._eof_seq is None:
                    self._eof_seq = seq
                self._ring_cv.notify_all()
        except Exception as e:  # noqa: BLE001 — surfaced at next(), in order
            with self._ring_cv:
                self._ring[seq] = ("err", e)
                self._eof_seq = seq + 1
                self._ring_cv.notify_all()
        for _ in range(self._workers):
            try:
                self._work.put_nowait(_DONE)
            except _queue.Full:
                break            # stopping: decoders exit on the flag

    def _put_work(self, item):
        while not self._stop.is_set():       # in-flight window first:
            if self._window.acquire(timeout=0.05):   # released by the
                break                        # transfer stage per chunk
        else:
            return False
        while not self._stop.is_set():
            try:
                self._work.put(item, timeout=0.05)
                return True
            except _queue.Full:
                continue
        return False

    # -- decode stage -----------------------------------------------------
    def _decode_loop(self):
        while not self._stop.is_set():
            try:
                item = self._work.get(timeout=0.05)
            except _queue.Empty:
                continue
            if item is _DONE:
                return
            seq, items = item
            t0 = time.perf_counter()
            try:
                entry = ("ok", self._decode(items))
            except Exception as e:  # noqa: BLE001 — surfaced at next()
                entry = ("err", e)
            self._c_decode.increment((time.perf_counter() - t0) * 1e3)
            with self._ring_cv:
                self._ring[seq] = entry
                self._ring_cv.notify_all()

    def _decode(self, items):
        """Host-side chunk decode: transform hook, raw conversion, the
        mixed-label check, numpy stacking. Returns (xs, ys) lists —
        singleton once stacked; device-array stacking is deferred to the
        transfer stage (it is device work)."""
        if self._transform is not None:
            items = [self._transform(x, y) for x, y in items]
        xs = [_raw(x) for x, _ in items]
        n_labeled = sum(1 for _, y in items if y is not None)
        if 0 < n_labeled < len(items):
            # fail HERE, not as a leading-axis mismatch deep inside the
            # compiled scan: a partially-labeled chunk is a source bug
            raise ValueError(
                f"mixed labeled/label-less batches in one prefetch chunk "
                f"({n_labeled}/{len(items)} labeled)")
        ys = [_raw(y) for _, y in items if y is not None]
        if self._chunk is not None:
            if all(isinstance(a, np.ndarray) for a in xs):
                xs = [np.stack(xs)]
            if ys and all(isinstance(a, np.ndarray) for a in ys):
                ys = [np.stack(ys)]
        return xs, ys

    # -- transfer stage ---------------------------------------------------
    def _transfer_loop(self):
        seq = 0
        try:
            while True:
                t0 = time.perf_counter()
                with self._ring_cv:
                    while True:
                        if self._stop.is_set():
                            return
                        if seq in self._ring:
                            kind, payload = self._ring.pop(seq)
                            self._window.release()   # reader may read on
                            break
                        if self._eof_seq is not None \
                                and seq >= self._eof_seq:
                            kind, payload = "eof", None
                            break
                        self._ring_cv.wait(0.05)
                self._c_stage.increment((time.perf_counter() - t0) * 1e3)
                if kind == "eof":
                    self._put(_SENTINEL)
                    return
                if kind == "err":
                    self._put(payload)
                    return
                if _defer_put_needed():
                    # CPU: no XLA call may leave this thread — park the
                    # host-staged batch; next() issues the put on the
                    # consumer thread (module docstring: safety model)
                    item = _HostStaged(payload)
                else:
                    item = self._to_device(payload)
                self._c_batches.increment(self._chunk or 1)
                if not self._put(item):
                    return
                seq += 1
        except Exception as e:  # noqa: BLE001 — surfaced at next()
            self._put(e)

    def _to_device(self, payload):
        import jax
        xs, ys = payload
        t0 = time.perf_counter()
        if self._chunk is not None:
            # device-array chunks could not np.stack in the decode pool
            if len(xs) > 1:
                xs = [_stack_dev(xs)]
            if len(ys) > 1:
                ys = [_stack_dev(ys)]
        sharding = self._sharding() if callable(self._sharding) \
            else self._sharding
        put = (lambda a: jax.device_put(a, sharding)) \
            if sharding is not None else jax.device_put
        with TRANSFER_GATE:
            out = (put(xs[0]), put(ys[0]) if ys else None)
        # materialize OUTSIDE the gate (holding it would stall dispatch
        # enqueues): device_put returns an async array, and a copy
        # still in flight when the batch reaches the buffer could race
        # a close()-time free. On the deferred path this runs on the
        # consumer thread, where a CPU put is a near-synchronous
        # host-memory copy — negligible wall, counted in io.put_ms.
        for a in out:
            if a is not None:
                jax.block_until_ready(a)
        self._c_put.increment((time.perf_counter() - t0) * 1e3)
        return out

    def _put(self, item):
        """Blocking put that stays responsive to close()."""
        while not self._stop.is_set():
            try:
                self._buf.put(item, timeout=0.05)
                return True
            except _queue.Full:
                continue
        return False

    # -- consumer ---------------------------------------------------------
    def __iter__(self):
        return self

    def __next__(self):
        if self._exhausted:
            raise StopIteration
        t0 = time.perf_counter()
        item = self._buf.get()
        self._c_wait.increment((time.perf_counter() - t0) * 1e3)
        _prof.set_gauge("io.buffer_fill", self._buf.qsize(), "io")
        if item is _SENTINEL:
            self._exhausted = True
            raise StopIteration
        if isinstance(item, Exception):
            self._exhausted = True
            raise item
        if isinstance(item, _HostStaged):
            # deferred put (CPU backend): the one XLA call the pipeline
            # makes off the worker threads happens HERE, on the same
            # thread that dispatches — single-threaded client usage
            item = self._to_device(item.payload)
        return item

    next = __next__

    # -- lifecycle --------------------------------------------------------
    def close(self, deadline_s=_CLOSE_DEADLINE_S):
        """Stop every stage and drop every buffered device batch. Safe
        to call at any point (mid-epoch early stop included) and
        idempotent; after close() the buffer holds no device references.

        A reader parked inside the SOURCE's ``next()`` (streaming/queue
        sources) cannot be interrupted; close() stops waiting for it
        after ``deadline_s`` — the threads are daemons, and once the
        stop flag is set ``_put`` refuses every item, so nothing can
        land in the buffer after close() returns either way."""
        self._stop.set()
        with self._ring_cv:
            self._ring_cv.notify_all()
        deadline = time.monotonic() + deadline_s
        # the transfer thread dies FIRST (all its waits are short-tick
        # timeouts, so it exits promptly once the flag is up): after
        # this join no off-thread device_put can be in flight, so the
        # drain below frees fully-landed arrays (or host-staged
        # batches, on the deferred-put backend) instead of racing an
        # async copy — the close()-time half of the PR 14 segfault
        while self._thread.is_alive() and time.monotonic() < deadline:
            self._thread.join(timeout=0.05)
        while True:
            try:
                with TRANSFER_GATE:
                    self._buf.get_nowait()
            except _queue.Empty:
                if not any(t.is_alive() for t in self._threads) \
                        or time.monotonic() > deadline:
                    break
                time.sleep(0.01)
        self._exhausted = True
        with self._ring_cv:
            self._ring.clear()
        _prof.set_gauge("io.buffer_fill", 0, "io")
        for t in self._threads:
            t.join(timeout=0.1)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


class ShardedRecordReader:
    """Deterministic rank-sharded iteration over an indexed record file.

    Wraps :class:`~..recordio.MXIndexedRecordIO` and yields
    ``decode_fn(payload)`` for every key in THIS rank's shard
    (``recordio.shard_keys``: interleaved ``keys[rank::num_ranks]``, so
    fleet replicas and elastic re-joins read disjoint, deterministic
    shards with no coordination — the shard is a pure function of
    (keys, rank, num_ranks)).

    Rewindable (``reset()``), so it cycles under the prefetcher; counts
    ``io.records_read`` and exports the shard geometry as gauges. The
    file handle is opened lazily per iteration pass and owned by the
    single reader thread — this class is NOT thread-safe by design (the
    pipeline's parallelism lives in the decode pool, not the reader).
    """

    def __init__(self, idx_path, rec_path, rank=0, num_ranks=1,
                 decode_fn=None, key_type=int):
        from ..recordio import MXIndexedRecordIO, shard_keys
        self._idx_path = idx_path
        self._rec_path = rec_path
        self._key_type = key_type
        self.rank = int(rank)
        self.num_ranks = int(num_ranks)
        self._decode_fn = decode_fn
        self._rec = MXIndexedRecordIO(idx_path, rec_path, "r",
                                      key_type=key_type)
        if not self._rec.keys:
            raise ValueError(f"record file {rec_path!r} has no index "
                             f"({idx_path!r} missing or empty)")
        self.keys = shard_keys(self._rec.keys, self.rank, self.num_ranks)
        self._pos = 0
        self._c_records = _prof.counter("io.records_read", "io")
        _prof.set_gauge("io.shard_rank", self.rank, "io")
        _prof.set_gauge("io.shard_ranks", self.num_ranks, "io")
        _prof.set_gauge("io.shard_records", len(self.keys), "io")

    def __len__(self):
        return len(self.keys)

    def reset(self):
        self._pos = 0

    def __iter__(self):
        return self

    def __next__(self):
        if self._pos >= len(self.keys):
            raise StopIteration
        payload = self._rec.read_idx(self.keys[self._pos])
        self._pos += 1
        self._c_records.increment()
        return payload if self._decode_fn is None \
            else self._decode_fn(payload)

    next = __next__

    def close(self):
        self._rec.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
