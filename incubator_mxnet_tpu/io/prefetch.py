"""On-device double-buffered prefetch (the chip-feeding half of the
whole-loop executor; reference analogue: the PrefetchingIter + the
ThreadedEngine IO lane, upgraded to land batches ON DEVICE).

`PrefetchingIter` overlaps host-side decode with compute but still hands
the training loop HOST arrays — the `device_put` (and under a mesh, the
shard placement) happens synchronously inside the step, on the critical
path. :class:`DevicePrefetcher` moves that transfer off the path: a
worker thread pulls batches from the source iterator, optionally groups
them into whole-loop chunks of k, converts + `jax.device_put`s them with
the step's batch sharding, and parks up to ``depth`` device-resident
batches in a bounded buffer. The consumer's ``next()`` is then a queue
pop of arrays already on the chip.

Telemetry (shared counters registry — visible in /metrics, flight dumps,
and BENCH_*.json like every other family):

* ``io/io.batches_prefetched``  counter — batches landed on device;
* ``io/io.wait_ms``             counter — cumulative ms the CONSUMER
  blocked on the buffer ("TPU starved by input" when this grows);
* ``io/io.put_ms``              counter — cumulative ms the worker spent
  converting + transferring (host-side cost of feeding);
* ``io/io.depth``               gauge — configured buffer depth;
* ``io/io.buffer_fill``         gauge — buffered batches at last pop.

Lifecycle: iterate to exhaustion, or ``close()`` early — close() always
drains the buffer and joins the worker, so device references are dropped
and nothing leaks when training stops mid-epoch. Context manager does
the same.
"""
from __future__ import annotations

import queue as _queue
import threading
import time

import numpy as np

from .. import profiler as _prof

__all__ = ["DevicePrefetcher"]

_SENTINEL = object()

# how long close() waits for a worker parked inside the source's next()
# before abandoning it (daemon thread; nothing can enter the buffer after
# the stop flag is set)
_CLOSE_DEADLINE_S = 5.0


def _split_batch(b):
    """Normalize one source item to (x, y): DataBatch, (x, y) pair, or a
    bare array (y=None)."""
    data = getattr(b, "data", None)
    if data is not None and not isinstance(b, (tuple, list, np.ndarray)):
        label = getattr(b, "label", None)
        return data[0], (label[0] if label else None)
    if isinstance(b, (tuple, list)) and len(b) == 2:
        return b[0], b[1]
    return b, None


def _raw(a):
    from ..ndarray import NDArray
    if isinstance(a, NDArray):
        return a._data
    return np.asarray(a)


class DevicePrefetcher:
    """Iterate device-resident batches ahead of the consumer.

    source    : DataIter / iterable / iterator yielding DataBatch or
                (x, y) pairs (NDArray or numpy).
    depth     : device-side buffer depth (2 = classic double buffering).
                A tunable knob: TrainLoop resolves it through the
                autotune knob table (BENCH_PREFETCH_DEPTH >
                MXTPU_PREFETCH_DEPTH > cached tuning winner > 2;
                docs/autotune.md), and the tuner explores it when the
                measured gap taxonomy says the chip is input-starved.
    chunk     : group k consecutive batches and stack them on a new
                leading axis — the shape the whole-loop executor's
                run_k/run_chunk consumes. None = per-batch.
    sharding  : a jax Sharding, or a zero-arg callable resolving to one
                (or None) at transfer time — lets the caller hand over
                the fused step's batch sharding once it exists.
    cycle     : on source exhaustion, reset() DataIter sources (or
                re-iter iterables) and keep feeding — for step-driven
                (rather than epoch-driven) loops.
    skip      : discard the first N source batches before prefetching —
                the data-cursor resume path (mxtpu.resilience): a
                restarted run skips the batches its checkpoint manifest
                records as consumed instead of replaying them. Skipped
                batches never touch the device; counted as
                ``io.batches_skipped``.
    """

    def __init__(self, source, depth=2, chunk=None, sharding=None,
                 cycle=False, skip=0):
        if depth < 1:
            raise ValueError(f"depth must be >= 1, got {depth}")
        if chunk is not None and chunk < 1:
            raise ValueError(f"chunk must be >= 1, got {chunk}")
        if skip < 0:
            raise ValueError(f"skip must be >= 0, got {skip}")
        self._source = source
        self._depth = int(depth)
        self._chunk = int(chunk) if chunk else None
        self._sharding = sharding
        self._cycle = bool(cycle)
        self._skip = int(skip)
        self._epoch_len = None   # learned at the first source wrap
        self._buf = _queue.Queue(maxsize=self._depth)
        self._stop = threading.Event()
        self._exhausted = False
        # counters exist from construction so smoke checks can assert on
        # them even for an all-hits run (wait_ms == 0 is a signal too)
        self._c_batches = _prof.counter("io.batches_prefetched", "io")
        self._c_wait = _prof.counter("io.wait_ms", "io")
        self._c_put = _prof.counter("io.put_ms", "io")
        _prof.set_gauge("io.depth", self._depth, "io")
        _prof.set_gauge("io.buffer_fill", 0, "io")
        self._thread = threading.Thread(target=self._worker, daemon=True,
                                        name="mxtpu-device-prefetch")
        self._thread.start()

    # -- worker -----------------------------------------------------------
    def _iter_source(self):
        src = self._source
        while True:
            it = iter(src) if not hasattr(src, "next") else src
            n = 0
            try:
                for b in it:
                    n += 1
                    yield b
            except StopIteration:
                pass
            if n and self._epoch_len is None:
                self._epoch_len = n
            if not self._cycle:
                return
            if hasattr(src, "reset"):
                src.reset()
            elif iter(src) is src:
                return          # a bare iterator can't be rewound

    def _to_device(self, items):
        import jax
        t0 = time.perf_counter()
        xs = [_raw(x) for x, _ in items]
        n_labeled = sum(1 for _, y in items if y is not None)
        if 0 < n_labeled < len(items):
            # fail HERE, not as a leading-axis mismatch deep inside the
            # compiled scan: a partially-labeled chunk is a source bug
            raise ValueError(
                f"mixed labeled/label-less batches in one prefetch chunk "
                f"({n_labeled}/{len(items)} labeled)")
        ys = [_raw(y) for _, y in items if y is not None]
        if self._chunk is not None:
            xs = [np.stack(xs) if all(isinstance(a, np.ndarray) for a in xs)
                  else _stack_dev(xs)]
            if ys:
                ys = [np.stack(ys) if all(isinstance(a, np.ndarray)
                                          for a in ys)
                      else _stack_dev(ys)]
        sharding = self._sharding() if callable(self._sharding) \
            else self._sharding
        put = (lambda a: jax.device_put(a, sharding)) if sharding is not None \
            else jax.device_put
        out = (put(xs[0]), put(ys[0]) if ys else None)
        self._c_put.increment((time.perf_counter() - t0) * 1e3)
        return out

    def _worker(self):
        try:
            pending = []
            n = self._chunk or 1
            to_skip = self._skip
            if to_skip:
                c_skip = _prof.counter("io.batches_skipped", "io")
            for b in self._iter_source():
                if self._stop.is_set():
                    return
                if to_skip > 0:
                    # cursor resume: already-consumed batches are
                    # dropped host-side, before any conversion/transfer.
                    # An ABSOLUTE cursor through a cycling source only
                    # matters modulo the epoch: once the first wrap
                    # teaches us the epoch length, whole epochs of the
                    # remaining skip fold away instead of being read and
                    # discarded — resume cost stays bounded by ~one
                    # epoch of host reads however long the run was
                    if self._cycle and self._epoch_len:
                        to_skip %= self._epoch_len
                        if to_skip == 0:
                            pass   # fell exactly on a boundary: train b
                        else:
                            to_skip -= 1
                            c_skip.increment()
                            continue
                    else:
                        to_skip -= 1
                        c_skip.increment()
                        continue
                pending.append(_split_batch(b))
                if len(pending) < n:
                    continue
                item = self._to_device(pending)
                pending = []
                self._c_batches.increment(n)
                if not self._put(item):
                    return
            # a trailing partial chunk is dropped (static-shape programs
            # can't take a short chunk); per-batch mode has no remainder
            self._put(_SENTINEL)
        except Exception as e:  # noqa: BLE001 — surfaced at next()
            self._put(e)

    def _put(self, item):
        """Blocking put that stays responsive to close()."""
        while not self._stop.is_set():
            try:
                self._buf.put(item, timeout=0.05)
                return True
            except _queue.Full:
                continue
        return False

    # -- consumer ---------------------------------------------------------
    def __iter__(self):
        return self

    def __next__(self):
        if self._exhausted:
            raise StopIteration
        t0 = time.perf_counter()
        item = self._buf.get()
        self._c_wait.increment((time.perf_counter() - t0) * 1e3)
        _prof.set_gauge("io.buffer_fill", self._buf.qsize(), "io")
        if item is _SENTINEL:
            self._exhausted = True
            raise StopIteration
        if isinstance(item, Exception):
            self._exhausted = True
            raise item
        return item

    next = __next__

    # -- lifecycle --------------------------------------------------------
    def close(self):
        """Stop the worker and drop every buffered device batch. Safe to
        call at any point (mid-epoch early stop included) and idempotent;
        after close() the buffer holds no device references.

        A worker parked inside the SOURCE's ``next()`` (streaming/queue
        sources) cannot be interrupted; close() stops waiting for it
        after a short deadline — the thread is a daemon, and once the
        stop flag is set ``_put`` refuses every item, so nothing can land
        in the buffer after close() returns either way."""
        self._stop.set()
        deadline = time.monotonic() + _CLOSE_DEADLINE_S
        while True:
            try:
                self._buf.get_nowait()
            except _queue.Empty:
                if not self._thread.is_alive() \
                        or time.monotonic() > deadline:
                    break
                time.sleep(0.01)
        self._exhausted = True
        _prof.set_gauge("io.buffer_fill", 0, "io")
        self._thread.join(timeout=0.1)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


def _stack_dev(arrs):
    import jax.numpy as jnp
    return jnp.stack([jnp.asarray(a) for a in arrs])
