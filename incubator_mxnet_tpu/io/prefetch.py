"""On-device prefetch (the chip-feeding half of the whole-loop
executor; reference analogue: the PrefetchingIter + the ThreadedEngine
IO lane, upgraded to land batches ON DEVICE).

`PrefetchingIter` overlaps host-side decode with compute but still hands
the training loop HOST arrays — the `device_put` (and under a mesh, the
shard placement) happens synchronously inside the step, on the critical
path. :class:`DevicePrefetcher` moves that transfer off the path and,
since PR 17, overlaps the host stages against each other too: it is the
public face of the staged :class:`~.pipeline.Pipeline` (reader → decode
pool → ordered staging ring → transfer; see io/pipeline.py and
docs/io.md for the stage model). Batches are converted +
`jax.device_put` with the step's batch sharding and parked, up to
``depth`` deep, in a bounded device-resident buffer. The consumer's
``next()`` is then a queue pop of arrays already on the chip.

Telemetry (shared counters registry — visible in /metrics, flight dumps,
and BENCH_*.json like every other family):

* ``io/io.batches_prefetched``  counter — batches landed on device;
* ``io/io.wait_ms``             counter — cumulative ms the CONSUMER
  blocked on the buffer ("TPU starved by input" when this grows);
* ``io/io.read_ms``             counter — reader wall inside the
  source's next() (disk share of the starvation split);
* ``io/io.decode_ms``           counter — decode-pool wall, summed
  across workers (host-decode share);
* ``io/io.stage_ms``            counter — transfer-stage wall waiting
  for the next in-order chunk (reorder/decode-lag share);
* ``io/io.put_ms``              counter — cumulative ms spent
  converting + transferring (host→device share);
* ``io/io.depth``               gauge — configured buffer depth;
* ``io/io.buffer_fill``         gauge — buffered batches at last pop;
* ``io/io.workers``             gauge — resolved decode-pool width.

Lifecycle: iterate to exhaustion, or ``close()`` early — close() always
drains the buffer and joins the stage threads, so device references are
dropped and nothing leaks when training stops mid-epoch. Context
manager does the same.
"""
from __future__ import annotations

from .pipeline import (Pipeline, _SENTINEL, _raw,  # noqa: F401 — legacy
                       _split_batch, _stack_dev)   # import surface

__all__ = ["DevicePrefetcher"]

# how long close() waits for a reader parked inside the source's next()
# before abandoning it (daemon threads; nothing can enter the buffer
# after the stop flag is set). Module-level so tests/operators can tune
# the tradeoff — read at call time in close().
_CLOSE_DEADLINE_S = 5.0


class DevicePrefetcher(Pipeline):
    """Iterate device-resident batches ahead of the consumer.

    source    : DataIter / iterable / iterator yielding DataBatch or
                (x, y) pairs (NDArray or numpy).
    depth     : device-side buffer depth (2 = classic double buffering).
                A tunable knob: TrainLoop resolves it through the
                autotune knob table (BENCH_PREFETCH_DEPTH >
                MXTPU_PREFETCH_DEPTH > cached tuning winner > 2;
                docs/autotune.md), and the tuner explores it when the
                measured gap taxonomy says the chip is input-starved.
    chunk     : group k consecutive batches and stack them on a new
                leading axis — the shape the whole-loop executor's
                run_k/run_chunk consumes. None = per-batch.
    sharding  : a jax Sharding, or a zero-arg callable resolving to one
                (or None) at transfer time — lets the caller hand over
                the fused step's batch sharding once it exists.
    cycle     : on source exhaustion, reset() DataIter sources (or
                re-iter iterables) and keep feeding — for step-driven
                (rather than epoch-driven) loops.
    skip      : discard the first N source batches before prefetching —
                the data-cursor resume path (mxtpu.resilience): a
                restarted run skips the batches its checkpoint manifest
                records as consumed instead of replaying them. Skipped
                batches never touch the device; counted as
                ``io.batches_skipped``. The cursor is applied by the
                single reader stage BEFORE the decode pool, so resume
                order is identical at any worker count.
    workers   : decode-pool width (the ``io_workers`` knob; None
                resolves through the autotune table —
                BENCH_IO_WORKERS > MXTPU_IO_WORKERS > cached winner > 2).
    transform : optional host hook ``(x, y) -> (x, y)`` run inside the
                decode pool (per-batch decode/augment work).
    """

    def close(self):
        super().close(deadline_s=_CLOSE_DEADLINE_S)
