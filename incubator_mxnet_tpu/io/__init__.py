"""Data iterators (parity: python/mxnet/io/ + src/io/).

The reference's DataIter protocol: iter with .next() -> DataBatch carrying
data/label lists + provide_data/provide_label descriptors. The gluon
DataLoader (gluon/data) is the modern path; these iterators keep Module/fit
compatibility."""
from __future__ import annotations

from collections import namedtuple

import numpy as np

from .. import ndarray as nd
from ..ndarray import NDArray

__all__ = ["DataDesc", "DataBatch", "DataIter", "NDArrayIter", "CSVIter",
           "MNISTIter", "ResizeIter", "PrefetchingIter"]

DataDesc = namedtuple("DataDesc", ["name", "shape", "dtype", "layout"])
DataDesc.__new__.__defaults__ = (np.float32, "NCHW")


class DataBatch:
    def __init__(self, data, label=None, pad=0, index=None,
                 provide_data=None, provide_label=None):
        self.data = data
        self.label = label
        self.pad = pad
        self.index = index
        self.provide_data = provide_data
        self.provide_label = provide_label


class DataIter:
    def __init__(self, batch_size=0):
        self.batch_size = batch_size

    def __iter__(self):
        return self

    def reset(self):
        pass

    def next(self):
        raise NotImplementedError

    def __next__(self):
        return self.next()

    @property
    def provide_data(self):
        raise NotImplementedError

    @property
    def provide_label(self):
        raise NotImplementedError


class NDArrayIter(DataIter):
    """Iterate over in-memory arrays (parity: mx.io.NDArrayIter)."""

    def __init__(self, data, label=None, batch_size=1, shuffle=False,
                 last_batch_handle="pad", data_name="data", label_name="softmax_label"):
        super().__init__(batch_size)
        if last_batch_handle not in ("pad", "discard", "roll_over"):
            raise ValueError(f"unknown last_batch_handle {last_batch_handle!r}; "
                             "expected 'pad', 'discard' or 'roll_over'")
        self.data = self._normalize(data, data_name)
        self.label = self._normalize(label, label_name)
        self.shuffle = shuffle
        self.last_batch_handle = last_batch_handle
        self._rollover = np.array([], dtype=np.int64)
        self.num_data = self.data[0][1].shape[0]
        self.cursor = -batch_size
        self._order = np.arange(self.num_data)
        if shuffle:
            np.random.shuffle(self._order)

    @staticmethod
    def _normalize(data, default_name):
        if data is None:
            return []
        if isinstance(data, (np.ndarray, NDArray)):
            data = {default_name: data}
        if isinstance(data, (list, tuple)):
            data = {f"{default_name}_{i}" if i else default_name: d
                    for i, d in enumerate(data)}
        out = []
        for k, v in data.items():
            arr = np.asarray(v) if not isinstance(v, NDArray) else v.asnumpy()
            out.append((k, arr))
        return out

    @property
    def provide_data(self):
        return [DataDesc(k, (self.batch_size,) + v.shape[1:], v.dtype)
                for k, v in self.data]

    @property
    def provide_label(self):
        return [DataDesc(k, (self.batch_size,) + v.shape[1:], v.dtype)
                for k, v in self.label]

    def reset(self):
        self.cursor = -self.batch_size
        order = np.arange(self.num_data)
        if self.shuffle:
            np.random.shuffle(order)
        if self.last_batch_handle == "roll_over" and len(self._rollover):
            # reference semantics: last epoch's leftover samples lead off
            order = np.concatenate([self._rollover, order])
            self._rollover = np.array([], dtype=np.int64)
        self._order = order

    def iter_next(self):
        self.cursor += self.batch_size
        return self.cursor < len(self._order)

    def next(self):
        if not self.iter_next():
            raise StopIteration
        idx = self._order[self.cursor:self.cursor + self.batch_size]
        pad = 0
        if len(idx) < self.batch_size:
            if self.last_batch_handle == "discard":
                raise StopIteration
            if self.last_batch_handle == "roll_over":
                self._rollover = np.asarray(idx)
                raise StopIteration
            pad = self.batch_size - len(idx)
            idx = np.concatenate([idx, self._order[:pad]])
        data = [nd.array(v[idx]) for _, v in self.data]
        label = [nd.array(v[idx]) for _, v in self.label]
        return DataBatch(data, label, pad=pad,
                         provide_data=self.provide_data,
                         provide_label=self.provide_label)


class CSVIter(NDArrayIter):
    """Parity: mx.io.CSVIter — reads dense CSVs into memory."""

    def __init__(self, data_csv, data_shape, label_csv=None, label_shape=(1,),
                 batch_size=1, **kwargs):
        data = np.loadtxt(data_csv, delimiter=",", dtype=np.float32)
        data = data.reshape((-1,) + tuple(data_shape))
        label = None
        if label_csv is not None:
            label = np.loadtxt(label_csv, delimiter=",", dtype=np.float32)
            label = label.reshape((-1,) + tuple(label_shape))
        super().__init__(data, label, batch_size=batch_size, **kwargs)


class MNISTIter(NDArrayIter):
    """Parity: mx.io.MNISTIter — reads idx-format MNIST files; if the files
    are absent (zero-egress environments) generates a deterministic synthetic
    stand-in with the same shapes so example scripts still run."""

    def __init__(self, image=None, label=None, batch_size=128, shuffle=True,
                 flat=False, num_examples=60000, **kwargs):
        import os
        # idx parsing + synthetic fallback shared with gluon.data.vision
        from ..gluon.data.vision import _read_idx, _synthetic_images

        if image is not None and os.path.exists(image):
            X = _read_idx(image).astype(np.float32) / 255.0
            Y = _read_idx(label).astype(np.float32)
        else:
            n = min(num_examples, 10000)
            X, Y = _synthetic_images(n, (28, 28), 10, seed=42)
            X = X.astype(np.float32) / 255.0
            Y = Y.astype(np.float32)
        X = X.reshape(-1, 784) if flat else X.reshape(-1, 1, 28, 28)
        super().__init__(X, Y, batch_size=batch_size, shuffle=shuffle, **kwargs)


class ResizeIter(DataIter):
    """Truncate/extend an iterator to a fixed number of batches."""

    def __init__(self, data_iter, size, reset_internal=True):
        super().__init__(data_iter.batch_size)
        self.data_iter = data_iter
        self.size = size
        self.reset_internal = reset_internal
        self.cur = 0

    @property
    def provide_data(self):
        return self.data_iter.provide_data

    @property
    def provide_label(self):
        return self.data_iter.provide_label

    def reset(self):
        self.cur = 0
        if self.reset_internal:
            self.data_iter.reset()

    def next(self):
        if self.cur >= self.size:
            raise StopIteration
        self.cur += 1
        try:
            return self.data_iter.next()
        except StopIteration:
            self.data_iter.reset()
            return self.data_iter.next()


class PrefetchingIter(DataIter):
    """Background-thread prefetch (parity: mx.io.PrefetchingIter); the C++
    runtime pipeline (runtime/) backs gluon DataLoader's multi-worker path."""

    def __init__(self, iters, rename_data=None, rename_label=None):
        import queue
        import threading
        if not isinstance(iters, (list, tuple)):
            iters = [iters]
        self.iters = iters
        super().__init__(iters[0].batch_size)
        self._queue = queue.Queue(maxsize=4)
        self._stop = False
        self._exhausted = False
        self._thread = None
        self._start()

    def _start(self):
        import threading

        def worker():
            try:
                while not self._stop:
                    try:
                        batch = self.iters[0].next()
                    except StopIteration:
                        self._queue.put(None)
                        return
                    self._queue.put(batch)
            except Exception as e:  # surface async errors at next()
                self._queue.put(e)

        self._thread = threading.Thread(target=worker, daemon=True)
        self._thread.start()

    @property
    def provide_data(self):
        return self.iters[0].provide_data

    @property
    def provide_label(self):
        return self.iters[0].provide_label

    def reset(self):
        import queue as _queue
        self._stop = True
        # Drain until the worker has exited AND the queue is empty — stale
        # batches or the previous epoch's sentinel must not leak into the
        # next epoch.
        while self._thread.is_alive() or not self._queue.empty():
            try:
                self._queue.get(timeout=0.05)
            except _queue.Empty:
                pass
        self._thread.join()
        self.iters[0].reset()
        self._stop = False
        self._exhausted = False
        self._start()

    def next(self):
        if self._exhausted:   # sentinel already consumed; worker is dead
            raise StopIteration
        item = self._queue.get()
        if item is None:
            self._exhausted = True
            raise StopIteration
        if isinstance(item, Exception):
            raise item
        return item
