"""Data iterators (parity: python/mxnet/io/ + src/io/).

The reference's DataIter protocol: iter with .next() -> DataBatch carrying
data/label lists + provide_data/provide_label descriptors. The gluon
DataLoader (gluon/data) is the modern path; these iterators keep Module/fit
compatibility."""
from __future__ import annotations

from collections import namedtuple

import numpy as np

from .. import ndarray as nd
from ..ndarray import NDArray

__all__ = ["DataDesc", "DataBatch", "DataIter", "NDArrayIter", "CSVIter",
           "BucketSentenceIter", "LibSVMIter",
           "MNISTIter", "ResizeIter", "PrefetchingIter", "ImageRecordIter",
           "ImageDetRecordIter", "DevicePrefetcher"]

from .prefetch import DevicePrefetcher  # noqa: E402  (device-side buffering)

DataDesc = namedtuple("DataDesc", ["name", "shape", "dtype", "layout"])
DataDesc.__new__.__defaults__ = (np.float32, "NCHW")


class DataBatch:
    def __init__(self, data, label=None, pad=0, index=None,
                 provide_data=None, provide_label=None, bucket_key=None):
        self.data = data
        self.label = label
        self.pad = pad
        self.index = index
        self.provide_data = provide_data
        self.provide_label = provide_label
        self.bucket_key = bucket_key


class DataIter:
    def __init__(self, batch_size=0):
        self.batch_size = batch_size

    def __iter__(self):
        return self

    def reset(self):
        pass

    def next(self):
        raise NotImplementedError

    def __next__(self):
        return self.next()

    @property
    def provide_data(self):
        raise NotImplementedError

    @property
    def provide_label(self):
        raise NotImplementedError


class NDArrayIter(DataIter):
    """Iterate over in-memory arrays (parity: mx.io.NDArrayIter)."""

    def __init__(self, data, label=None, batch_size=1, shuffle=False,
                 last_batch_handle="pad", data_name="data", label_name="softmax_label"):
        super().__init__(batch_size)
        if last_batch_handle not in ("pad", "discard", "roll_over"):
            raise ValueError(f"unknown last_batch_handle {last_batch_handle!r}; "
                             "expected 'pad', 'discard' or 'roll_over'")
        self.data = self._normalize(data, data_name)
        self.label = self._normalize(label, label_name)
        self.shuffle = shuffle
        self.last_batch_handle = last_batch_handle
        self._rollover = np.array([], dtype=np.int64)
        self.num_data = self.data[0][1].shape[0]
        self.cursor = -batch_size
        self._order = np.arange(self.num_data)
        if shuffle:
            np.random.shuffle(self._order)

    @staticmethod
    def _normalize(data, default_name):
        if data is None:
            return []
        if isinstance(data, (np.ndarray, NDArray)):
            data = {default_name: data}
        if isinstance(data, (list, tuple)):
            data = {f"{default_name}_{i}" if i else default_name: d
                    for i, d in enumerate(data)}
        out = []
        for k, v in data.items():
            arr = np.asarray(v) if not isinstance(v, NDArray) else v.asnumpy()
            out.append((k, arr))
        return out

    @property
    def provide_data(self):
        return [DataDesc(k, (self.batch_size,) + v.shape[1:], v.dtype)
                for k, v in self.data]

    @property
    def provide_label(self):
        return [DataDesc(k, (self.batch_size,) + v.shape[1:], v.dtype)
                for k, v in self.label]

    def reset(self):
        self.cursor = -self.batch_size
        order = np.arange(self.num_data)
        if self.shuffle:
            np.random.shuffle(order)
        if self.last_batch_handle == "roll_over" and len(self._rollover):
            # reference semantics: last epoch's leftover samples lead off
            order = np.concatenate([self._rollover, order])
            self._rollover = np.array([], dtype=np.int64)
        self._order = order

    def iter_next(self):
        self.cursor += self.batch_size
        return self.cursor < len(self._order)

    def next(self):
        if not self.iter_next():
            raise StopIteration
        idx = self._order[self.cursor:self.cursor + self.batch_size]
        pad = 0
        if len(idx) < self.batch_size:
            if self.last_batch_handle == "discard":
                raise StopIteration
            if self.last_batch_handle == "roll_over":
                self._rollover = np.asarray(idx)
                raise StopIteration
            pad = self.batch_size - len(idx)
            idx = np.concatenate([idx, self._order[:pad]])
        data = [nd.array(v[idx]) for _, v in self.data]
        label = [nd.array(v[idx]) for _, v in self.label]
        return DataBatch(data, label, pad=pad,
                         provide_data=self.provide_data,
                         provide_label=self.provide_label)


class CSVIter(NDArrayIter):
    """Parity: mx.io.CSVIter — reads dense CSVs into memory."""

    def __init__(self, data_csv, data_shape, label_csv=None, label_shape=(1,),
                 batch_size=1, **kwargs):
        data = np.loadtxt(data_csv, delimiter=",", dtype=np.float32)
        data = data.reshape((-1,) + tuple(data_shape))
        label = None
        if label_csv is not None:
            label = np.loadtxt(label_csv, delimiter=",", dtype=np.float32)
            label = label.reshape((-1,) + tuple(label_shape))
        super().__init__(data, label, batch_size=batch_size, **kwargs)


class MNISTIter(NDArrayIter):
    """Parity: mx.io.MNISTIter — reads idx-format MNIST files; if the files
    are absent (zero-egress environments) generates a deterministic synthetic
    stand-in with the same shapes so example scripts still run."""

    def __init__(self, image=None, label=None, batch_size=128, shuffle=True,
                 flat=False, num_examples=60000, **kwargs):
        import os
        # idx parsing + synthetic fallback shared with gluon.data.vision
        from ..gluon.data.vision import _read_idx, _synthetic_images

        if image is not None and os.path.exists(image):
            X = _read_idx(image).astype(np.float32) / 255.0
            Y = _read_idx(label).astype(np.float32)
        else:
            n = min(num_examples, 10000)
            X, Y = _synthetic_images(n, (28, 28), 10, seed=42)
            X = X.astype(np.float32) / 255.0
            Y = Y.astype(np.float32)
        X = X.reshape(-1, 784) if flat else X.reshape(-1, 1, 28, 28)
        super().__init__(X, Y, batch_size=batch_size, shuffle=shuffle, **kwargs)


class ResizeIter(DataIter):
    """Truncate/extend an iterator to a fixed number of batches."""

    def __init__(self, data_iter, size, reset_internal=True):
        super().__init__(data_iter.batch_size)
        self.data_iter = data_iter
        self.size = size
        self.reset_internal = reset_internal
        self.cur = 0

    @property
    def provide_data(self):
        return self.data_iter.provide_data

    @property
    def provide_label(self):
        return self.data_iter.provide_label

    def reset(self):
        self.cur = 0
        if self.reset_internal:
            self.data_iter.reset()

    def next(self):
        if self.cur >= self.size:
            raise StopIteration
        self.cur += 1
        try:
            return self.data_iter.next()
        except StopIteration:
            self.data_iter.reset()
            return self.data_iter.next()


class ImageRecordIter(DataIter):
    """High-throughput image-record iterator (parity: mx.io.ImageRecordIter,
    reference src/io/iter_image_recordio_2.cc): reads IRHeader records from a
    .rec file, JPEG-decodes and augments on `preprocess_threads` worker
    threads of the native C++ dependency engine, with a bounded prefetch
    queue for backpressure — the chip-feeding path for ImageNet-style
    training. Yields DataBatch of NCHW float32 data (or NHWC with
    layout="NHWC" — the TPU-preferred layout) + labels.
    """

    def __init__(self, path_imgrec, data_shape, batch_size, shuffle=False,
                 rand_crop=False, rand_mirror=False, rand_resize=False,
                 resize=0, mean_r=0.0, mean_g=0.0, mean_b=0.0,
                 std_r=1.0, std_g=1.0, std_b=1.0, label_width=1,
                 preprocess_threads=4, prefetch_buffer=4, layout="NCHW",
                 aug_list=None, data_name="data",
                 label_name="softmax_label", round_batch=True, **kwargs):
        super().__init__(batch_size)
        from ..image import CreateAugmenter, imdecode, finalize_image, \
            idx_path_for
        from ..recordio import MXIndexedRecordIO, unpack

        if layout not in ("NCHW", "NHWC"):
            raise ValueError(f"unknown layout {layout!r}")
        self._layout = layout
        self.data_shape = tuple(data_shape)       # CHW, like the reference
        self.label_width = label_width
        self._shuffle = shuffle
        self._round_batch = round_batch
        self._threads = max(1, preprocess_threads)
        self._prefetch = max(1, prefetch_buffer)
        self.data_name, self.label_name = data_name, label_name

        self._rec = MXIndexedRecordIO(idx_path_for(path_imgrec),
                                      path_imgrec, "r")
        if not self._rec.keys:
            raise ValueError(f"no .idx index found for {path_imgrec}; "
                             "ImageRecordIter needs random access")
        self._keys = list(self._rec.keys)
        mean = np.array([mean_r, mean_g, mean_b], np.float32)
        std = np.array([std_r, std_g, std_b], np.float32)
        if aug_list is None:
            aug_list = CreateAugmenter(
                self.data_shape, resize=resize, rand_crop=rand_crop,
                rand_resize=rand_resize, rand_mirror=rand_mirror,
                mean=mean if mean.any() else None,
                std=std if (std != 1.0).any() else None)
        self._auglist = aug_list
        self._unpack, self._imdecode, self._finalize = \
            unpack, imdecode, finalize_image
        self._lock = __import__("threading").Lock()
        self._gen = None
        self.reset()

    def __len__(self):
        return len(self._keys)

    @property
    def provide_data(self):
        c, h, w = self.data_shape
        shape = (self.batch_size, c, h, w) if self._layout == "NCHW" \
            else (self.batch_size, h, w, c)
        return [DataDesc(self.data_name, shape, np.float32, self._layout)]

    @property
    def provide_label(self):
        shape = (self.batch_size,) if self.label_width == 1 else \
            (self.batch_size, self.label_width)
        return [DataDesc(self.label_name, shape, np.float32)]

    def _load_one(self, key):
        """Worker-thread path: record bytes -> augmented layout-major image
        + label. The record read holds a lock (one shared handle); decode
        and augment run unlocked and overlap across engine workers."""
        with self._lock:
            payload = self._rec.read_idx(key)
        header, img_bytes = self._unpack(payload)
        label = np.atleast_1d(np.asarray(header.label, np.float32))
        img = self._imdecode(img_bytes).asnumpy()
        c, h, w = self.data_shape
        img = self._finalize(img, self._auglist, (h, w))
        if self._layout == "NCHW":
            img = np.transpose(img, (2, 0, 1))
        return img, label[:self.label_width]

    def _batches(self):
        order = list(self._keys)
        if self._shuffle:
            np.random.shuffle(order)
        out = [order[i:i + self.batch_size]
               for i in range(0, len(order), self.batch_size)]
        return out

    def _epoch_gen(self):
        """Prefetch pipeline: each batch is one engine task (decode+augment
        of batch_size images, assembled into a contiguous numpy block)."""
        import threading
        from .. import runtime as _rt

        batches = self._batches()
        if not batches:
            return
        eng = _rt.Engine(self._threads)
        q = _rt.TokenQueue(self._prefetch)
        results = {}
        lock = threading.Lock()

        def make_task(i, keys):
            def task():
                try:
                    items = [self._load_one(k) for k in keys]
                    data = np.stack([d for d, _ in items])
                    label = np.stack([l for _, l in items])
                    b = (data, label, keys)
                except Exception as e:    # surfaced at consume time
                    b = e
                with lock:
                    results[i] = b
                q.push(i)
            return task

        submitted = 0

        def submit_next():
            nonlocal submitted
            if submitted < len(batches):
                eng.push(make_task(submitted, batches[submitted]))
                submitted += 1

        for _ in range(min(self._prefetch, len(batches))):
            submit_next()
        try:
            next_i, ready = 0, set()
            while next_i < len(batches):
                while next_i not in ready:
                    tok = q.pop()
                    if tok is None:
                        return
                    ready.add(tok)
                ready.discard(next_i)
                with lock:
                    b = results.pop(next_i)
                if isinstance(b, Exception):
                    raise b
                submit_next()
                yield b
                next_i += 1
        finally:
            q.close()
            eng.wait_all()

    def reset(self):
        self._gen = self._epoch_gen()

    def next(self):
        if self._gen is None:
            self.reset()
        try:
            data, label, keys = next(self._gen)
        except StopIteration:
            self._gen = None
            raise
        pad = 0
        if data.shape[0] < self.batch_size:
            if not self._round_batch:
                self._gen = None
                raise StopIteration
            pad = self.batch_size - data.shape[0]
            data = np.concatenate(
                [data, np.repeat(data[-1:], pad, axis=0)])
            label = np.concatenate(
                [label, np.repeat(label[-1:], pad, axis=0)])
        lab = label[:, 0] if self.label_width == 1 else label
        return DataBatch([nd.array(data)], [nd.array(lab)], pad=pad,
                         index=keys,
                         provide_data=self.provide_data,
                         provide_label=self.provide_label)


class PrefetchingIter(DataIter):
    """Background-thread prefetch (parity: mx.io.PrefetchingIter); the C++
    runtime pipeline (runtime/) backs gluon DataLoader's multi-worker path."""

    def __init__(self, iters, rename_data=None, rename_label=None):
        import queue
        import threading
        if not isinstance(iters, (list, tuple)):
            iters = [iters]
        self.iters = iters
        super().__init__(iters[0].batch_size)
        self._queue = queue.Queue(maxsize=4)
        self._stop = False
        self._exhausted = False
        self._thread = None
        self._start()

    def _start(self):
        import threading

        def worker():
            try:
                while not self._stop:
                    try:
                        batch = self.iters[0].next()
                    except StopIteration:
                        self._queue.put(None)
                        return
                    self._queue.put(batch)
            except Exception as e:  # surface async errors at next()
                self._queue.put(e)

        self._thread = threading.Thread(target=worker, daemon=True)
        self._thread.start()

    @property
    def provide_data(self):
        return self.iters[0].provide_data

    @property
    def provide_label(self):
        return self.iters[0].provide_label

    def reset(self):
        import queue as _queue
        self._stop = True
        # Drain until the worker has exited AND the queue is empty — stale
        # batches or the previous epoch's sentinel must not leak into the
        # next epoch.
        while self._thread.is_alive() or not self._queue.empty():
            try:
                self._queue.get(timeout=0.05)
            except _queue.Empty:
                pass
        self._thread.join()
        self.iters[0].reset()
        self._stop = False
        self._exhausted = False
        self._start()

    def next(self):
        if self._exhausted:   # sentinel already consumed; worker is dead
            raise StopIteration
        item = self._queue.get()
        if item is None:
            self._exhausted = True
            raise StopIteration
        if isinstance(item, Exception):
            raise item
        return item


class BucketSentenceIter(DataIter):
    """Bucketed variable-length sequence iterator (parity:
    python/mxnet/rnn/io.py BucketSentenceIter): sentences are assigned to
    the smallest bucket that fits, padded to the bucket length, and each
    batch carries its `bucket_key` so BucketingModule switches to the
    matching static-shape executable."""

    def __init__(self, sentences, batch_size, buckets=None, invalid_label=-1,
                 data_name="data", label_name="softmax_label", dtype="float32",
                 layout="NT"):
        super().__init__(batch_size)
        if layout not in ("NT", "TN"):
            raise ValueError(f"unknown layout {layout!r}; expected NT or TN")
        if buckets is None:
            buckets = sorted({len(s) for s in sentences})
        self.buckets = sorted(buckets)
        self.data_name, self.label_name = data_name, label_name
        self.invalid_label = invalid_label
        self._dtype = np.dtype(dtype)
        self._layout = layout
        rows_by_bucket = {b: [] for b in self.buckets}
        ndiscard = 0
        for s in sentences:
            b = next((b for b in self.buckets if b >= len(s)), None)
            if b is None:
                ndiscard += 1
                continue
            row = np.full(b, invalid_label, dtype=self._dtype)
            row[:len(s)] = s
            rows_by_bucket[b].append(row)
        if ndiscard:
            import logging
            logging.warning("BucketSentenceIter: discarded %d sentences "
                            "longer than the largest bucket", ndiscard)
        self._arrays = {b: np.stack(v) if v else np.zeros((0, b), self._dtype)
                        for b, v in rows_by_bucket.items()}
        self.default_bucket_key = max(self.buckets)
        self.reset()

    def _shape(self, b):
        return ((self.batch_size, b) if self._layout == "NT"
                else (b, self.batch_size))

    @property
    def provide_data(self):
        return [DataDesc(self.data_name, self._shape(self.default_bucket_key),
                         self._dtype)]

    @property
    def provide_label(self):
        return [DataDesc(self.label_name,
                         self._shape(self.default_bucket_key), self._dtype)]

    def reset(self):
        self._plan = []
        for b in self.buckets:
            arr = self._arrays[b]
            idx = np.random.permutation(len(arr))
            for i in range(0, len(arr) - self.batch_size + 1,
                           self.batch_size):
                self._plan.append((b, idx[i:i + self.batch_size]))
        np.random.shuffle(self._plan)
        self._cursor = 0

    def next(self):
        if self._cursor >= len(self._plan):
            raise StopIteration
        b, idx = self._plan[self._cursor]
        self._cursor += 1
        from ..ndarray import NDArray
        import jax.numpy as jnp
        rows = self._arrays[b][idx]
        # next-token labels: shift left, pad with invalid_label
        labels = np.full_like(rows, self.invalid_label)
        labels[:, :-1] = rows[:, 1:]
        if self._layout == "TN":
            rows, labels = rows.T, labels.T
        data = NDArray(jnp.asarray(rows))
        label = NDArray(jnp.asarray(labels))
        return DataBatch(
            [data], [label], bucket_key=b,
            provide_data=[DataDesc(self.data_name, self._shape(b),
                                   self._dtype)],
            provide_label=[DataDesc(self.label_name, self._shape(b),
                                    self._dtype)])


class LibSVMIter(DataIter):
    """Sparse libsvm-format iterator (parity: mx.io.LibSVMIter,
    src/io/iter_libsvm.cc): each batch's data is a CSRNDArray. Feed
    `sparse.dot(csr, dense_weight)` models, or call `.todense()` for dense
    layers — on TPU the dense carrier after embedding IS the fast path."""

    def __init__(self, data_libsvm, data_shape, label_libsvm=None,
                 label_shape=None, batch_size=1, shuffle=False,
                 data_name="data", label_name="softmax_label"):
        super().__init__(batch_size)
        self.data_name, self.label_name = data_name, label_name
        n_feat = int(data_shape[0]) if isinstance(data_shape, (tuple, list)) \
            else int(data_shape)
        self._n_feat = n_feat
        self._label_shape = (tuple(label_shape)
                             if label_shape not in (None, (1,), 1) else ())
        labels, rows_idx, rows_val = [], [], []
        with open(data_libsvm) as f:
            for line in f:
                parts = line.split()
                if not parts:
                    continue
                labels.append([float(parts[0])])
                idx, val = [], []
                for tok in parts[1:]:
                    i, v = tok.split(":")
                    idx.append(int(i))
                    val.append(float(v))
                rows_idx.append(np.asarray(idx, np.int64))
                rows_val.append(np.asarray(val, np.float32))
        if label_libsvm is not None:
            labels = []
            with open(label_libsvm) as f:
                for line in f:
                    if line.strip():
                        labels.append([float(t) for t in line.split()])
        self._labels = np.asarray(labels, np.float32)
        if self._label_shape:
            if self._labels.shape[1:] != self._label_shape:
                raise ValueError(
                    f"label file rows have shape {self._labels.shape[1:]}, "
                    f"label_shape says {self._label_shape}")
        else:
            self._labels = self._labels[:, 0]
        self._rows_idx = rows_idx
        self._rows_val = rows_val
        self._shuffle = shuffle
        self.num_data = len(self._labels)
        self.reset()

    @property
    def provide_data(self):
        return [DataDesc(self.data_name, (self.batch_size, self._n_feat),
                         np.float32)]

    @property
    def provide_label(self):
        return [DataDesc(self.label_name,
                         (self.batch_size,) + self._label_shape, np.float32)]

    def reset(self):
        self._order = np.arange(self.num_data)
        if self._shuffle:
            np.random.shuffle(self._order)
        self._cursor = 0

    def next(self):
        if self._cursor >= self.num_data:
            raise StopIteration
        sel = self._order[self._cursor:self._cursor + self.batch_size]
        self._cursor += self.batch_size
        pad = self.batch_size - len(sel)
        if pad:  # reference behavior: pad the final batch, report .pad
            sel = np.concatenate([sel, self._order[:pad]])
        from ..ndarray import sparse as _sparse
        indices = np.concatenate([self._rows_idx[i] for i in sel]) \
            if len(sel) else np.zeros(0, np.int64)
        values = np.concatenate([self._rows_val[i] for i in sel]) \
            if len(sel) else np.zeros(0, np.float32)
        indptr = np.zeros(self.batch_size + 1, np.int64)
        for n, i in enumerate(sel):
            indptr[n + 1] = indptr[n] + len(self._rows_idx[i])
        csr = _sparse.CSRNDArray(values, indices, indptr,
                                 (self.batch_size, self._n_feat))
        label = nd.array(self._labels[sel])
        return DataBatch([csr], [label], pad=pad,
                         provide_data=self.provide_data,
                         provide_label=self.provide_label)


def ImageDetRecordIter(path_imgrec=None, batch_size=1, data_shape=(3, 300, 300),
                       shuffle=False, label_pad_width=None, **kwargs):
    """Detection record iterator (parity: mx.io.ImageDetRecordIter,
    src/io/iter_image_det_recordio.cc): .rec of images with object-list
    labels -> batches of (data, (B, max_objs, 5) [cls x0 y0 x1 y1] labels,
    -1 padded) — the io-namespace spelling of image.ImageDetIter."""
    from ..image import ImageDetIter
    max_objs = None
    if label_pad_width is not None:
        # reference counts label_pad_width in floats: header(2) + objs*5
        max_objs = max(1, (int(label_pad_width) - 2) // 5)
    return ImageDetIter(batch_size=batch_size, data_shape=data_shape,
                        path_imgrec=path_imgrec, shuffle=shuffle,
                        max_objs=max_objs, **kwargs)
