"""KVStore (parity: python/mxnet/kvstore.py + src/kvstore/).

The reference aggregates gradients through ps-lite servers or NCCL
(`dist_sync_device`). TPU-native: aggregation IS an XLA collective over the
device mesh. Two surfaces:

* object API here (init/push/pull/pushpull, server-side optimizer) — keeps
  Trainer/Module code shape-compatible with the reference; `local`/`device`
  run single-chip, `dist_*` aggregate across `jax.devices()` eagerly;
* the fused path (parallel/trainer_step) inlines a `psum` over the 'dp' mesh
  axis inside the compiled train step — that is the NCCL-allreduce
  replacement that rides ICI and is what bench/dryrun use.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..ndarray import NDArray
from .. import optimizer as _opt

__all__ = ["KVStore", "create"]


class KVStore:
    def __init__(self, kv_type="local"):
        self.type = kv_type
        self._store = {}
        self._optimizer = None
        self._states = {}
        self._is_dist = kv_type.startswith("dist")

    # -- topology ---------------------------------------------------------
    @property
    def rank(self):
        return jax.process_index() if self._is_dist else 0

    @property
    def num_workers(self):
        return jax.process_count() if self._is_dist else 1

    # -- data plane -------------------------------------------------------
    def init(self, key, value):
        if isinstance(key, (list, tuple)):
            for k, v in zip(key, value):
                self.init(k, v)
            return
        self._store[key] = value.copy() if isinstance(value, NDArray) else NDArray(value)

    def _aggregate(self, values):
        """Sum per-device NDArrays; in dist_* mode additionally allreduce
        across processes (the reference's ps-lite/NCCL leg — here an XLA
        collective over hosts)."""
        if isinstance(values, NDArray):
            total = values._data
        elif len(values) == 1:
            total = values[0]._data
        else:
            dev0 = next(iter(values[0]._data.devices()))
            total = values[0]._data
            for v in values[1:]:
                total = total + jax.device_put(v._data, dev0)
        if self._is_dist and jax.process_count() > 1:
            from jax.experimental import multihost_utils
            gathered = multihost_utils.process_allgather(total)
            total = jnp.sum(gathered, axis=0)
        return NDArray(total)

    def push(self, key, value, priority=0):
        if isinstance(key, (list, tuple)):
            for k, v in zip(key, value):
                self.push(k, v, priority)
            return
        agg = self._aggregate(value)
        if self._optimizer is not None:
            weight = self._store[key]
            if key not in self._states:
                self._states[key] = self._optimizer.create_state_multi_precision(
                    key, weight._data)
            self._states[key] = self._optimizer.update(key, weight, agg,
                                                       self._states[key])
        else:
            if key in self._store:
                self._store[key]._data = self._store[key]._data + agg._data
            else:
                self._store[key] = agg.copy()

    def pull(self, key, out=None, priority=0, ignore_sparse=True):
        if isinstance(key, (list, tuple)):
            for k, o in zip(key, out):
                self.pull(k, o, priority)
            return
        src = self._store[key]
        outs = out if isinstance(out, (list, tuple)) else [out]
        for o in outs:
            src.copyto(o)

    def pushpull(self, key, value, out=None, priority=0):
        """Fused allreduce (parity: kv.pushpull in dist_sync_device)."""
        if isinstance(key, (list, tuple)):
            for i, k in enumerate(key):
                self.pushpull(k, value[i], None if out is None else out[i], priority)
            return
        agg = self._aggregate(value)
        if out is None:
            return agg
        outs = out if isinstance(out, (list, tuple)) else [out]
        for o in outs:
            agg.copyto(o)

    def row_sparse_pull(self, key, out=None, priority=0, row_ids=None):
        self.pull(key, out, priority)

    # -- server-side optimizer --------------------------------------------
    def set_optimizer(self, optimizer):
        self._optimizer = (_opt.create(optimizer)
                           if isinstance(optimizer, str) else optimizer)

    def is_capable(self, capability):
        return capability in ("optimizer",)

    def set_gradient_compression(self, compression_params):
        # XLA collectives over ICI make 2-bit compression unnecessary at the
        # bandwidths TPU interconnect provides; accepted for API parity.
        self._compression = compression_params

    def save_optimizer_states(self, fname, dump_optimizer=False):
        import pickle
        import numpy as np
        blob = {k: jax.tree_util.tree_map(lambda a: np.asarray(a), v)
                for k, v in self._states.items()}
        with open(fname, "wb") as f:
            pickle.dump(blob, f)

    def load_optimizer_states(self, fname):
        import pickle
        with open(fname, "rb") as f:
            blob = pickle.load(f)
        self._states = {k: jax.tree_util.tree_map(jnp.asarray, v)
                        for k, v in blob.items()}

    def barrier(self):
        from ..ndarray import waitall
        waitall()


def create(name="local") -> KVStore:
    if name not in ("local", "device", "dist_sync", "dist_sync_device",
                    "dist_async", "dist_device_sync"):
        raise ValueError(f"unknown kvstore type {name!r}")
    return KVStore(name)
