"""KVStore (parity: python/mxnet/kvstore.py + src/kvstore/).

The reference aggregates gradients through ps-lite servers or NCCL
(`dist_sync_device`, `src/kvstore/kvstore_dist.h`). TPU-native: aggregation
IS an XLA collective over the device mesh. Two surfaces:

* object API here (init/push/pull/pushpull, server-side optimizer) — keeps
  Trainer/Module code shape-compatible with the reference. Multi-device
  values aggregate through ONE jitted bucketed computation: per-device
  shards are flattened into a single fusion buffer per device (the
  reference's kvstore big-array batching), assembled into a global array
  sharded over a Mesh, and summed with replicated output sharding — XLA
  lowers that to an all-reduce that rides ICI on real hardware;
* the fused path (parallel/trainer_step) inlines a `psum` over the 'dp' mesh
  axis inside the compiled train step — the highest-performance route that
  bench/dryrun use.

`dist_async` semantics (parity: `src/kvstore/kvstore_dist_server.h`): each
worker's push applies as its OWN optimizer update in arrival order — no
cross-worker aggregation barrier, so the server performs num_workers
updates per round and a worker's pull may miss other workers' in-flight
pushes. Here each device slot of a pushed value acts as one virtual
worker. Because a single process has a deterministic arrival order, the
multi-host race is reproduced explicitly: `set_async_staleness(max_delay,
seed)` holds a random subset of pushes back up to `max_delay` rounds
before applying them in shuffled order — the bounded-staleness model of
async PS. `barrier()` drains every pending push (the reference's
Wait/Barrier on the server queue).

Gradient compression (parity: src/kvstore/gradient_compression.cc): `2bit`
quantizes each pushed value to {-threshold, 0, +threshold} with
error-feedback residuals kept per (key, device-slot); `fp16` casts to
half precision for the wire. Unsupported types raise (no silent no-ops).
"""
from __future__ import annotations

import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ndarray import NDArray
from .. import healthmon as _hm
from .. import perfscope as _ps
from .. import optimizer as _opt
from .. import profiler as _prof
from ..diagnostics import flight as _flight
from ..diagnostics.memory import logical_nbytes as _logical_nbytes


def _value_nbytes(value) -> int:
    """Logical bytes of an NDArray / (nested) list of NDArrays — the
    always-live `kvstore.*_bytes` counters the metrics exporter scrapes."""
    total = 0
    stack = [value]
    while stack:
        v = stack.pop()
        if isinstance(v, (list, tuple)):
            stack.extend(v)
        elif isinstance(v, NDArray):
            total += _logical_nbytes(v._data)
    return total


def _account(op: str, value) -> None:
    """Count one collective-surface call + its payload bytes, and drop a
    flight-recorder breadcrumb when the ring is live."""
    nb = _value_nbytes(value)
    _prof.counter("kvstore.%s_calls" % op).increment()
    _prof.counter("kvstore.%s_bytes" % op).increment(nb)
    if _flight._REC is not None:
        _flight.record("collective", "kvstore.%s" % op, {"bytes": nb})


def _timed(op: str, fn):
    """Run one collective-surface call, feeding its entry-to-exit wall
    time to the healthmon skew timeline (docs/observability.md) and the
    cumulative ``kvstore.collective_ms`` counter perfscope's step-time
    decomposition reads. The duration includes the cross-rank wait
    inside blocking collectives — exactly the quantity straggler
    attribution and the step budget decompose — and the hook costs two
    predicate checks when both layers are off."""
    hm = _hm._HM
    if hm is None and _ps._PS is None:
        return fn()
    t0 = time.perf_counter()
    try:
        return fn()
    finally:
        ms = (time.perf_counter() - t0) * 1e3
        if hm is not None:
            hm.record_collective(op, ms)
        _prof.counter("kvstore.collective_ms").increment(ms)

__all__ = ["KVStore", "create"]


# --------------------------------------------------------------------------
# Bucketed compiled aggregation
# --------------------------------------------------------------------------

@jax.jit
def _tree_sum(values_per_key):
    """Sum each key's list of same-device arrays in one compiled call.
    jit caches per pytree-structure/shape signature automatically."""
    out = []
    for vals in values_per_key:
        total = vals[0]
        for v in vals[1:]:
            total = total + v
        out.append(total)
    return out


class _BucketedAllReduce:
    """Aggregates many (key -> per-device shards) in one compiled XLA call.

    Strategy (mirrors the reference kvstore's fusion-buffer batching, but
    as a compiled collective instead of server RPCs):
      1. ravel each key's shard and concatenate per device slot into one
         flat fusion buffer (one cached-jit dispatch per device);
      2. assemble the n_dev buffers into a global (n_dev, total) array
         sharded over a 1-axis Mesh of those devices;
      3. jitted sum over the sharded axis with replicated out_shardings —
         XLA inserts the all-reduce — and split/reshape back per key,
         all inside the same compiled computation.

    Compiled callables are cached per (devices, dtype, shapes) signature.
    """

    def __init__(self):
        self._reduce_cache = {}
        self._flatten_cache = {}
        self._lock = threading.Lock()

    def _flatten_fn(self, shapes, dtype):
        key = (shapes, dtype)
        fn = self._flatten_cache.get(key)
        if fn is None:
            def flatten(vals):
                return jnp.concatenate([v.ravel().astype(dtype) for v in vals])
            fn = jax.jit(flatten)
            with self._lock:
                self._flatten_cache[key] = fn
        return fn

    @staticmethod
    def _collective_mesh(devs):
        """The 1-axis mesh the fused all-reduce rides. When the process-
        global sharding mesh (parallel.sharding.set_mesh) is itself a
        single axis over exactly these devices, return THE SAME Mesh
        object — kvstore collectives and the sharded executor share one
        mesh identity (one ICI ring layout, one XLA mesh context)
        instead of each path minting its own. Multi-axis registry meshes
        can't be identity-shared (the reduce needs one flat axis), so
        those fall through to a private mesh and are not counted."""
        from ..parallel import sharding as _sharding
        gm = _sharding.get_mesh()
        if gm is not None and len(gm.axis_names) == 1:
            gdevs = tuple(np.ravel(np.asarray(gm.devices, dtype=object)))
            if gdevs == tuple(devs):
                _prof.counter("kvstore.mesh_reuse").increment()
                return gm
        return Mesh(np.array(devs), ("kv",))

    def _reduce_fn(self, devs, shapes, dtype):
        key = (devs, shapes, dtype)
        hit = self._reduce_cache.get(key)
        if hit is None:
            mesh = self._collective_mesh(devs)
            sizes = [int(np.prod(s)) if s else 1 for s in shapes]
            offs = np.cumsum([0] + sizes)

            def reduce_split(stacked):
                flat = stacked.sum(axis=0)
                return tuple(
                    flat[offs[i]:offs[i + 1]].reshape(shapes[i])
                    for i in range(len(shapes)))

            fn = jax.jit(
                reduce_split,
                out_shardings=tuple(NamedSharding(mesh, P())
                                    for _ in shapes))
            with self._lock:
                self._reduce_cache[key] = (fn, mesh)
            return fn, mesh
        return hit

    def __call__(self, values_per_key):
        """values_per_key: list over keys of lists of jax.Array shards
        (equal length n_dev, consistent device order). Returns list of
        aggregated jax.Array, one per key."""
        n_dev = len(values_per_key[0])
        if n_dev == 1:
            return [v[0] for v in values_per_key]
        dev_slots = [tuple(sorted(v.devices(), key=lambda d: d.id))[0]
                     for v in values_per_key[0]]
        distinct = len(set(dev_slots)) == n_dev
        if not distinct:
            # shared-device shards (e.g. emulated workers on one chip): one
            # fused compiled tree-sum. Coalesce stragglers onto slot 0's
            # device first — jit refuses mixed committed devices.
            if len(set(dev_slots)) > 1:
                dev0 = dev_slots[0]
                values_per_key = [
                    [v if dev0 in v.devices() else jax.device_put(v, dev0)
                     for v in vals]
                    for vals in values_per_key]
            return _tree_sum(values_per_key)

        shapes = tuple(tuple(v[0].shape) for v in values_per_key)
        dtype = jnp.result_type(*[v[0].dtype for v in values_per_key])
        flatten = self._flatten_fn(shapes, dtype)
        bufs = []
        for slot in range(n_dev):
            bufs.append(flatten([v[slot] for v in values_per_key]))
        total = bufs[0].shape[0]
        devs = tuple(dev_slots)
        fn, mesh = self._reduce_fn(devs, shapes, dtype)
        # the mesh may be the reused registry mesh, whose one axis is
        # named dp/ep/… rather than "kv" — shard over whatever it has
        sharding = NamedSharding(mesh, P(mesh.axis_names[0]))
        stacked = jax.make_array_from_single_device_arrays(
            (n_dev, total), sharding,
            [jax.device_put(b, d)[None] for b, d in zip(bufs, devs)])
        return list(fn(stacked))


# --------------------------------------------------------------------------
# Gradient compression (parity: src/kvstore/gradient_compression.cc)
# --------------------------------------------------------------------------

@jax.jit
def _compress_2bit(grad, residual, threshold):
    acc = grad + residual
    q = jnp.where(acc >= threshold, threshold,
                  jnp.where(acc <= -threshold, -threshold, 0.0)
                  ).astype(grad.dtype)
    return q, acc - q


class _AsyncQueue:
    """Arrival-order update queue with induced bounded staleness.

    Models the reference async server (`kvstore_dist_server.h`): pushes
    apply independently, possibly delayed and reordered relative to other
    workers. `max_delay=0` = deterministic arrival order (still per-worker
    updates, the async/sync semantic difference); `max_delay=k` holds a
    random subset of pushes up to k rounds and releases them shuffled,
    reproducing multi-host arrival races reproducibly (seeded).
    """

    def __init__(self, apply_fn, max_delay=0, seed=0):
        self._apply = apply_fn
        self._pending = []      # [age, key, grad]
        self._rng = np.random.RandomState(seed)
        self.max_delay = max_delay
        self.delayed_total = 0  # pushes that were held back at least once
        self.applied_total = 0
        # on rank 0 of a cross-process cluster BOTH the main thread
        # (barrier/flush) and the AsyncPSTransport server thread mutate
        # this queue; unlocked, a push between _drain's iteration and its
        # reassignment of _pending would be silently dropped
        self._qlock = threading.RLock()

    def push(self, key, grad):
        with self._qlock:
            self._pending.append([0, key, grad])
            self._drain(force=False)

    def _drain(self, force):
        with self._qlock:
            now, keep = [], []
            for item in self._pending:
                overdue = item[0] >= self.max_delay
                if force or overdue or self._rng.rand() < 0.5:
                    now.append(item)
                else:
                    if item[0] == 0:
                        self.delayed_total += 1  # distinct pushes held back
                    item[0] += 1
                    keep.append(item)
            self._rng.shuffle(now)
            for _, k, g in now:
                self._apply(k, g)
                self.applied_total += 1
            self._pending = keep

    def flush(self):
        self._drain(force=True)

    @property
    def pending_count(self):
        with self._qlock:
            return len(self._pending)


class KVStore:
    def __init__(self, kv_type="local"):
        self.type = kv_type
        self._store = {}
        self._optimizer = None
        self._states = {}
        self._is_dist = kv_type.startswith("dist")
        self._is_async = kv_type == "dist_async"
        self._compression = None
        self._residuals = {}
        self._allreduce = _BucketedAllReduce()
        self._async_queue = (_AsyncQueue(self._async_apply)
                             if self._is_async else None)
        self._async_ps = None     # cross-process transport, created lazily
        # dist_async flush deadline (seconds); None = transport default
        # (MXTPU_APS_FLUSH_TIMEOUT env or 120 s)
        self.async_flush_timeout = None

    def _ps(self):
        """Cross-process async transport (kvstore/async_ps.py), active
        when this is a dist_async store in a real multi-process cluster.
        Lazy: the store may be created before mx.distributed.init()."""
        if not self._is_async or jax.process_count() <= 1:
            return None
        if self._async_ps is None:
            from .async_ps import AsyncPSTransport
            self._async_ps = AsyncPSTransport(
                self, flush_timeout=self.async_flush_timeout)
        return self._async_ps

    def _async_apply(self, key, grad):
        """Apply target for the async queue: plain keys are this
        process's virtual-worker pushes; (key, rank) tuples were tagged
        by the cross-process server for per-worker accounting."""
        if isinstance(key, tuple):
            self._async_ps._apply(key, grad)
        else:
            self._apply_one_update(key, grad)

    # -- topology ---------------------------------------------------------
    @property
    def rank(self):
        return jax.process_index() if self._is_dist else 0

    @property
    def num_workers(self):
        return jax.process_count() if self._is_dist else 1

    # -- data plane -------------------------------------------------------
    def init(self, key, value):
        if isinstance(key, (list, tuple)):
            for k, v in zip(key, value):
                self.init(k, v)
            return
        self._store[key] = value.copy() if isinstance(value, NDArray) else NDArray(value)
        ps = self._ps()
        if ps is not None:
            # server publishes initial weights; workers block until seen
            ps.publish_init(key, self._store[key].asnumpy())

    def _compress(self, values):
        """Apply gradient compression per device slot with error-feedback
        residuals, before aggregation (the 'wire' stage of the reference)."""
        if self._compression is None:
            return values
        ctype = self._compression["type"]
        if ctype == "fp16":
            return [[v.astype(jnp.float16).astype(v.dtype) for v in vals]
                    for key_i, vals in values]
        threshold = float(self._compression.get("threshold", 0.5))
        out = []
        for key_i, vals in values:
            cvals = []
            for slot, v in enumerate(vals):
                rkey = (key_i, slot)
                r = self._residuals.get(rkey)
                if r is None or r.shape != v.shape:
                    r = jnp.zeros_like(v)
                q, r = _compress_2bit(v, r, jnp.asarray(threshold, v.dtype))
                self._residuals[rkey] = r
                cvals.append(q)
            out.append(cvals)
        return out

    def _batch_aggregate(self, keys, values):
        """Aggregate a batch of keys' multi-device values in one compiled
        bucketed collective. values: list (per key) of NDArray or list of
        NDArray. Returns list of aggregated NDArray."""
        norm = []
        for v in values:
            if isinstance(v, NDArray):
                norm.append([v._data])
            elif len(v) == 0:
                raise ValueError("empty value list in kvstore aggregation")
            else:
                norm.append([x._data for x in v])
        n_dev = len(norm[0])
        if any(len(v) != n_dev for v in norm):
            # ragged: aggregate each key independently
            return [self._batch_aggregate([k], [v])[0]
                    for k, v in zip(keys, values)]
        if self._compression is not None and n_dev > 1:
            norm = self._compress(list(zip(keys, norm)))
        aggs = self._allreduce(norm)
        if self._is_dist and jax.process_count() > 1:
            from jax.experimental import multihost_utils
            aggs = [jnp.sum(multihost_utils.process_allgather(a), axis=0)
                    for a in aggs]
        return [NDArray(a) for a in aggs]

    def _aggregate(self, values, key=None):
        return self._batch_aggregate([key], [values])[0]

    def push(self, key, value, priority=0):
        _account("push", value)
        if _prof._ACTIVE:
            with _prof.Scope("kvstore.push", "kvstore", sync=False):
                return _timed("push",
                              lambda: self._push_impl(key, value, priority))
        return _timed("push", lambda: self._push_impl(key, value, priority))

    def _push_impl(self, key, value, priority=0):
        if self._is_async:
            ps = self._ps()
            keys = key if isinstance(key, (list, tuple)) else [key]
            vals = value if isinstance(key, (list, tuple)) else [value]
            for k, v in zip(keys, vals):
                slots = list(v) if isinstance(v, (list, tuple)) else [v]
                slots = self._compress_slots(k, slots)
                for g in slots:  # each device slot = one virtual worker
                    if ps is not None:
                        # cross-process: ship to the rank-0 server, which
                        # applies it in genuine arrival order
                        ps.push(k, np.asarray(g))
                    else:
                        self._async_queue.push(k, g)
            return
        if isinstance(key, (list, tuple)):
            aggs = self._batch_aggregate(key, value)
            for k, a in zip(key, aggs):
                self._apply_push(k, a)
            return
        self._apply_push(key, self._aggregate(value, key))

    def set_async_staleness(self, max_delay, seed=0):
        """Configure the induced-staleness simulation for `dist_async`
        (see module docstring). max_delay=0 restores deterministic
        arrival order."""
        if not self._is_async:
            raise ValueError("set_async_staleness requires a dist_async "
                             "store, got %r" % self.type)
        self._async_queue.flush()  # don't drop in-flight delayed pushes
        self._async_queue = _AsyncQueue(self._async_apply,
                                        max_delay=max_delay, seed=seed)

    def _apply_one_update(self, key, grad):
        """One worker's push = one server-side update (async semantics)."""
        self._apply_push(key, grad if isinstance(grad, NDArray)
                         else NDArray(grad))

    def _compress_slots(self, key, slots):
        """Wire-stage compression for async per-worker pushes. Single-slot
        pushes skip compression, matching the sync path's n_dev > 1 guard
        (no wire between worker and server)."""
        raws = [s._data if isinstance(s, NDArray) else jnp.asarray(s)
                for s in slots]
        if self._compression is None or len(raws) <= 1:
            return raws
        return self._compress([(key, raws)])[0]

    def _apply_push(self, key, agg):
        if self._optimizer is not None:
            weight = self._store[key]
            if key not in self._states:
                self._states[key] = self._optimizer.create_state_multi_precision(
                    key, weight._data)
            self._states[key] = self._optimizer.update(key, weight, agg,
                                                       self._states[key])
        else:
            if key in self._store:
                self._store[key]._data = self._store[key]._data + agg._data
            else:
                self._store[key] = agg.copy()

    def pull(self, key, out=None, priority=0, ignore_sparse=True):
        _account("pull", out)
        if _prof._ACTIVE:
            with _prof.Scope("kvstore.pull", "kvstore", sync=False):
                return _timed("pull", lambda: self._pull_impl(
                    key, out, priority, ignore_sparse))
        return _timed("pull", lambda: self._pull_impl(key, out, priority,
                                                      ignore_sparse))

    def _pull_impl(self, key, out=None, priority=0, ignore_sparse=True):
        if isinstance(key, (list, tuple)):
            for k, o in zip(key, out):
                self._pull_impl(k, o, priority)
            return
        ps = self._ps()
        if ps is not None and ps.rank != 0:
            # CURRENT published server weights — in-flight pushes may be
            # missing, which is the async contract. (Rank 0 reads its own
            # store: the server thread updates it in place, and swapping
            # the entry here would race a concurrent update.)
            src = NDArray(ps.pull(key))
        else:
            src = self._store[key]
        outs = out if isinstance(out, (list, tuple)) else [out]
        for o in outs:
            src.copyto(o)

    def pushpull(self, key, value, out=None, priority=0):
        """Fused allreduce (parity: kv.pushpull in dist_sync_device).
        List-form calls aggregate ALL keys in one compiled bucketed
        collective — the efficient path Trainer uses. In dist_async the
        push applies per-worker server updates and the pull returns the
        CURRENT server weights (which may not yet include delayed
        workers' pushes — the async contract)."""
        _account("pushpull", value)
        if _prof._ACTIVE:
            with _prof.Scope("kvstore.pushpull", "kvstore", sync=False):
                return _timed("pushpull", lambda: self._pushpull_impl(
                    key, value, out, priority))
        return _timed("pushpull", lambda: self._pushpull_impl(
            key, value, out, priority))

    def _pushpull_impl(self, key, value, out=None, priority=0):
        if self._is_async and self._optimizer is not None:
            self._push_impl(key, value)
            if out is not None:
                self._pull_impl(key, out=out)
                return None
            ps = self._ps()
            if ps is not None and ps.rank != 0:
                if isinstance(key, (list, tuple)):
                    return [NDArray(ps.pull(k)) for k in key]
                return NDArray(ps.pull(key))
            if isinstance(key, (list, tuple)):
                return [self._store[k].copy() for k in key]
            return self._store[key].copy()
        if isinstance(key, (list, tuple)):
            aggs = self._batch_aggregate(key, value)
            if out is None:
                return aggs
            for a, o in zip(aggs, out):
                outs = o if isinstance(o, (list, tuple)) else [o]
                for oo in outs:
                    a.copyto(oo)
            return
        agg = self._aggregate(value, key)
        if out is None:
            return agg
        outs = out if isinstance(out, (list, tuple)) else [out]
        for o in outs:
            agg.copyto(o)

    def row_sparse_pull(self, key, out=None, priority=0, row_ids=None):
        """Pull only the requested rows (parity: reference row_sparse_pull,
        python/mxnet/kvstore.py). `row_ids` selects rows of the stored
        value; result rows appear at their row_id positions (other rows
        zero), matching the reference's RowSparseNDArray densified view."""
        if row_ids is None:
            self.pull(key, out, priority)
            return
        if isinstance(key, (list, tuple)):
            rids = row_ids if isinstance(row_ids, (list, tuple)) else [row_ids] * len(key)
            for k, o, r in zip(key, out, rids):
                self.row_sparse_pull(k, o, priority, r)
            return
        src = self._store[key]
        ids = row_ids._data if isinstance(row_ids, NDArray) else jnp.asarray(row_ids)
        ids_np = np.unique(np.asarray(ids).astype(np.int64).ravel())
        rows = jnp.take(src._data, jnp.asarray(ids_np), axis=0)
        if out is None:
            from ..ndarray import sparse as _sparse
            return _sparse.RowSparseNDArray(rows, ids_np, src.shape)
        dense = jnp.zeros_like(src._data).at[jnp.asarray(ids_np)].set(rows)
        outs = out if isinstance(out, (list, tuple)) else [out]
        for o in outs:
            NDArray(dense).copyto(o)

    # -- server-side optimizer --------------------------------------------
    def set_optimizer(self, optimizer):
        self._optimizer = (_opt.create(optimizer)
                           if isinstance(optimizer, str) else optimizer)

    def is_capable(self, capability):
        return capability in ("optimizer",)

    def set_gradient_compression(self, compression_params):
        ctype = (compression_params or {}).get("type")
        if ctype not in ("2bit", "fp16"):
            raise ValueError(
                f"unsupported gradient compression type {ctype!r}: "
                "supported are '2bit' (error-feedback sign quantization, "
                "parity: src/kvstore/gradient_compression.cc) and 'fp16'")
        self._compression = dict(compression_params)
        self._residuals = {}

    def save_optimizer_states(self, fname, dump_optimizer=False):
        import pickle
        blob = {k: jax.tree_util.tree_map(lambda a: np.asarray(a), v)
                for k, v in self._states.items()}
        with open(fname, "wb") as f:
            pickle.dump(blob, f)

    def load_optimizer_states(self, fname):
        import pickle
        with open(fname, "rb") as f:
            blob = pickle.load(f)
        self._states = {k: jax.tree_util.tree_map(jnp.asarray, v)
                        for k, v in blob.items()}

    def async_applied_counts(self):
        """dist_async: per-worker counts of server-applied updates.
        Cross-process these come from the rank-0 server's published
        accounting; single-process, all pushes are worker 0's."""
        if not self._is_async:
            raise ValueError("async_applied_counts requires dist_async")
        ps = self._ps()
        if ps is not None:
            return ps.applied_counts()
        return {0: self._async_queue.applied_total}

    def barrier(self):
        ps = self._ps() if self._is_async else None
        if ps is not None:
            # wait until MY pushes are all server-applied, then rendezvous
            # with the other workers (reference: Barrier on the server).
            # The deadline is read here, not at transport construction, so
            # adjusting kv.async_flush_timeout mid-run takes effect.
            ps.flush(timeout=self.async_flush_timeout)
            from .. import distributed
            distributed.barrier("mxtpu_kv_barrier")
        if self._async_queue is not None:
            self._async_queue.flush()  # drain in-flight async pushes
        from ..ndarray import waitall
        waitall()


def create(name="local") -> KVStore:
    if name not in ("local", "device", "dist_sync", "dist_sync_device",
                    "dist_async", "dist_device_sync"):
        raise ValueError(f"unknown kvstore type {name!r}")
    return KVStore(name)
