"""Cross-process asynchronous parameter server for `dist_async`.

Parity: the reference's ps-lite server path (`src/kvstore/
kvstore_dist_server.h`) — each worker's push is applied as its OWN
server-side optimizer update in arrival order, with no cross-worker
aggregation barrier, and pulls return the server's CURRENT weights
(possibly missing other workers' in-flight pushes).

TPU-native rebuild, second iteration: rank 0 hosts the server state and
a plain TCP listener on loopback/pod-LAN; the jax coordination service
is used ONLY for the one-time address exchange (one `key_value_set` by
the server, one `blocking_key_value_get` per worker). All data-plane
traffic — pushes, pulls, applied-count acks, flushes — rides
length-prefixed pickled frames over sockets, exactly ps-lite's own
van/zmq layout.

Why not the coordination-service KV as the wire (the first iteration)?
Sustained traffic through this jaxlib's KV client (polled dir listings,
repeated blocking gets) segfaults the client after a few hundred RPCs —
a C++ bug we cannot patch from here, and one the low-volume rendezvous
usage never hits. A socket wire is also the honest rebuild: the
reference never routed gradients through its tracker either.

Per-worker FIFO is preserved by connection order + sequence numbers;
cross-worker interleaving is genuine arrival nondeterminism (TCP accept
order and thread scheduling decide it). Induced bounded staleness
(`set_async_staleness`) still applies through the store's `_AsyncQueue`,
aged by a server-side ticker so held-back entries release by time as
well as by traffic.
"""
from __future__ import annotations

import pickle
import socket
import struct
import threading
import time

import numpy as np

_NS = "mxtpu_aps"
_LIVE = []      # live transports; distributed.shutdown() stops them first


def stop_all(timeout=5.0):
    """Stop every live server thread (joined, not abandoned): called by
    mx.distributed.shutdown() before the coordination client dies.
    Snapshot first: stop() deregisters each transport from _LIVE."""
    live = list(_LIVE)
    for t in live:
        t.stop()
    for t in live:
        if t._thread is not None:
            t._thread.join(timeout)
    _LIVE.clear()


def _client():
    from jax._src import distributed
    c = distributed.global_state.client
    if c is None:
        raise RuntimeError(
            "dist_async across processes needs jax.distributed "
            "(mx.distributed.init()) — the coordination service is the "
            "rendezvous")
    return c


# -- framing ----------------------------------------------------------------

def _send_frame(sock, obj):
    blob = pickle.dumps(obj, protocol=4)
    sock.sendall(struct.pack(">Q", len(blob)) + blob)


def _recv_exact(sock, n):
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed mid-frame")
        buf += chunk
    return buf


def _recv_frame(sock):
    (n,) = struct.unpack(">Q", _recv_exact(sock, 8))
    return pickle.loads(_recv_exact(sock, n))


class AsyncPSTransport:
    """One per dist_async KVStore when process_count > 1."""

    def __init__(self, kv, poll_ms=2.0, flush_timeout=None):
        import jax
        self._kv = kv
        self._c = _client()
        self.rank = jax.process_index()
        self.nproc = jax.process_count()
        self._seq = 0                 # my push sequence (per-worker FIFO)
        self._pushed = 0
        self._poll_s = poll_ms / 1e3
        from ..autotune.knobs import env_float
        self.flush_timeout = float(env_float(
            "MXTPU_APS_FLUSH_TIMEOUT", 120.0, call_site=flush_timeout))
        self._stop = threading.Event()
        self._applied = {}            # server: worker rank -> applied count
        self._last_seq = {}           # server: rank -> newest applied seq
        self._health = {}             # server: rank -> latest health record
        self._lock = threading.Lock()
        self._apply_lock = threading.Lock()  # serializes optimizer applies
        self._thread = None
        self._listener = None
        self._server_addr = None
        if self.rank == 0:
            self._listener = socket.socket(socket.AF_INET,
                                           socket.SOCK_STREAM)
            self._listener.setsockopt(socket.SOL_SOCKET,
                                      socket.SO_REUSEADDR, 1)
            from ..autotune.knobs import env_str
            host = env_str("MXTPU_APS_HOST", "127.0.0.1")
            self._listener.bind((host, 0))
            self._listener.listen(64)
            self._listener.settimeout(0.2)   # lets the accept loop stop
            self._server_addr = self._listener.getsockname()
            # rendezvous: the ONLY coordination-KV write on the data path
            self._c.key_value_set_bytes(
                f"{_NS}/addr", pickle.dumps(self._server_addr),
                allow_overwrite=True)
            self._thread = threading.Thread(target=self._serve, daemon=True)
            self._thread.start()
        _LIVE.append(self)

    # -- worker side -------------------------------------------------------
    def _addr(self):
        if self._server_addr is None:
            blob = self._c.blocking_key_value_get_bytes(f"{_NS}/addr",
                                                        60_000)
            self._server_addr = tuple(pickle.loads(blob))
        return self._server_addr

    def _rpc(self, *msg, timeout=30.0):
        """One request/response round trip (connection per call: the
        volume is one RPC per push/pull/ack, trivial for loopback/LAN)."""
        with socket.create_connection(self._addr(), timeout=timeout) as s:
            _send_frame(s, msg)
            kind, payload = _recv_frame(s)
        if kind == "err":
            raise RuntimeError(f"dist_async server: {payload}")
        return payload

    def publish_init(self, key, value_np):
        """Rank 0 (the server) holds initial weights in its own store;
        workers block until the server reports the key initialized (the
        reference's init-on-server + worker pull-before-train)."""
        if self.rank == 0:
            return
        deadline = time.time() + 60.0
        while time.time() < deadline:
            if self._rpc("has", key):
                return
            time.sleep(self._poll_s)
        raise TimeoutError(f"dist_async: server never initialized {key!r}")

    def push(self, key, grad_np):
        self._seq += 1
        self._pushed += 1
        if self.rank == 0:
            self._ingest(self.rank, self._seq, key, np.asarray(grad_np))
        else:
            self._rpc("push", self.rank, self._seq, key,
                      np.asarray(grad_np))

    def pull(self, key):
        return self._rpc("pull", key)

    def flush(self, timeout=None):
        """Block until every push THIS worker issued has been applied
        server-side (the reference's per-worker Wait on the send queue).
        Push RPCs are synchronous, so by entry every push has been
        RECEIVED; the flush RPC force-drains staleness-delayed entries
        and the loop waits out any apply still in flight."""
        limit = self.flush_timeout if timeout is None else float(timeout)
        deadline = time.time() + limit
        self._rpc("flush")
        last_flush = time.time()
        while time.time() < deadline:
            if self._applied_count(self.rank) >= self._pushed:
                return
            time.sleep(max(self._poll_s, 0.01))
            if time.time() - last_flush >= 0.5:
                # re-force-drain only occasionally (covers pushes that
                # raced past the first flush); re-sending per poll would
                # hammer rank 0 with a connection + full queue drain
                # every couple of milliseconds
                self._rpc("flush")
                last_flush = time.time()
        raise TimeoutError(
            f"dist_async flush: rank {self.rank} pushed {self._pushed} "
            f"but the server did not acknowledge them in {limit:g}s")

    def _applied_count(self, rank):
        if self.rank == 0:
            with self._lock:
                return self._applied.get(rank, 0)
        return self._rpc("applied", rank)

    def wait_outstanding(self, max_outstanding, timeout=60.0):
        """Block until at most `max_outstanding` of MY pushes are still
        unapplied — the worker-side pacing ps-lite gets implicitly from
        pulling updated weights after each push. Cross-worker staleness
        stays unbounded; only a worker's lead over ITSELF is capped."""
        applied = 0   # a non-positive timeout must raise TimeoutError
        deadline = time.time() + timeout
        while time.time() < deadline:
            applied = self._applied_count(self.rank)
            if self._pushed - applied <= max_outstanding:
                return
            time.sleep(max(self._poll_s, 0.01))  # each poll = one RPC
        raise TimeoutError(
            f"rank {self.rank}: {self._pushed} pushed but server applied "
            f"only {applied} after {timeout}s")

    def health_exchange(self, record):
        """healthmon skew-timeline transport for dist_async (workers are
        NOT in lockstep, so the sync path's allgather would deadlock):
        post this worker's fixed-width timing record to the rank-0
        server, get back the merged {rank: record} table — best-effort
        and possibly stale for other ranks, the async contract."""
        record = [float(v) for v in record]
        if self.rank == 0:
            with self._lock:
                self._health[0] = record
                return {int(r): list(v) for r, v in self._health.items()}
        merged = self._rpc("health", self.rank, record)
        return {int(r): list(v) for r, v in merged.items()}

    def applied_counts(self):
        """Per-worker applied-update counts from the server."""
        if self.rank == 0:
            with self._lock:
                return {r: self._applied.get(r, 0)
                        for r in range(self.nproc)}
        counts = self._rpc("counts")
        return {r: counts.get(r, 0) for r in range(self.nproc)}

    def stop(self):
        """Signal the server thread to exit and deregister from _LIVE so a
        discarded dist_async store doesn't pin an accept-loop daemon (and
        its listener socket) for the life of the process."""
        self._stop.set()
        try:
            _LIVE.remove(self)
        except ValueError:
            pass

    # -- server side (rank 0) ---------------------------------------------
    def _apply(self, tagged_key, grad):
        """_AsyncQueue apply hook: one worker push = one optimizer step."""
        key, rank = tagged_key
        with self._apply_lock:
            self._kv._apply_one_update(key, grad)
        with self._lock:
            self._applied[rank] = self._applied.get(rank, 0) + 1

    def _ingest(self, rank, seq, key, grad):
        """Seq-deduped enqueue into the staleness queue (per-worker FIFO:
        TCP + the per-connection handler give per-worker ordering)."""
        from ..ndarray import NDArray
        with self._lock:
            if seq <= self._last_seq.get(rank, 0):
                return            # duplicate delivery; already applied
            self._last_seq[rank] = seq
        self._kv._async_queue.push((key, rank), NDArray(np.asarray(grad)))

    def _handle(self, conn):
        try:
            with conn:
                msg = _recv_frame(conn)
                op, args = msg[0], msg[1:]
                try:
                    if op == "push":
                        rank, seq, key, grad = args
                        self._ingest(int(rank), int(seq), key, grad)
                        reply = ("ok", True)
                    elif op == "pull":
                        (key,) = args
                        with self._apply_lock:
                            w = np.asarray(self._kv._store[key].asnumpy())
                        reply = ("ok", w)
                    elif op == "has":
                        (key,) = args
                        reply = ("ok", key in self._kv._store)
                    elif op == "applied":
                        (rank,) = args
                        with self._lock:
                            reply = ("ok", self._applied.get(rank, 0))
                    elif op == "counts":
                        with self._lock:
                            reply = ("ok", dict(self._applied))
                    elif op == "health":
                        rank, rec = args
                        with self._lock:
                            self._health[int(rank)] = [float(v)
                                                       for v in rec]
                            reply = ("ok", dict(self._health))
                    elif op == "flush":
                        self._kv._async_queue.flush()
                        reply = ("ok", True)
                    else:
                        reply = ("err", f"unknown op {op!r}")
                except Exception as e:  # noqa: BLE001 — one bad request
                    reply = ("err", f"{type(e).__name__}: {e}")
                _send_frame(conn, reply)
        except Exception:
            pass                  # a dropped client must not kill serving

    def _serve(self):
        """Accept loop + staleness ticker. Handler threads are short-lived
        (one request per connection); the ticker ages delayed entries so
        induced staleness releases by TIME as well as by traffic."""
        last_tick = time.time()
        while not self._stop.is_set():
            try:
                conn, _ = self._listener.accept()
                threading.Thread(target=self._handle, args=(conn,),
                                 daemon=True).start()
            except socket.timeout:
                pass
            except Exception:
                if self._stop.is_set():
                    break
                # persistent accept failures (EMFILE, invalidated fd)
                # must not hot-spin a rank-0 core; pause and retry
                time.sleep(0.05)
            now = time.time()
            if now - last_tick >= max(self._poll_s, 0.01):
                last_tick = now
                q = self._kv._async_queue
                if q is not None and q.pending_count:
                    q._drain(force=False)
        try:
            self._listener.close()
        except Exception:
            pass
