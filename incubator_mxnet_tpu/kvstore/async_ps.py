"""Cross-process asynchronous parameter server for `dist_async`.

Parity: the reference's ps-lite server path (`src/kvstore/
kvstore_dist_server.h`) — each worker's push is applied as its OWN
server-side optimizer update in arrival order, with no cross-worker
aggregation barrier, and pulls return the server's CURRENT weights
(possibly missing other workers' in-flight pushes).

TPU-native rebuild: there are no ps-lite server processes to rebuild —
the wire is the jax coordination service's key-value store (the same
channel `jax.distributed` already runs on), and rank 0 hosts the server
state. Workers publish pickled gradients under per-worker monotonic
sequence keys (per-worker FIFO — ps-lite's ordering guarantee); a server
thread on rank 0 discovers them by polling, feeds them through the
store's `_AsyncQueue` (so `set_async_staleness` bounds REAL cross-process
staleness too), applies them with the server-side optimizer, and
republishes weights. Cross-worker interleaving is genuine arrival
nondeterminism: grpc delivery and poll timing decide it.
"""
from __future__ import annotations

import os
import pickle
import threading
import time

import numpy as np

_NS = "mxtpu_aps"
_LIVE = []      # live transports; distributed.shutdown() stops them first


def stop_all(timeout=5.0):
    """Stop every live server thread (joined, not abandoned): called by
    mx.distributed.shutdown() before the coordination client dies.
    Snapshot first: stop() deregisters each transport from _LIVE."""
    live = list(_LIVE)
    for t in live:
        t.stop()
    for t in live:
        if t._thread is not None:
            t._thread.join(timeout)
    _LIVE.clear()


def _client():
    from jax._src import distributed
    c = distributed.global_state.client
    if c is None:
        raise RuntimeError(
            "dist_async across processes needs jax.distributed "
            "(mx.distributed.init()) — the coordination service is the "
            "transport")
    return c


class AsyncPSTransport:
    """One per dist_async KVStore when process_count > 1."""

    def __init__(self, kv, poll_ms=2.0, flush_timeout=None):
        import jax
        self._kv = kv
        self._c = _client()
        self.rank = jax.process_index()
        self.nproc = jax.process_count()
        self._seq = 0                 # my push sequence (per-worker FIFO)
        self._pushed = 0
        self._poll_s = poll_ms / 1e3
        if flush_timeout is None:
            flush_timeout = float(os.environ.get(
                "MXTPU_APS_FLUSH_TIMEOUT", "120"))
        self.flush_timeout = float(flush_timeout)
        self._stop = threading.Event()
        self._applied = {}            # server: worker rank -> applied count
        self._touched = set()         # server: keys updated since publish
        self._lock = threading.Lock()
        self._thread = None
        if self.rank == 0:
            self._thread = threading.Thread(target=self._serve, daemon=True)
            self._thread.start()
        _LIVE.append(self)

    # -- worker side -------------------------------------------------------
    def publish_init(self, key, value_np):
        """Rank 0 publishes initial weights; others wait for them (the
        reference's init-on-server + worker pull-before-train)."""
        if self.rank == 0:
            self._c.key_value_set_bytes(
                f"{_NS}/w/{key}", pickle.dumps(np.asarray(value_np)),
                allow_overwrite=True)
        else:
            self._c.blocking_key_value_get_bytes(f"{_NS}/w/{key}", 60_000)

    def push(self, key, grad_np):
        from urllib.parse import quote
        self._seq += 1
        self._pushed += 1
        # quote the user key: kvstore keys may contain '/' (layer paths),
        # which would corrupt the wire-key structure the server parses
        self._c.key_value_set_bytes(
            f"{_NS}/push/{self.rank:04d}/{self._seq:012d}/"
            f"{quote(str(key), safe='')}",
            pickle.dumps(np.asarray(grad_np)))

    def pull(self, key):
        blob = self._c.blocking_key_value_get_bytes(f"{_NS}/w/{key}", 60_000)
        return pickle.loads(blob)

    def _try_get(self, key):
        """try_get that treats NOT_FOUND as None (the client raises)."""
        try:
            return self._c.key_value_try_get_bytes(key)
        except Exception:
            return None

    def flush(self, timeout=None):
        """Block until every push THIS worker issued has been applied
        server-side (the reference's per-worker Wait on the send queue).
        Signals the server to force-drain any staleness-delayed entries.
        Deadline: `timeout` arg, else the transport's `flush_timeout`
        (constructor arg / MXTPU_APS_FLUSH_TIMEOUT env, default 120 s)."""
        self._c.key_value_set_bytes(f"{_NS}/flushreq/{self.rank}", b"1",
                                    allow_overwrite=True)
        if self._pushed == 0:
            return   # nothing to wait for (the flushreq still releases
                     # any delayed peers' entries on the server)
        limit = self.flush_timeout if timeout is None else float(timeout)
        deadline = time.time() + limit
        while time.time() < deadline:
            blob = self._try_get(f"{_NS}/applied/{self.rank}")
            if blob is not None and int(blob) >= self._pushed:
                return
            time.sleep(self._poll_s)
        raise TimeoutError(
            f"dist_async flush: rank {self.rank} pushed {self._pushed} "
            f"but the server did not acknowledge them in {limit:g}s")

    def wait_outstanding(self, max_outstanding, timeout=60.0):
        """Block until at most `max_outstanding` of MY pushes are still
        unapplied — the worker-side pacing ps-lite gets implicitly from
        pulling updated weights after each push. Cross-worker staleness
        stays unbounded; only a worker's lead over ITSELF is capped."""
        applied = 0   # a non-positive timeout must raise TimeoutError
        deadline = time.time() + timeout
        while time.time() < deadline:
            blob = self._try_get(f"{_NS}/applied/{self.rank}")
            applied = int(blob) if blob is not None else 0
            if self._pushed - applied <= max_outstanding:
                return
            time.sleep(self._poll_s)
        raise TimeoutError(
            f"rank {self.rank}: {self._pushed} pushed but server applied "
            f"only {applied} after {timeout}s")

    def applied_counts(self):
        """Per-worker applied-update counts as published by the server."""
        out = {}
        for r in range(self.nproc):
            blob = self._try_get(f"{_NS}/applied/{r}")
            out[r] = int(blob) if blob is not None else 0
        return out

    def stop(self):
        """Signal the server thread to exit and deregister from _LIVE so a
        discarded dist_async store doesn't pin a 2 ms-poll daemon (and its
        transport) for the life of the process."""
        self._stop.set()
        try:
            _LIVE.remove(self)
        except ValueError:
            pass

    # -- server side (rank 0 thread) --------------------------------------
    def _apply(self, tagged_key, grad):
        """_AsyncQueue apply hook: one worker push = one optimizer step."""
        key, rank = tagged_key
        self._kv._apply_one_update(key, grad)
        with self._lock:
            self._applied[rank] = self._applied.get(rank, 0) + 1
            self._touched.add(key)

    def _publish(self):
        with self._lock:
            touched, self._touched = self._touched, set()
            applied = dict(self._applied)
        for key in touched:
            w = self._kv._store[key]
            self._c.key_value_set_bytes(
                f"{_NS}/w/{key}", pickle.dumps(np.asarray(w.asnumpy())),
                allow_overwrite=True)
        for rank, n in applied.items():
            self._c.key_value_set_bytes(f"{_NS}/applied/{rank}",
                                        str(n).encode(),
                                        allow_overwrite=True)

    def _serve(self):
        import sys
        from urllib.parse import unquote
        from ..ndarray import NDArray
        queue = lambda: self._kv._async_queue  # noqa: E731 — swappable via
        last_seq = {}                         # set_async_staleness
        while not self._stop.is_set():
            try:
                entries = self._c.key_value_dir_get_bytes(f"{_NS}/push/")
            except Exception:
                # NOT_FOUND = simply no pending pushes; real transport
                # failures land here too and resolve when the daemon
                # thread dies with the process
                entries = []
            # dir order is key-sorted: per-worker FIFO by sequence number;
            # cross-worker interleave = whatever had ARRIVED by this poll.
            # Per-entry guard: one malformed/poison entry must not kill
            # the server thread (workers would block until flush timeout).
            for k, blob in entries:
                try:
                    parts = k.rsplit("/", 3)  # .../push/<rank>/<seq>/<key>
                    rank, seq = int(parts[1]), int(parts[2])
                    key = unquote(parts[3])
                    # seq dedup: if a delete failed last round the entry
                    # reappears — applying it twice would double-update
                    if seq > last_seq.get(rank, 0):
                        grad = pickle.loads(blob)
                        queue().push((key, rank), NDArray(np.asarray(grad)))
                        last_seq[rank] = seq
                except Exception as e:  # noqa: BLE001
                    print(f"mxtpu dist_async server: dropping push "
                          f"{k!r}: {type(e).__name__}: {e}",
                          file=sys.stderr, flush=True)
                try:
                    self._c.key_value_delete(k)
                except Exception:
                    pass
            q = queue()
            if not entries and q.pending_count:
                # a service round with no arrivals still ages held-back
                # entries, so induced staleness releases by TIME as well
                # as by traffic (otherwise a quiet wire deadlocks pacing
                # workers against the delayed queue)
                q._drain(force=False)
            try:
                reqs = self._c.key_value_dir_get_bytes(f"{_NS}/flushreq/")
            except Exception:
                reqs = []
            if reqs:
                q.flush()                     # release delayed entries
                for k, _ in reqs:
                    try:
                        self._c.key_value_delete(k)
                    except Exception:
                        pass
            with self._lock:
                dirty = bool(self._touched)
            if dirty:
                self._publish()
            if not entries:
                time.sleep(self._poll_s)
